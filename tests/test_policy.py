"""Policy engine tests (ISSUE 11 tentpole, part b).

Covers the sandboxed loading contract (imports and filesystem access
blocked at load time), the three decision points (a scoring override
changes the GetPreferredAllocation winner; health-verdict overrides
partition the ANDed sources; admission throttles reject prepare/
allocate with typed errors), the containment story (per-hook call
deadline discards late results with a counter; the circuit breaker
opens after repeated failures and the engine reverts to builtin), and
the observable surfaces (/status policy section, /debug/policy,
tdp_policy_* metrics, the policy.hook fault site).
"""

from __future__ import annotations

import os
from dataclasses import replace

import pytest

from tests.fakehost import FakeChip, FakeHost
from tpu_device_plugin import faults
from tpu_device_plugin.config import Config
from tpu_device_plugin.discovery import discover_passthrough
from tpu_device_plugin.kubeletapi import pb
from tpu_device_plugin.policy import (HOOK_NAMES, PolicyEngine,
                                      PolicyLoadError)
from tpu_device_plugin.server import TpuDevicePlugin


@pytest.fixture(autouse=True)
def clean_faults():
    faults.reset()
    yield
    faults.reset()


class FakeClock:
    def __init__(self):
        self.now = 0.0

    def __call__(self):
        return self.now

    def advance(self, s):
        self.now += s


def engine_with(source, name="testpol", **kw):
    engine = PolicyEngine(**kw)
    engine.load_source(name, source)
    return engine


# ------------------------------------------------------------- sandbox


def test_sandbox_blocks_imports():
    with pytest.raises(PolicyLoadError, match="failed at load"):
        engine_with("import os\n\ndef admit(ctx):\n    return True\n")


def test_sandbox_blocks_filesystem_and_escape_primitives():
    # removed builtins fail at exec; dunder references fail even
    # earlier, at the static AST check — either way the load refuses
    for body in ("open('/etc/passwd')",
                 "__import__('os')",
                 "getattr(int, '__subclasses__')",
                 "eval('1+1')"):
        with pytest.raises(PolicyLoadError,
                           match="failed at load|dunder access"):
            engine_with(f"x = {body}\n\ndef admit(ctx):\n    return True\n")


def test_sandboxed_hook_raising_at_call_time_is_contained():
    engine = engine_with(
        "def admit(ctx):\n    return open('/etc/passwd') and True\n")
    # NameError at call time: counted, builtin behavior (admit)
    assert engine.admit({"op": "prepare"}) is None
    assert engine.snapshot()["hooks"][0]["errors"] == 1


def test_module_without_hooks_is_refused():
    with pytest.raises(PolicyLoadError, match="defines none"):
        engine_with("x = 1\n")


def test_load_dir_loads_sorted_modules(tmp_path):
    (tmp_path / "a_scoring.py").write_text(
        "def score_allocation(ctx):\n    return None\n")
    (tmp_path / "b_admit.py").write_text(
        "def admit(ctx):\n    return True\n")
    engine = PolicyEngine()
    assert engine.load_dir(str(tmp_path)) == 2
    assert engine.modules == ["a_scoring", "b_admit"]
    assert engine.has_hook("score_allocation")
    assert engine.has_hook("admit")
    assert not engine.has_hook("health_verdict")


# ----------------------------------------------------- decision points


def test_scoring_override_changes_preferred_winner(short_root):
    """The acceptance-named test: an operator policy re-picks the
    GetPreferredAllocation winner (here: highest-BDF chips, the exact
    opposite of the builtin's low-coordinate sub-box packing)."""
    host = FakeHost(short_root)
    for i in range(4):
        host.add_chip(FakeChip(f"0000:00:{4 + i:02x}.0",
                               iommu_group=str(11 + i)))
    cfg = Config().with_root(host.root)
    os.makedirs(cfg.device_plugin_path, exist_ok=True)
    registry, generations = discover_passthrough(cfg)
    devices = registry.devices_by_model["0062"]
    torus = generations["0062"].host_topology
    avail = [d.bdf for d in devices]
    req = pb.PreferredAllocationRequest(container_requests=[
        pb.ContainerPreferredAllocationRequest(
            available_deviceIDs=avail, allocation_size=2)])

    builtin_plugin = TpuDevicePlugin(cfg, "v4", registry, devices,
                                     torus_dims=torus)
    builtin_choice = list(builtin_plugin.GetPreferredAllocation(
        req, None).container_responses[0].deviceIDs)

    engine = engine_with(
        "def score_allocation(ctx):\n"
        "    ranked = sorted(ctx['available'], reverse=True)\n"
        "    return ranked[:ctx['size']]\n")
    policed = TpuDevicePlugin(cfg, "v4", registry, devices,
                              torus_dims=torus, policy=engine)
    override_choice = list(policed.GetPreferredAllocation(
        req, None).container_responses[0].deviceIDs)
    assert override_choice == sorted(avail, reverse=True)[:2]
    assert override_choice != builtin_choice
    hook = engine.snapshot()["hooks"][0]
    assert hook["calls"] == 1 and hook["overrides"] == 1
    # the ctx carried the builtin choice + its placement score for
    # composition — prove the engine validated against it
    assert engine.invalid_overrides.value == 0


def test_invalid_scoring_override_keeps_builtin(short_root):
    host = FakeHost(short_root)
    for i in range(4):
        host.add_chip(FakeChip(f"0000:00:{4 + i:02x}.0",
                               iommu_group=str(11 + i)))
    cfg = Config().with_root(host.root)
    os.makedirs(cfg.device_plugin_path, exist_ok=True)
    registry, generations = discover_passthrough(cfg)
    devices = registry.devices_by_model["0062"]
    engine = engine_with(
        "def score_allocation(ctx):\n"
        "    return ['not-a-device', 'also-bogus']\n")
    plugin = TpuDevicePlugin(cfg, "v4", registry, devices,
                             torus_dims=generations["0062"].host_topology,
                             policy=engine)
    req = pb.PreferredAllocationRequest(container_requests=[
        pb.ContainerPreferredAllocationRequest(
            available_deviceIDs=[d.bdf for d in devices],
            allocation_size=2)])
    ids = list(plugin.GetPreferredAllocation(
        req, None).container_responses[0].deviceIDs)
    assert set(ids) <= {d.bdf for d in devices}
    assert engine.invalid_overrides.value == 1


def test_health_verdict_override_partitions_sources(short_root):
    """A quarantine policy forces one chip's verdict Unhealthy whatever
    the observed source said; siblings keep the observed verdict."""
    host = FakeHost(short_root)
    host.add_chip(FakeChip("0000:00:04.0", iommu_group="11"))
    host.add_chip(FakeChip("0000:00:05.0", iommu_group="12"))
    cfg = Config().with_root(host.root)
    os.makedirs(cfg.device_plugin_path, exist_ok=True)
    registry, _ = discover_passthrough(cfg)
    engine = engine_with(
        "QUARANTINE = {'0000:00:04.0'}\n"
        "def health_verdict(ctx):\n"
        "    if ctx['device'] in QUARANTINE:\n"
        "        return False\n"
        "    return None\n")
    plugin = TpuDevicePlugin(cfg, "v4", registry,
                             registry.devices_by_model["0062"],
                             policy=engine)
    plugin.set_devices_health(["0000:00:04.0", "0000:00:05.0"],
                              healthy=True, source="probe")
    health = plugin._store.current.device_health
    assert health["0000:00:04.0"] == "Unhealthy"
    assert health["0000:00:05.0"] == "Healthy"


def test_admit_rejects_allocate_resource_exhausted(short_root):
    import grpc

    from tests.fakehost import FakeKubelet

    host = FakeHost(short_root)
    host.add_chip(FakeChip("0000:00:04.0", iommu_group="11"))
    cfg = replace(Config().with_root(host.root), health_poll_s=5.0)
    os.makedirs(cfg.device_plugin_path, exist_ok=True)
    kubelet = FakeKubelet(cfg.kubelet_socket)
    registry, _ = discover_passthrough(cfg)
    engine = engine_with(
        "def admit(ctx):\n"
        "    if ctx['op'] == 'allocate':\n"
        "        return 'maintenance window'\n"
        "    return True\n")
    plugin = TpuDevicePlugin(cfg, "v4", registry,
                             registry.devices_by_model["0062"],
                             policy=engine)
    plugin.start()
    try:
        with grpc.insecure_channel(f"unix://{plugin.socket_path}") as ch:
            from tpu_device_plugin import kubeletapi as api
            stub = api.DevicePluginStub(ch)
            with pytest.raises(grpc.RpcError) as exc_info:
                stub.Allocate(pb.AllocateRequest(container_requests=[
                    pb.ContainerAllocateRequest(
                        devices_ids=["0000:00:04.0"])]), timeout=5)
            assert exc_info.value.code() \
                == grpc.StatusCode.RESOURCE_EXHAUSTED
            assert "maintenance window" in exc_info.value.details()
    finally:
        plugin.stop()
        kubelet.stop()


def test_admit_rejects_dra_prepare_per_claim(short_root):
    """The DRA plane: a rejected claim errors with the policy reason;
    admitted claims in the same RPC still prepare."""
    from tests.test_dra import FakeApiServer
    from tpu_device_plugin.dra import DraDriver, slice_device_name
    from tpu_device_plugin.kubeapi import ApiClient
    from tpu_device_plugin.kubeletapi import drapb

    host = FakeHost(short_root)
    host.add_chip(FakeChip("0000:00:04.0", iommu_group="11"))
    host.add_chip(FakeChip("0000:00:05.0", iommu_group="12"))
    cfg = Config().with_root(host.root)
    os.makedirs(cfg.device_plugin_path, exist_ok=True)
    registry, generations = discover_passthrough(cfg)
    apiserver = FakeApiServer()
    engine = engine_with(
        "def admit(ctx):\n"
        "    if ctx.get('name') == 'blocked-claim':\n"
        "        return 'tenant over quota'\n"
        "    return None\n")
    driver = DraDriver(cfg, registry, generations, node_name="n1",
                       api=ApiClient(apiserver.url,
                                     token_path="/nonexistent"),
                       policy=engine)
    try:
        for name, bdf in (("ok-claim", "0000:00:04.0"),
                          ("blocked-claim", "0000:00:05.0")):
            apiserver.add_claim("ns", name, name, driver.driver_name,
                                [{"device": slice_device_name(bdf)}])
        resp = driver.NodePrepareResources(
            drapb.NodePrepareResourcesRequest(claims=[
                drapb.Claim(namespace="ns", name=n, uid=n)
                for n in ("ok-claim", "blocked-claim")]), None)
        assert not resp.claims["ok-claim"].error
        assert "tenant over quota" in resp.claims["blocked-claim"].error
        assert driver.prepared_claim_count() == 1
    finally:
        driver.stop()
        apiserver.stop()


# ------------------------------------------------------- containment


def test_deadline_exceeded_falls_back_to_builtin_with_counter():
    clock = FakeClock()
    engine = PolicyEngine(hook_deadline_ms=10.0, clock=clock)
    engine.load_source("slowpol",
                       "def admit(ctx):\n    return 'reject-everything'\n")
    orig_fn = engine._hooks["admit"][0].fn

    def slow(ctx):
        clock.advance(0.050)     # 50 ms > the 10 ms deadline
        return orig_fn(ctx)

    engine._hooks["admit"][0].fn = slow
    # the rejection arrived late: DISCARDED — builtin behavior (admit)
    assert engine.admit({"op": "prepare"}) is None
    hook = engine.snapshot()["hooks"][0]
    assert hook["deadline_exceeded"] == 1
    assert hook["overrides"] == 0


def test_breaker_opens_after_repeated_hook_failures():
    clock = FakeClock()
    engine = PolicyEngine(breaker_threshold=3, breaker_cooldown_s=30.0,
                          clock=clock)
    engine.load_source("badpol",
                       "def admit(ctx):\n    raise ValueError('boom')\n")
    for _ in range(3):
        assert engine.admit({"op": "prepare"}) is None   # builtin kept
    hook = engine.snapshot()["hooks"][0]
    assert hook["errors"] == 3
    assert hook["breaker"]["state"] == "open"
    # while open the hook is SKIPPED (no new error, rejected counter)
    assert engine.admit({"op": "prepare"}) is None
    hook = engine.snapshot()["hooks"][0]
    assert hook["errors"] == 3
    assert hook["rejected_while_open"] == 1
    # cooldown: the half-open probe calls the hook again
    clock.advance(31.0)
    assert engine.admit({"op": "prepare"}) is None
    assert engine.snapshot()["hooks"][0]["errors"] == 4


def test_policy_hook_fault_site_reads_as_raising_policy():
    engine = engine_with("def admit(ctx):\n    return True\n")
    with faults.injected("policy.hook", kind="error", count=2):
        assert engine.admit({"op": "prepare"}) is None
        assert engine.admit({"op": "prepare"}) is None
    hook = engine.snapshot()["hooks"][0]
    assert hook["errors"] == 2
    assert faults.stats().get("policy.hook") == 2
    # disarmed: the hook answers again
    assert engine.admit({"op": "prepare"}) is None
    assert engine.snapshot()["hooks"][0]["errors"] == 2


def test_slow_policy_via_timeout_fault_kind():
    """kind=timeout arms a TimeoutError — the 'slow policy' simulation
    the chaos docs name; the engine contains it like any raiser."""
    engine = engine_with("def admit(ctx):\n    return True\n")
    with faults.injected("policy.hook", kind="timeout", count=1):
        assert engine.admit({"op": "prepare"}) is None
    assert engine.snapshot()["hooks"][0]["errors"] == 1


# ---------------------------------------------------------- surfaces


def test_first_non_none_hook_wins_across_modules():
    engine = PolicyEngine()
    engine.load_source("first", "def admit(ctx):\n    return None\n")
    engine.load_source("second", "def admit(ctx):\n    return 'no'\n")
    assert engine.admit({"op": "prepare"}) == "no"
    by_module = {h["module"]: h for h in engine.snapshot()["hooks"]}
    assert by_module["second"]["overrides"] == 1
    assert by_module["first"]["overrides"] == 0


def test_debug_surface_carries_recent_decisions():
    engine = engine_with("def admit(ctx):\n    return 'nope'\n")
    assert engine.admit({"op": "prepare", "claim_uid": "u1"}) == "nope"
    debug = engine.debug()
    assert debug["modules"] == ["testpol"]
    assert debug["recent_decisions"][-1]["hook"] == "admit"
    assert debug["recent_decisions"][-1]["outcome"] == "reject"
    assert debug["recent_decisions"][-1]["ctx"]["claim_uid"] == "u1"


def test_status_and_metrics_surface_policy(short_root):
    """/status carries the policy section, /metrics the tdp_policy_*
    families and the broker crossing counters, /debug/policy answers."""
    import json
    import urllib.request

    from tpu_device_plugin.lifecycle import PluginManager
    from tpu_device_plugin.status import StatusServer

    host = FakeHost(short_root)
    host.add_chip(FakeChip("0000:00:04.0", iommu_group="11"))
    cfg = Config().with_root(host.root)
    os.makedirs(cfg.device_plugin_path, exist_ok=True)
    engine = engine_with("def admit(ctx):\n    return True\n")
    manager = PluginManager(cfg, policy_engine=engine)
    server = StatusServer(manager, port=0, host="127.0.0.1")
    server.start()
    try:
        engine.admit({"op": "prepare"})
        base = f"http://127.0.0.1:{server.port}"
        status = json.load(urllib.request.urlopen(f"{base}/status"))
        assert status["policy"]["modules"] == ["testpol"]
        assert status["policy"]["hooks"][0]["calls"] >= 1
        assert "crossings_total" in status["broker"]
        metrics = urllib.request.urlopen(f"{base}/metrics").read().decode()
        assert "tdp_policy_hook_calls_total" in metrics
        assert "tdp_policy_breaker_open" in metrics
        assert "tdp_broker_crossings_total" in metrics
        debug = json.load(urllib.request.urlopen(f"{base}/debug/policy"))
        assert debug["modules"] == ["testpol"]
        broker_dbg = json.load(
            urllib.request.urlopen(f"{base}/debug/broker"))
        assert broker_dbg["mode"] in ("inproc", "spawn")
    finally:
        server.stop()


def test_debug_policy_404_without_engine(short_root):
    import urllib.error
    import urllib.request

    from tpu_device_plugin.lifecycle import PluginManager
    from tpu_device_plugin.status import StatusServer

    host = FakeHost(short_root)
    host.add_chip(FakeChip("0000:00:04.0", iommu_group="11"))
    cfg = Config().with_root(host.root)
    os.makedirs(cfg.device_plugin_path, exist_ok=True)
    manager = PluginManager(cfg)
    server = StatusServer(manager, port=0, host="127.0.0.1")
    server.start()
    try:
        with pytest.raises(urllib.error.HTTPError) as exc_info:
            urllib.request.urlopen(
                f"http://127.0.0.1:{server.port}/debug/policy")
        assert exc_info.value.code == 404
    finally:
        server.stop()


def test_hook_names_are_the_documented_contract():
    assert HOOK_NAMES == ("score_allocation", "health_verdict", "admit",
                          "remediate")


def test_shipped_example_policy_loads_and_decides():
    """examples/policy_prefer_high_bdf.py must stay loadable under the
    sandbox and produce the documented decisions."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    with open(os.path.join(repo, "examples",
                           "policy_prefer_high_bdf.py")) as f:
        engine = engine_with(f.read(), name="prefer_high_bdf")
    # a perfect builtin placement is kept
    assert engine.score_allocation({
        "available": ["a", "b"], "must_include": [], "size": 2,
        "builtin_choice": ["a", "b"], "builtin_score": 1.0}) is None
    # a fragmented one is re-ranked highest-first
    assert engine.score_allocation({
        "available": ["a", "b", "c"], "must_include": [], "size": 2,
        "builtin_choice": ["a", "b"], "builtin_score": 0.5}) == ["c", "b"]
    assert engine.admit({"op": "prepare", "namespace": "frozen"}) \
        == "namespace frozen for maintenance"
    assert engine.admit({"op": "prepare", "namespace": "prod"}) is None


def test_sandbox_rejects_dunder_object_graph_walks():
    """The classic curated-builtins escape — walking the object graph
    through dunder attributes — is rejected STATICALLY at load."""
    escape = (
        "def admit(ctx):\n"
        "    for c in ().__class__.__base__.__subclasses__():\n"
        "        pass\n"
        "    return True\n")
    with pytest.raises(PolicyLoadError, match="dunder access"):
        engine_with(escape)
    # dunder NAMES are rejected too, anywhere in the module body
    with pytest.raises(PolicyLoadError, match="dunder access"):
        engine_with("x = __builtins__\n\ndef admit(ctx):\n    return x\n")


def test_first_winner_short_circuits_remaining_hooks():
    """Once a hook answers, later hooks must not run at all — their
    results could never apply, so charging their latency (and their
    breakers) would be pure waste on the decision path."""
    engine = PolicyEngine()
    engine.load_source("first", "def admit(ctx):\n    return 'no'\n")
    engine.load_source("second", "def admit(ctx):\n    return 'also-no'\n")
    assert engine.admit({"op": "prepare"}) == "no"
    by_module = {h["module"]: h for h in engine.snapshot()["hooks"]}
    assert by_module["first"]["calls"] == 1
    assert by_module["second"]["calls"] == 0


def test_admit_true_is_not_counted_as_override():
    engine = engine_with("def admit(ctx):\n    return True\n")
    assert engine.admit({"op": "prepare"}) is None
    hook = engine.snapshot()["hooks"][0]
    assert hook["calls"] == 1
    assert hook["overrides"] == 0


def test_scoring_override_validated_against_pre_hook_snapshot():
    """A hook mutating its ctx lists must not smuggle a nonexistent
    device past the validator: validation reads the pre-invocation
    snapshot, not the hook-mutated lists."""
    engine = engine_with(
        "def score_allocation(ctx):\n"
        "    ctx['available'].append('bogus-device')\n"
        "    return ['bogus-device', 'a']\n")
    ids = engine.score_allocation({
        "available": ["a", "b"], "must_include": [], "size": 2,
        "builtin_choice": ["a", "b"], "builtin_score": 0.5})
    assert ids is None
    assert engine.invalid_overrides.value == 1
