"""Flash-vs-einsum attention benchmark (single device, one process claim).

The build environment's TPU tunnel grants one exclusive claim per process
and has historically been flaky, so this packs the whole kernel-tuning
protocol — forward and train timings for the Pallas flash kernel against
the einsum reference across sequence lengths and block sizes — into one
command:

    python -m tpu_device_plugin.validator --mode attn-bench \
        --seqs 1024,2048,4096 --blocks 128x128,256x128

Emits one JSON line per (seq, block) cell plus a winner summary, feeding
BASELINE.md and the flash block-size tuning loop (roadmap item 2).
On CPU the kernel runs in interpret mode (slow): keep seqs small there.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

# module-level name so tests can monkeypatch the timing seam
from .timing import paired_time as _paired_time  # noqa: E402


def _chain_fwd(fn_one, repeats: int):
    """jit(q,k,v) -> scalar: `repeats` serially-dependent forwards (each
    output feeds the next call's q, so XLA can neither DCE nor overlap
    them), reduced to one float so fetching it forces full execution."""
    import jax
    import jax.numpy as jnp

    def run(q, k, v):
        out = jax.lax.fori_loop(
            0, max(repeats, 1), lambda i, qq: fn_one(qq, k, v), q)
        return jnp.sum(out.astype(jnp.float32))
    return jax.jit(run)


def _chain_train(grad_fn, repeats: int):
    """Same, for a grad fn returning (dq, dk, dv). ALL THREE grads feed the
    next iteration's inputs (dq becomes q; dk/dv perturb k/v) — carrying dq
    alone would let XLA dead-code-eliminate the entire dk/dv computation
    (the dkv backward kernel), silently timing a partial backward."""
    import jax
    import jax.numpy as jnp

    def run(q, k, v):
        def body(i, qkv):
            qq, kk, vv = qkv
            dq, dk, dv = grad_fn(qq, kk, vv)
            return (dq,
                    kk + (0.001 * dk).astype(kk.dtype),
                    vv + (0.001 * dv).astype(vv.dtype))
        out = jax.lax.fori_loop(0, max(repeats, 1), body, (q, k, v))
        return sum(jnp.sum(x.astype(jnp.float32)) for x in out)
    return jax.jit(run)


def bench_attention(
    seq_lens: Sequence[int] = (1024, 2048, 4096),
    blocks: Sequence[Tuple[int, int]] = ((128, 128),),
    hb: int = 8,
    head_dim: int = 128,
    iters: int = 10,
    causal: bool = True,
    device=None,
    interpret: Optional[bool] = None,
    bwd_blocks: Sequence[Optional[Tuple[int, int]]] = (None,),
    repeats: int = 1,
) -> dict:
    """Compare Pallas flash vs einsum reference on one device.

    Returns {"cells": [...], "flash_wins_at": [...], "device_kind": ...}.
    Each cell: seq, block_q, block_k, flash/einsum forward + train (ms) and
    speedups (>1 means flash is faster).
    """
    import jax
    import jax.numpy as jnp

    from .flash_attention import (DEFAULT_BWD_BLOCK, _reference_attention,
                                  flash_attention)

    if device is None:
        # local: in a multi-VMI slice jax.devices() spans other guests'
        # non-addressable devices (same trap probe._microbench documents)
        device = jax.local_devices()[0]
    if interpret is None:
        interpret = device.platform != "tpu"
    iters = max(iters, 1)  # _median needs >=1 sample

    def rand(shape, seed):
        x = jax.random.normal(jax.random.key(seed), shape, jnp.float32)
        return jax.device_put(x.astype(jnp.bfloat16), device)

    sm = head_dim ** -0.5
    cells = []
    for seq in seq_lens:
        # Differencing cancels the fixed relay overhead but its run-to-run
        # noise (~ms) remains: scale the chain length so R x t_iter stays
        # well above it at every seq (attention compute ~ seq^2). Floor of
        # 2 — collapsing to 1 would silently re-enter the plain-timing
        # path this module documents as untrustworthy on relayed devices.
        reps = (max(2, min(2048, int(repeats * (4096 / seq) ** 2)))
                if repeats > 1 else repeats)
        q, k, v = (rand((hb, seq, head_dim), i) for i in (1, 2, 3))
        # cast to q.dtype so the chained carry type matches q's
        ein_fwd_one = (lambda q, k, v: _reference_attention(q, k, v, sm, causal)
                       .astype(q.dtype))
        ein_grad = jax.grad(
            lambda q, k, v: jnp.sum(
                _reference_attention(q, k, v, sm, causal)
                .astype(jnp.float32) ** 2), argnums=(0, 1, 2))
        try:
            ein_fwd_s = _paired_time(
                lambda r: _chain_fwd(ein_fwd_one, r), (q, k, v), iters, reps)
            ein_train_s = _paired_time(
                lambda r: _chain_train(ein_grad, r), (q, k, v), iters, reps)
            ein_err = ""
        except Exception as exc:
            # the einsum reference materializes the (S, S) matrix and can
            # OOM at lengths flash handles fine — keep sweeping
            ein_fwd_s = ein_train_s = None
            ein_err = f"einsum: {type(exc).__name__}: {exc}"
        for bq, bk in blocks:
            for bwd in bwd_blocks:
                bwq, bwk = bwd if bwd is not None else (None, None)
                fl_fwd_one = (
                    lambda q, k, v, bq=bq, bk=bk: flash_attention(
                        q, k, v, None, causal, bq, bk, interpret))
                fl_grad = jax.grad(
                    lambda q, k, v, bq=bq, bk=bk, bwq=bwq, bwk=bwk: jnp.sum(
                        flash_attention(q, k, v, None, causal, bq, bk,
                                        interpret, bwq, bwk)
                        .astype(jnp.float32) ** 2), argnums=(0, 1, 2))
                try:
                    fl_fwd_s = _paired_time(
                        lambda r: _chain_fwd(fl_fwd_one, r),
                        (q, k, v), iters, reps)
                    fl_train_s = _paired_time(
                        lambda r: _chain_train(fl_grad, r),
                        (q, k, v), iters, reps)
                    err = ein_err
                except Exception as exc:  # report the cell, keep sweeping
                    fl_fwd_s = fl_train_s = None  # None -> JSON null
                    err = "; ".join(
                        x for x in (ein_err,
                                    f"flash: {type(exc).__name__}: {exc}")
                        if x)

                def ms(s):
                    return None if s is None else s * 1e3

                def speedup(ref_s, new_s):
                    return (ref_s / new_s
                            if ref_s is not None and new_s else None)

                cells.append({
                    "seq": seq, "block_q": bq, "block_k": bk,
                    # record the EFFECTIVE backward tiling: None resolves to
                    # DEFAULT_BWD_BLOCK in _bwd, and both axes clamp to seq
                    "bwd_block_q": min(bwq or DEFAULT_BWD_BLOCK, seq),
                    "bwd_block_k": min(bwk or DEFAULT_BWD_BLOCK, seq),
                    "reps": reps,  # effective chain length for this seq
                    "flash_fwd_ms": ms(fl_fwd_s),
                    "einsum_fwd_ms": ms(ein_fwd_s),
                    "flash_train_ms": ms(fl_train_s),
                    "einsum_train_ms": ms(ein_train_s),
                    "fwd_speedup": speedup(ein_fwd_s, fl_fwd_s),
                    "train_speedup": speedup(ein_train_s, fl_train_s),
                    "error": err,
                })
    wins = sorted({c["seq"] for c in cells
                   if c["flash_fwd_ms"] is not None
                   and (c["fwd_speedup"] or 0) > 1.0})
    return {
        "device_kind": device.device_kind,
        "platform": device.platform,
        "interpret": interpret,
        "hb": hb,
        "head_dim": head_dim,
        "repeats": repeats,
        "cells": cells,
        "flash_wins_at": wins,
        # the verdict the CLI uses: the FLASH kernel must have run in every
        # cell; an einsum-reference failure (it OOMs at lengths flash
        # handles fine) degrades that cell's comparison, never the sweep
        "flash_ok": bool(cells) and all(
            c["flash_fwd_ms"] is not None for c in cells),
    }
