"""Device registry: typed records + lookup maps, no package globals.

The reference keeps five package-global maps mutated during discovery
(reference: pkg/device_plugin/device_plugin.go:50-68, getters :359-369).
Here discovery returns one immutable `Registry` value that is injected into
every consumer, so tests never share state and servers can atomically swap
registries on re-discovery.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple


@dataclass(frozen=True)
class TpuDevice:
    """One TPU PCIe endpoint bound to a VFIO driver.

    Extends the reference's `NvidiaGpuDevice{addr, numaNode}`
    (device_plugin.go:50-53) with TPU-native attributes: the PCI device id
    (drives generation naming), the correlated `/dev/accel*` index when the
    accel driver owns the chip, and the chip's ICI torus coordinates.
    """

    bdf: str                                  # PCI address, e.g. "0000:00:05.0"
    device_id: str                            # PCI device id hex, no 0x prefix
    iommu_group: str                          # e.g. "42"
    numa_node: int                            # negative values clamped to 0
    accel_index: Optional[int] = None         # /dev/accelN, if correlated
    ici_coords: Optional[Tuple[int, ...]] = None  # host-local torus coords


@dataclass(frozen=True)
class TpuPartition:
    """One shareable sub-chip partition (vTPU; the reference's vGPU/mdev slot).

    Covers both providers: kernel mdev devices (uuid = mdev UUID,
    reference: device_plugin.go:255-291) and logical partitions declared in
    a partition config for hardware without mdev (uuid is synthesized).
    """

    uuid: str
    type_name: str                            # sanitized partition type
    parent_bdf: str
    numa_node: int
    provider: str = "mdev"                    # "mdev" | "logical"
    accel_index: Optional[int] = None         # logical partitions ride /dev/accelN


@dataclass(frozen=True)
class SharedDevice:
    """A host device shared across several chips (EGM analogue, reference #9).

    Injected into an allocation only when *every* member chip is allocated
    (all-or-nothing, reference: generic_device_plugin.go:159-184).
    """

    name: str                                 # e.g. "egm0"
    dev_path: str                             # e.g. "/dev/egm0"
    member_bdfs: Tuple[str, ...]


@dataclass(frozen=True)
class Registry:
    """Immutable snapshot of everything discovery found on this host."""

    # device id → devices of that model (reference `deviceMap`, :59)
    devices_by_model: Dict[str, Tuple[TpuDevice, ...]] = field(default_factory=dict)
    # iommu group → all devices in the group (reference `iommuMap`, :56)
    iommu_map: Dict[str, Tuple[TpuDevice, ...]] = field(default_factory=dict)
    # BDF → iommu group (reference `bdfToIommuMap`, :62)
    bdf_to_group: Dict[str, str] = field(default_factory=dict)
    # partition type → partitions (reference `vGpuMap`, :65)
    partitions_by_type: Dict[str, Tuple[TpuPartition, ...]] = field(default_factory=dict)
    # parent BDF → partition uuids (reference `gpuVgpuMap`, :68)
    parent_to_partitions: Dict[str, Tuple[str, ...]] = field(default_factory=dict)

    def device(self, bdf: str) -> Optional[TpuDevice]:
        group = self.bdf_to_group.get(bdf)
        if group is None:
            return None
        for dev in self.iommu_map.get(group, ()):
            if dev.bdf == bdf:
                return dev
        return None

    def all_devices(self) -> List[TpuDevice]:
        out: List[TpuDevice] = []
        for devs in self.devices_by_model.values():
            out.extend(devs)
        return out
