"""SPMD transformer burn-in workload.

A deliberately small but *real* training step — embedding, multi-head causal
attention, MLP, cross-entropy, SGD-with-momentum — written TPU-first:

- all matmuls run in bfloat16 (MXU-shaped), accumulating in float32;
- parallelism is expressed through sharding annotations on a
  ("dp", "sp", "tp") mesh plus `shard_map` for the attention inner loop; XLA
  inserts the collectives (gradient psum over dp/sp, activation all-gathers
  for tp);
- long context gets three attention strategies: `ring` (sequence-parallel
  ring attention, K/V rotate over ICI via ppermute — O(S/sp) residency in
  forward AND backward via a rematerializing custom VJP), `flash` (Pallas
  blockwise kernel when the full sequence is local), and `einsum` (KV
  all-gather reference path);
- control flow is static: one traced step, no data-dependent Python.

Used by the guest validator to burn in a passed-through slice, and by
`__graft_entry__.dryrun_multichip` to compile-check the multi-chip path.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

Params = Dict[str, Any]


@dataclass(frozen=True)
class ModelConfig:
    vocab: int = 256
    d_model: int = 128
    n_heads: int = 8
    d_ff: int = 512
    n_layers: int = 2
    seq_len: int = 128
    batch: int = 8
    lr: float = 1e-2
    momentum: float = 0.9
    # Mixture-of-experts: 0 = dense MLP; >0 replaces the MLP with a top-1
    # switch layer of n_experts experts (weights shardable over "ep").
    n_experts: int = 0
    capacity_factor: float = 1.25
    # Rematerialize each layer in the backward (jax.checkpoint around the
    # scanned block): activation memory drops from O(L) layers to O(1) at
    # the cost of one extra forward — the standard HBM-for-FLOPs trade for
    # deep models on TPU.
    remat: bool = False


def init_params(key: jax.Array, cfg: ModelConfig) -> Params:
    """Layer weights are STACKED on a leading n_layers dim and consumed by
    `lax.scan` in the forward — one traced layer body regardless of depth,
    and the stacked dim is what "pp" shards (stage-partitioned weights)."""
    keys = jax.random.split(key, 10)
    scale = cfg.d_model ** -0.5
    L, d, ff, E = cfg.n_layers, cfg.d_model, cfg.d_ff, cfg.n_experts

    def dense(k, shape):
        return (jax.random.normal(k, shape, jnp.float32) * scale)

    layers = {
        "wq": dense(keys[2], (L, d, d)),
        "wk": dense(keys[3], (L, d, d)),
        "wv": dense(keys[4], (L, d, d)),
        "wo": dense(keys[5], (L, d, d)),
    }
    if E:
        layers["wr"] = dense(keys[6], (L, d, E))
        layers["w1e"] = dense(keys[7], (L, E, d, ff))
        layers["w2e"] = dense(keys[8], (L, E, ff, d))
    else:
        layers["w1"] = dense(keys[6], (L, d, ff))
        layers["w2"] = dense(keys[7], (L, ff, d))
    return {
        "embed": dense(keys[0], (cfg.vocab, d)),
        "unembed": dense(keys[1], (d, cfg.vocab)),
        "layers": layers,
    }


def param_specs(cfg: ModelConfig) -> Params:
    """PartitionSpecs: "pp" on the stacked layer dim, "tp" over heads/ffn,
    "ep" over experts; replicated over dp/sp. Axes absent from the actual
    mesh are filtered out at sharding-build time (`_filter_spec`)."""
    layers = {
        "wq": P("pp", None, "tp"), "wk": P("pp", None, "tp"),
        "wv": P("pp", None, "tp"), "wo": P("pp", "tp", None),
    }
    if cfg.n_experts:
        layers["wr"] = P("pp", None, None)
        layers["w1e"] = P("pp", "ep", None, "tp")
        layers["w2e"] = P("pp", "ep", "tp", None)
    else:
        layers["w1"] = P("pp", None, "tp")
        layers["w2"] = P("pp", "tp", None)
    return {
        "embed": P(None, "tp"),
        "unembed": P("tp", None),
        "layers": layers,
    }


def _filter_spec(spec: P, mesh: Mesh) -> P:
    """Drop axes the mesh doesn't have (pp/ep are optional mesh axes)."""
    names = set(mesh.axis_names)

    def keep(a):
        if a is None:
            return None
        if isinstance(a, (tuple, list)):
            kept = tuple(x for x in a if x in names)
            return kept if kept else None
        return a if a in names else None

    return P(*[keep(a) for a in spec])


def _constrain(x: jax.Array, spec: P, mesh: Optional[Mesh]) -> jax.Array:
    """Sharding constraint against an explicit mesh; no-op without one.

    Explicit NamedShardings keep the whole program jittable without an
    ambient `jax.set_mesh` context (which is illegal inside a jit trace).
    """
    if mesh is None:
        return x
    return jax.lax.with_sharding_constraint(
        x, NamedSharding(mesh, _filter_spec(spec, mesh)))


def _fold_heads(t: jax.Array):
    bl, sl, hl, dl = t.shape
    return t.transpose(0, 2, 1, 3).reshape(bl * hl, sl, dl)


def _unfold_heads(t: jax.Array, bl: int, hl: int):
    _, sl, dl = t.shape
    return t.reshape(bl, hl, sl, dl).transpose(0, 2, 1, 3)


def _attention(x: jax.Array, layer: Params, cfg: ModelConfig,
               attention: str = "einsum", interpret: bool = True,
               mesh: Optional[Mesh] = None) -> jax.Array:
    b, s, d = x.shape
    h, dh = cfg.n_heads, cfg.d_model // cfg.n_heads
    q = (x @ layer["wq"].astype(jnp.bfloat16)).reshape(b, s, h, dh)
    k = (x @ layer["wk"].astype(jnp.bfloat16)).reshape(b, s, h, dh)
    v = (x @ layer["wv"].astype(jnp.bfloat16)).reshape(b, s, h, dh)
    if attention == "ring":
        # sequence-parallel ring attention: K/V stay sharded along sp and
        # rotate around the ICI ring (O(S/sp) memory vs the all-gather's
        # O(S)). On real TPU each ring step runs the Pallas flash kernel on
        # its local block (scores never hit HBM); interpret mode keeps the
        # einsum inner loop — Pallas interpretation is orders of magnitude
        # slower than XLA:CPU einsums and the two are merge-identical
        # (tests/test_flash_attention.py::test_ring_flash_matches_einsum_ring)
        from .ring_attention import (RING_STEP_BLOCK, ring_attention,
                                     ring_flash_attention)

        def local_ring(q_, k_, v_):
            bl, _, hl, _ = q_.shape
            if interpret:
                o = ring_attention(_fold_heads(q_), _fold_heads(k_),
                                   _fold_heads(v_), dh ** -0.5,
                                   axis_name="sp")
            else:
                # forward blocks from the tuned constant; backward blocks
                # default to flash_attention.DEFAULT_BWD_BLOCK (256x256,
                # hardware-swept) inside _ring_flash_bwd
                o = ring_flash_attention(_fold_heads(q_), _fold_heads(k_),
                                         _fold_heads(v_), dh ** -0.5, "sp",
                                         *RING_STEP_BLOCK, False)
            return _unfold_heads(o, bl, hl)

        out4 = jax.shard_map(
            local_ring,
            mesh=mesh,
            in_specs=(P("dp", "sp", "tp", None),) * 3,
            out_specs=P("dp", "sp", "tp", None),
            check_vma=False,
        )(q, k, v)
        out = out4.reshape(b, s, d)
    elif attention == "flash":
        # batch and heads are embarrassingly parallel over dp x tp: run the
        # Pallas flash kernel per shard via shard_map (requires sp == 1 so
        # every shard holds the full sequence)
        from .flash_attention import flash_attention

        def local_attn(q_, k_, v_):
            bl, _, hl, _ = q_.shape
            o = flash_attention(_fold_heads(q_), _fold_heads(k_),
                                _fold_heads(v_), None, True, 128, 128,
                                interpret)
            return _unfold_heads(o, bl, hl)

        out4 = jax.shard_map(
            local_attn,
            mesh=mesh,
            in_specs=(P("dp", None, "tp", None),) * 3,
            out_specs=P("dp", None, "tp", None),
            # pallas_call's out_shape carries no varying-mesh-axes metadata
            check_vma=False,
        )(q, k, v)
        out = out4.reshape(b, s, d)
    else:
        # Sequence parallelism: queries stay sequence-sharded; keys/values
        # are gathered across the sp axis (XLA emits the all-gather) so
        # every query block attends over the full context.
        q = _constrain(q, P("dp", "sp", "tp", None), mesh)
        k = _constrain(k, P("dp", None, "tp", None), mesh)
        v = _constrain(v, P("dp", None, "tp", None), mesh)
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * (dh ** -0.5)
        mask = jnp.tril(jnp.ones((s, s), jnp.bool_))
        scores = jnp.where(mask[None, None, :, :], scores, -1e9)
        probs = jax.nn.softmax(scores, axis=-1).astype(jnp.bfloat16)
        out = jnp.einsum("bhqk,bkhd->bqhd", probs, v).reshape(b, s, d)
    return out @ layer["wo"].astype(jnp.bfloat16)


def _mlp(x: jax.Array, layer: Params) -> jax.Array:
    hidden = jax.nn.gelu(x @ layer["w1"].astype(jnp.bfloat16))
    return hidden @ layer["w2"].astype(jnp.bfloat16)


def _moe(x: jax.Array, layer: Params, cfg: ModelConfig,
         mesh: Optional[Mesh]) -> jax.Array:
    """Top-1 switch MoE, expert-parallel over the "ep" mesh axis.

    Static shapes throughout (capacity-based dispatch): tokens route to
    their argmax expert via one-hot dispatch/combine einsums, so XLA sees
    three batched matmuls and inserts the token all-to-alls implied by the
    (tokens dp/sp-sharded) → (experts ep-sharded) resharding. Tokens over
    an expert's capacity are dropped (standard switch behavior, fine for a
    burn-in; no load-balancing aux loss).
    """
    import math
    b, s, d = x.shape
    t = b * s
    e = cfg.n_experts
    # per-expert capacity, padded to a lane-friendly multiple of 8
    cap = min(t, max(8, math.ceil(math.ceil(t * cfg.capacity_factor / e) / 8) * 8))
    xt = x.reshape(t, d)
    logits = (xt @ layer["wr"].astype(jnp.bfloat16)).astype(jnp.float32)
    gates = jax.nn.softmax(logits, axis=-1)                    # (t, e)
    top1 = jnp.argmax(gates, axis=-1)                          # (t,)
    onehot = jax.nn.one_hot(top1, e, dtype=jnp.float32)        # (t, e)
    # position of each token within its expert's queue
    pos = jnp.cumsum(onehot, axis=0) * onehot                  # (t, e), 1-based
    within = (pos > 0) & (pos <= cap)
    dispatch = jax.nn.one_hot(
        (pos - 1).astype(jnp.int32), cap, dtype=jnp.float32) \
        * within[..., None]                                    # (t, e, cap)
    combine = dispatch * (jnp.sum(gates * onehot, axis=-1)[:, None, None])

    expert_in = jnp.einsum("tec,td->ecd", dispatch.astype(jnp.bfloat16), xt)
    expert_in = _constrain(expert_in, P("ep", None, None), mesh)
    hidden = jax.nn.gelu(jnp.einsum(
        "ecd,edf->ecf", expert_in, layer["w1e"].astype(jnp.bfloat16)))
    hidden = _constrain(hidden, P("ep", None, "tp"), mesh)
    expert_out = jnp.einsum(
        "ecf,efd->ecd", hidden, layer["w2e"].astype(jnp.bfloat16))
    expert_out = _constrain(expert_out, P("ep", None, None), mesh)
    out = jnp.einsum("tec,ecd->td", combine.astype(jnp.bfloat16), expert_out)
    return out.reshape(b, s, d)


def _rms_norm(x: jax.Array) -> jax.Array:
    var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
    return (x * jax.lax.rsqrt(var + 1e-6)).astype(x.dtype)


def _layer_body(x: jax.Array, layer: Params, cfg: ModelConfig,
                attention: str, interpret: bool,
                mesh: Optional[Mesh]) -> jax.Array:
    """One transformer block (attention + MoE/MLP residuals); shared by the
    scanned forward and the GPipe per-stage apply so they cannot drift."""
    x = x + _attention(_rms_norm(x), layer, cfg, attention, interpret, mesh)
    if cfg.n_experts:
        x = x + _moe(_rms_norm(x), layer, cfg, mesh)
    else:
        x = x + _mlp(_rms_norm(x), layer)
    return x


def layer_block(cfg: ModelConfig):
    """The (possibly rematerialized) block both scan consumers use — the
    static_argnums layout lives in exactly one place. prevent_cse=False per
    the jax.checkpoint guidance for use under lax.scan (scan already blocks
    the problematic CSE; the barriers would only cost performance)."""
    if cfg.remat:
        return jax.checkpoint(_layer_body, static_argnums=(2, 3, 4, 5),
                              prevent_cse=False)
    return _layer_body


def forward(params: Params, tokens: jax.Array, cfg: ModelConfig,
            attention: str = "einsum", interpret: bool = True,
            mesh: Optional[Mesh] = None) -> jax.Array:
    x = params["embed"].astype(jnp.bfloat16)[tokens]
    x = _constrain(x, P("dp", "sp", None), mesh)

    block = layer_block(cfg)

    def body(x, layer):
        x = block(x, layer, cfg, attention, interpret, mesh)
        x = _constrain(x, P("dp", "sp", None), mesh)
        return x, None

    # scan over the stacked layer dim: one traced body for any depth; with a
    # "pp" mesh axis the stacked weights are stage-sharded and activations
    # flow across stage boundaries between scan steps
    x, _ = jax.lax.scan(body, x, params["layers"])
    logits = _rms_norm(x) @ params["unembed"].astype(jnp.bfloat16)
    return logits.astype(jnp.float32)


def loss_fn(params: Params, tokens: jax.Array, cfg: ModelConfig,
            attention: str = "einsum", interpret: bool = True,
            mesh: Optional[Mesh] = None) -> jax.Array:
    logits = forward(params, tokens, cfg, attention, interpret, mesh)
    targets = tokens[:, 1:]
    logprobs = jax.nn.log_softmax(logits[:, :-1])
    nll = -jnp.take_along_axis(logprobs, targets[..., None], axis=-1)
    return jnp.mean(nll)


def sgd_step(params: Params, momentum: Params, tokens: jax.Array,
             cfg: ModelConfig, attention: str = "einsum",
             interpret: bool = True,
             mesh: Optional[Mesh] = None) -> Tuple[Params, Params, jax.Array]:
    """One full training step: loss, grads (psum over dp/sp implicit), SGD-M."""
    loss, grads = jax.value_and_grad(loss_fn)(params, tokens, cfg, attention,
                                              interpret, mesh)
    new_momentum = jax.tree.map(
        lambda m, g: cfg.momentum * m + g, momentum, grads)
    new_params = jax.tree.map(
        lambda p, m: p - cfg.lr * m, params, new_momentum)
    return new_params, new_momentum, loss


# Below this GLOBAL sequence length the XLA-fused einsum attention beats the
# Pallas flash kernel on real hardware (honest chained sweep,
# docs/validator_tpu_attn_r03b.json: flash fwd 0.30x / train 0.43x at 1024,
# ~parity fwd / 1.56x train at 2048, 2.5x/2.8x at 4096, 37x/18x at 8192 —
# einsum's (S, S) materialization collapses once it blows past VMEM-friendly
# sizes). Auto mode dispatches on it; explicit "flash" is always honored.
FLASH_MIN_SEQ = 2048


def _resolve(cfg, mesh, attention):
    """Shared mesh/platform/attention selection for train and infer builds."""
    cfg = cfg or ModelConfig()
    if mesh is None:
        from .mesh import slice_mesh
        mesh = slice_mesh(jax.devices()[:1])
    platform = mesh.devices.flat[0].platform
    sp_size = dict(zip(mesh.axis_names, mesh.devices.shape)).get("sp", 1)
    if attention is None:
        if sp_size > 1:
            attention = "ring"
        elif platform == "tpu" and cfg.seq_len >= FLASH_MIN_SEQ:
            attention = "flash"
        else:
            attention = "einsum"
    if attention == "flash" and sp_size != 1:
        raise ValueError("flash attention requires sp == 1 (full local sequence)")
    if attention not in ("flash", "ring", "einsum"):
        raise ValueError(f"unknown attention mode {attention!r}")
    return cfg, mesh, platform, attention


def build_workload(
    cfg: Optional[ModelConfig] = None,
    mesh: Optional[Mesh] = None,
    seed: int = 0,
    attention: Optional[str] = None,
):
    """Returns (jitted step, params, momentum, tokens), device-placed.

    Params/optimizer state follow `param_specs`, the batch is sharded
    (dp, sp). Without a mesh a trivial 1x1x1 mesh over the first visible
    device is used, so the same annotated program compiles single-chip.

    attention: "flash" (Pallas kernel, needs sp == 1), "ring"
    (sequence-parallel ring attention, K/V rotate over the sp axis),
    "einsum" (KV all-gather). None auto-selects: ring when sp > 1; flash on
    TPU when sp == 1 AND cfg.seq_len >= FLASH_MIN_SEQ (the hardware sweep's
    crossover — XLA's fused einsum wins below it); einsum otherwise.
    """
    cfg, mesh, platform, attention = _resolve(cfg, mesh, attention)
    params, tokens, param_sh, batch_sh = _place(cfg, mesh, seed)
    momentum = jax.device_put(
        jax.tree.map(jnp.zeros_like, params), param_sh)

    step = partial(sgd_step, cfg=cfg, attention=attention,
                   interpret=platform != "tpu", mesh=mesh)
    jitted = jax.jit(
        step,
        in_shardings=(param_sh, param_sh, batch_sh),
        out_shardings=(param_sh, param_sh, NamedSharding(mesh, P())),
        donate_argnums=(0, 1),
    )
    return jitted, params, momentum, tokens


def _place(cfg: ModelConfig, mesh: Mesh, seed: int):
    """Init + device-place params and a token batch per the mesh shardings."""
    params = init_params(jax.random.key(seed), cfg)
    tokens = jax.random.randint(
        jax.random.key(seed + 1), (cfg.batch, cfg.seq_len), 0, cfg.vocab,
        dtype=jnp.int32)
    pspecs = param_specs(cfg)
    param_sh = jax.tree.map(
        lambda spec: NamedSharding(mesh, _filter_spec(spec, mesh)), pspecs,
        is_leaf=lambda x: isinstance(x, P))
    batch_sh = NamedSharding(mesh, P("dp", "sp"))
    return (jax.device_put(params, param_sh),
            jax.device_put(tokens, batch_sh), param_sh, batch_sh)


def build_infer(
    cfg: Optional[ModelConfig] = None,
    mesh: Optional[Mesh] = None,
    seed: int = 0,
    attention: Optional[str] = None,
):
    """Serving-path build: a jitted forward over the same sharded model.

    Returns (jitted forward -> logits, params, tokens). Same mesh/attention
    selection as `build_workload`; no optimizer state, no donation, so the
    caller can invoke it repeatedly for latency percentiles.
    """
    cfg, mesh, platform, attention = _resolve(cfg, mesh, attention)
    params, tokens, param_sh, batch_sh = _place(cfg, mesh, seed)
    interpret = platform != "tpu"
    jitted = jax.jit(
        lambda p, t: forward(p, t, cfg, attention, interpret, mesh),
        in_shardings=(param_sh, batch_sh),
        out_shardings=NamedSharding(mesh, P("dp", "sp", None)),
    )
    return jitted, params, tokens
