"""CDI (Container Device Interface) support.

The v1beta1 AllocateResponse can name CDI devices instead of raw DeviceSpecs
(api.proto `cdi_devices`); kubelets with the CDI feature resolve those names
against spec files in /var/run/cdi or /etc/cdi. When `Config.cdi_spec_dir`
is set, the plugin:

1. writes one spec file per resource at startup
   (`<dir>/cloud-tpus.google.com-<suffix>.json`, CDI v0.6.0 schema) mapping
   each chip/partition to its device nodes, pruning files from resources
   that no longer exist, and
2. returns `CDIDevice` names (`cloud-tpus.google.com/tpu=<id>`) from
   Allocate alongside the classic DeviceSpecs + env var — older kubelets
   ignore the CDI names, CDI-aware ones get first-class device injection.
   Names are only emitted for resources whose spec file was actually
   written; a failed write degrades that resource to the classic path
   rather than handing out unresolvable names.

The reference plugin predates CDI; this is a forward-compatibility addition,
kept strictly additive.
"""

from __future__ import annotations

import json
import logging
import os
import tempfile
from typing import Dict, List, Optional, Sequence

from .config import Config
from .registry import TpuDevice, TpuPartition

log = logging.getLogger(__name__)

CDI_VERSION = "0.6.0"
CDI_KIND_DEVICE = "tpu"


def cdi_kind(cfg: Config) -> str:
    return f"{cfg.resource_namespace}/{CDI_KIND_DEVICE}"


def cdi_device_name(cfg: Config, device_id: str) -> str:
    """Fully-qualified CDI name the kubelet resolves: <kind>=<id>."""
    return f"{cdi_kind(cfg)}={device_id}"


def device_entries(cfg: Config, devices: Sequence[TpuDevice]) -> List[dict]:
    """Spec entries for passthrough chips: VFIO group (+ accel) nodes."""
    entries = []
    for dev in devices:
        nodes = [{"path": f"/dev/vfio/{dev.iommu_group}",
                  "hostPath": cfg.dev_path("dev/vfio", dev.iommu_group)}]
        if dev.accel_index is not None:
            nodes.append({"path": f"/dev/accel{dev.accel_index}",
                          "hostPath": cfg.dev_path("dev", f"accel{dev.accel_index}")})
        entries.append({"name": dev.bdf, "containerEdits": {"deviceNodes": nodes}})
    return entries


def partition_entries(cfg: Config, partitions: Sequence[TpuPartition],
                      bdf_to_group: Optional[Dict[str, str]] = None) -> List[dict]:
    """Spec entries for vTPU partitions.

    Every returned entry resolves to ≥1 STABLE device node: the partition's
    accel node, or its vfio-bound parent's group (stable for the registry's
    lifetime, like the passthrough entries). A partition whose nodes are only
    known at allocate time gets NO entry — notably mdevs, whose iommu group
    changes if the mdev is destroyed and recreated under the same UUID (the
    live-resolution the plugin's Allocate already does, vtpu.py) — Allocate
    then omits its CDI name and the classic DeviceSpec path carries the
    injection (a stale or unresolvable CDI name is worse than none)."""
    entries = []
    for p in partitions:
        nodes = []
        if p.accel_index is not None:
            # carry the operator's node-permission policy into the CDI path
            # too — otherwise a CDI-aware kubelet would inject the node with
            # runtime-default (rwm) access, bypassing
            # --partition-node-permissions r
            nodes.append({"path": f"/dev/accel{p.accel_index}",
                          "hostPath": cfg.dev_path("dev", f"accel{p.accel_index}"),
                          "permissions": cfg.partition_node_permissions})
        elif p.provider != "mdev" and bdf_to_group is not None:
            group = bdf_to_group.get(p.parent_bdf)
            # legacy VFIO group node only (iommufd-only hosts have no
            # /dev/vfio/<group>; their cdev set is allocate-time knowledge)
            if group is not None and os.path.exists(
                    cfg.dev_path("dev/vfio", group)):
                nodes.append({"path": f"/dev/vfio/{group}",
                              "hostPath": cfg.dev_path("dev/vfio", group)})
        if not nodes:
            log.info("partition %s has no statically stable device node; "
                     "omitting from CDI spec (classic DeviceSpec path covers "
                     "it)", p.uuid)
            continue
        entries.append({"name": p.uuid, "containerEdits": {"deviceNodes": nodes}})
    return entries


def spec_path(cfg: Config, suffix: str) -> str:
    """Where a resource's CDI spec file lives (whether or not it exists)."""
    return os.path.join(
        cfg.cdi_spec_dir,
        f"{cfg.resource_namespace.replace('/', '_')}-{suffix}.json")


def write_spec(cfg: Config, entries: Sequence[dict], suffix: str) -> Optional[str]:
    """Atomically write one resource's spec file; None on failure/disabled."""
    if not cfg.cdi_spec_dir:
        return None
    spec = {
        "cdiVersion": CDI_VERSION,
        "kind": cdi_kind(cfg),
        "containerEdits": {
            "deviceNodes": [{"path": "/dev/vfio/vfio",
                             "hostPath": cfg.dev_path("dev/vfio/vfio")}],
        },
        "devices": list(entries),
    }
    path = spec_path(cfg, suffix)
    try:
        os.makedirs(cfg.cdi_spec_dir, exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=cfg.cdi_spec_dir, suffix=".tmp")
    except OSError as exc:
        log.error("could not write CDI spec %s: %s", path, exc)
        return None
    try:
        with os.fdopen(fd, "w", encoding="utf-8") as f:
            json.dump(spec, f, indent=2, sort_keys=True)
        os.replace(tmp, path)
    except OSError as exc:
        log.error("could not write CDI spec %s: %s", path, exc)
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return None
    log.info("wrote CDI spec %s (%d devices)", path, len(spec["devices"]))
    return path


def prune_specs(cfg: Config, keep_paths: Sequence[str]) -> None:
    """Remove this plugin's spec files not in `keep_paths` (resources that
    disappeared across a rediscovery must not keep advertising dead nodes)."""
    if not cfg.cdi_spec_dir:
        return
    prefix = f"{cfg.resource_namespace.replace('/', '_')}-"
    keep = {os.path.basename(p) for p in keep_paths}
    try:
        entries = os.listdir(cfg.cdi_spec_dir)
    except OSError:
        return
    for name in entries:
        if name.startswith(prefix) and name.endswith(".json") and name not in keep:
            try:
                os.unlink(os.path.join(cfg.cdi_spec_dir, name))
                log.info("pruned stale CDI spec %s", name)
            except OSError as exc:
                log.warning("could not prune CDI spec %s: %s", name, exc)
