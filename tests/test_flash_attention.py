"""Pallas flash-attention kernel: numerics vs reference, grads, sharding.

Runs in interpret mode on the virtual CPU mesh; the same kernel compiles for
real TPU (interpret=False) in the guest validator.
"""

import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp

from tpu_device_plugin.validator.flash_attention import (
    _reference_attention, flash_attention)


def rand(shape, seed):
    return jax.random.normal(jax.random.key(seed), shape, jnp.float32)


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("seq,block", [(128, 64), (96, 64), (64, 128)])
def test_forward_matches_reference(causal, seq, block):
    hb, d = 2, 32
    q, k, v = rand((hb, seq, d), 1), rand((hb, seq, d), 2), rand((hb, seq, d), 3)
    out = flash_attention(q, k, v, None, causal, block, block, True)
    ref = _reference_attention(q, k, v, d ** -0.5, causal)
    assert float(jnp.max(jnp.abs(out - ref))) < 1e-5


def test_gradients_match_reference():
    hb, seq, d = 2, 64, 32
    q, k, v = rand((hb, seq, d), 1), rand((hb, seq, d), 2), rand((hb, seq, d), 3)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, None, True, 32, 32, True) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(_reference_attention(q, k, v, d ** -0.5, True) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        assert float(jnp.max(jnp.abs(a - b))) < 1e-4


def test_bfloat16_inputs():
    hb, seq, d = 2, 64, 32
    q = rand((hb, seq, d), 1).astype(jnp.bfloat16)
    k = rand((hb, seq, d), 2).astype(jnp.bfloat16)
    v = rand((hb, seq, d), 3).astype(jnp.bfloat16)
    out = flash_attention(q, k, v, None, True, 32, 32, True)
    ref = _reference_attention(q, k, v, d ** -0.5, True)
    assert out.dtype == jnp.bfloat16
    assert float(jnp.max(jnp.abs(out.astype(jnp.float32)
                                 - ref.astype(jnp.float32)))) < 3e-2


def test_flash_training_matches_einsum_sharded():
    cpus = jax.devices("cpu")
    if len(cpus) < 8:
        pytest.skip("need 8 virtual CPU devices")
    from tpu_device_plugin.validator.mesh import slice_mesh
    from tpu_device_plugin.validator.workload import ModelConfig, build_workload
    cfg = ModelConfig(vocab=64, d_model=64, n_heads=4, d_ff=128, n_layers=1,
                      seq_len=64, batch=4)
    mesh = slice_mesh(cpus, tp=2, sp=1)
    step_f, p, m, t = build_workload(cfg, mesh, seed=3, attention="flash")
    _, _, loss_flash = step_f(p, m, t)
    step_e, p, m, t = build_workload(cfg, mesh, seed=3, attention="einsum")
    _, _, loss_einsum = step_e(p, m, t)
    assert abs(float(loss_flash) - float(loss_einsum)) < 2e-2


def test_flash_requires_full_sequence():
    cpus = jax.devices("cpu")
    if len(cpus) < 8:
        pytest.skip("need 8 virtual CPU devices")
    from tpu_device_plugin.validator.mesh import slice_mesh
    from tpu_device_plugin.validator.workload import ModelConfig, build_workload
    mesh = slice_mesh(cpus, tp=2, sp=2)
    with pytest.raises(ValueError, match="sp == 1"):
        build_workload(ModelConfig(), mesh, attention="flash")


def test_ring_training_matches_einsum_sharded():
    """Ring attention (sp=2) must train identically to the KV-all-gather."""
    cpus = jax.devices("cpu")
    if len(cpus) < 8:
        pytest.skip("need 8 virtual CPU devices")
    from tpu_device_plugin.validator.mesh import slice_mesh
    from tpu_device_plugin.validator.workload import ModelConfig, build_workload
    cfg = ModelConfig(vocab=64, d_model=64, n_heads=4, d_ff=128, n_layers=1,
                      seq_len=64, batch=4)
    mesh = slice_mesh(cpus, tp=2, sp=2)
    step_r, p, m, t = build_workload(cfg, mesh, seed=3, attention="ring")
    _, _, loss_ring = step_r(p, m, t)
    step_e, p, m, t = build_workload(cfg, mesh, seed=3, attention="einsum")
    _, _, loss_einsum = step_e(p, m, t)
    assert abs(float(loss_ring) - float(loss_einsum)) < 2e-2


def test_ring_is_default_for_sp_meshes():
    cpus = jax.devices("cpu")
    if len(cpus) < 8:
        pytest.skip("need 8 virtual CPU devices")
    from tpu_device_plugin.validator.mesh import slice_mesh
    from tpu_device_plugin.validator.workload import ModelConfig, build_workload
    cfg = ModelConfig(vocab=64, d_model=32, n_heads=4, d_ff=64, n_layers=1,
                      seq_len=32, batch=4)
    mesh = slice_mesh(cpus, tp=1, sp=4)  # dp=2, sp=4
    step, p, m, t = build_workload(cfg, mesh, seed=1)  # attention=None -> ring
    p, m, loss0 = step(p, m, t)
    for _ in range(3):
        p, m, loss = step(p, m, t)
    assert float(loss) < float(loss0)


def test_unknown_attention_mode_rejected():
    from tpu_device_plugin.validator.mesh import slice_mesh
    from tpu_device_plugin.validator.workload import ModelConfig, build_workload
    with pytest.raises(ValueError, match="unknown attention"):
        build_workload(ModelConfig(), slice_mesh(jax.devices("cpu")[:1]),
                       attention="quantum")


def test_ring_custom_vjp_grads_match_reference():
    """Ring backward (re-rotating KV, rematerialized tiles) must produce the
    same gradients as differentiating global causal attention."""
    import numpy as np
    from jax.sharding import Mesh, PartitionSpec as P
    from tpu_device_plugin.validator.ring_attention import ring_attention
    cpus = jax.devices("cpu")
    if len(cpus) < 4:
        pytest.skip("need 4 virtual CPU devices")
    mesh = Mesh(np.array(cpus[:4]).reshape(4), ("sp",))
    bh, seq, d = 2, 64, 16
    q, k, v = (rand((bh, seq, d), i) for i in (1, 2, 3))

    def ring_global(q, k, v):
        f = jax.shard_map(
            lambda a, b, c: ring_attention(a, b, c, d ** -0.5, "sp"),
            mesh=mesh, in_specs=(P(None, "sp", None),) * 3,
            out_specs=P(None, "sp", None), check_vma=False)
        return f(q, k, v)

    from tpu_device_plugin.validator.flash_attention import _reference_attention
    out = ring_global(q, k, v)
    ref = _reference_attention(q, k, v, d ** -0.5, True)
    assert float(jnp.max(jnp.abs(out - ref))) < 1e-5

    g_ring = jax.grad(lambda q, k, v: jnp.sum(ring_global(q, k, v) ** 2),
                      argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(
        lambda q, k, v: jnp.sum(_reference_attention(q, k, v, d ** -0.5, True) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ring, g_ref):
        assert float(jnp.max(jnp.abs(a - b))) < 1e-4


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("seq,block", [(96, 64), (128, 32), (64, 128)])
def test_pallas_backward_matches_reference(causal, seq, block):
    """The Pallas backward (dq/dk/dv kernels, O(S) memory) must reproduce
    reference gradients incl. the padded-tail case (seq % block != 0)."""
    hb, d = 2, 32
    q, k, v = rand((hb, seq, d), 4), rand((hb, seq, d), 5), rand((hb, seq, d), 6)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, None, causal, block, block, True) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(_reference_attention(q, k, v, d ** -0.5, causal) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        assert float(jnp.max(jnp.abs(a - b))) < 1e-4


@pytest.mark.parametrize("bwd_block", [(64, 32), (32, 64), (None, None)])
def test_pallas_backward_decoupled_blocks(bwd_block):
    """Backward blocks decoupled from the forward's (incl. the None default,
    which resolves to DEFAULT_BWD_BLOCK and must clamp to short seqs) still
    reproduce reference gradients."""
    bwq, bwk = bwd_block
    hb, seq, d = 2, 96, 32
    q, k, v = rand((hb, seq, d), 7), rand((hb, seq, d), 8), rand((hb, seq, d), 9)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, None, True, 32, 32, True,
                                       bwq, bwk) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(_reference_attention(q, k, v, d ** -0.5, True) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        assert float(jnp.max(jnp.abs(a - b))) < 1e-4


def test_pallas_backward_bfloat16():
    hb, seq, d = 2, 64, 32
    q = rand((hb, seq, d), 1).astype(jnp.bfloat16)
    k = rand((hb, seq, d), 2).astype(jnp.bfloat16)
    v = rand((hb, seq, d), 3).astype(jnp.bfloat16)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, None, True, 32, 32, True)
                       .astype(jnp.float32) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(_reference_attention(q, k, v, d ** -0.5, True)
                       .astype(jnp.float32) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        assert a.dtype == b.dtype
        assert float(jnp.max(jnp.abs(a.astype(jnp.float32)
                                     - b.astype(jnp.float32)))) < 1e-1


def test_ring_flash_matches_reference():
    """ring_flash (Pallas kernel per ring step, block-level lse merge) must
    reproduce global causal attention forward AND gradients."""
    import numpy as np
    from jax.sharding import Mesh, PartitionSpec as P
    from tpu_device_plugin.validator.ring_attention import ring_flash_attention
    cpus = jax.devices("cpu")
    if len(cpus) < 4:
        pytest.skip("need 4 virtual CPU devices")
    mesh = Mesh(np.array(cpus[:4]).reshape(4), ("sp",))
    bh, seq, d = 2, 128, 16   # s_local = 32, exercises block clamping

    q, k, v = (rand((bh, seq, d), i) for i in (1, 2, 3))

    def ring_global(q, k, v):
        f = jax.shard_map(
            lambda a, b, c: ring_flash_attention(
                a, b, c, d ** -0.5, "sp", 32, 32, True, 32, 32),
            mesh=mesh, in_specs=(P(None, "sp", None),) * 3,
            out_specs=P(None, "sp", None), check_vma=False)
        return f(q, k, v)

    out = ring_global(q, k, v)
    ref = _reference_attention(q, k, v, d ** -0.5, True)
    assert float(jnp.max(jnp.abs(out - ref))) < 1e-4

    g_ring = jax.grad(lambda q, k, v: jnp.sum(ring_global(q, k, v) ** 2),
                      argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(
        lambda q, k, v: jnp.sum(
            _reference_attention(q, k, v, d ** -0.5, True) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ring, g_ref):
        assert float(jnp.max(jnp.abs(a - b))) < 1e-3


def test_ring_flash_matches_einsum_ring():
    """The two ring inner implementations agree step for step (same merge
    semantics, logsumexp included)."""
    import numpy as np
    from jax.sharding import Mesh, PartitionSpec as P
    from tpu_device_plugin.validator.ring_attention import (
        ring_attention, ring_flash_attention)
    cpus = jax.devices("cpu")
    if len(cpus) < 2:
        pytest.skip("need 2 virtual CPU devices")
    mesh = Mesh(np.array(cpus[:2]).reshape(2), ("sp",))
    bh, seq, d = 2, 96, 16    # s_local = 48: padded tail inside the kernel

    q, k, v = (rand((bh, seq, d), i) for i in (7, 8, 9))

    def run(fn):
        f = jax.shard_map(
            fn, mesh=mesh, in_specs=(P(None, "sp", None),) * 3,
            out_specs=P(None, "sp", None), check_vma=False)
        return f(q, k, v)

    out_e = run(lambda a, b, c: ring_attention(a, b, c, d ** -0.5, "sp"))
    out_f = run(lambda a, b, c: ring_flash_attention(
        a, b, c, d ** -0.5, "sp", 32, 32, True, 32, 32))
    assert float(jnp.max(jnp.abs(out_e - out_f))) < 1e-5
