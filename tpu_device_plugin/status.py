"""Optional HTTP status endpoint for the DaemonSet.

The reference exposes no health surface (SURVEY §5: "no Prometheus, no
/healthz"); a kubelet can only observe the process. This adds a minimal,
dependency-free endpoint for liveness probes and debugging:

  GET /healthz  -> 200 "ok" while the manager has plugins serving
                   (503 otherwise)
  GET /status   -> JSON: per-plugin resource name, socket, restart count,
                   device health table, pending (not-yet-registered) plugins

Disabled by default (--status-port 0).
"""

from __future__ import annotations

import json
import logging
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

log = logging.getLogger(__name__)


class StatusServer:
    def __init__(self, manager, port: int = 0, host: str = "127.0.0.1"):
        self.manager = manager
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # route to our logger
                log.debug("status: " + fmt, *args)

            def _send(self, code: int, body: bytes,
                      ctype: str = "application/json") -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/healthz":
                    if outer.healthy():
                        self._send(200, b"ok", "text/plain")
                    else:
                        self._send(503, b"no plugins serving", "text/plain")
                elif self.path == "/status":
                    self._send(200, json.dumps(outer.status(),
                                               sort_keys=True).encode())
                else:
                    self._send(404, b"not found", "text/plain")

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True, name="status-http")

    def start(self) -> None:
        self._thread.start()
        log.info("status endpoint on http://127.0.0.1:%d", self.port)

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()

    def healthy(self) -> bool:
        plugins = self.manager.plugins
        return bool(plugins) and any(p.serving for p in plugins)

    def status(self) -> dict:
        return {
            "plugins": [p.status_snapshot() for p in self.manager.plugins],
            "pending": [p.resource_name for p in self.manager.pending],
        }
