#!/usr/bin/env python3
"""Concurrency lint entrypoint: run tsalint over tpu_device_plugin/.

Usage:
    python scripts/lint_concurrency.py                 # gate: new findings fail
    python scripts/lint_concurrency.py --list          # print ALL findings
    python scripts/lint_concurrency.py --update-baseline

Exit codes: 0 clean (no findings outside the baseline), 1 new findings,
2 usage/configuration error. Stale baseline entries (debt that no longer
fires) are reported but never fail the run — delete them via
--update-baseline when convenient.

See docs/static-analysis.md for the rule set and the baseline workflow;
the runtime counterpart is tpu_device_plugin/lockdep.py ($TDP_LOCKDEP=1).
"""

from __future__ import annotations

import argparse
import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

from tools.tsalint import (analyze_sources, diff_against_baseline,  # noqa: E402
                           load_baseline, project_config, save_baseline)

PACKAGE = "tpu_device_plugin"
DEFAULT_BASELINE = os.path.join("tools", "tsalint", "baseline.json")


def _package_files(root: str) -> list:
    paths = []
    pkg = os.path.join(root, PACKAGE)
    for dirpath, dirnames, filenames in os.walk(pkg):
        dirnames[:] = [d for d in dirnames
                       if d not in ("__pycache__", "kubeletapi", "data")]
        for fn in sorted(filenames):
            if fn.endswith(".py") and not fn.endswith("_pb2.py"):
                paths.append(os.path.join(dirpath, fn))
    return sorted(paths)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--root", default=REPO_ROOT,
                        help="repo root (default: this checkout)")
    parser.add_argument("--baseline", default=None,
                        help=f"baseline path (default: {DEFAULT_BASELINE})")
    parser.add_argument("--update-baseline", action="store_true",
                        help="rewrite the baseline to the current findings")
    parser.add_argument("--list", action="store_true",
                        help="print every finding, baselined or not")
    args = parser.parse_args(argv)

    root = os.path.abspath(args.root)
    baseline_path = args.baseline or os.path.join(root, DEFAULT_BASELINE)
    faults_py = os.path.join(root, PACKAGE, "faults.py")
    doc_md = os.path.join(root, "docs", "fault-injection.md")
    obs_md = os.path.join(root, "docs", "observability.md")
    try:
        with open(faults_py, "r", encoding="utf-8") as f:
            faults_src = f.read()
        with open(doc_md, "r", encoding="utf-8") as f:
            doc_text = f.read()
        with open(obs_md, "r", encoding="utf-8") as f:
            obs_text = f.read()
    except OSError as exc:
        print(f"tsalint: cannot read rule inputs: {exc}", file=sys.stderr)
        return 2

    config = project_config(faults_src, doc_text, obs_text)
    paths = _package_files(root)
    rel = [os.path.relpath(p, root).replace(os.sep, "/") for p in paths]
    sources = []
    for abs_path, rel_path in zip(paths, rel):
        with open(abs_path, "r", encoding="utf-8") as f:
            sources.append((rel_path, f.read()))

    findings = analyze_sources(sources, config)

    if args.update_baseline:
        save_baseline(baseline_path, findings)
        print(f"tsalint: baseline updated with {len(findings)} finding(s) "
              f"-> {baseline_path}")
        return 0

    try:
        baseline = load_baseline(baseline_path)
    except ValueError as exc:
        print(f"tsalint: {exc}", file=sys.stderr)
        return 2
    new, stale = diff_against_baseline(findings, baseline)

    if args.list:
        for f in findings:
            mark = " (baselined)" if f.key in baseline else ""
            print(f.render() + mark)

    print(f"tsalint: {len(paths)} files, {len(findings)} finding(s) "
          f"({len(findings) - len(new)} baselined, {len(new)} new, "
          f"{len(stale)} stale baseline entr{'y' if len(stale) == 1 else 'ies'})")
    for key in stale:
        print(f"tsalint: resolved (delete from baseline): {key}")
    if new:
        print("tsalint: NEW findings (fix them or, for accepted debt, "
              "run --update-baseline):", file=sys.stderr)
        for f in new:
            print("  " + f.render(), file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
