#!/bin/sh
# Serialized real-TPU validator attempts (round 5).
#
# Protocol (docs/roadmap.md item 1, learned in rounds 1-4): exactly ONE TPU
# process at a time, NEVER killed — SIGKILLing a mid-claim process wedges
# the exclusive-claim PJRT relay, after which attempts fail naturally
# (~40 min in backend init) until the relay recovers. Stop the loop
# gracefully between attempts:
#     touch /root/repo/.stop_tpu_attempts
#
# Round-5 change (VERDICT r4 item 1): launched in the round's first minutes
# so a mid-round relay recovery is caught. On the first train success the
# packed protocol runs inside the same window: infer, ring-bench
# (ring-flash vs einsum ring, VERDICT r4 item 5), attn-bench under the
# hardened estimator, then the sized-up --preset mfu capture (VERDICT r4
# item 3; unbounded time — the relay compiles big models slowly).
set -u
cd /root/repo
LOG=docs/tpu_attempts_r05.log
if [ -f .stop_tpu_attempts ]; then
    echo "=== sentinel .stop_tpu_attempts present at launch; not starting" \
         "(rm it and relaunch to run) $(date -u +%FT%TZ) ===" >>"$LOG"
fi
N=0
while [ ! -f .stop_tpu_attempts ]; do
    N=$((N + 1))
    echo "=== attempt $N start $(date -u +%FT%TZ) ===" >>"$LOG"
    python -m tpu_device_plugin.validator --steps 20 \
        >docs/validator_tpu_train_r05.json 2>>"$LOG"
    rc=$?
    tail -c 400 docs/validator_tpu_train_r05.json >>"$LOG"
    echo "" >>"$LOG"
    echo "=== attempt $N end rc=$rc $(date -u +%FT%TZ) ===" >>"$LOG"
    if [ "$rc" -eq 0 ]; then
        echo "SUCCESS: running packed round-5 protocol" >>"$LOG"
        python -m tpu_device_plugin.validator --mode infer --steps 30 \
            >docs/validator_tpu_infer_r05.json 2>>"$LOG"
        echo "infer rc=$? $(date -u +%FT%TZ)" >>"$LOG"
        python -m tpu_device_plugin.validator --mode ring-bench \
            --seqs 4096,8192 --blocks 128x128,256x256 --repeats 4 \
            --steps 5 \
            >docs/validator_tpu_ring_r05.json 2>>"$LOG"
        echo "ring-bench rc=$? $(date -u +%FT%TZ)" >>"$LOG"
        python -m tpu_device_plugin.validator --mode attn-bench \
            --seqs 2048,4096 --blocks 128x128 --repeats 4 --steps 5 \
            >docs/validator_tpu_attn_r05.json 2>>"$LOG"
        echo "attn-bench rc=$? $(date -u +%FT%TZ)" >>"$LOG"
        # mfu-lite FIRST: the relay compiles big models very slowly and a
        # hung compile cannot be killed without wedging the claim — the
        # lite run banks a valid sustained-MFU number before the
        # unbounded full-size attempt
        python -m tpu_device_plugin.validator --preset mfu-lite --steps 3 \
            >docs/validator_tpu_mfulite_r05.json 2>>"$LOG"
        echo "mfu-lite rc=$? $(date -u +%FT%TZ)" >>"$LOG"
        echo "mfu preset start $(date -u +%FT%TZ) (may take a while)" >>"$LOG"
        python -m tpu_device_plugin.validator --preset mfu --steps 3 \
            >docs/validator_tpu_mfu_r05.json 2>>"$LOG"
        echo "mfu rc=$? $(date -u +%FT%TZ)" >>"$LOG"
        echo "=== loop exit $(date -u +%FT%TZ) ===" >>"$LOG"
        exit 0
    fi
    sleep 30
done
echo "=== stopped by sentinel $(date -u +%FT%TZ) ===" >>"$LOG"
