"""Unit tests for the shared backoff/circuit-breaker policy (resilience.py).

These pin the *distributional* contract (decorrelated jitter: every delay
in [base, cap], growth bounded by 3x the previous) with a seeded RNG and
the breaker's full state machine with a fake clock — no sleeping.
"""

import random

import pytest

from conftest import FakeClock
from tpu_device_plugin.resilience import (BackoffPolicy, CircuitBreaker,
                                          CircuitOpen)


# ------------------------------------------------------------- BackoffPolicy


def test_backoff_delays_within_bounds_and_deterministic():
    rng = random.Random(42)
    p = BackoffPolicy(base_s=1.0, cap_s=30.0, rng=rng)
    delays = [p.next_delay() for _ in range(50)]
    assert all(1.0 <= d <= 30.0 for d in delays)
    # decorrelated jitter: each delay is at most 3x its predecessor
    prev = 1.0
    for d in delays:
        assert d <= max(prev * 3.0, 1.0) + 1e-9
        prev = d
    # seeded: the schedule replays exactly
    p2 = BackoffPolicy(base_s=1.0, cap_s=30.0, rng=random.Random(42))
    assert [p2.next_delay() for _ in range(50)] == delays


def test_backoff_grows_under_sustained_failure():
    p = BackoffPolicy(base_s=1.0, cap_s=30.0, rng=random.Random(7))
    delays = [p.next_delay() for _ in range(30)]
    # by the tail of a long failure run, delays should be near the cap far
    # more often than near the base (the whole point of growth)
    assert max(delays[10:]) > 10.0


def test_backoff_reset_returns_to_base():
    p = BackoffPolicy(base_s=1.0, cap_s=30.0, rng=random.Random(7))
    for _ in range(10):
        p.next_delay()
    assert p.attempts == 10
    p.reset()
    assert p.attempts == 0
    assert p.total_attempts == 10          # lifetime counter survives
    assert p.next_delay() <= 3.0           # back to U(base, 3*base)


def test_backoff_rejects_bad_params():
    with pytest.raises(ValueError):
        BackoffPolicy(base_s=0)
    with pytest.raises(ValueError):
        BackoffPolicy(base_s=5.0, cap_s=1.0)


def test_backoff_snapshot_counts():
    p = BackoffPolicy(base_s=0.1, cap_s=1.0, rng=random.Random(1))
    p.next_delay()
    snap = p.snapshot()
    assert snap["attempts"] == 1
    assert snap["total_attempts"] == 1
    assert 0.1 <= snap["current_delay_s"] <= 1.0


# ------------------------------------------------------------ CircuitBreaker


def test_breaker_trips_after_threshold_and_half_opens():
    clock = FakeClock()
    b = CircuitBreaker(failure_threshold=3, reset_timeout_s=10.0, clock=clock)
    assert b.state == "closed"
    for _ in range(2):
        assert b.allow()
        b.record_failure()
    assert b.state == "closed"             # threshold not reached
    assert b.allow()
    b.record_failure()                     # third consecutive failure
    assert b.state == "open"
    assert b.trips == 1
    assert not b.allow()                   # fails fast while open
    clock.advance(10.0)
    assert b.allow()                       # cooldown elapsed: the ONE probe
    assert b.state == "half-open"
    assert not b.allow()                   # second caller is still rejected
    b.record_success()                     # probe succeeded
    assert b.state == "closed"
    assert b.allow()


def test_breaker_failed_probe_reopens_with_fresh_cooldown():
    clock = FakeClock()
    b = CircuitBreaker(failure_threshold=1, reset_timeout_s=5.0, clock=clock)
    b.record_failure()
    assert b.state == "open"
    clock.advance(5.0)
    assert b.allow()                       # half-open probe
    b.record_failure()                     # probe failed
    assert b.state == "open"
    assert b.trips == 2
    clock.advance(4.9)
    assert not b.allow()                   # cooldown restarted at the probe
    clock.advance(0.2)
    assert b.allow()


def test_breaker_success_resets_consecutive_count():
    b = CircuitBreaker(failure_threshold=3)
    b.record_failure()
    b.record_failure()
    b.record_success()                     # streak broken
    b.record_failure()
    b.record_failure()
    assert b.state == "closed"             # never 3 consecutive


def test_breaker_call_wrapper():
    clock = FakeClock()
    b = CircuitBreaker(failure_threshold=1, reset_timeout_s=5.0, clock=clock)

    def boom():
        raise RuntimeError("no")

    with pytest.raises(RuntimeError):
        b.call(boom)
    with pytest.raises(CircuitOpen):
        b.call(lambda: "never runs")
    assert b.rejected == 1
    clock.advance(5.0)
    assert b.call(lambda: "ok") == "ok"    # half-open probe succeeds
    assert b.state == "closed"


def test_breaker_snapshot_shape():
    b = CircuitBreaker(failure_threshold=2, name="t")
    b.record_failure()
    snap = b.snapshot()
    assert snap == {"state": "closed", "consecutive_failures": 1,
                    "trips": 0, "rejected": 0, "half_open_rejected": 0}


def test_breaker_half_open_admits_exactly_one_concurrent_probe():
    """Regression (ISSUE 16 satellite): N threads racing the half-open
    transition must yield exactly ONE executed probe — the losers fail
    fast as open and are counted — and a STALE result from a caller
    admitted before the trip must not resolve the probe window."""
    import threading

    clock = FakeClock()
    b = CircuitBreaker(failure_threshold=1, reset_timeout_s=5.0, clock=clock)
    b.record_failure()
    assert b.state == "open"
    clock.advance(5.0)                     # cooldown elapsed: probe window

    n = 8
    admitted = []
    barrier = threading.Barrier(n)

    def racer(i):
        barrier.wait()
        if b.allow():
            admitted.append(i)

    threads = [threading.Thread(target=racer, args=(i,)) for i in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=5)
    assert len(admitted) == 1, admitted    # exactly one probe executed
    assert b.state == "half-open"
    assert b.half_open_rejected == n - 1   # losers counted, typed
    assert b.rejected >= n - 1

    # a stale success from THIS thread (not the probe owner) must not
    # close the circuit under the probe's feet
    b.record_success()
    assert b.state == "half-open"
    # nor may a stale failure re-trip it and restart the cooldown
    trips_before = b.trips
    b.record_failure()
    assert b.state == "half-open"
    assert b.trips == trips_before


def test_breaker_probe_owner_resolves_window_cross_thread():
    """The probe handed to thread T is resolved only by T: T's success
    closes the circuit even while stale results from other threads are
    being discarded."""
    import threading

    clock = FakeClock()
    b = CircuitBreaker(failure_threshold=1, reset_timeout_s=5.0, clock=clock)
    b.record_failure()
    clock.advance(5.0)

    outcome = {}

    def probe():
        outcome["admitted"] = b.allow()
        # a stale success from the main thread lands mid-probe …
        ready.set()
        stale_done.wait(timeout=5)
        # … then the probe's own success closes the circuit
        b.record_success()

    ready = threading.Event()
    stale_done = threading.Event()
    t = threading.Thread(target=probe)
    t.start()
    ready.wait(timeout=5)
    assert b.state == "half-open"
    b.record_success()                     # stale: discarded
    assert b.state == "half-open"
    stale_done.set()
    t.join(timeout=5)
    assert outcome["admitted"]
    assert b.state == "closed"
