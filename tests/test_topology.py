"""ICI topology: coordinate assignment and 3-tier preferred allocation."""

import pytest

from tpu_device_plugin.naming import GenerationInfo
from tpu_device_plugin.topology import (
    AllocatableDevice,
    MustIncludeTooLarge,
    assign_coords,
    preferred_allocation,
)

V5E = GenerationInfo("v5e", 8, (2, 4))
V4 = GenerationInfo("v4", 4, (2, 2, 1))


def bdfs(n, start=4):
    return [f"0000:00:{i:02x}.0" for i in range(start, start + n)]


def test_assign_coords_lexicographic():
    ids = bdfs(4)
    coords = assign_coords(ids, V4)
    assert coords[ids[0]] == (0, 0, 0)
    assert coords[ids[1]] == (0, 1, 0)
    assert coords[ids[2]] == (1, 0, 0)
    assert coords[ids[3]] == (1, 1, 0)


def test_assign_coords_hints_win():
    ids = bdfs(2)
    coords = assign_coords(ids, V4, hints={ids[1]: (1, 1, 0)})
    assert coords[ids[1]] == (1, 1, 0)
    assert coords[ids[0]] == (0, 0, 0)  # first free slot


def test_assign_coords_overflow_gets_none():
    ids = bdfs(5)
    coords = assign_coords(ids, V4)
    assert sum(1 for c in coords.values() if c is None) == 1


def _v5e_devices():
    ids = bdfs(8)
    coords = assign_coords(ids, V5E)
    return ids, [AllocatableDevice(i, numa_node=0 if coords[i][0] == 0 else 1,
                                   coords=coords[i]) for i in ids]


def test_ici_contiguous_pair_preferred():
    ids, devs = _v5e_devices()
    # ask for 2 with a scattered availability order: a contiguous pair must win
    order = [ids[0], ids[7], ids[1], ids[6]]
    picked = preferred_allocation(devs, order, [], 2, torus_dims=(2, 4))
    by_id = {d.device_id: d for d in devs}
    c0, c1 = by_id[picked[0]].coords, by_id[picked[1]].coords
    # manhattan-adjacent on the torus
    dist = sum(min(abs(a - b), dim - abs(a - b))
               for a, b, dim in zip(c0, c1, (2, 4)))
    assert dist == 1


def test_ici_full_host_slice():
    ids, devs = _v5e_devices()
    picked = preferred_allocation(devs, ids, [], 8, torus_dims=(2, 4))
    assert sorted(picked) == sorted(ids)


def test_must_include_kept_and_box_built_around_it():
    ids, devs = _v5e_devices()
    picked = preferred_allocation(devs, ids, [ids[5]], 4, torus_dims=(2, 4))
    assert ids[5] in picked
    assert len(picked) == 4


def test_must_include_too_large():
    ids, devs = _v5e_devices()
    with pytest.raises(MustIncludeTooLarge):
        preferred_allocation(devs, ids, ids[:3], 2, torus_dims=(2, 4))


def test_numa_tier_without_coords():
    # no torus dims -> reference-style NUMA preference
    devs = [AllocatableDevice(f"d{i}", numa_node=i % 2) for i in range(6)]
    order = [f"d{i}" for i in range(6)]  # alternating numa 0/1
    picked = preferred_allocation(devs, order, [], 3)
    assert {d for d in picked} == {"d0", "d2", "d4"}  # single NUMA node 0


def test_kubelet_order_fallback():
    # sizes too big for any single numa node -> kubelet order preserved
    devs = [AllocatableDevice(f"d{i}", numa_node=i % 2) for i in range(4)]
    order = ["d3", "d1", "d0", "d2"]
    picked = preferred_allocation(devs, order, [], 4)
    assert picked == order


def test_numa_respects_must_include_node():
    devs = [AllocatableDevice(f"d{i}", numa_node=0 if i < 3 else 1) for i in range(6)]
    order = [f"d{i}" for i in range(6)]
    picked = preferred_allocation(devs, order, ["d4"], 3)
    assert "d4" in picked
    assert all(d in {"d3", "d4", "d5"} for d in picked)


def test_no_false_wraparound_adjacency():
    # free chips at (0,0) and (0,3) are NOT adjacent on a partial axis of a
    # larger pod torus; a truly adjacent pair must win
    devs = [
        AllocatableDevice("a", 0, (0, 0)),
        AllocatableDevice("b", 0, (0, 3)),
        AllocatableDevice("c", 0, (1, 1)),
        AllocatableDevice("d", 0, (1, 2)),
    ]
    picked = preferred_allocation(devs, ["a", "b", "c", "d"], [], 2,
                                  torus_dims=(2, 4))
    assert sorted(picked) == ["c", "d"]


def test_malformed_hints_ignored():
    ids = bdfs(2)
    coords = assign_coords(ids, V5E, hints={ids[0]: (1,), ids[1]: (9, 9)})
    # both hints invalid (arity / range) -> chips fall back to free slots
    assert coords[ids[0]] == (0, 0)
    assert coords[ids[1]] == (0, 1)


def test_short_arity_coords_never_match_boxes():
    devs = [
        AllocatableDevice("short", 0, (1,)),
        AllocatableDevice("c", 0, (1, 1)),
        AllocatableDevice("d", 0, (1, 2)),
    ]
    picked = preferred_allocation(devs, ["short", "c", "d"], [], 2,
                                  torus_dims=(2, 4))
    assert sorted(picked) == ["c", "d"]


def test_load_topology_hints_bad_json(tmp_path):
    from tpu_device_plugin.topology import load_topology_hints
    p = tmp_path / "h.json"
    p.write_text("[1,2,3]")
    assert load_topology_hints(str(p)) == {}
    p.write_text("{\"bdf\": [0, 1]}")
    assert load_topology_hints(str(p)) == {"bdf": (0, 1)}
    assert load_topology_hints(None) == {}
