# Example operator policy module (docs/policy.md).
#
# Load with:  tpu-device-plugin --policy-dir examples/
#
# Runs under the sandboxed evaluator (tpu_device_plugin/policy.py): no
# imports, no filesystem — pure functions over the decision ctx.


def score_allocation(ctx):
    """Keep the ICI placement engine's answer when it found a single
    contiguous sub-box; otherwise prefer the highest-numbered chips
    (e.g. the freshest silicon bank on this fleet's boards)."""
    if ctx["builtin_score"] >= 1.0:
        return None
    ranked = sorted(ctx["available"], reverse=True)
    must = list(ctx["must_include"])
    take = [d for d in ranked if d not in must]
    return (must + take)[:ctx["size"]]


def admit(ctx):
    """Freeze DRA prepares for a namespace under maintenance; admit
    everything else (None = builtin behavior)."""
    if ctx["op"] == "prepare" and ctx.get("namespace") == "frozen":
        return "namespace frozen for maintenance"
    return None
