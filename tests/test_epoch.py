"""Epoch read-plane tests (tpu_device_plugin/epoch.py) + the lockdep
read-path gate.

The gate is the PR's headline correctness claim: in steady state the four
hot read paths — Allocate, GetPreferredAllocation, ListAndWatch payload
assembly, /status — plus DRA prepare planning acquire ZERO registered
locks. It runs under lockdep.scoped(), so it enforces in every tier-1
run (not only the TDP_LOCKDEP=1 CI job): objects built inside the scope
get recording lock proxies, and lockdep.read_path charges every
acquisition to the bracket it happened in.
"""

import dataclasses
import os
import threading
import time

import pytest

from tests.fakehost import FakeChip, FakeHost
from tpu_device_plugin import epoch as epoch_mod
from tpu_device_plugin import lockdep
from tpu_device_plugin.config import Config
from tpu_device_plugin.discovery import discover_passthrough
from tpu_device_plugin.epoch import (AtomicCounter, Epoch, EpochStore,
                                     build_server_epoch)
from tpu_device_plugin.kubeletapi import pb
from tpu_device_plugin.server import TpuDevicePlugin


# ------------------------------------------------------------ primitives


def test_atomic_counter_add_and_value():
    c = AtomicCounter()
    assert c.value == 0
    c.add()
    c.add()
    assert c.value == 2
    c2 = AtomicCounter(start=10)
    c2.add()
    assert c2.value == 11


def test_atomic_counter_concurrent_adds_are_exact_and_monotonic():
    c = AtomicCounter()
    n_threads, per_thread = 8, 2000
    observed = []
    stop = threading.Event()

    def worker():
        for _ in range(per_thread):
            c.add()

    def observer():
        # a concurrent /metrics scraper: successive reads must never go
        # backwards (Prometheus counters treat a decrease as a restart)
        while not stop.is_set():
            observed.append(c.value)

    threads = [threading.Thread(target=worker) for _ in range(n_threads)]
    obs = threading.Thread(target=observer)
    obs.start()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    stop.set()
    obs.join()
    # EXACT: no add is ever lost
    assert c.value == n_threads * per_thread
    c.add()
    assert c.value == n_threads * per_thread + 1
    # MONOTONIC: the observer never saw the counter move backwards
    assert all(a <= b for a, b in zip(observed, observed[1:]))


def test_epoch_is_frozen_and_mapping_readonly():
    ep = build_server_epoch(3, (("a", 0), ("b", 1)), {"b": {"fs": False}})
    with pytest.raises(dataclasses.FrozenInstanceError):
        ep.epoch_id = 4
    with pytest.raises(TypeError):
        ep.device_health["a"] = "Unhealthy"
    assert ep.device_health == {"a": "Healthy", "b": "Unhealthy"}
    # the payload parses back to exactly the table the builder rendered
    resp = pb.ListAndWatchResponse.FromString(ep.lw_payload)
    assert {d.ID: d.health for d in resp.devices} == dict(ep.device_health)


def test_epoch_builder_health_is_anded_across_sources():
    sources = {"a": {"fs": True, "probe": False}}
    ep = build_server_epoch(1, (("a", 0),), sources)
    assert ep.device_health["a"] == "Unhealthy"
    sources["a"]["probe"] = True
    ep2 = build_server_epoch(2, (("a", 0),), sources)
    assert ep2.device_health["a"] == "Healthy"
    # the earlier epoch is untouched by the writer's continued mutation
    assert ep.device_health["a"] == "Unhealthy"


def test_store_publish_swaps_atomically_and_counts():
    store = EpochStore()
    assert store.current.epoch_id == 0
    ep1 = Epoch(1)
    assert store.publish(ep1) is ep1
    assert store.current is ep1
    assert store.publishes.value == 1


def test_store_wait_for_observes_publish():
    store = EpochStore()
    seen = []

    def waiter():
        store.wait_for(lambda: store.current.epoch_id >= 2, timeout=5)
        seen.append(store.current.epoch_id)

    t = threading.Thread(target=waiter)
    t.start()
    time.sleep(0.05)
    store.publish(Epoch(2))
    t.join(timeout=5)
    assert seen == [2]


def test_store_poke_wakes_without_publishing():
    store = EpochStore()
    woke = threading.Event()

    def waiter():
        store.wait_for(lambda: woke.is_set(), timeout=5)
        woke.set()

    t = threading.Thread(target=waiter)
    t.start()
    time.sleep(0.05)
    woke.set()
    store.poke()
    t.join(timeout=5)
    assert not t.is_alive()
    assert store.publishes.value == 0


# ----------------------------------------------------- server integration


def _plugin(root, n=4):
    host = FakeHost(root)
    for i in range(n):
        host.add_chip(FakeChip(f"0000:00:{4 + i:02x}.0",
                               iommu_group=str(11 + i), numa_node=i // 2))
    cfg = Config().with_root(host.root)
    os.makedirs(cfg.device_plugin_path, exist_ok=True)
    registry, generations = discover_passthrough(cfg)
    plugin = TpuDevicePlugin(cfg, "v4", registry,
                             registry.devices_by_model["0062"],
                             torus_dims=generations["0062"].host_topology)
    return host, cfg, plugin


def test_effective_flip_publishes_new_epoch(short_root):
    _, _, plugin = _plugin(short_root)
    ep0 = plugin._store.current
    plugin.set_devices_health(["0000:00:04.0"], False, source="t")
    ep1 = plugin._store.current
    assert ep1.epoch_id == ep0.epoch_id + 1
    assert ep1.device_health["0000:00:04.0"] == "Unhealthy"
    # the OLD epoch still reads its old state (readers mid-flight are safe)
    assert ep0.device_health["0000:00:04.0"] == "Healthy"
    # the pre-serialized payload matches the table
    resp = pb.ListAndWatchResponse.FromString(ep1.lw_payload)
    assert {d.ID: d.health for d in resp.devices} == dict(ep1.device_health)


def test_repeat_verdict_publishes_nothing(short_root):
    """Probe polls re-deliver every id each cycle; a delivery that flips
    no EFFECTIVE verdict must not publish (readers pay zero)."""
    _, _, plugin = _plugin(short_root)
    plugin.set_devices_health(["0000:00:04.0"], False, source="t")
    publishes = plugin._store.publishes.value
    for _ in range(5):
        plugin.set_devices_health(["0000:00:04.0"], False, source="t")
        plugin.set_devices_health(["0000:00:05.0"], True, source="t")
    assert plugin._store.publishes.value == publishes


def test_fragment_cache_is_invalidated_by_epoch_key(short_root):
    """A health flap publishes a new epoch, and THAT (not any listener)
    makes the next plan recompile its fragments: the renamed-cdev case
    that PR 4 needed invalidation plumbing for now heals by key."""
    import shutil

    host = FakeHost(short_root)
    for i in range(2):
        host.add_chip(FakeChip(f"0000:00:{4 + i:02x}.0",
                               iommu_group=str(11 + i),
                               vfio_dev=f"vfio{i}"))
    host.enable_iommufd()
    cfg = Config().with_root(host.root)
    os.makedirs(cfg.device_plugin_path, exist_ok=True)
    registry, _ = discover_passthrough(cfg)
    plugin = TpuDevicePlugin(cfg, "v4", registry,
                             registry.devices_by_model["0062"])
    bdf = "0000:00:04.0"
    req = pb.AllocateRequest(container_requests=[
        pb.ContainerAllocateRequest(devices_ids=[bdf])])
    resp = plugin.Allocate(req, None)
    paths = [d.host_path for d in resp.container_responses[0].devices]
    assert any(p.endswith("vfio0") for p in paths)
    # kernel re-enumerates the cdev (unbind/rebind)
    base = os.path.join(host.pci, bdf, "vfio-dev")
    shutil.rmtree(base)
    os.makedirs(os.path.join(base, "vfio9"))
    with open(os.path.join(host.devfs, "vfio", "devices", "vfio9"), "w"):
        pass
    # same epoch: the stale fragment still serves vfio0 (documented
    # blind spot, same contract as incremental discovery)
    resp = plugin.Allocate(req, None)
    paths = [d.host_path for d in resp.container_responses[0].devices]
    assert any(p.endswith("vfio0") for p in paths)
    # the flap publishes a new epoch -> fresh fragment cache -> vfio9
    plugin.set_devices_health([bdf], False, source="t")
    plugin.set_devices_health([bdf], True, source="t")
    resp = plugin.Allocate(req, None)
    paths = [d.host_path for d in resp.container_responses[0].devices]
    assert any(p.endswith("vfio9") for p in paths)
    assert not any(p.endswith("vfio0") for p in paths)


def test_dra_health_flip_bumps_inventory_epoch(short_root):
    from tpu_device_plugin.dra import DraDriver

    host = FakeHost(short_root)
    host.add_chip(FakeChip("0000:00:04.0", iommu_group="11"))
    cfg = Config().with_root(host.root)
    registry, generations = discover_passthrough(cfg)
    driver = DraDriver(cfg, registry, generations, node_name="n")
    ep0 = driver._inventory_snapshot()
    assert driver.apply_health({"0000:00:04.0": False}) is True
    ep1 = driver._inventory_snapshot()
    assert ep1.epoch_id == ep0.epoch_id + 1
    assert "0000:00:04.0" in ep1.unhealthy
    assert ep0.unhealthy == frozenset()
    # repeat delivery: no epoch churn
    assert driver.apply_health({"0000:00:04.0": False}) is False
    assert driver._inventory_snapshot().epoch_id == ep1.epoch_id
    # the slice body prunes from the epoch, no lock
    devices = driver.build_slice()["spec"]["devices"]
    assert devices == []


# ------------------------------------------------- the lockdep read gate


def test_read_paths_acquire_zero_registered_locks(short_root):
    """THE gate: steady-state Allocate / GetPreferredAllocation /
    ListAndWatch assembly / /status (plugin snapshot + hub stats + DRA
    read stats) / DRA prepare planning acquire ZERO registered locks.
    Counted (lockdep proxies + read_path brackets), so CI load cannot
    flip the verdict. Runs inside lockdep.scoped() — enforced in every
    tier-1 run, with or without TDP_LOCKDEP=1."""
    from tpu_device_plugin.dra import DraDriver
    from tpu_device_plugin.healthhub import HealthHub

    with lockdep.scoped():
        host = FakeHost(short_root)
        for i in range(4):
            host.add_chip(FakeChip(f"0000:00:{4 + i:02x}.0",
                                   iommu_group=str(11 + i),
                                   vfio_dev=f"vfio{i}", numa_node=i // 2))
        host.enable_iommufd()
        cfg = Config().with_root(host.root)
        os.makedirs(cfg.device_plugin_path, exist_ok=True)
        registry, generations = discover_passthrough(cfg)
        plugin = TpuDevicePlugin(cfg, "v4", registry,
                                 registry.devices_by_model["0062"])
        driver = DraDriver(cfg, registry, generations, node_name="n")
        hub = HealthHub()   # never started: stats() is the read side

        ids = [d.bdf for d in registry.devices_by_model["0062"]]
        pref_req = pb.PreferredAllocationRequest(container_requests=[
            pb.ContainerPreferredAllocationRequest(
                available_deviceIDs=ids, allocation_size=2)])
        alloc_req = pb.AllocateRequest(container_requests=[
            pb.ContainerAllocateRequest(devices_ids=ids[:2])])
        slice_names = [n for n in driver._by_name]
        results = [{"device": n, "pool": "n", "request": "r"}
                   for n in slice_names[:2]]

        # WARM-UP: first-touch slow paths (fd opens, fragment builds,
        # memo misses) are allowed to lock — that is the design
        plugin.GetPreferredAllocation(pref_req, None)
        plugin.Allocate(alloc_req, None)
        plugin.status_snapshot()
        plugin._lw_response(plugin._store.current)
        driver._plan_devices(results)
        hub.stats()

        # STEADY STATE: everything below must charge 0 acquisitions
        lockdep.reset()
        for _ in range(5):
            plugin.GetPreferredAllocation(pref_req, None)
            plugin.Allocate(alloc_req, None)
            plugin.status_snapshot()
            plugin._lw_response(plugin._store.current)
            driver._plan_devices(results)
            hub.stats()
            driver.checkpoint_stats()
            driver.prepared_claim_count()
            driver.unhealthy_devices()

        stats = lockdep.path_stats()
        expected = {"server.Allocate", "server.GetPreferredAllocation",
                    "server.ListAndWatch.assembly",
                    "server.status_snapshot", "dra.plan",
                    # ISSUE 10: the ICI placement scoring every
                    # GetPreferredAllocation answer pays (placement.py)
                    # is part of the zero-lock contract too
                    "placement.score"}
        assert expected <= set(stats), stats
        for name in expected:
            assert stats[name]["calls"] >= 5, (name, stats[name])
            assert stats[name]["lock_acquisitions"] == 0, \
                f"hot read path {name} acquired " \
                f"{stats[name]['lock_acquisitions']} registered lock(s) " \
                f"in steady state — the epoch refactor's zero-lock " \
                f"contract is broken"


def test_status_endpoint_acquires_zero_registered_locks(short_root):
    """The full /status + /metrics endpoint body (StatusServer.status)
    over a real manager + DRA driver: zero registered-lock acquisitions
    once warm — a slow scrape can no longer stall ListAndWatch or claim
    commits behind a held lock."""
    from tpu_device_plugin.dra import DraDriver
    from tpu_device_plugin.lifecycle import PluginManager
    from tpu_device_plugin.status import StatusServer

    with lockdep.scoped():
        host = FakeHost(short_root)
        host.add_chip(FakeChip("0000:00:04.0", iommu_group="11"))
        cfg = Config().with_root(host.root)
        os.makedirs(cfg.device_plugin_path, exist_ok=True)
        manager = PluginManager(cfg)
        registry, generations = discover_passthrough(cfg)
        manager.plugins = [TpuDevicePlugin(
            cfg, "v4", registry, registry.devices_by_model["0062"])]
        driver = DraDriver(cfg, registry, generations, node_name="n")
        server = StatusServer(manager, port=0, dra_driver=driver)
        try:
            server.status()          # warm-up (native shim first touch)
            server.metrics()
            lockdep.reset()
            for _ in range(3):
                server.status()
                server.metrics()
            stats = lockdep.path_stats()
            assert stats["status.endpoint"]["calls"] >= 6
            assert stats["status.endpoint"]["lock_acquisitions"] == 0, stats
        finally:
            server._httpd.server_close()


def test_read_path_counters_surface_on_status(short_root):
    """The per-path counters are an observable /status surface under
    lockdep (satellite: expose a per-path registered-lock-acquisition
    counter)."""
    from tpu_device_plugin.lifecycle import PluginManager
    from tpu_device_plugin.status import StatusServer

    with lockdep.scoped():
        host = FakeHost(short_root)
        host.add_chip(FakeChip("0000:00:04.0", iommu_group="11"))
        cfg = Config().with_root(host.root)
        os.makedirs(cfg.device_plugin_path, exist_ok=True)
        manager = PluginManager(cfg)
        registry, _ = discover_passthrough(cfg)
        manager.plugins = [TpuDevicePlugin(
            cfg, "v4", registry, registry.devices_by_model["0062"])]
        manager.plugins[0].status_snapshot()
        server = StatusServer(manager, port=0)
        try:
            out = server.status()
            assert "server.status_snapshot" in out["read_paths"]
            text = server.metrics()
            assert "tdp_read_path_lock_acquisitions_total" in text
        finally:
            server._httpd.server_close()


# ------------------------------------------------ mass-churn waiter wakeup


def test_mass_churn_one_flip_wakes_only_that_resources_waiters(short_root):
    """ISSUE 9 satellite: 256 concurrent ListAndWatch subscribers across
    16 resources, ONE health flip. Exactly the flipped resource's waiters
    assemble a send; every untouched resource keeps its epoch — and its
    pre-serialized payload — by OBJECT IDENTITY (`is`), pays zero epoch
    builds (counted), and none of its 240 parked streams produce a send.
    At 4096 devices a spurious rebuild is a multi-ms serialize per flip;
    identity is the proof it cannot happen."""
    n_resources, n_streams = 16, 16
    host = FakeHost(short_root)
    for i in range(n_resources * 4):
        host.add_chip(FakeChip(f"0000:{i // 32:02x}:{4 + i % 32:02x}.0",
                               iommu_group=str(11 + i), numa_node=0))
    cfg = dataclasses.replace(Config().with_root(host.root),
                              lw_debounce_s=0.0)
    os.makedirs(cfg.device_plugin_path, exist_ok=True)
    registry, _ = discover_passthrough(cfg)
    devices = registry.devices_by_model["0062"]
    plugins = [TpuDevicePlugin(cfg, f"v4-r{i:02d}", registry,
                               devices[i * 4:(i + 1) * 4])
               for i in range(n_resources)]

    class Ctx:
        def is_active(self):
            return True

        def add_callback(self, cb):
            return True

    responses = [[[] for _ in range(n_streams)]
                 for _ in range(n_resources)]
    threads = []
    for pi, plugin in enumerate(plugins):
        for si in range(n_streams):
            def consume(pi=pi, si=si, plugin=plugin):
                for resp in plugin.ListAndWatch(None, Ctx()):
                    responses[pi][si].append(
                        {d.ID: d.health for d in resp.devices})

            t = threading.Thread(target=consume, daemon=True)
            t.start()
            threads.append(t)
    try:
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline:
            if all(p._store.waiters >= n_streams for p in plugins):
                break
            time.sleep(0.01)
        else:
            raise AssertionError(
                f"streams never parked: waiters="
                f"{[p._store.waiters for p in plugins]}")

        before = [p._store.current for p in plugins]
        builds_before = [p._epoch_builds.value for p in plugins]
        flip_dev = devices[0].bdf
        plugins[0].set_devices_health([flip_dev], healthy=False,
                                      source="churn")

        deadline = time.monotonic() + 15
        while time.monotonic() < deadline:
            if all(len(responses[0][si]) == 2 for si in range(n_streams)):
                break
            time.sleep(0.01)
        else:
            raise AssertionError(
                f"flipped resource's waiters did not all send: "
                f"{[len(r) for r in responses[0]]}")
        time.sleep(0.1)   # grace: any spurious wakeup would send now

        # exactly the flipped resource's waiters assembled a send
        assert plugins[0]._lw_resends.value == n_streams
        for si in range(n_streams):
            assert responses[0][si][-1][flip_dev] == "Unhealthy"
        for pi in range(1, n_resources):
            # epoch AND payload identity-reused — not equal, THE SAME
            assert plugins[pi]._store.current is before[pi]
            assert plugins[pi]._store.current.lw_payload \
                is before[pi].lw_payload
            assert plugins[pi]._epoch_builds.value == builds_before[pi]
            assert plugins[pi]._lw_resends.value == 0
            for si in range(n_streams):
                assert len(responses[pi][si]) == 1, (pi, si)
    finally:
        for p in plugins:
            p._stop.set()
            p._store.poke()
        for t in threads:
            t.join(timeout=5)
    assert not any(t.is_alive() for t in threads)
