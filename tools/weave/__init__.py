"""weave — deterministic interleaving checker for the lock-free planes.

See tools/weave/core.py for the cooperative scheduler + DPOR explorer
and tools/weave/scenarios.py for the checked production scenarios.
Run ``python -m tools.weave`` (or ``make weave``).
"""

from tools.weave.core import (Counterexample, DeadlockError, ExploreResult,
                              Scenario, WeaveError, WeaveHang, explore,
                              replay, run_once)

__all__ = ["Counterexample", "DeadlockError", "ExploreResult", "Scenario",
           "WeaveError", "WeaveHang", "explore", "replay", "run_once"]
