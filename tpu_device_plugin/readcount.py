"""Shared sysfs-access accounting windows.

One registry instance per instrumented module (discovery's full-walk
reads, allocate's plan-path reads). The perf-honesty guards and the
benches assert on access COUNTS because counts — unlike wall clock on a
shared CPU — are load-insensitive. Factored here so the window semantics
(nesting, thread confinement) exist exactly once: discovery grew the
confine-thread option precisely because concurrent readers on other
threads inflated its stats gauge, and any registry hands the same
protection to its callers.
"""

from __future__ import annotations

import threading
from contextlib import contextmanager
from typing import Iterator, List, Optional

from . import lockdep


class ReadWindow:
    """One open accounting window: every access noted on the owning
    registry while the window is open bumps `reads` and appends the path
    to `paths`."""

    def __init__(self, owner: Optional[int] = None) -> None:
        self.reads = 0
        self.paths: List[str] = []
        # thread ident this window is confined to; None = count reads
        # from every thread (the default — tests observe a worker
        # thread's reads from the test thread)
        self._owner = owner


class WindowRegistry:
    """The open windows of one instrumented module. `note()` with no
    windows open costs one truthiness check (the production state)."""

    def __init__(self) -> None:
        self._windows: List[ReadWindow] = []
        self._lock = lockdep.instrument(
            "readcount.WindowRegistry._lock", threading.Lock())

    def note(self, path: str) -> None:
        if not self._windows:
            return
        ident: Optional[int] = None
        for w in tuple(self._windows):
            if w._owner is not None:
                if ident is None:
                    ident = threading.get_ident()
                if w._owner != ident:
                    continue
            w.reads += 1
            w.paths.append(path)

    @contextmanager
    def window(self, confine_thread: bool = False) -> Iterator[ReadWindow]:
        """Open an accounting window for the with-block. Windows nest:
        each sees every access made while it is open. With
        `confine_thread`, only the opening thread's accesses count."""
        w = ReadWindow(threading.get_ident() if confine_thread else None)
        with self._lock:
            self._windows.append(w)
        try:
            yield w
        finally:
            with self._lock:
                self._windows.remove(w)
