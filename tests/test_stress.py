"""Concurrency stress: parallel RPCs + health churn + kubelet restarts.

The reference has known-benign data races (SURVEY §5 "race detection");
this suite exists to show the redesigned lifecycle holds up under the same
pressure: no deadlocks, no lost sockets, consistent terminal state.
"""

import os
import random
import threading
import time

import grpc
import pytest

from tests.fakehost import FakeChip, FakeHost, FakeKubelet
from tpu_device_plugin import kubeletapi as api
from tpu_device_plugin.config import Config
from tpu_device_plugin.discovery import discover_passthrough
from tpu_device_plugin.kubeletapi import pb
from tpu_device_plugin.server import TpuDevicePlugin


@pytest.fixture
def rig(short_root):
    host = FakeHost(short_root)
    for i in range(8):
        host.add_chip(FakeChip(f"0000:00:{4 + i:02x}.0", device_id="0063",
                               iommu_group=str(11 + i), numa_node=i // 4))
    cfg = Config().with_root(host.root)
    os.makedirs(cfg.device_plugin_path, exist_ok=True)
    kubelet = FakeKubelet(cfg.kubelet_socket)
    regs = kubelet.registrations
    registry, generations = discover_passthrough(cfg)
    plugin = TpuDevicePlugin(cfg, "v5e", registry,
                             registry.devices_by_model["0063"],
                             torus_dims=generations["0063"].host_topology)
    plugin.start()
    yield host, cfg, plugin, regs
    plugin.stop()
    kubelet.stop()


def test_parallel_rpcs_under_health_churn(rig):
    host, cfg, plugin, regs = rig
    ids = [f"0000:00:{4 + i:02x}.0" for i in range(8)]
    stop = threading.Event()
    errors = []

    def rpc_worker(seed):
        rng = random.Random(seed)
        with grpc.insecure_channel(f"unix://{plugin.socket_path}") as ch:
            stub = api.DevicePluginStub(ch)
            while not stop.is_set():
                try:
                    k = rng.choice([1, 2, 4])
                    pref = stub.GetPreferredAllocation(
                        pb.PreferredAllocationRequest(container_requests=[
                            pb.ContainerPreferredAllocationRequest(
                                available_deviceIDs=ids, allocation_size=k)]),
                        timeout=5)
                    picked = list(pref.container_responses[0].deviceIDs)
                    assert len(picked) == k
                    stub.Allocate(
                        pb.AllocateRequest(container_requests=[
                            pb.ContainerAllocateRequest(devices_ids=picked)]),
                        timeout=5)
                except grpc.RpcError as exc:
                    if exc.code() != grpc.StatusCode.UNAVAILABLE:
                        errors.append(exc)
                except AssertionError as exc:
                    errors.append(exc)

    def churn_worker():
        rng = random.Random(42)
        while not stop.is_set():
            g = str(11 + rng.randrange(8))
            path = os.path.join(host.devfs, "vfio", g)
            try:
                if os.path.exists(path):
                    os.unlink(path)
                else:
                    with open(path, "w") as f:
                        f.write("")
            except OSError:
                pass
            time.sleep(0.01)

    workers = [threading.Thread(target=rpc_worker, args=(i,), daemon=True)
               for i in range(6)]
    churner = threading.Thread(target=churn_worker, daemon=True)
    for w in workers:
        w.start()
    churner.start()
    time.sleep(3)
    stop.set()
    for w in workers:
        w.join(timeout=5)
        assert not w.is_alive(), "rpc worker deadlocked"
    churner.join(timeout=5)
    assert not errors, errors[:3]
    # restore all nodes; plugin must converge back to all-Healthy
    for i in range(8):
        path = os.path.join(host.devfs, "vfio", str(11 + i))
        if not os.path.exists(path):
            with open(path, "w") as f:
                f.write("")
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        # lock-free reader contract: the epoch snapshot needs no lock
        states = set(plugin._store.current.device_health.values())
        if states == {"Healthy"}:
            break
        time.sleep(0.1)
    assert states == {"Healthy"}


def test_restart_storm(rig):
    """Repeated kubelet-restart signals; plugin must keep re-registering."""
    host, cfg, plugin, regs = rig
    deadline = time.monotonic() + 10
    rounds = 0
    while rounds < 4 and time.monotonic() < deadline:
        n = len(regs)
        if os.path.exists(plugin.socket_path):
            os.unlink(plugin.socket_path)
            while len(regs) == n and time.monotonic() < deadline:
                time.sleep(0.05)
            rounds += 1
        else:
            time.sleep(0.05)
    assert rounds == 4
    assert len(regs) >= 5  # initial + 4 restarts
    # still serving
    deadline = time.monotonic() + 5
    while not os.path.exists(plugin.socket_path) and time.monotonic() < deadline:
        time.sleep(0.05)
    with grpc.insecure_channel(f"unix://{plugin.socket_path}") as ch:
        opts = api.DevicePluginStub(ch).GetDevicePluginOptions(pb.Empty(), timeout=5)
        assert opts.get_preferred_allocation_available is True


def test_vtpu_parallel_rpcs_under_partition_churn(short_root):
    """vTPU plugin under the same pressure: concurrent Allocate/Preferred
    RPCs while mdev partitions' sysfs entries churn. No deadlock, every
    response either succeeds or fails INVALID_ARGUMENT (never UNKNOWN)."""
    from tpu_device_plugin.discovery import discover
    from tpu_device_plugin.vtpu import VtpuDevicePlugin

    host = FakeHost(short_root)
    host.add_chip(FakeChip("0000:00:04.0", iommu_group="11"))
    host.add_chip(FakeChip("0000:00:05.0", iommu_group="12"))
    for i in range(4):
        host.add_mdev(f"uuid-{i}", "TPU vhalf",
                      f"0000:00:{4 + i % 2:02x}.0", iommu_group=str(21 + i))
    cfg = Config().with_root(host.root)
    os.makedirs(cfg.device_plugin_path, exist_ok=True)
    kubelet = FakeKubelet(cfg.kubelet_socket)
    registry, _ = discover(cfg)
    plugin = VtpuDevicePlugin(cfg, "TPU_vhalf", registry,
                              registry.partitions_by_type["TPU_vhalf"])
    plugin.start()
    stop = threading.Event()
    errors = []
    uuids = [f"uuid-{i}" for i in range(4)]

    def rpc_worker(seed):
        rng = random.Random(seed)
        with grpc.insecure_channel(f"unix://{plugin.socket_path}") as ch:
            stub = api.DevicePluginStub(ch)
            while not stop.is_set():
                try:
                    picked = rng.sample(uuids, rng.choice([1, 2]))
                    stub.Allocate(
                        pb.AllocateRequest(container_requests=[
                            pb.ContainerAllocateRequest(devices_ids=picked)]),
                        timeout=5)
                except grpc.RpcError as exc:
                    if exc.code() != grpc.StatusCode.INVALID_ARGUMENT:
                        errors.append(exc)

    def churn_worker():
        rng = random.Random(99)
        while not stop.is_set():
            uuid = rng.choice(uuids)
            name = os.path.join(host.pci, f"0000:00:{4 + int(uuid[-1]) % 2:02x}.0",
                                uuid, "mdev_type", "name")
            try:
                with open(name, "w") as f:
                    f.write(rng.choice(["TPU vhalf\n", "TPU vother\n"]))
            except OSError:
                pass
            time.sleep(0.002)

    workers = [threading.Thread(target=rpc_worker, args=(i,), daemon=True)
               for i in range(4)]
    workers.append(threading.Thread(target=churn_worker, daemon=True))
    try:
        for w in workers:
            w.start()
        time.sleep(3)
    finally:
        stop.set()
        for w in workers:
            w.join(timeout=5)
        plugin.stop()
        kubelet.stop()
    assert not any(w.is_alive() for w in workers), "worker deadlocked"
    assert not errors, errors[:3]
    # terminal state clean: socket removed
    assert not os.path.exists(plugin.socket_path)


def test_incremental_rediscovery_under_churn(short_root):
    """Rapid hotplug/unplug churn + concurrent RPCs against the incremental
    rediscovery path: no deadlock, no UNKNOWN errors, and the final plugin
    set converges to the final inventory."""
    from tpu_device_plugin.lifecycle import PluginManager

    host = FakeHost(short_root)
    host.add_chip(FakeChip("0000:00:04.0", device_id="0062", iommu_group="11"))
    cfg = Config().with_root(host.root)
    from dataclasses import replace as dc_replace
    cfg = dc_replace(cfg, rediscovery_interval_s=0.15, grpc_timeout_s=2.0)
    os.makedirs(cfg.device_plugin_path, exist_ok=True)
    kubelet = FakeKubelet(cfg.kubelet_socket)
    manager = PluginManager(cfg)
    stop_run = threading.Event()
    t = threading.Thread(target=manager.run, args=(stop_run,), daemon=True)
    t.start()
    stop = threading.Event()
    errors = []

    def churn():
        rng = random.Random(7)
        import shutil as sh
        while not stop.is_set():
            bdf = f"0000:01:{rng.randrange(3):02x}.0"
            path = os.path.join(host.pci, bdf)
            try:
                if os.path.exists(path):
                    sh.rmtree(path)
                else:
                    host.add_chip(FakeChip(bdf, device_id="0063",
                                           iommu_group=f"2{bdf[-3]}"))
            except OSError:
                pass
            time.sleep(0.05)

    successes = [0]

    def rpc_worker():
        sock = os.path.join(cfg.device_plugin_path, "tpukubevirt-v4.sock")
        while not stop.is_set():
            try:
                with grpc.insecure_channel(f"unix://{sock}") as ch:
                    api.DevicePluginStub(ch).Allocate(
                        pb.AllocateRequest(container_requests=[
                            pb.ContainerAllocateRequest(
                                devices_ids=["0000:00:04.0"])]),
                        timeout=3)
                successes[0] += 1
            except grpc.RpcError as exc:
                # UNAVAILABLE is legitimate mid-restart; a wedged handler
                # (DEADLINE_EXCEEDED) or servicer crash (UNKNOWN) never is
                if exc.code() in (grpc.StatusCode.UNKNOWN,
                                  grpc.StatusCode.DEADLINE_EXCEEDED):
                    errors.append(exc)
            time.sleep(0.01)

    workers = [threading.Thread(target=churn, daemon=True),
               threading.Thread(target=rpc_worker, daemon=True)]
    try:
        assert kubelet.wait_for(1, timeout=10)
        for w in workers:
            w.start()
        time.sleep(4)
    finally:
        stop.set()
        for w in workers:
            w.join(timeout=5)
        assert not any(w.is_alive() for w in workers), "worker deadlocked"
    try:
        # churn stopped: within a few ticks the plugin set matches sysfs
        expected = {"v4"}
        if any(b.startswith("0000:01:") for b in os.listdir(host.pci)):
            expected.add("v5e")
        deadline = time.monotonic() + 10
        current = set()
        while time.monotonic() < deadline:
            current = {p.resource_suffix for p in manager.plugins
                       if p.serving}
            if current == expected and not manager.pending:
                break
            time.sleep(0.1)
        assert current == expected and not manager.pending, \
            f"did not converge: serving={current} pending={manager.pending}"
        assert not errors, errors[:3]
        assert successes[0] > 0, "no Allocate ever succeeded during churn"
        # the stable v4 plugin never restarted through all of it
        v4 = next(p for p in manager.plugins if p.resource_suffix == "v4")
        assert v4._restart_count == 0
    finally:
        stop_run.set()
        t.join(timeout=10)
        kubelet.stop()
