"""Continuous fleet autopilot tests (ISSUE 12).

The tier-1 smoke drives a small fleet through EVERY overlapping storm
type for a few seconds — claim batches, multi-host slices, flip waves,
hot-unplugs with orphan cleanup, handoff migrations, defrag advisories,
rolling upgrades, republish waves — on the watch-stream fabric with
watch chaos and the kubeapi.watch fault sites armed, and requires the
continuously-checked soak invariants green plus a clean quiesce (zero
orphans, converged, exactly-once). The full-length 256-node / 100k-
claim-event soak is `make soak-autopilot` (bench.py --autopilot) and
its recorded artifact is pinned by test_perf_honesty.

fleet_invariants itself is tested to DETECT what it guards against:
a planted lost claim and a planted orphaned spec file must be reported
(after the transient-suspect confirmation pass), and a clean fleet must
not be."""

import json
import os
import time

from tpu_device_plugin import faults
from tpu_device_plugin.autopilot import (AutopilotConfig, FleetAutopilot,
                                         measure_read_repair)
from tpu_device_plugin.fleetsim import FleetSim, fleet_invariants


def test_autopilot_smoke_all_storms_continuous_invariants_green():
    cfg = AutopilotConfig(
        nodes=4, duration_s=6.0, seed=11,
        claim_workers=3, multiclaim_workers=1, flip_workers=1,
        unplug_workers=1, migration_workers=1, defrag_workers=1,
        upgrade_workers=1, upgrade_wave_size=2,
        boot_workers=1, boot_wave_size=2,
        pinned_per_nodes=2, invariant_interval_s=1.0)
    pilot = FleetAutopilot(cfg)
    try:
        report = pilot.run()
    finally:
        faults.reset()
    assert report["ok"], report["violations"]
    assert report["converged"]
    c = report["counters"]
    # every storm type actually ran
    assert c["prepares"] > 50 and c["unprepares"] > 50
    assert c["multiclaims_placed"] >= 1
    assert c["flip_storms"] >= 1
    assert c["unplugs"] >= 1 and c["readmits"] >= 1
    assert c["upgrades"] >= 1
    assert c["republish_waves"] >= 1
    # invariants were checked DURING the run, not only at the end
    assert c["invariant_checks"] >= 3
    fi = report["final_invariants"]
    assert fi["ok"] and fi["exactly_once"] and fi["multiclaim_exactly_once"]
    assert fi["orphaned_claims"] == 0
    # the watch plane carried the run and its chaos fired
    assert report["watch"]["watch_events_total"] > 0
    assert report["fabric"]["watch_opened_total"] > 0
    assert sum(report["faults_fired"].values()) >= 1
    # the report is a JSON artifact (the CI smoke leg uploads it)
    json.dumps(report)


def test_fleet_invariants_clean_and_planted_violations():
    sim = FleetSim(n_nodes=2, latency_s=0.0, max_inflight=0, seed=5)
    try:
        sim.boot_storm()
        uids = sim.nodes[0].register_claims(2)
        resp = sim.nodes[0].attach(uids)
        assert all(not resp.claims[u].error for u in uids)
        clean = fleet_invariants(sim, confirm=lambda: None)
        assert clean["ok"], clean["violations"]
        assert clean["prepared_total"] == 2
        # planted LOST claim: a checkpoint entry the fabric never knew
        driver = sim.nodes[0].driver
        with driver._lock:
            driver._checkpoint["ghost-claim"] = {
                "name": "ghost-claim", "namespace": "fleet",
                "spec_path": driver._claim_spec_path("ghost-claim"),
                "devices": [], "device_raws": [], "generation": 1}
        # planted ORPHANED spec: a claim spec file with no checkpoint
        orphan_path = sim.nodes[1].driver._claim_spec_path("ghost-spec")
        os.makedirs(os.path.dirname(orphan_path), exist_ok=True)
        with open(orphan_path, "w") as f:
            f.write("{}")
        bad = fleet_invariants(sim, confirm=lambda: None)
        assert not bad["ok"]
        text = "; ".join(bad["violations"])
        assert "ghost-claim" in text and "lost" in text
        assert "ghost-spec" in text and "orphaned spec" in text
        # a TRANSIENT suspect (gone by the confirmation pass) is not
        # reported: the confirm hook deletes the planted state
        with driver._lock:
            driver._checkpoint["ghost-2"] = {
                "name": "ghost-2", "namespace": "fleet",
                "spec_path": driver._claim_spec_path("ghost-2"),
                "devices": [], "device_raws": [], "generation": 1}

        def heal():
            with driver._lock:
                driver._checkpoint.pop("ghost-claim", None)
                driver._checkpoint.pop("ghost-2", None)
            os.unlink(orphan_path)

        healed = fleet_invariants(sim, confirm=heal)
        assert healed["ok"], healed["violations"]
    finally:
        sim.stop()


def test_measure_read_repair_watch_vs_polling():
    """The r14 comparison at toy scale: polling pays one liveness GET
    per node per tick, the watch fleet's ticks read nothing — and the
    watch fleet still HEALS a wiped slice."""
    out = measure_read_repair(n_nodes=2, rounds=4)
    assert out["poll_reads"] == 2 * 4
    assert out["watch_reads"] == 0
    assert out["read_reduction_x"] >= 5.0
    assert out["wipe_healed_by_watch"]
    assert out["exactly_once"]


def test_migrated_pinned_claim_story_reconstructs_from_fleet_trace():
    """ACCEPTANCE (ISSUE 15 satellite): the report's cross-node claim
    story is reconstructed PURELY from the fleet trace query
    (/debug/fleet/trace?trace= body via fleetplace.FleetFlight), not
    from ad-hoc snapshot stitching — driven deterministically through
    the autopilot's own migration applier."""
    from tpu_device_plugin import trace
    trace.reset()
    cfg = AutopilotConfig(nodes=2, duration_s=0.1, seed=7,
                          watch=False, watch_chaos=False,
                          watch_faults=False)
    pilot = FleetAutopilot(cfg)
    try:
        src, dst = pilot.sim.nodes
        uid = "pin-story"
        free_src = sorted(src.host_view().free)
        src.claim_devices(uid, [free_src[0]])
        with pilot._lock:
            pilot._pinned[uid] = src.name
        mig = {"claim": uid, "devices": [free_src[0]],
               "target_devices": [sorted(dst.host_view().free)[0]]}
        assert pilot._apply_one_migration(src, dst, mig,
                                          counter="migrations")
        story = pilot._story
        assert story is not None
        # the story IS a fleet-trace reconstruction: one trace id, the
        # endpoint that serves it, both hosts present, all three acts
        assert story["endpoint"] == \
            f"/debug/fleet/trace?trace={story['trace_id']}"
        assert {src.name, dst.name} <= set(story["nodes"])
        for needed in ("dra.prepare.claim", "dra.unprepare.claim",
                       "dra.handoff.completed"):
            assert needed in story["ops"], (needed, story["ops"])
        # and the same query over the collector returns the same spans
        replay = pilot.sim.fleet_flight().trace(story["trace_id"])
        assert len(replay["spans"]) == story["spans"]
    finally:
        pilot.sim.stop()
        faults.reset()


def test_autopilot_report_counts_claim_events_toward_target():
    """claim_event_target extends the run past duration_s until the
    event budget is met (the 100k-event lever of the full soak)."""
    cfg = AutopilotConfig(
        nodes=2, duration_s=0.5, claim_event_target=200,
        max_wall_s=60.0, seed=3, claim_workers=2,
        multiclaim_workers=0, flip_workers=0, unplug_workers=0,
        migration_workers=0, defrag_workers=0, upgrade_workers=0,
        boot_workers=0, pinned_per_nodes=100,
        invariant_interval_s=1.0, watch_chaos=False, watch_faults=False)
    t0 = time.monotonic()
    pilot = FleetAutopilot(cfg)
    try:
        report = pilot.run()
    finally:
        faults.reset()
    assert report["counters"]["claim_events"] >= 200, report["counters"]
    assert report["ok"], report["violations"]
    assert time.monotonic() - t0 < 60


def test_upgrade_wave_wider_than_fleet_does_not_deadlock():
    """An upgrade wave wider than the fleet wraps onto the same node
    indices; acquiring a node lock twice would deadlock the upgrade
    worker INSIDE the fleet lock and stall every multi-node storm until
    max_wall_s. The wave must dedupe."""
    cfg = AutopilotConfig(
        nodes=2, duration_s=2.0, max_wall_s=30.0, seed=5,
        claim_workers=1, multiclaim_workers=0, flip_workers=0,
        unplug_workers=0, migration_workers=0, defrag_workers=0,
        upgrade_workers=1, upgrade_wave_size=5,   # > nodes: wraps
        boot_workers=0, pinned_per_nodes=100,
        invariant_interval_s=1.0, watch_chaos=False, watch_faults=False)
    t0 = time.monotonic()
    pilot = FleetAutopilot(cfg)
    try:
        report = pilot.run()
    finally:
        faults.reset()
    assert report["counters"]["upgrades"] >= 1, report["counters"]
    assert report["ok"], report["violations"]
    # a deadlocked upgrade worker rides to max_wall_s; a healthy run
    # ends just past duration_s
    assert time.monotonic() - t0 < 25
