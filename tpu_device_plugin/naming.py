"""Resource naming: PCI device id → TPU generation, with pci.ids fallback.

The reference names resources by streaming /usr/pci.ids for the device's
marketing name (reference: pkg/device_plugin/device_plugin.go:371-438) and
falls back to the raw device id (:125-127). pci.ids carries **no Cloud TPU
device ids** (vendor 1ae0 lists only NVMe/gVNIC/Pixel entries), so the TPU
build leads with a built-in, overridable device-id → generation table and
keeps the pci.ids scan only as a display-name fallback for unknown ids.
"""

from __future__ import annotations

import json
import logging
import re
from dataclasses import dataclass
from typing import Dict, Optional, Tuple

log = logging.getLogger(__name__)


@dataclass(frozen=True)
class GenerationInfo:
    """Static per-generation facts used for naming and ICI topology."""

    name: str                     # resource name suffix, e.g. "v5e"
    chips_per_host: int           # chips a single host exposes
    host_topology: Tuple[int, ...]  # host-local ICI torus dims, prod(dims) == chips_per_host
    cores_per_chip: int = 1       # logical vTPU partitions a chip supports


def _parse_generation(info: dict) -> GenerationInfo:
    return GenerationInfo(
        name=str(info["name"]),
        chips_per_host=int(info["chips_per_host"]),
        host_topology=tuple(int(d) for d in info["host_topology"]),
        cores_per_chip=int(info.get("cores_per_chip", 1)),
    )


def _load_packaged_defaults() -> Dict[str, GenerationInfo]:
    """Parse the packaged tpu_ids.json — the ONE authoritative table.

    pci.ids has no Cloud TPU ids, and Google does not publish a PCI-id table
    for TPUs, so the ids in data/tpu_ids.json are *placeholders chosen for
    tests and examples*; production fleets override via --generation-map
    (Config.generation_map_path). The table shape — id → generation + host
    torus — is the contract; the key values are data. Strict parse: a broken
    packaged file is a broken install and should fail loudly at import.
    """
    from importlib import resources
    text = (resources.files(__package__) / "data" / "tpu_ids.json") \
        .read_text(encoding="utf-8")
    return {
        dev_id.lower(): _parse_generation(info)
        for dev_id, info in json.loads(text).items()
        if not dev_id.startswith("_")  # "_comment" documentation key
    }


DEFAULT_GENERATIONS: Dict[str, GenerationInfo] = _load_packaged_defaults()

_SANITIZE_KEEP = re.compile(r"[^A-Z0-9_]")


def sanitize_name(raw: str) -> str:
    """Uppercase and strip to [A-Z0-9_], mapping separators to underscores.

    Mirrors the reference's name sanitizer so resource names stay valid k8s
    extended-resource names (reference: device_plugin.go:388-415).
    """
    out = raw.strip().upper()
    for ch in ("/", ".", " ", "-", ":"):
        out = out.replace(ch, "_")
    return _SANITIZE_KEEP.sub("", out)


def load_generation_map(path: Optional[str]) -> Dict[str, GenerationInfo]:
    """Built-in table, optionally overlaid with a JSON override file.

    Override format: {"<device_id>": {"name": "v5e", "chips_per_host": 8,
    "host_topology": [2, 4], "cores_per_chip": 1}, ...}
    """
    table = dict(DEFAULT_GENERATIONS)
    if not path:
        return table
    try:
        with open(path, "r", encoding="utf-8") as f:
            raw = json.load(f)
        if not isinstance(raw, dict):
            raise ValueError("top level must be an object of device_id -> info")
    except (OSError, ValueError) as exc:
        log.warning("generation map %s unreadable (%s); using built-ins", path, exc)
        return table
    for dev_id, info in raw.items():
        if dev_id.startswith("_"):
            continue  # "_comment" documentation key
        try:
            table[dev_id.lower()] = _parse_generation(info)
        except (KeyError, TypeError, ValueError) as exc:
            log.warning("generation map entry %r invalid (%s); skipped", dev_id, exc)
    return table


def pci_ids_device_name(pci_ids_path: str, vendor_id: str, device_id: str) -> Optional[str]:
    """Stream pci.ids for `vendor_id`'s `device_id` name; None if absent.

    Same scan discipline as the reference — seek the vendor line, then match
    tab-indented device lines under it, stopping at the next vendor
    (reference: device_plugin.go:424-438, :371-422) — but written as a
    single-pass generator over the file.
    """
    vendor_id = vendor_id.lower()
    device_id = device_id.lower()
    try:
        with open(pci_ids_path, "r", encoding="utf-8", errors="replace") as f:
            in_vendor = False
            for line in f:
                if not line.strip() or line.startswith("#"):
                    continue
                if not line.startswith("\t"):
                    in_vendor = line[:4].lower() == vendor_id
                    continue
                if in_vendor and not line.startswith("\t\t"):
                    entry = line.strip()
                    if entry[:4].lower() == device_id:
                        return entry[4:].strip()
    except OSError as exc:
        log.warning("pci.ids %s unreadable: %s", pci_ids_path, exc)
    return None


def resource_name_for(
    device_id: str,
    generations: Dict[str, GenerationInfo],
    pci_ids_path: Optional[str] = None,
    vendor_id: str = "1ae0",
) -> str:
    """Resource-name suffix for a device id: generation, pci.ids name, or raw id.

    Advertised as `<namespace>/<this>`, e.g. `cloud-tpus.google.com/v5e`.
    """
    info = generations.get(device_id.lower())
    if info is not None:
        return info.name
    if pci_ids_path:
        name = pci_ids_device_name(pci_ids_path, vendor_id, device_id)
        if name:
            return sanitize_name(name)
    return sanitize_name(f"TPU_{device_id}")
