"""Test env: force JAX onto a virtual 8-device CPU mesh before any jax import.

Exception: TDP_TPU_TESTS=1 leaves the platform un-pinned so the `-m tpu`
Mosaic-compile gate (tests/test_tpu_gate.py) can claim the real chip. Use it
only for that file — running the whole suite that way would put every jax
test in contention for the single exclusive-claim TPU:

    TDP_TPU_TESTS=1 python -m pytest tests/test_tpu_gate.py -v
"""

import os
import shutil
import sys
import tempfile

import pytest

_want_tpu = os.environ.get("TDP_TPU_TESTS") == "1"
if not _want_tpu:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

# Some environments force-register an out-of-process TPU PJRT plugin from
# sitecustomize, overriding JAX_PLATFORMS; initializing it would contend for
# the (single) real chip from every test process. Pin the config to CPU
# before any backend initialization.
if not _want_tpu:
    try:
        import jax

        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "tpu: needs a real TPU backend (TDP_TPU_TESTS=1)")
    config.addinivalue_line(
        "markers", "slow: long randomized chaos soak (TDP_CHAOS_SOAK=1; "
                   "run via `make chaos-soak`)")


class FakeClock:
    """Injectable monotonic clock for CircuitBreaker tests — advance time
    without sleeping (used by test_resilience.py and test_kubeapi.py)."""

    def __init__(self):
        self.now = 0.0

    def __call__(self):
        return self.now

    def advance(self, s):
        self.now += s


@pytest.fixture
def short_root():
    """A short tmpdir for fixtures that bind unix sockets: pytest's tmp_path
    can push socket paths past the kernel's 107-char sun_path limit."""
    root = tempfile.mkdtemp(prefix="tdp-")
    yield root
    shutil.rmtree(root, ignore_errors=True)
