"""End-to-end plugin server tests against a fake kubelet.

Goes beyond the reference's fake-stream harness
(generic_device_plugin_test.go:55-62): a real gRPC Registration server plays
kubelet, the plugin serves on a real unix socket, and health transitions are
induced by deleting/creating actual device nodes.
"""

import os
import threading
import time

import grpc
import pytest

from tests.fakehost import FakeChip, FakeHost, FakeKubelet
from tpu_device_plugin import kubeletapi as api
from tpu_device_plugin.config import Config
from tpu_device_plugin.discovery import discover_passthrough
from tpu_device_plugin.kubeletapi import pb
from tpu_device_plugin.server import TpuDevicePlugin


@pytest.fixture
def rig(short_root):
    """FakeHost + fake kubelet Registration server + started plugin."""
    host = FakeHost(short_root)
    for i, (g, n) in enumerate([("11", 0), ("11", 0), ("12", 1), ("12", 1)]):
        host.add_chip(FakeChip(f"0000:00:{4 + i:02x}.0", iommu_group=g, numa_node=n))
    # short probe cadence: the native probe now also observes group nodes, so
    # recovery after a node reappears is bounded by health_poll_s
    from dataclasses import replace
    cfg = replace(Config().with_root(host.root), health_poll_s=0.2)
    os.makedirs(cfg.device_plugin_path, exist_ok=True)
    kubelet = FakeKubelet(cfg.kubelet_socket)
    registry, generations = discover_passthrough(cfg)
    plugin = TpuDevicePlugin(cfg, "v4", registry,
                             registry.devices_by_model["0062"],
                             torus_dims=generations["0062"].host_topology)
    plugin.start()
    yield host, cfg, kubelet, plugin
    plugin.stop()
    kubelet.stop()


def _wait(pred, timeout=5.0, interval=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return False


def test_start_registers_with_kubelet(rig):
    host, cfg, kubelet, plugin = rig
    assert kubelet.wait_for(1, timeout=5)
    req = kubelet.registrations[0]
    assert req.resource_name == "cloud-tpus.google.com/v4"
    assert req.version == "v1beta1"
    assert req.endpoint == os.path.basename(plugin.socket_path)
    assert req.options.get_preferred_allocation_available is True
    assert os.path.exists(plugin.socket_path)


def test_list_and_watch_health_transitions(rig):
    host, cfg, kubelet, plugin = rig
    updates = []
    done = threading.Event()

    def consume():
        with grpc.insecure_channel(f"unix://{plugin.socket_path}") as ch:
            stub = api.DevicePluginStub(ch)
            try:
                for resp in stub.ListAndWatch(pb.Empty()):
                    updates.append({d.ID: d.health for d in resp.devices})
                    done.set()
            except grpc.RpcError:
                pass

    t = threading.Thread(target=consume, daemon=True)
    t.start()
    assert _wait(lambda: len(updates) >= 1)
    assert set(updates[0].values()) == {"Healthy"}
    assert len(updates[0]) == 4

    # kill group 12's vfio node -> chips 06/07 go Unhealthy
    host.remove_vfio_group("12")
    assert _wait(lambda: len(updates) >= 2 and
                 updates[-1]["0000:00:06.0"] == "Unhealthy")
    assert updates[-1]["0000:00:07.0"] == "Unhealthy"
    assert updates[-1]["0000:00:04.0"] == "Healthy"

    # node comes back -> Healthy again
    with open(os.path.join(host.devfs, "vfio", "12"), "w") as f:
        f.write("")
    assert _wait(lambda: updates[-1]["0000:00:06.0"] == "Healthy")


def test_list_and_watch_client_cancel_frees_worker(rig):
    """The event-driven stream sleeps on the condvar with no timeout; a
    client cancel must wake it via the RPC-termination callback so the
    worker thread is freed (not pinned until the next health event)."""
    host, cfg, kubelet, plugin = rig
    before = {t.name for t in threading.enumerate()}
    calls = []
    for i in range(3):
        ch = grpc.insecure_channel(f"unix://{plugin.socket_path}")
        call = api.DevicePluginStub(ch).ListAndWatch(pb.Empty())
        next(call)  # initial list delivered; stream now parked on condvar
        calls.append((ch, call))
    for ch, call in calls:
        call.cancel()
        ch.close()
    # the freed workers must be able to serve new RPCs: the pool has 8
    # threads, so burn through 8 fresh streams to prove none stayed pinned
    for i in range(8):
        with grpc.insecure_channel(f"unix://{plugin.socket_path}") as ch:
            call = api.DevicePluginStub(ch).ListAndWatch(pb.Empty())
            assert len(next(call).devices) == 4
            call.cancel()
    assert _wait(
        lambda: len({t.name for t in threading.enumerate()} - before) <= 8)


def test_allocate_and_preferred_over_socket(rig):
    host, cfg, kubelet, plugin = rig
    with grpc.insecure_channel(f"unix://{plugin.socket_path}") as ch:
        stub = api.DevicePluginStub(ch)
        pref = stub.GetPreferredAllocation(
            pb.PreferredAllocationRequest(container_requests=[
                pb.ContainerPreferredAllocationRequest(
                    available_deviceIDs=["0000:00:04.0", "0000:00:07.0",
                                         "0000:00:05.0", "0000:00:06.0"],
                    allocation_size=2)]),
            timeout=5)
        picked = list(pref.container_responses[0].deviceIDs)
        assert picked == ["0000:00:04.0", "0000:00:05.0"]

        resp = stub.Allocate(
            pb.AllocateRequest(container_requests=[
                pb.ContainerAllocateRequest(devices_ids=picked)]),
            timeout=5)
        creps = resp.container_responses[0]
        assert creps.envs["PCI_RESOURCE_CLOUD_TPUS_GOOGLE_COM_V4"] == \
            "0000:00:04.0,0000:00:05.0"
        assert [d.container_path for d in creps.devices] == \
            ["/dev/vfio/vfio", "/dev/vfio/11"]


def test_allocate_unknown_device_is_invalid_argument(rig):
    host, cfg, kubelet, plugin = rig
    with grpc.insecure_channel(f"unix://{plugin.socket_path}") as ch:
        stub = api.DevicePluginStub(ch)
        with pytest.raises(grpc.RpcError) as exc_info:
            stub.Allocate(
                pb.AllocateRequest(container_requests=[
                    pb.ContainerAllocateRequest(devices_ids=["0000:00:99.0"])]),
                timeout=5)
        assert exc_info.value.code() == grpc.StatusCode.INVALID_ARGUMENT


def test_must_include_too_large_is_invalid_argument(rig):
    host, cfg, kubelet, plugin = rig
    with grpc.insecure_channel(f"unix://{plugin.socket_path}") as ch:
        stub = api.DevicePluginStub(ch)
        with pytest.raises(grpc.RpcError) as exc_info:
            stub.GetPreferredAllocation(
                pb.PreferredAllocationRequest(container_requests=[
                    pb.ContainerPreferredAllocationRequest(
                        available_deviceIDs=["0000:00:04.0", "0000:00:05.0"],
                        must_include_deviceIDs=["0000:00:04.0", "0000:00:05.0"],
                        allocation_size=1)]),
                timeout=5)
        assert exc_info.value.code() == grpc.StatusCode.INVALID_ARGUMENT


def test_kubelet_restart_triggers_reregistration(rig):
    host, cfg, kubelet, plugin = rig
    assert kubelet.wait_for(1, timeout=5)
    # kubelet restart wipes the device-plugin dir: remove the plugin's socket
    os.unlink(plugin.socket_path)
    assert kubelet.wait_for(2, timeout=10), "plugin did not re-register"
    assert len(kubelet.registrations) == 2
    assert _wait(lambda: os.path.exists(plugin.socket_path))
    # plugin is serving again on the fresh socket
    with grpc.insecure_channel(f"unix://{plugin.socket_path}") as ch:
        stub = api.DevicePluginStub(ch)
        opts = stub.GetDevicePluginOptions(pb.Empty(), timeout=5)
        assert opts.get_preferred_allocation_available is True


def test_stop_removes_socket(rig):
    host, cfg, kubelet, plugin = rig
    assert os.path.exists(plugin.socket_path)
    plugin.stop()
    assert not os.path.exists(plugin.socket_path)


def test_list_and_watch_coalesces_flap_storm(rig):
    """A burst of health flips inside the debounce window must reach the
    stream as ONE re-send carrying the final state — and a trailing lone
    flip must still propagate (no lost final transition)."""
    host, cfg, kubelet, plugin = rig
    updates = []

    def consume():
        with grpc.insecure_channel(f"unix://{plugin.socket_path}") as ch:
            try:
                for resp in api.DevicePluginStub(ch).ListAndWatch(pb.Empty()):
                    updates.append({d.ID: d.health for d in resp.devices})
            except grpc.RpcError:
                pass

    t = threading.Thread(target=consume, daemon=True)
    t.start()
    assert _wait(lambda: len(updates) >= 1)
    # 40 flips back-to-back, ending with group 11 Unhealthy (i=39 -> False)
    for i in range(40):
        plugin.set_devices_health(["0000:00:04.0", "0000:00:05.0"],
                                  healthy=(i % 2 == 0), source="storm")
    assert _wait(lambda: updates[-1].get("0000:00:04.0") == "Unhealthy")
    assert len(updates) == 2, updates  # initial + ONE coalesced re-send
    assert plugin.status_snapshot()["lw_resends"] == 1
    # a single trailing flip still goes out on its own
    plugin.set_devices_health(["0000:00:04.0", "0000:00:05.0"],
                              healthy=True, source="storm")
    assert _wait(lambda: updates[-1].get("0000:00:04.0") == "Healthy")
    assert len(updates) == 3


def test_lw_debounce_zero_sends_per_flip(short_root):
    """cfg.lw_debounce_s=0 restores the send-per-transition behavior."""
    host = FakeHost(short_root)
    host.add_chip(FakeChip("0000:00:04.0", iommu_group="11"))
    from dataclasses import replace
    cfg = replace(Config().with_root(host.root), health_poll_s=60,
                  lw_debounce_s=0.0)
    os.makedirs(cfg.device_plugin_path, exist_ok=True)
    kubelet = FakeKubelet(cfg.kubelet_socket)
    registry, _ = discover_passthrough(cfg)
    plugin = TpuDevicePlugin(cfg, "v4", registry,
                             registry.devices_by_model["0062"])
    plugin.start()
    updates = []
    try:
        def consume():
            with grpc.insecure_channel(f"unix://{plugin.socket_path}") as ch:
                try:
                    for resp in api.DevicePluginStub(ch).ListAndWatch(
                            pb.Empty()):
                        updates.append(
                            {d.ID: d.health for d in resp.devices})
                except grpc.RpcError:
                    pass

        t = threading.Thread(target=consume, daemon=True)
        t.start()
        assert _wait(lambda: len(updates) >= 1)
        plugin.set_devices_health(["0000:00:04.0"], False, "storm")
        assert _wait(lambda: len(updates) >= 2)
        plugin.set_devices_health(["0000:00:04.0"], True, "storm")
        assert _wait(lambda: len(updates) >= 3)
        assert updates[-1]["0000:00:04.0"] == "Healthy"
    finally:
        plugin.stop()
        kubelet.stop()


def test_nan_or_negative_debounce_rejected_at_arm_time(short_root):
    host = FakeHost(short_root)
    host.add_chip(FakeChip("0000:00:04.0", iommu_group="11"))
    from dataclasses import replace
    base = Config().with_root(host.root)
    registry, _ = discover_passthrough(base)
    devs = registry.devices_by_model["0062"]
    for bad in (float("nan"), -0.5, float("inf")):
        with pytest.raises(ValueError, match="lw_debounce_s"):
            TpuDevicePlugin(replace(base, lw_debounce_s=bad), "v4",
                            registry, devs)


def test_preferred_cache_hot_key_survives_fill_and_epoch_swap(rig):
    """The per-epoch memo must (a) keep serving a hot key as a HIT while
    the cache fills past capacity (no wholesale clear mid-epoch), (b) stay
    bounded at PREF_CACHE_SIZE, and (c) be invalidated by construction on
    an epoch publish — a health flip swaps in a fresh dict, so the next
    ask recomputes instead of serving under a dead epoch's key."""
    from tpu_device_plugin import server as server_mod
    host, cfg, kubelet, plugin = rig

    def ask(ids, size=1):
        plugin.GetPreferredAllocation(
            pb.PreferredAllocationRequest(container_requests=[
                pb.ContainerPreferredAllocationRequest(
                    available_deviceIDs=ids, allocation_size=size)]), None)

    hot = ["0000:00:04.0", "0000:00:05.0"]
    ask(hot)                                   # miss 1: the hot key
    misses0 = plugin._pref_misses.value
    # fill the cache past capacity with distinct keys (unknown ids are
    # filtered from the scan but stay in the memo key), touching the hot
    # key along the way — it was cached before the fill, so it stays one
    for i in range(server_mod.PREF_CACHE_SIZE + 10):
        ask(["0000:00:04.0", f"filler-{i}"])
        ask(hot)                               # the hot key keeps hitting
    assert len(plugin._pref_cache) <= server_mod.PREF_CACHE_SIZE
    before_hits = plugin._pref_hits.value
    ask(hot)
    assert plugin._pref_hits.value == before_hits + 1
    snap = plugin.status_snapshot()["preferred_cache"]
    assert snap["hits"] == plugin._pref_hits.value
    assert snap["misses"] >= misses0
    assert snap["capacity"] == server_mod.PREF_CACHE_SIZE
    # an epoch publish (health flip) swaps the memo wholesale: the hot
    # key misses exactly once under the new epoch id, then hits again
    epoch0 = plugin._store.current.epoch_id
    plugin.set_devices_health(["0000:00:06.0"], False, source="test")
    assert plugin._store.current.epoch_id > epoch0
    misses_before = plugin._pref_misses.value
    ask(hot)
    assert plugin._pref_misses.value == misses_before + 1
    hits_before = plugin._pref_hits.value
    ask(hot)
    assert plugin._pref_hits.value == hits_before + 1


def test_allocate_rejects_other_models_bdf(short_root):
    """The v5e plugin must refuse a v4 BDF even though both live in the same
    registry (the reference's global map would hand it out)."""
    host = FakeHost(short_root)
    host.add_chip(FakeChip("0000:00:04.0", device_id="0062", iommu_group="11"))
    host.add_chip(FakeChip("0000:01:00.0", device_id="0063", iommu_group="21"))
    from dataclasses import replace
    cfg = replace(Config().with_root(host.root), health_poll_s=60)
    os.makedirs(cfg.device_plugin_path, exist_ok=True)
    kubelet = FakeKubelet(cfg.kubelet_socket)
    registry, generations = discover_passthrough(cfg)
    plugin = TpuDevicePlugin(cfg, "v5e", registry,
                             registry.devices_by_model["0063"])
    plugin.start()
    try:
        with grpc.insecure_channel(f"unix://{plugin.socket_path}") as ch:
            stub = api.DevicePluginStub(ch)
            with pytest.raises(grpc.RpcError) as exc_info:
                stub.Allocate(
                    pb.AllocateRequest(container_requests=[
                        pb.ContainerAllocateRequest(
                            devices_ids=["0000:00:04.0"])]),
                    timeout=5)
            assert exc_info.value.code() == grpc.StatusCode.INVALID_ARGUMENT
            # its own chip still allocates fine
            resp = stub.Allocate(
                pb.AllocateRequest(container_requests=[
                    pb.ContainerAllocateRequest(devices_ids=["0000:01:00.0"])]),
                timeout=5)
            assert resp.container_responses[0].devices
    finally:
        plugin.stop()
        kubelet.stop()
