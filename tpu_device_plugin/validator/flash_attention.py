"""Pallas flash-attention kernel — the burn-in's hot op, TPU-first.

Causal multi-head attention computed blockwise with the online-softmax
recurrence so the (S, S) score matrix never materializes in HBM: each grid
step streams one (block_q, block_k) tile through VMEM, keeping running max
`m`, normalizer `l`, and output accumulator in VMEM scratch. The MXU sees two
matmuls per tile (Q·Kᵀ and P·V) with float32 accumulation; blocks entirely
above the causal diagonal are skipped via `pl.when`.

Training integration uses `jax.custom_vjp` with a rematerialized reference
backward: the forward runs the Pallas kernel; the backward recomputes
attention with plain einsum math and differentiates that. This keeps the
kernel forward-only (the expensive, latency-critical direction for burn-in)
while gradients stay exactly correct.

`interpret=True` runs the same kernel on CPU for tests.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _reference_attention(q, k, v, sm_scale: float, causal: bool):
    """Plain einsum attention; used for the custom-vjp backward and tests.

    Shapes: q, k, v are (heads_batch, seq, head_dim).
    """
    s = jnp.einsum("bqd,bkd->bqk", q, k).astype(jnp.float32) * sm_scale
    if causal:
        seq = q.shape[1]
        mask = jnp.tril(jnp.ones((seq, seq), jnp.bool_))
        s = jnp.where(mask[None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1).astype(v.dtype)
    return jnp.einsum("bqk,bkd->bqd", p, v)


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, m_ref, l_ref, acc_ref, *,
                  sm_scale: float, causal: bool,
                  block_q: int, block_k: int, num_k: int, seq_len: int):
    qi = pl.program_id(1)
    kj = pl.program_id(2)

    @pl.when(kj == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # skip tiles strictly above the causal diagonal
    run = (kj * block_k <= qi * block_q + block_q - 1) if causal else (kj >= 0)

    @pl.when(run)
    def _compute():
        q = q_ref[0]                       # (block_q, d)
        k = k_ref[0]                       # (block_k, d)
        v = v_ref[0]
        # Padding discipline: when seq_len is not a block multiple, Pallas
        # pads the trailing block with undefined data (NaN in interpret
        # mode). Padding key columns must be masked out of the softmax and
        # padding value rows zeroed, or NaN poisons every query row.
        rows = qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        cols = kj * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        k_valid = cols < seq_len
        v_rows_valid = (kj * block_k + jax.lax.broadcasted_iota(
            jnp.int32, v.shape, 0)) < seq_len
        v = jnp.where(v_rows_valid, v, jnp.zeros_like(v))
        k = jnp.where(v_rows_valid, k, jnp.zeros_like(k))
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale  # (bq, bk)
        mask = k_valid if not causal else (k_valid & (cols <= rows))
        s = jnp.where(mask, s, NEG_INF)
        m_prev = m_ref[:, :1]                                # (bq, 1)
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)                               # (bq, bk) f32
        alpha = jnp.exp(m_prev - m_new)                      # (bq, 1)
        l_ref[:, :1] = alpha * l_ref[:, :1] + jnp.sum(p, axis=1, keepdims=True)
        m_ref[:, :1] = m_new
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    last_k = (jnp.minimum((qi * block_q + block_q - 1) // block_k, num_k - 1)
              if causal else num_k - 1)

    @pl.when(kj == last_k)
    def _finalize():
        o_ref[0] = (acc_ref[:] / l_ref[:, :1]).astype(o_ref.dtype)


def _flash_3d(q, k, v, sm_scale: float, causal: bool,
              block_q: int, block_k: int, interpret: bool):
    """(heads_batch, seq, head_dim) flash attention via pallas_call."""
    hb, seq, d = q.shape
    block_q = min(block_q, seq)
    block_k = min(block_k, seq)
    num_q = pl.cdiv(seq, block_q)
    num_k = pl.cdiv(seq, block_k)
    kernel = functools.partial(
        _flash_kernel, sm_scale=sm_scale, causal=causal,
        block_q=block_q, block_k=block_k, num_k=num_k, seq_len=seq)
    return pl.pallas_call(
        kernel,
        grid=(hb, num_q, num_k),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((hb, seq, d), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), jnp.float32),  # running max
            pltpu.VMEM((block_q, 128), jnp.float32),  # running normalizer
            pltpu.VMEM((block_q, d), jnp.float32),    # output accumulator
        ],
        interpret=interpret,
    )(q, k, v)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def flash_attention(q, k, v, sm_scale: Optional[float] = None,
                    causal: bool = True, block_q: int = 128,
                    block_k: int = 128, interpret: bool = False):
    """Blockwise causal attention. q, k, v: (heads_batch, seq, head_dim)."""
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    return _flash_3d(q, k, v, sm_scale, causal, block_q, block_k, interpret)


def _fwd(q, k, v, sm_scale, causal, block_q, block_k, interpret):
    out = flash_attention(q, k, v, sm_scale, causal, block_q, block_k, interpret)
    return out, (q, k, v)


def _bwd(sm_scale, causal, block_q, block_k, interpret, residuals, d_out):
    q, k, v = residuals
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    # rematerialized reference backward: exact gradients, no kernel state
    _, vjp = jax.vjp(
        lambda q_, k_, v_: _reference_attention(q_, k_, v_, sm_scale, causal),
        q, k, v)
    return vjp(d_out)


flash_attention.defvjp(_fwd, _bwd)
