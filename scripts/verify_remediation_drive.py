"""End-to-end drive of the self-heal plane (PR 16).

Real daemon (cli.main subprocess) with --dra + status server, booted
under the r17 latency fault ($TDP_FAULTS kubeapi.request:delay) with
the remediation engine on by default:
  1. claim traffic under the fault burns the publish/prepare SLOs and
     latches breaches (the /status polls drive the evaluations)
  2. the remediation engine's BACKGROUND thread — never the scrape —
     applies the policy-gated knobs: pacer_backoff (+ the attach
     plane's admission_throttle once prepare_wall breaches too)
  3. /status remediation.* shows the active actions, counters moved
  4. /debug/remediation replays the audit ring; the applied entry
     carries the breach's exemplar trace id
  5. /debug/flight?trace=<that id> shows the remediation.action span
     on the SAME trace as the breaching kubeapi request — the one-query
     causal chain, daemon-local
  6. the tpu_plugin_remediation_* families are on /metrics
Prints REMEDIATION DRIVE PASS on success.
"""
import json
import os
import subprocess
import sys
import tempfile
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "tests"))

import grpc  # noqa: E402
from fakehost import FakeChip, FakeHost  # noqa: E402
from test_dra import FakeApiServer  # noqa: E402
from tpu_device_plugin.kubeletapi import draapi, drapb  # noqa: E402

root = tempfile.mkdtemp(prefix="vfyrem-", dir="/tmp")
fh = FakeHost(root)
for i in range(2):
    fh.add_chip(FakeChip(f"0000:00:{4 + i:02x}.0", device_id="0063",
                         iommu_group=str(10 + i)))
os.makedirs(os.path.join(root, "device-plugins"), exist_ok=True)
api = FakeApiServer()
port = 18191
env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu",
           NODE_NAME="node-a",
           # +300 ms on every apiserver round-trip: publish_rtt (and,
           # through the claim GET inside prepare, prepare_wall) burn
           TDP_FAULTS="kubeapi.request:delay:delay=0.3")
proc = subprocess.Popen(
    [sys.executable, "-m", "tpu_device_plugin", "--root", root,
     "--dra", "--api-server", api.url, "--status-port", str(port),
     "--health-poll-seconds", "0.3"],
    env=env, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)


def get(path):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=2) as r:
        body = r.read()
    return json.loads(body) if path != "/metrics" else body.decode()


def wait_for(pred, what, timeout=40):
    dl = time.time() + timeout
    while time.time() < dl:
        try:
            if pred():
                print(f"OK: {what}")
                return
        except Exception:
            pass
        time.sleep(0.3)
    raise SystemExit(f"FAIL: timeout waiting for {what}")


try:
    wait_for(lambda: get("/status"), "daemon up")
    wait_for(lambda: api.slices, "ResourceSlice published")
    # claim traffic: each prepare's claim GET pays the +300ms delay —
    # bad publish_rtt/prepare_wall samples that burn the SLO budget
    dra_sock = os.path.join(root, "plugins/cloud-tpus.google.com/dra.sock")
    with grpc.insecure_channel(f"unix://{dra_sock}") as ch:
        stub = draapi.DraPluginStub(ch)
        for i in range(6):
            api.add_claim("ns", f"vm{i}", f"uid-{i}",
                          "cloud-tpus.google.com",
                          [{"device": "d0000-00-04-0"}], generation=5)
            resp = stub.NodePrepareResources(
                drapb.NodePrepareResourcesRequest(claims=[
                    drapb.Claim(namespace="ns", name=f"vm{i}",
                                uid=f"uid-{i}")]), timeout=15)
            err = resp.claims[f"uid-{i}"].error
            if err:   # a typed shed IS the remediation throttle working
                assert "shed" in err, err
                print(f"OK: prepare uid-{i} shed with typed reason: "
                      f"{err!r}")
            stub.NodeUnprepareResources(
                drapb.NodeUnprepareResourcesRequest(claims=[
                    drapb.Claim(namespace="ns", name=f"vm{i}",
                                uid=f"uid-{i}")]), timeout=15)
    print("OK: claim traffic generated under the latency fault")
    wait_for(lambda: get("/status")["slo"]["objectives"]["publish_rtt"]
             ["breached"], "publish_rtt breach latched")
    wait_for(lambda: get("/status")["remediation"]["actions_total"] >= 1,
             "remediation engine acted (background tick)")
    st = get("/status")["remediation"]
    active = {a["action"] for a in st["active_actions"]}
    assert "pacer_backoff" in active, st
    print(f"OK: pacer_backoff active on /status (active={sorted(active)})")
    dbg = get("/debug/remediation")
    applied = [a for a in dbg["audit"] if a["status"] == "applied"]
    assert applied and applied[0]["trace_id"], dbg["audit"]
    tid = applied[0]["trace_id"]
    flight = get(f"/debug/flight?trace={tid}")
    ops = {s.get("op") for s in flight["spans"]}
    assert "remediation.action" in ops, ops
    print(f"OK: remediation.action span on the breach trace {tid[:8]}... "
          f"(ops={sorted(o for o in ops if o)})")
    m = get("/metrics")
    assert "tpu_plugin_remediation_actions_total" in m
    print("OK: tpu_plugin_remediation_actions_total on /metrics")
    print("REMEDIATION DRIVE PASS")
finally:
    proc.terminate()
    proc.wait(timeout=10)
    api.stop()
