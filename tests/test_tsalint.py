"""tsalint (tools/tsalint) unit tests: each rule must fire on a fixture
snippet that contains exactly the defect the rule exists for, stay quiet
on the corrected version, and the baseline must round-trip."""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tools.tsalint import (LintConfig, analyze_sources,  # noqa: E402
                           diff_against_baseline, load_baseline,
                           save_baseline)
from tools.tsalint.config import (BLOCKING_CALLS, BLOCKING_METHODS,  # noqa: E402
                                  CARRIERS, CarrierSpec,
                                  documented_carriers,
                                  documented_fault_sites,
                                  registered_fault_sites)


def run(source, *, hot=(), counters=None, registered=None, documented=None,
        path="mod.py", privileged=None, carriers=None, carrier_docs=None):
    cfg = LintConfig(
        hot_locks=frozenset(hot),
        counters=counters or {},
        blocking_calls=BLOCKING_CALLS,
        blocking_methods=BLOCKING_METHODS,
        registered_sites=registered,
        documented_sites=documented,
        privileged_modules=privileged,
        carriers=carriers,
        documented_carriers=carrier_docs,
    )
    return analyze_sources([(path, source)], cfg)


def rules(findings):
    return sorted({f.rule for f in findings})


# ------------------------------------------------------------- lock order


LOCK_INVERSION = """
import threading

class C:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()

    def one(self):
        with self._a:
            with self._b:
                pass

    def other(self):
        with self._b:
            with self._a:
                pass
"""


def test_lock_order_inversion_fires():
    findings = run(LOCK_INVERSION)
    assert rules(findings) == ["lock-order-cycle"]
    assert "mod.C._a" in findings[0].message
    assert "mod.C._b" in findings[0].message


def test_consistent_lock_order_is_clean():
    clean = LOCK_INVERSION.replace(
        "with self._b:\n            with self._a:",
        "with self._a:\n            with self._b:")
    assert run(clean) == []


INTERPROCEDURAL_INVERSION = """
import threading

class C:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()

    def helper(self):
        with self._b:
            pass

    def one(self):
        with self._a:
            self.helper()        # a -> b, via the call graph

    def other(self):
        with self._b:
            with self._a:
                pass             # b -> a: cycle
"""


def test_lock_order_sees_through_method_calls():
    findings = run(INTERPROCEDURAL_INVERSION)
    assert rules(findings) == ["lock-order-cycle"]


SELF_DEADLOCK = """
import threading

class C:
    def __init__(self):
        self._a = threading.Lock()

    def helper(self):
        with self._a:
            pass

    def outer(self):
        with self._a:
            self.helper()        # plain Lock re-entered: self-deadlock
"""


def test_plain_lock_self_reentry_fires_and_rlock_does_not():
    assert rules(run(SELF_DEADLOCK)) == ["lock-order-cycle"]
    rlock = SELF_DEADLOCK.replace("threading.Lock()", "threading.RLock()")
    assert run(rlock) == []


# -------------------------------------------------------- blocking calls


BLOCKING_UNDER_HOT = """
import os
import threading
import time

class C:
    def __init__(self):
        self._lock = threading.Lock()

    def bad(self):
        with self._lock:
            time.sleep(1)

    def also_bad(self):
        with self._lock:
            os.listdir("/dev")

    def fine(self):
        with self._lock:
            x = 1
        time.sleep(1)
        return x
"""


def test_blocking_under_hot_lock_fires():
    findings = run(BLOCKING_UNDER_HOT, hot={"mod.C._lock"})
    assert rules(findings) == ["blocking-under-hot-lock"]
    assert {f.qualname for f in findings} == {"mod.C.bad", "mod.C.also_bad"}


def test_blocking_needs_hot_designation():
    # same code, lock not designated hot: quiet (LiveAttrReader-style
    # by-design small I/O under a private lock stays legal)
    assert run(BLOCKING_UNDER_HOT) == []


BLOCKING_VIA_HELPER = """
import threading

class C:
    def __init__(self):
        self._lock = threading.Lock()

    def _write(self, path, data):
        with open(path, "w") as f:
            f.write(data)

    def bad(self):
        with self._lock:
            self._write("/tmp/x", "y")
"""


def test_blocking_propagates_through_helpers():
    findings = run(BLOCKING_VIA_HELPER, hot={"mod.C._lock"})
    assert any(f.qualname == "mod.C.bad" for f in findings)


# -------------------------------------------------------------- counters


COUNTER_NO_LOCK = """
import threading

class C:
    def __init__(self):
        self._lock = threading.Lock()
        self.hits = 0
        self.stats = {"writes": 0}

    def good(self):
        with self._lock:
            self.hits += 1
            self.stats["writes"] += 1

    def bad(self):
        self.hits += 1

    def bad_dict(self):
        self.stats["writes"] = self.stats["writes"] + 1
"""


def test_counter_mutation_requires_owning_lock():
    counters = {"mod.C": {"hits": "mod.C._lock",
                          "stats[*]": "mod.C._lock"}}
    findings = run(COUNTER_NO_LOCK, counters=counters)
    assert rules(findings) == ["counter-lock"]
    assert {f.qualname for f in findings} == {"mod.C.bad", "mod.C.bad_dict"}


COUNTER_IN_SUBCLASS = """
import threading

class Base:
    def __init__(self):
        self._lock = threading.Lock()
        self.hits = 0

class Sub(Base):
    def good(self):
        with self._lock:
            self.hits += 1

    def bad(self):
        self.hits += 1
"""


def test_counter_rule_follows_inheritance():
    """vtpu.VtpuDevicePlugin mutates server.TpuDevicePlugin's counters
    under the BASE class's locks: both the lock attr and the counter
    config must resolve through the bases."""
    counters = {"mod.Base": {"hits": "mod.Base._lock"}}
    findings = run(COUNTER_IN_SUBCLASS, counters=counters)
    assert [f.qualname for f in findings] == ["mod.Sub.bad"]


COUNTER_VIA_PRIVATE_HELPER = """
import threading

class C:
    def __init__(self):
        self._lock = threading.Lock()
        self.hits = 0

    def _bump(self):
        self.hits += 1      # only ever called under the lock

    def entry(self):
        with self._lock:
            self._bump()
"""


def test_counter_in_helper_called_under_lock_is_clean():
    counters = {"mod.C": {"hits": "mod.C._lock"}}
    assert run(COUNTER_VIA_PRIVATE_HELPER, counters=counters) == []


LOCKFREE_COUNTER_PLAIN_MUTATION = """
import threading

class C:
    def __init__(self):
        self._lock = threading.Lock()
        self.reused = AtomicCounter()

    def bad(self):
        self.reused += 1

    def also_bad(self):
        with self._lock:
            self.reused = self.reused + 1
"""

LOCKFREE_COUNTER_CLEAN = """
class C:
    def __init__(self):
        self.reused = AtomicCounter()

    def good(self):
        self.reused.add()
"""


def test_lockfree_counter_plain_mutation_fires():
    """Round 15: a counter registered with the LOCKFREE sentinel is
    epoch.AtomicCounter-owned — ANY plain attribute mutation is a
    finding, even under a lock (re-locking a lock-free counter is as
    wrong as mutating it bare)."""
    from tools.tsalint.config import LOCKFREE
    counters = {"mod.C": {"reused": LOCKFREE}}
    findings = run(LOCKFREE_COUNTER_PLAIN_MUTATION, counters=counters)
    assert rules(findings) == ["counter-lock"]
    assert {f.qualname for f in findings} == {"mod.C.bad", "mod.C.also_bad"}
    assert "AtomicCounter" in findings[0].message


def test_lockfree_counter_add_is_clean():
    from tools.tsalint.config import LOCKFREE
    counters = {"mod.C": {"reused": LOCKFREE}}
    assert run(LOCKFREE_COUNTER_CLEAN, counters=counters) == []


# ------------------------------------------------------------ fault sites


FIRE_SITES = """
from . import faults

class C:
    def good(self):
        faults.fire("known.site")

    def bad(self):
        faults.fire("typo.site")
"""


def test_unregistered_fire_site_fires():
    findings = run(FIRE_SITES, registered={"known.site"},
                   documented={"known.site"})
    assert rules(findings) == ["fault-site"]
    assert any("typo.site" in f.message for f in findings)


def test_undocumented_and_dead_sites_fire():
    findings = run(FIRE_SITES, registered={"known.site", "dead.site"},
                   documented=set())
    details = {f.detail for f in findings}
    assert "undocumented:known.site" in details
    assert "dead:dead.site" in details


# ---------------------------------------------------------------- threads


THREAD_BAD = """
import threading

class C:
    def spawn(self):
        threading.Thread(target=self.run).start()

    def run(self):
        pass
"""

THREAD_GOOD = """
import threading

class C:
    def __init__(self):
        self._thread = None

    def spawn(self):
        self._thread = threading.Thread(target=self.run, daemon=True)
        self._thread.start()

    def run(self):
        pass

    def stop(self):
        if self._thread is not None:
            self._thread.join(timeout=2)
"""


def test_unjoined_undaemonized_thread_fires():
    findings = run(THREAD_BAD)
    assert rules(findings) == ["thread-lifecycle"]
    details = {f.detail for f in findings}
    assert details == {"not-daemon:Thread", "not-joined:Thread"}


def test_tracked_daemon_joined_thread_is_clean():
    assert run(THREAD_GOOD) == []


TWO_THREADS_ONE_JOINED = """
import threading

class C:
    def __init__(self):
        self._a = None
        self._b = None

    def spawn(self):
        self._a = threading.Thread(target=self.run, daemon=True)
        self._a.start()
        self._b = threading.Thread(target=self.run, daemon=True)
        self._b.start()

    def run(self):
        pass

    def stop(self):
        if self._a is not None:
            self._a.join(timeout=2)
        if self._b is not None:   # read but NEVER joined
            pass
"""


def test_join_evidence_is_per_attribute():
    """A sibling thread's join must not vouch for an unjoined one: the
    evidence is per attribute, not per class."""
    findings = run(TWO_THREADS_ONE_JOINED)
    assert [f.detail for f in findings] == ["not-joined:Thread"]
    assert all("self._b" not in f.message for f in findings)


THREAD_JOINED_VIA_SWAP = """
import threading

class C:
    def __init__(self):
        self._thread = None

    def spawn(self):
        self._thread = threading.Thread(target=self.run, daemon=True)
        self._thread.start()

    def run(self):
        pass

    def stop(self):
        thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(timeout=2)
"""


def test_join_through_teardown_swap_alias_counts():
    """healthhub.stop's `thread, self._thread = self._thread, None` form:
    the local alias's join must credit the attribute."""
    assert run(THREAD_JOINED_VIA_SWAP) == []


NONALPHABETIC_CYCLE = """
import threading

class C:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()
        self._c = threading.Lock()

    def one(self):
        with self._a:
            with self._c:
                pass

    def two(self):
        with self._c:
            with self._b:
                pass

    def three(self):
        with self._b:
            with self._a:
                pass
"""


def test_cycle_rendered_in_actual_edge_order():
    """Edges a->c, c->b, b->a: the arc must follow REAL edges (a->c->b->a),
    not the sorted SCC (a->b->c->a names edges nobody takes), and the
    finding must anchor to a real source line, not a <graph> fallback."""
    findings = run(NONALPHABETIC_CYCLE)
    assert len(findings) == 1
    f = findings[0]
    assert f.detail == "mod.C._a -> mod.C._c -> mod.C._b -> mod.C._a"
    assert f.path == "mod.py" and f.line > 0 and f.qualname == "mod.C.one"


TIMER_CANCELLED = """
import threading

class C:
    def __init__(self):
        self._timer = None

    def arm(self):
        t = threading.Timer(5.0, self.firefn)
        t.daemon = True
        self._timer = t
        t.start()

    def firefn(self):
        pass

    def stop(self):
        if self._timer is not None:
            self._timer.cancel()
"""


def test_timer_cancel_counts_as_reaping():
    assert run(TIMER_CANCELLED) == []


# --------------------------------------------------------------- baseline


def test_baseline_round_trip(tmp_path):
    findings = run(LOCK_INVERSION)
    assert findings
    path = str(tmp_path / "baseline.json")
    save_baseline(path, findings)
    baseline = load_baseline(path)
    assert len(baseline) == len(findings)
    new, stale = diff_against_baseline(findings, baseline)
    assert new == [] and stale == []
    # the same defect reported from a shifted line is STILL baselined
    shifted = run("\n\n\n" + LOCK_INVERSION)
    new, stale = diff_against_baseline(shifted, baseline)
    assert new == []
    # a fixed defect shows up as stale debt, a fresh one as new
    clean = LOCK_INVERSION.replace(
        "with self._b:\n            with self._a:",
        "with self._a:\n            with self._b:")
    new, stale = diff_against_baseline(run(clean), baseline)
    assert new == [] and len(stale) == 1
    new, _ = diff_against_baseline(
        run(BLOCKING_UNDER_HOT, hot={"mod.C._lock"}), baseline)
    assert new


def test_missing_baseline_is_empty(tmp_path):
    assert load_baseline(str(tmp_path / "absent.json")) == {}


# --------------------------------------------------- project-level inputs


def test_registered_sites_parsed_from_faults_py():
    with open(os.path.join(REPO, "tpu_device_plugin", "faults.py")) as f:
        sites = registered_fault_sites(f.read())
    assert "kubelet.register" in sites
    assert "checkpoint.write" in sites


def test_documented_sites_parsed_from_doc():
    with open(os.path.join(REPO, "docs", "fault-injection.md")) as f:
        sites = documented_fault_sites(f.read())
    assert "dra.publish" in sites
    assert "native.probe" in sites


def test_project_tree_is_clean_against_baseline():
    """The repo's own gate: scripts/lint_concurrency.py must exit 0 — any
    new concurrency-lint finding in the package fails tier-1 right here,
    not just in the CI lint job."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts",
                                      "lint_concurrency.py")],
        capture_output=True, text=True, cwd=REPO)
    assert proc.returncode == 0, proc.stdout + proc.stderr


@pytest.mark.parametrize("flag", ["--list"])
def test_cli_list_mode_runs(flag):
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts",
                                      "lint_concurrency.py"), flag],
        capture_output=True, text=True, cwd=REPO)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "tsalint:" in proc.stdout


# ---------------------------------------------------------- epoch-mutation


EPOCH_ATTR_WRITE = """
def poison(store):
    ep = store.current
    ep.epoch_id = 99
"""

EPOCH_DICT_WRITE = """
def poison(store):
    ep = store.current
    ep.device_health["0000:00:04.0"] = "Unhealthy"
"""

EPOCH_MUTATOR_CALL = """
def poison(store):
    ep = store.current
    ep.device_health.update({"x": "Unhealthy"})
"""

EPOCH_PARAM_WRITE = """
def assemble(ep):
    ep.lw_payload = b"stale"
"""

EPOCH_ATTR_CHAIN_WRITE = """
class C:
    def flip(self):
        self._inv_epoch.unhealthy.add("dead")
"""

EPOCH_CLEAN_READS = """
def serve(store):
    ep = store.current
    health = dict(ep.device_health)
    n = len(ep.device_health)
    return ep.epoch_id, health, n
"""

EPOCH_REBOUND_LOCAL = """
def fine(store):
    ep = store.current
    ep = {}          # rebinding releases the alias...
    ep["k"] = 1      # ...but the NAME stays epoch-like by convention
"""


def test_epoch_attr_write_fires():
    findings = run(EPOCH_ATTR_WRITE)
    assert rules(findings) == ["epoch-mutation"]
    assert "ep.epoch_id" in findings[0].message


def test_epoch_dict_write_fires():
    findings = run(EPOCH_DICT_WRITE)
    assert rules(findings) == ["epoch-mutation"]


def test_epoch_mutator_call_fires():
    findings = run(EPOCH_MUTATOR_CALL)
    assert rules(findings) == ["epoch-mutation"]
    assert findings[0].detail.endswith("update()")


def test_epoch_param_write_fires():
    findings = run(EPOCH_PARAM_WRITE)
    assert rules(findings) == ["epoch-mutation"]


def test_epoch_attr_chain_write_fires():
    findings = run(EPOCH_ATTR_CHAIN_WRITE)
    assert rules(findings) == ["epoch-mutation"]


def test_epoch_reads_are_clean():
    assert run(EPOCH_CLEAN_READS) == []


def test_epoch_name_convention_still_guards_rebound_local():
    # the name-based net is deliberately wider than the alias tracking:
    # a local NAMED ep stays treated as an epoch even after rebinding
    findings = run(EPOCH_REBOUND_LOCAL)
    assert rules(findings) == ["epoch-mutation"]


def test_epoch_builder_module_is_exempt():
    # the same mutation inside epoch.py (the builder) is the one place
    # allowed to assemble epoch state
    assert run(EPOCH_DICT_WRITE, path="tpu_device_plugin/epoch.py") == []


def test_epoch_unrelated_writes_are_clean():
    src = """
class C:
    def bump(self):
        self._fds["k"] = 3
        self.counter = self.counter + 1
        self._unhealthy.add("x")
"""
    assert run(src) == []


EPOCH_CURRENT_CHAIN_WRITE = """
class C:
    def poison(self):
        self._store.current.device_health["x"] = "Unhealthy"

def poison2(store):
    store.current.lw_payload = b"stale"

def poison3(store):
    store.current.device_health.update({"x": "Unhealthy"})
"""


def test_epoch_current_chain_write_fires_without_alias():
    # the most direct mutation shape — straight through `.current`, no
    # intermediate local for the alias tracking to catch
    findings = run(EPOCH_CURRENT_CHAIN_WRITE)
    assert rules(findings) == ["epoch-mutation"]
    assert len(findings) == 3


# ---------------------------------------------------------- trace plane


TRACE_SPAN_ON_EPOCH_READ_PATH = """
import threading
from tpu_device_plugin import lockdep, trace

class Server:
    def __init__(self, store):
        self._store = store
        self._cond = lockdep.instrument(
            "mod.Server._cond", threading.Condition())

    def allocate(self, request):
        ep = self._store.current
        with lockdep.read_path("server.Allocate"), trace.span(
                "server.Allocate", histogram="tdp_attach_wall_ms",
                epoch_id=ep.epoch_id, devices=len(ep.device_health)):
            trace.event("allocate.fragment.rebuild", group="g0")
            return list(ep.device_health)

    def commit(self):
        # spans may wrap work under a HOT lock too: trace takes no
        # registered lock and makes no blocking call
        with self._cond:
            with trace.span("dra.checkpoint.commit", claims=1):
                pass
"""


def test_span_on_epoch_read_path_trips_no_rule():
    """ISSUE 8 fixture: instrumenting an epoch read path (span attrs
    READ the epoch; the span itself takes no registered lock and makes
    no blocking call) must not fire epoch-mutation, blocking-under-hot-
    lock, lock-order, or counter findings — the tracing plane is lint-
    invisible by design (docs/observability.md)."""
    findings = run(TRACE_SPAN_ON_EPOCH_READ_PATH,
                   hot={"mod.Server._cond"})
    assert findings == []


TRACE_EPOCH_MUTATION_STILL_FIRES = """
from tpu_device_plugin import trace

def bad(store):
    ep = store.current
    with trace.span("server.Allocate"):
        ep.device_health["x"] = "Unhealthy"
"""


def test_epoch_mutation_inside_span_still_fires():
    # the span context must not LAUNDER a real epoch mutation
    findings = run(TRACE_EPOCH_MUTATION_STILL_FIRES)
    assert rules(findings) == ["epoch-mutation"]


# --------------------------------------------------------- broker-boundary


PRIV_DEV_OPEN = """
import os

def grab_group(group):
    return os.open("/dev/vfio/" + group, os.O_RDWR)
"""

PRIV_DEV_OPEN_VIA_DEV_PATH = """
def grab(cfg, group):
    return open(cfg.dev_path("dev/vfio", group))
"""

PRIV_REBIND_WRITE = """
def rebind(bdf):
    with open("/sys/bus/pci/drivers/vfio-pci/unbind", "w") as f:
        f.write(bdf)
"""

PRIV_CONFIG_READ = """
def probe(config_path):
    with open(config_path, "rb") as f:
        return f.read(2)
"""

PRIV_CONFIG_LITERAL = """
def probe(base):
    with open(base + "/config", "rb") as f:
        return f.read(2)
"""

INNOCUOUS_OPENS = """
import os

def fine(checkpoint_path, reconfigure_path):
    with open(checkpoint_path, "w") as f:
        f.write("{}")
    with open(reconfigure_path) as f:
        data = f.read()
    # read-mode open of a bind-named path is not a rebind write
    with open("/sys/bus/pci/drivers/vfio-pci/bind") as f:
        return f.read(), data
"""

WHITELIST = frozenset({"broker.py", "discovery.py"})


def test_broker_boundary_device_node_open_fires():
    for fixture in (PRIV_DEV_OPEN, PRIV_DEV_OPEN_VIA_DEV_PATH):
        findings = run(fixture, privileged=WHITELIST)
        assert rules(findings) == ["broker-boundary"], fixture
        assert "device-node-open" in findings[0].detail


def test_broker_boundary_rebind_write_fires():
    findings = run(PRIV_REBIND_WRITE, privileged=WHITELIST)
    assert rules(findings) == ["broker-boundary"]
    assert findings[0].detail == "sysfs-rebind-write:unbind"


def test_broker_boundary_config_space_read_fires():
    for fixture in (PRIV_CONFIG_READ, PRIV_CONFIG_LITERAL):
        findings = run(fixture, privileged=WHITELIST)
        assert rules(findings) == ["broker-boundary"], fixture
        assert findings[0].detail == "config-space-read:config"


def test_broker_boundary_whitelisted_seam_is_clean():
    """The SAME privileged calls inside a whitelisted seam file pass —
    the clean variant of every fire fixture."""
    for fixture in (PRIV_DEV_OPEN, PRIV_REBIND_WRITE, PRIV_CONFIG_READ):
        assert run(fixture, path="pkg/broker.py",
                   privileged=WHITELIST) == []
    assert run(PRIV_CONFIG_READ, path="pkg/discovery.py",
               privileged=WHITELIST) == []


def test_broker_boundary_innocuous_opens_are_clean():
    assert run(INNOCUOUS_OPENS, privileged=WHITELIST) == []


def test_broker_boundary_disabled_without_whitelist():
    assert run(PRIV_DEV_OPEN, privileged=None) == []


def test_broker_boundary_project_whitelist_names_the_seams():
    from tools.tsalint.config import PRIVILEGED_SEAMS
    assert PRIVILEGED_SEAMS == {
        "tpu_device_plugin/broker.py",
        "tpu_device_plugin/discovery.py",
        "tpu_device_plugin/native/__init__.py",
    }


THREAD_LIST_TRACKED = """
import threading

class Pool:
    def __init__(self):
        self._threads = []

    def spawn(self, n):
        for _ in range(n):
            thread = threading.Thread(target=self.run, daemon=True)
            self._threads.append(thread)
            thread.start()

    def run(self):
        pass

    def stop(self):
        for thread in self._threads:
            thread.join(timeout=5)
"""


def test_thread_list_append_plus_loop_join_is_clean():
    """The tracked-thread-LIST pattern (ISSUE 12, autopilot worker
    pools): threads appended to one attribute and joined by a stop
    path looping that attribute are reaped — no finding."""
    assert run(THREAD_LIST_TRACKED) == []


def test_thread_list_without_loop_join_still_fires():
    leaked = THREAD_LIST_TRACKED.replace(
        "        for thread in self._threads:\n"
        "            thread.join(timeout=5)",
        "        pass")
    findings = run(leaked)
    assert [f.detail for f in findings] == ["not-joined:Thread"]


def test_thread_list_join_over_other_attr_does_not_vouch():
    """Looping a DIFFERENT list must not credit the tracked one."""
    wrong = THREAD_LIST_TRACKED.replace(
        "for thread in self._threads:",
        "for thread in self._others:")
    findings = run(wrong)
    assert [f.detail for f in findings] == ["not-joined:Thread"]


# ----------------------------------------------------- trace-carrier (r8)


MULTICLAIM_SPEC = (CarrierSpec(
    name="multiclaim.traceparent", kind="call-kwarg",
    field="traceparent", call="multiclaim_begin", arg_index=3),)

RECORD_SPEC = (CarrierSpec(
    name="rec.traceparent", kind="dict-key", field="traceparent",
    markers=frozenset({"source_node", "generation"})),)

FRAME_SPEC = (CarrierSpec(
    name="frame.span", kind="dict-key", field="span",
    markers=frozenset({"op", "seq"})),)

HEADER_SPEC = (CarrierSpec(
    name="header.traceparent", kind="header-store", field="Traceparent"),)


def _docs(specs):
    return {s.name for s in specs}


CARRIER_CALL_BARE = """
def begin(api, uid, plan):
    api.multiclaim_begin(uid, plan.shape, plan.shards)
"""

CARRIER_CALL_KWARG = """
def begin(api, uid, plan, tp):
    api.multiclaim_begin(uid, plan.shape, plan.shards, traceparent=tp)
"""

CARRIER_CALL_POSITIONAL = """
def begin(api, uid, plan, tp):
    api.multiclaim_begin(uid, plan.shape, plan.shards, tp)
"""

CARRIER_CALL_EXPLICIT_NONE = """
def begin(api, uid, plan):
    api.multiclaim_begin(uid, plan.shape, plan.shards, traceparent=None)
"""


def test_carrier_call_without_context_fires():
    findings = run(CARRIER_CALL_BARE, carriers=MULTICLAIM_SPEC,
                   carrier_docs=_docs(MULTICLAIM_SPEC))
    assert [f.detail for f in findings] == \
        ["unthreaded:multiclaim.traceparent"]
    assert "multiclaim_begin()" in findings[0].message


def test_carrier_call_threaded_is_clean():
    for fixture in (CARRIER_CALL_KWARG, CARRIER_CALL_POSITIONAL):
        assert run(fixture, carriers=MULTICLAIM_SPEC,
                   carrier_docs=_docs(MULTICLAIM_SPEC)) == [], fixture


def test_carrier_call_explicit_none_fires():
    # traceparent=None is dropping the context on purpose, not threading
    findings = run(CARRIER_CALL_EXPLICIT_NONE, carriers=MULTICLAIM_SPEC,
                   carrier_docs=_docs(MULTICLAIM_SPEC))
    assert [f.detail for f in findings] == \
        ["unthreaded:multiclaim.traceparent"]


CARRIER_RECORD_BARE = """
class D:
    def emit(self, entry):
        self._records[entry.uid] = {
            "source_node": self.node_name,
            "generation": entry.get("generation"),
        }
"""

CARRIER_RECORD_STAMPED = """
class D:
    def emit(self, entry, tp):
        self._records[entry.uid] = {
            "source_node": self.node_name,
            "generation": entry.get("generation"),
            "traceparent": tp,
        }
"""

CARRIER_RECORD_NONE = CARRIER_RECORD_STAMPED.replace(
    '"traceparent": tp,', '"traceparent": None,')

CARRIER_RECORD_LATE_STAMP = """
class D:
    def emit(self, entry, tp):
        rec = {
            "source_node": self.node_name,
            "generation": entry.get("generation"),
        }
        rec["traceparent"] = tp
        self._records[entry.uid] = rec
"""

CARRIER_RECORD_WRAPPER_STAMP = """
class D:
    def _base_record(self, entry):
        return {
            "source_node": self.node_name,
            "generation": entry.get("generation"),
        }

    def emit(self, entry, tp):
        rec = self._base_record(entry)
        rec["traceparent"] = tp
        return rec
"""

CARRIER_RECORD_WRAPPER_LEAK = CARRIER_RECORD_WRAPPER_STAMP + """
    def emit_bare(self, entry):
        return self._base_record(entry)
"""


def test_carrier_record_without_field_fires():
    findings = run(CARRIER_RECORD_BARE, carriers=RECORD_SPEC,
                   carrier_docs=_docs(RECORD_SPEC))
    assert [f.detail for f in findings] == ["unthreaded:rec.traceparent"]
    assert "generation, source_node" in findings[0].message


def test_carrier_record_stamped_is_clean():
    for fixture in (CARRIER_RECORD_STAMPED, CARRIER_RECORD_LATE_STAMP):
        assert run(fixture, carriers=RECORD_SPEC,
                   carrier_docs=_docs(RECORD_SPEC)) == [], fixture


def test_carrier_record_none_field_fires():
    findings = run(CARRIER_RECORD_NONE, carriers=RECORD_SPEC,
                   carrier_docs=_docs(RECORD_SPEC))
    assert [f.detail for f in findings] == ["unthreaded:rec.traceparent"]


def test_carrier_record_wrapper_stamp_is_clean():
    """The interprocedural credit: a record BUILDER stays clean when
    every resolved caller stamps the context field after the call —
    the wrapper fixpoint, not just same-function subscript stores."""
    assert run(CARRIER_RECORD_WRAPPER_STAMP, carriers=RECORD_SPEC,
               carrier_docs=_docs(RECORD_SPEC)) == []


def test_carrier_record_wrapper_leak_fires():
    """...and ONE caller that forwards the record without stamping
    un-credits the builder (all-callers quantifier, not any-caller)."""
    findings = run(CARRIER_RECORD_WRAPPER_LEAK, carriers=RECORD_SPEC,
                   carrier_docs=_docs(RECORD_SPEC))
    assert [f.detail for f in findings] == ["unthreaded:rec.traceparent"]
    assert findings[0].qualname == "mod.D._base_record"


CARRIER_FRAME_SHAPES = """
class Client:
    def request(self, op, tp):
        self._seq += 1
        req = {"op": op, "seq": self._seq, "span": tp}
        return req

    def synthesized(self, i):
        # constant-op frame: an injected placeholder, not a crossing
        return {"op": "invalid", "seq": i}

    def spread(self, base):
        # a ** spread makes the literal opaque: absence is unprovable
        return {**base, "op": self._op, "seq": self._seq}
"""


def test_carrier_frame_const_and_spread_are_not_crossings():
    assert run(CARRIER_FRAME_SHAPES, carriers=FRAME_SPEC,
               carrier_docs=_docs(FRAME_SPEC)) == []


def test_carrier_frame_without_span_fires():
    broken = CARRIER_FRAME_SHAPES.replace(', "span": tp', "")
    findings = run(broken, carriers=FRAME_SPEC,
                   carrier_docs=_docs(FRAME_SPEC))
    assert [f.detail for f in findings] == ["unthreaded:frame.span"]


def test_carrier_scope_limits_detection():
    scoped = (CarrierSpec(
        name="frame.span", kind="dict-key", field="span",
        markers=frozenset({"op", "seq"}),
        scope=frozenset({"pkg/broker.py"})),)
    broken = CARRIER_FRAME_SHAPES.replace(', "span": tp', "")
    # out of scope: the decode-side twin of the frame shape is not a
    # crossing — but the carrier is then dead (nothing crossed it)
    findings = run(broken, path="pkg/brokeripc.py", carriers=scoped,
                   carrier_docs=_docs(scoped))
    assert [f.detail for f in findings] == ["dead:frame.span"]
    findings = run(broken, path="pkg/broker.py", carriers=scoped,
                   carrier_docs=_docs(scoped))
    assert [f.detail for f in findings] == ["unthreaded:frame.span"]


CARRIER_HEADER_STORE = """
def request(headers, tp):
    headers["Traceparent"] = tp
"""


def test_carrier_header_store_is_the_crossing():
    assert run(CARRIER_HEADER_STORE, carriers=HEADER_SPEC,
               carrier_docs=_docs(HEADER_SPEC)) == []


def test_carrier_header_missing_everywhere_is_dead():
    findings = run("def request(headers, tp):\n    pass\n",
                   carriers=HEADER_SPEC, carrier_docs=_docs(HEADER_SPEC))
    assert [f.detail for f in findings] == ["dead:header.traceparent"]


def test_carrier_doc_drift_fires_both_ways():
    # registered but not documented; documented but not registered
    findings = run(CARRIER_CALL_KWARG, carriers=MULTICLAIM_SPEC,
                   carrier_docs={"ghost.carrier"})
    assert sorted(f.detail for f in findings) == [
        "undeclared:ghost.carrier",
        "undocumented:multiclaim.traceparent"]


def test_carrier_rule_disabled_without_registry():
    assert run(CARRIER_CALL_BARE, carriers=None) == []


def test_documented_carriers_parsed_from_doc():
    with open(os.path.join(REPO, "docs", "observability.md")) as f:
        ids = documented_carriers(f.read())
    assert ids == {s.name for s in CARRIERS}


def test_project_carriers_name_the_r17_boundaries():
    kinds = {s.name: s.kind for s in CARRIERS}
    assert kinds == {
        "multiclaim.traceparent": "call-kwarg",
        "checkpoint-entry.traceparent": "dict-key",
        "handoff.traceparent": "dict-key",
        "broker-frame.span": "dict-key",
        "kubeapi.traceparent-header": "header-store",
    }


def test_carrier_mutation_on_real_tree_fires():
    """Mutation-test rule 8 against the REAL package: strip the span
    field from the broker client's request frame and the traceparent
    kwarg from a fabric multiclaim_begin call — each mutation must
    produce a new trace-carrier finding (a rule that cannot fire on the
    production crossing sites is a failing test)."""
    from tools.tsalint.config import (CARRIERS as REAL_CARRIERS,
                                      documented_carriers as parse_docs)
    with open(os.path.join(REPO, "docs", "observability.md")) as f:
        docs = parse_docs(f.read())

    def lint(path, text):
        cfg = LintConfig(carriers=REAL_CARRIERS, documented_carriers=docs)
        return [f for f in analyze_sources([(path, text)], cfg)
                if f.rule == "trace-carrier"
                and f.detail.startswith("unthreaded:")]

    broker_path = "tpu_device_plugin/broker.py"
    with open(os.path.join(REPO, broker_path)) as f:
        broker_src = f.read()
    assert lint(broker_path, broker_src) == []
    mutated = broker_src.replace(
        '"span": brokeripc.span_context()}', '}')
    assert mutated != broker_src
    assert [f.detail for f in lint(broker_path, mutated)] == \
        ["unthreaded:broker-frame.span"]

    fleetsim_path = "tpu_device_plugin/fleetsim.py"
    with open(os.path.join(REPO, fleetsim_path)) as f:
        fleetsim_src = f.read()
    assert lint(fleetsim_path, fleetsim_src) == []
    mutated = fleetsim_src.replace(
        "self.apiserver.multiclaim_begin(uid, plan.shape, plan.shards,\n"
        "                                        "
        "traceparent=trace.propagate())",
        "self.apiserver.multiclaim_begin(uid, plan.shape, plan.shards)")
    assert mutated != fleetsim_src
    assert [f.detail for f in lint(fleetsim_path, mutated)] == \
        ["unthreaded:multiclaim.traceparent"]
