"""Full-daemon verify drive: registration + DRA + health prune/restore.

The repo's canonical build-and-drive check (`make verify-drive`): launch
the real daemon against a fake host, drive it as the kubelet would
(tests/kubelet_sim.py), and assert the end-to-end health loop — a deleted
vfio group node prunes the chip from both the ListAndWatch stream and the
published ResourceSlice; recreating it restores both. Exit 0 iff every
stage passed.
"""
import json
import os
import subprocess
import sys
import tempfile
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "tests"))

from fakehost import FakeChip, FakeHost  # noqa: E402
from kubelet_sim import DeviceManagerSim  # noqa: E402
from test_dra import FakeApiServer  # noqa: E402

root = tempfile.mkdtemp(prefix="vfy-", dir="/tmp")
fh = FakeHost(root)
for i in range(4):
    fh.add_chip(FakeChip(f"0000:00:{4 + i:02x}.0", device_id="0063",
                         iommu_group=str(10 + i), numa_node=i // 2))

os.makedirs(os.path.join(root, "device-plugins"), exist_ok=True)
sim = DeviceManagerSim(os.path.join(root, "device-plugins"))
api = FakeApiServer()

port = 18123
env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu",
           NODE_NAME="node-a")
proc = subprocess.Popen(
    [sys.executable, "-m", "tpu_device_plugin", "--root", root,
     "--dra", "--api-server", api.url, "--status-port", str(port),
     "--health-poll-seconds", "0.3", "-v"],
    env=env, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)


def status():
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/status", timeout=2) as r:
        return json.load(r)


def wait_for(pred, what, timeout=30):
    dl = time.time() + timeout
    while time.time() < dl:
        try:
            if pred():
                print(f"OK: {what}")
                return
        except Exception:
            pass
        time.sleep(0.25)
    raise SystemExit(f"FAIL: timeout waiting for {what}")


try:
    wait_for(lambda: status(), "daemon up (/status serving)")
    rname = None

    def have_resource():
        global rname
        eps = list(sim.endpoints)
        if eps:
            rname = eps[0]
            return sim.endpoints[rname].updates > 0
        return False

    wait_for(have_resource, "plugin registered + ListAndWatch streaming")
    wait_for(lambda: sim.allocatable(rname) == 4, "4 healthy devices")
    wait_for(lambda: api.slices, "ResourceSlice published")
    obj = next(iter(api.slices.values()))
    devs = [d["name"] for d in obj["spec"]["devices"]]
    assert len(devs) == 4, devs
    print("OK: slice has 4 devices:", devs)

    ids, resp = sim.admit_pod(rname, 2)
    nspecs = len(resp.container_responses[0].devices)
    assert nspecs >= 2, nspecs
    print(f"OK: pod admission allocated {ids} -> {nspecs} device specs")

    victim = os.path.join(root, "dev/vfio/10")
    os.unlink(victim)
    wait_for(lambda: status()["dra"]["unhealthy_devices"],
             "DRA prunes dead chip", timeout=20)
    wait_for(lambda: sim.allocatable(rname) == 3,
             "kubelet sees 3 healthy after fault")
    wait_for(lambda: len(next(iter(api.slices.values()))
                         ["spec"]["devices"]) == 3,
             "slice devices -> 3 after prune")
    with open(victim, "w"):
        pass
    wait_for(lambda: not status()["dra"]["unhealthy_devices"],
             "chip restored after node recreate", timeout=20)
    wait_for(lambda: len(next(iter(api.slices.values()))
                         ["spec"]["devices"]) == 4,
             "slice devices -> 4 after restore")
    wait_for(lambda: sim.allocatable(rname) == 4,
             "kubelet sees 4 healthy again")
    print("VERIFY PASS")
finally:
    proc.terminate()
    try:
        proc.wait(timeout=10)
    except subprocess.TimeoutExpired:
        proc.kill()
    api.stop()
