"""broker — the privilege-separated VFIO/sysfs/iommufd broker.

ROADMAP item 1, Glider-style (PAPERS.md): the daemon used to hold
root-equivalent powers (device-node opens, sysfs driver bind/unbind
writes, config-space probes) in the same process that serves the
kubelet-facing gRPC surface. This module splits them: a tiny privileged
BROKER process owns every such operation behind the narrow, versioned,
audited IPC of brokeripc.py, and the unprivileged SERVING daemon reaches
it through a BrokerClient. The serving daemon can then crash and upgrade
freely (the PR 7 schema-versioned checkpoint + re-serve machinery makes
it restartable) while the broker keeps its device fds; a dead broker
degrades the daemon to TYPED unavailable errors instead of undefined
behavior, and a respawn + handshake recovers.

Three client shapes, one seam:

- ``InProcessBroker`` — the in-process fallback (tests, read-only
  daemons, the default production mode until operators opt into spawn):
  the same narrow operation surface executed by direct calls, still
  audited (every call is a ``broker.ipc`` flight-recorder span and a
  counted crossing) so the privilege boundary is observable and
  benchable in BOTH modes. Hot-path operations stay lock-free — the
  zero-lock read-path gates (tests/test_epoch.py) run against this
  client.
- ``SocketBrokerClient`` — the real two-process path: one unix-socket
  connection, requests serialized under a plain (unregistered) channel
  lock, fds received via SCM_RIGHTS. Connection loss surfaces as
  ``BrokerUnavailable`` — the typed signal dra.py/server.py turn into
  per-claim / per-RPC unavailable errors.
- ``BrokerServer`` — the privileged side: path-policy-validated
  dispatch, an audit ring linking every crossing to the caller's span,
  and a held-fd registry (device nodes stay open in the broker across
  serving-daemon restarts). Runs standalone via
  ``python -m tpu_device_plugin.broker --socket PATH --root ROOT``.

The process-global seam (``get_client``/``set_client``) is what
allocate.py, vtpu.py, dra.py and lifecycle.py route privileged accesses
through — tsalint's broker-boundary rule (tools/tsalint, rule 7) fails
any privileged call outside this module's whitelisted seams, so the
boundary is enforced statically, not just by convention.

Fault site ``broker.ipc`` (value kind) fires on the client's crossing
path: an armed drop turns the next crossing into BrokerUnavailable —
test_chaos.py scripts broker crashes mid-Allocate with it.

The crossing fast path (round 20): spawn-mode connections NEGOTIATE the
compact binary framing at hello (brokeripc v2 — pre-serialized varint
frames via RequestEncoder; a v1 peer on either side keeps JSON framing,
a version outside SUPPORTED_VERSIONS is refused before any op);
``run_batch`` coalesces up to MAX_BATCH_OPS fd-free sub-operations into
ONE round trip with per-sub typed results (one refused sub never
poisons the batch; a dead broker types every sub "unavailable"); and
hot read-only ops (readlinks, attr/vendor reads, config probes) consult
the shared-memory RESPONSE RING the broker hands over at handshake
before paying a socket round trip — torn/stale/missed slots fall back
to the socket, counted (``ring_hits``/``ring_fallbacks``). Fault site
``broker.ring`` (value kind) forces that fallback on demand. The audit
ring, path policy and span-context contracts are framing-blind:
tests/test_broker.py diffs audit entries across both framings.
"""

from __future__ import annotations

import logging
import os
import socket
import subprocess
import sys
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Sequence, Tuple

from . import brokeripc
from . import faults
from . import trace
from .epoch import AtomicCounter
from .native import TpuHealth

log = logging.getLogger(__name__)

# sysfs attribute leaves the broker will write — the driver rebind
# surface, nothing else (not `remove`, not `rescan`: a compromised
# serving daemon must not be able to eject devices through the broker)
SYSFS_WRITE_LEAVES = frozenset({"bind", "unbind", "driver_override"})
# device-node path segments the broker will open
DEV_NODE_SEGMENTS = ("dev/vfio", "dev/iommu", "dev/accel")
AUDIT_RING = 256
# ops a batch may NOT carry: handshake/lifecycle ops are connection
# state, fd-passing ops keep SCM_RIGHTS on dedicated frames (an fd
# buried in a batch reply could not be paired with its sub-op), and
# mutations cross one at a time so the audit ring orders them exactly
BATCH_FORBIDDEN = frozenset({"hello", "open_node", "batch",
                             "shutdown", "stats", "write_sysfs"})


class BrokerError(Exception):
    """The broker answered and refused the request (policy violation,
    bad path, failed syscall) — retrying without a fix is futile."""


class BrokerUnavailable(BrokerError):
    """The broker did not answer (process dead, connection lost, injected
    drop): the serving daemon degrades to typed unavailable errors until
    a respawn + handshake recovers. The message always carries the
    'broker unavailable' prefix tests and operators match on."""

    def __init__(self, detail: str) -> None:
        super().__init__(f"broker unavailable: {detail}")


def _unavailable_detail(message: str) -> str:
    """Strip the BrokerUnavailable prefix from an already-typed message
    so re-raising it does not stutter 'broker unavailable: broker
    unavailable: ...'."""
    prefix = "broker unavailable: "
    return message[len(prefix):] if message.startswith(prefix) else message


def _is_dev_node(path: str) -> bool:
    norm = os.path.normpath(path)
    return any(f"/{seg}" in norm or norm.startswith(seg)
               for seg in DEV_NODE_SEGMENTS)


# --------------------------------------------------------------- clients

class _BaseClient:
    """Shared crossing accounting: every operation is one counted
    crossing, one ``broker.ipc`` span (histogram tdp_broker_crossing_ms),
    and one ``broker.ipc`` fault-point consultation. Subclasses implement
    the operations themselves."""

    mode = "none"

    def __init__(self) -> None:
        self.crossings = AtomicCounter()
        self.errors = AtomicCounter()
        # sub-operations carried by batched crossings (round 20): the
        # gap between batched_ops and crossings is the round trips the
        # batch path saved — /metrics tdp_broker_batched_ops_total
        self.batched_ops = AtomicCounter()
        # response-ring outcomes: a hit skipped a socket round trip
        # entirely; a fallback (miss/stale/torn/injected) paid one
        self.ring_hits = AtomicCounter()
        self.ring_fallbacks = AtomicCounter()
        # crossings the LAST claim paid (gauge, not a counter): written
        # by note_claim_crossings from the Allocate/NodePrepare bracket,
        # read by /status + /metrics — single plain write, last wins
        self._last_claim_crossings = 0

    def _cross(self, op: str, **attrs: object):
        """Open the audited crossing span (call under ``with``). Counts
        the crossing FIRST so even an injected drop is a visible
        crossing, then consults the fault point: an armed drop turns
        this crossing into BrokerUnavailable — the same typed error a
        real broker death produces."""
        self.crossings.add()
        if faults.fire("broker.ipc", broker_op=op):
            self.errors.add()
            raise BrokerUnavailable(f"injected fault at op {op!r}")
        return trace.span("broker.ipc", histogram="tdp_broker_crossing_ms",
                          broker_op=op, broker_mode=self.mode, **attrs)

    def note_claim_crossings(self, n: int) -> None:
        """Record how many crossings the claim that just completed paid
        (the Allocate / NodePrepareResources bracket) — the live
        `crossings_per_claim` gauge the batching work is judged by."""
        self._last_claim_crossings = max(int(n), 0)

    # ---------------------------------------------------- batched subops

    def run_batch(self, subops: Sequence[dict]) -> List[dict]:
        """Submit fd-free sub-operations as ONE crossing; subclasses
        implement the transport. Returns one typed result dict per
        sub-op ({ok: True, ...fields} or {ok: False, kind, error}) —
        partial failure by construction."""
        raise NotImplementedError

    def read_link_batch(self, paths: Sequence[str],
                        ) -> List[Optional[str]]:
        """Basenames of many symlink targets in ONE crossing (None per
        vanished link). A refused sub-op raises BrokerError; a dead
        broker raises BrokerUnavailable — same typed surface as the
        singular read_link."""
        paths = list(paths)
        if not paths:
            return []
        out: List[Optional[str]] = []
        for path, res in zip(paths,
                             self.run_batch([{"op": "read_link",
                                              "path": p} for p in paths])):
            if res.get("ok"):
                out.append(res.get("target"))
            elif res.get("kind") == "unavailable":
                raise BrokerUnavailable(
                    _unavailable_detail(str(res.get("error", ""))))
            else:
                raise BrokerError(
                    f"broker refused read_link {path!r}: "
                    f"{res.get('error', 'unknown')}")
        return out

    def chip_alive_batch(self, pci_base_path: str,
                         items: Sequence[Tuple[str, Optional[str]]],
                         ) -> Dict[str, bool]:
        """One health-cycle's chip probes in ONE crossing: `items` is
        (bdf, node_path) pairs, result maps bdf -> alive. A refused
        sub-op scores its chip dead (partial failure, the cycle
        continues); a dead broker raises BrokerUnavailable so the hub
        counts the degradation exactly as on the singular path."""
        items = list(items)
        if not items:
            return {}
        subs = [{"op": "chip_alive", "pci_base": pci_base_path,
                 "bdf": bdf, "node": node} for bdf, node in items]
        out: Dict[str, bool] = {}
        for (bdf, _node), res in zip(items, self.run_batch(subs)):
            if res.get("ok"):
                out[bdf] = bool(res.get("alive"))
            elif res.get("kind") == "unavailable":
                raise BrokerUnavailable(
                    _unavailable_detail(str(res.get("error", ""))))
            else:
                out[bdf] = False
        return out

    # ------------------------------------------------------------- stats

    def client_stats(self) -> Dict[str, object]:
        return {"mode": self.mode,
                "crossings_total": self.crossings.value,
                "errors_total": self.errors.value,
                "batched_ops_total": self.batched_ops.value,
                "ring_hits_total": self.ring_hits.value,
                "ring_fallbacks_total": self.ring_fallbacks.value,
                "crossings_per_claim": self._last_claim_crossings}

    def stats(self) -> Dict[str, object]:
        return self.client_stats()

    def close(self) -> None:
        return None


class InProcessBroker(_BaseClient):
    """The in-process fallback: the broker's operation surface executed
    by direct calls in THIS process. Used by tests, read-only daemons
    (CI never needs real /dev access — every /dev probe funnels through
    here and answers honestly about the fixture tree), and production
    daemons that have not opted into spawn mode. Per-operation cost is
    one AtomicCounter add + one trace span — the zero-lock gates pin the
    brokered Allocate path at 0 registered-lock acquisitions against
    this client."""

    mode = "inproc"

    def __init__(self, native_lib_path: Optional[str] = None) -> None:
        super().__init__()
        # lazy import breaks the module cycle (allocate imports broker
        # for the seam; both are loaded by the time a client is built)
        from .allocate import LiveAttrReader
        self._native_lib_path = native_lib_path
        self._health_obj: Optional[TpuHealth] = None
        self._reader = LiveAttrReader()

    @property
    def _health(self) -> TpuHealth:
        # built on first PROBE use, not at seam construction: the lazy
        # default client must not dlopen a (possibly wrong) native lib
        # that nothing in-process routes probes through — cli installs a
        # client carrying cfg.native_lib_path when it matters
        health = self._health_obj
        if health is None:
            health = self._health_obj = TpuHealth(self._native_lib_path)
        return health

    # --------------------------------------------------------- node ops

    def node_exists(self, path: str) -> bool:
        with self._cross("node_exists", path=path):
            return os.path.exists(path)

    def open_node(self, path: str) -> int:
        """Open a device node; caller owns the returned fd. Only vfio/
        iommu/accel nodes qualify — the same policy the spawned broker
        enforces, so a path that works in tests works in production."""
        with self._cross("open_node", path=path):
            if not _is_dev_node(path):
                raise BrokerError(
                    f"open_node refused: {path!r} is not a device node "
                    f"under {'/'.join(DEV_NODE_SEGMENTS)}")
            try:
                return os.open(path, os.O_RDWR)
            except OSError as exc:
                raise BrokerError(f"open_node {path!r}: {exc}") from exc

    # -------------------------------------------------------- sysfs ops

    def read_attr(self, key: str, path: str) -> Optional[bytes]:
        """Fresh non-empty bytes of a small sysfs attribute (kept-fd
        cached by `key`, LiveAttrReader semantics); None if gone."""
        with self._cross("read_attr", path=path):
            return self._reader.read(key, path)

    def read_link(self, path: str) -> Optional[str]:
        with self._cross("read_link", path=path):
            try:
                return os.path.basename(os.readlink(path))
            except OSError:
                return None

    def write_sysfs(self, path: str, data: str) -> None:
        """Driver bind/unbind/driver_override write — the rebind surface
        and nothing else (SYSFS_WRITE_LEAVES)."""
        with self._cross("write_sysfs", path=path):
            if os.path.basename(path) not in SYSFS_WRITE_LEAVES:
                raise BrokerError(
                    f"write_sysfs refused: {os.path.basename(path)!r} not "
                    f"in {sorted(SYSFS_WRITE_LEAVES)}")
            try:
                with open(path, "w", encoding="ascii") as f:
                    f.write(data)
            except OSError as exc:
                raise BrokerError(f"write_sysfs {path!r}: {exc}") from exc

    # ------------------------------------------------------- health ops

    def probe_config(self, config_path: str) -> int:
        with self._cross("probe_config", path=config_path):
            return self._health.probe_config(config_path)

    def probe_node(self, dev_path: str) -> int:
        with self._cross("probe_node", path=dev_path):
            return self._health.probe_node(dev_path)

    def chip_alive(self, pci_base_path: str, bdf: str,
                   node_path: Optional[str] = None) -> bool:
        with self._cross("chip_alive", bdf=bdf):
            return self._health.chip_alive(pci_base_path, bdf, node_path)

    def chip_diagnostics(self, pci_base_path: str, bdf: str):
        with self._cross("chip_diagnostics", bdf=bdf):
            return self._health.chip_diagnostics(pci_base_path, bdf)

    # ---------------------------------------------------- batched plan op

    def revalidate_batch(self, planner, pairs: Sequence[Tuple[str, str]],
                         ) -> None:
        """ONE crossing for a whole Allocate plan's TOCTOU revalidation.
        In-process the reads are the planner's own live readers (kept-fd
        vendor pread + group readlink — the exact pre-broker behavior the
        r09 syscall pins count); the spawned broker runs the equivalent
        reads privileged-side. Raises allocate.AllocationError on the
        first stale member."""
        if not pairs:
            return
        with self._cross("revalidate", members=len(pairs)):
            for member, group in pairs:
                planner._revalidate_live(member, group)

    # --------------------------------------------------- batched subops

    def run_batch(self, subops: Sequence[dict]) -> List[dict]:
        """ONE crossing for many fd-free sub-operations, executed by
        direct calls — same typed per-sub results as the spawned broker
        so callers are mode-blind."""
        subs = list(subops)
        if not subs:
            return []
        if len(subs) > brokeripc.MAX_BATCH_OPS:
            raise BrokerError(
                f"batch of {len(subs)} sub-ops exceeds MAX_BATCH_OPS "
                f"{brokeripc.MAX_BATCH_OPS}")
        results: List[dict] = []
        try:
            span = self._cross("batch", ops=len(subs))
        except BrokerUnavailable as exc:
            return [{"ok": False, "seq": i, "kind": "unavailable",
                     "error": str(exc)} for i in range(len(subs))]
        with span:
            for i, sub in enumerate(subs):
                results.append(self._run_sub(sub, i))
                self.batched_ops.add()
        return results

    def _run_sub(self, sub: dict, index: int) -> dict:
        op = str(sub.get("op"))
        try:
            if op in BATCH_FORBIDDEN:
                raise BrokerError(f"op {op!r} not allowed in a batch")
            if op == "node_exists":
                return {"ok": True, "seq": index,
                        "exists": os.path.exists(str(sub["path"]))}
            if op == "read_attr":
                path = str(sub["path"])
                data = self._reader.read(str(sub.get("key") or path), path)
                return {"ok": True, "seq": index,
                        "data": (data.decode("latin-1")
                                 if data is not None else None)}
            if op == "read_link":
                try:
                    target: Optional[str] = os.path.basename(
                        os.readlink(str(sub["path"])))
                except OSError:
                    target = None
                return {"ok": True, "seq": index, "target": target}
            if op == "stat_sig":
                try:
                    st = os.stat(str(sub["path"]))
                    sig: Optional[List[int]] = [st.st_mtime_ns, st.st_size]
                except OSError:
                    sig = None
                return {"ok": True, "seq": index, "sig": sig}
            if op == "probe_config":
                return {"ok": True, "seq": index,
                        "verdict": self._health.probe_config(
                            str(sub["path"]))}
            if op == "probe_node":
                return {"ok": True, "seq": index,
                        "verdict": self._health.probe_node(
                            str(sub["path"]))}
            if op == "chip_alive":
                node = sub.get("node")
                return {"ok": True, "seq": index,
                        "alive": self._health.chip_alive(
                            str(sub["pci_base"]), str(sub["bdf"]),
                            str(node) if node is not None else None)}
            if op == "chip_diagnostics":
                bits, link = self._health.chip_diagnostics(
                    str(sub["pci_base"]), str(sub["bdf"]))
                return {"ok": True, "seq": index, "bits": bits,
                        "link": link}
            raise BrokerError(f"unknown batch op {op!r}")
        except BrokerError as exc:
            return {"ok": False, "seq": index, "kind": "refused",
                    "error": str(exc)}
        except Exception as exc:
            return {"ok": False, "seq": index, "kind": "bad-request",
                    "error": f"{type(exc).__name__}: {exc}"}


class SocketBrokerClient(_BaseClient):
    """The unprivileged side of the two-process path: one unix-socket
    connection to the broker, one request/reply pair per operation,
    serialized under a plain channel lock (spawn mode is explicitly not
    the zero-lock path — the gates run against InProcessBroker). Any
    connection loss raises BrokerUnavailable; ``reconnect()`` re-dials
    and re-handshakes after a broker respawn."""

    mode = "spawn"

    def __init__(self, socket_path: str, connect_timeout_s: float = 5.0,
                 op_timeout_s: float = 30.0,
                 protocol_version: int = brokeripc.PROTOCOL_VERSION,
                 ring: bool = True,
                 ring_ttl_s: float = brokeripc.RING_DEFAULT_TTL_S) -> None:
        super().__init__()
        if protocol_version not in brokeripc.SUPPORTED_VERSIONS:
            raise ValueError(
                f"protocol_version {protocol_version!r} not in "
                f"{sorted(brokeripc.SUPPORTED_VERSIONS)}")
        self.socket_path = socket_path
        self._timeout = connect_timeout_s
        # the framing we OFFER at hello; what we SPEAK afterwards is
        # whatever the broker negotiated down to (a v1 broker keeps the
        # whole connection on JSON frames)
        self._protocol = protocol_version
        self._want_ring = ring and protocol_version >= 2
        self._ring_ttl = ring_ttl_s
        self._ring: Optional[brokeripc.RingReader] = None
        self._binary = False
        self._encoder = brokeripc.RequestEncoder()
        self.negotiated_version = 0
        # every crossing is bounded: a broker that is alive but WEDGED
        # (stuck in an uninterruptible sysfs read on dying hardware)
        # must degrade to typed-unavailable like a dead one — an
        # unbounded recv here would pin the channel lock and stall the
        # whole privileged plane behind one stuck operation
        self._op_timeout = op_timeout_s
        # plain lock by design: serializes request/reply pairing on the
        # single channel; unregistered so it stays invisible to the
        # zero-lock gates (which pin the in-process mode, not this one)
        self._channel_lock = threading.Lock()
        self._sock: Optional[socket.socket] = None
        self._seq = 0
        self.reconnects = AtomicCounter()
        self._dial()

    # ------------------------------------------------------ connection

    def _dial(self) -> None:
        sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        # the timeout covers the WHOLE handshake, not just connect(): the
        # broker accepts one connection at a time, so a connect can land
        # in the listen backlog (stale previous connection, wedged
        # broker) and the hello reply never come — an unbounded recv
        # here would hang startup despite --broker-handshake-timeout
        sock.settimeout(self._timeout)
        try:
            sock.connect(self.socket_path)
            # hello is ALWAYS a v1 JSON frame so any broker can read it;
            # the negotiated version governs every frame after it
            brokeripc.send_frame(sock, brokeripc.hello_request(
                version=self._protocol, ring=self._want_ring))
            reply, fds = brokeripc.recv_frame(
                sock, want_fds=1 if self._want_ring else 0)
            try:
                negotiated = brokeripc.check_hello_reply(
                    reply, requested=self._protocol)
            except brokeripc.BrokerProtocolError:
                brokeripc.close_fds(fds)
                raise
            self._install_ring(reply, fds)
            sock.settimeout(self._op_timeout)
        except (OSError, brokeripc.BrokerConnectionLost) as exc:
            sock.close()
            raise BrokerUnavailable(f"dial {self.socket_path}: {exc}") \
                from exc
        except brokeripc.BrokerProtocolError:
            sock.close()
            raise
        self._sock = sock
        self.negotiated_version = negotiated
        self._binary = negotiated >= 2

    def _install_ring(self, reply: dict, fds: List[int]) -> None:
        """Map the response ring handed over at handshake (spawn-mode
        hot-read fast path). A rejected ring is a LOGGED downgrade to
        socket-only reads, never a failed dial — the ring is an
        optimization, not a correctness surface."""
        old, self._ring = self._ring, None
        if old is not None:
            old.close()
        if reply.get("ring") and fds:
            try:
                self._ring = brokeripc.RingReader(fds[0])
            except (brokeripc.BrokerProtocolError, OSError,
                    ValueError) as exc:
                log.warning("broker: response ring rejected (%s); "
                            "falling back to socket-only reads", exc)
        # the mmap holds the pages; the fds are not needed afterwards
        brokeripc.close_fds(fds)

    def _ring_lookup(self, op: str, path: str) -> Optional[dict]:
        """Consult the response ring before paying a crossing. A hit is
        NOT a crossing — no socket, no broker-side audit entry (the ring
        serves only values the broker already audited when it published
        them). Fault site broker.ring forces the socket fallback."""
        ring = self._ring
        if ring is None:
            return None
        if faults.fire("broker.ring", broker_op=op):
            self.ring_fallbacks.add()
            return None
        try:
            value, status = ring.lookup(brokeripc.ring_key(op, path),
                                        ttl_s=self._ring_ttl)
        except (OSError, ValueError):
            self.ring_fallbacks.add()
            return None
        if status == "hit":
            self.ring_hits.add()
            return value
        self.ring_fallbacks.add()
        return None

    def reconnect(self) -> None:
        """Re-dial + re-handshake (broker respawn recovery). Raises
        BrokerUnavailable if the broker is still gone."""
        with self._channel_lock:
            if self._sock is not None:
                try:
                    self._sock.close()
                except OSError:
                    pass
                self._sock = None
            self._dial()
            self.reconnects.add()

    def close(self) -> None:
        with self._channel_lock:
            if self._sock is not None:
                try:
                    self._sock.close()
                except OSError:
                    pass
                self._sock = None
            if self._ring is not None:
                self._ring.close()
                self._ring = None

    def _request(self, op: str, want_fds: int = 0,
                 **fields: object) -> Tuple[dict, List[int]]:
        with self._channel_lock:
            if self._sock is None:
                raise BrokerUnavailable("not connected (close/crash); "
                                        "reconnect() after respawn")
            self._seq += 1
            req = {"op": op, "seq": self._seq,
                   "span": brokeripc.span_context()}
            req.update(fields)
            try:
                if self._binary:
                    # v2 fast path: the static field segment of this
                    # request is pre-serialized and cached; only seq +
                    # span encode per call
                    brokeripc.send_encoded(
                        self._sock, self._encoder.encode_frame(req))
                else:
                    brokeripc.send_frame(self._sock, req)
                reply, fds = brokeripc.recv_frame(self._sock,
                                                  want_fds=want_fds)
            except brokeripc.BrokerConnectionLost as exc:
                # the kill -9 path: drop the dead socket so every later
                # call fails fast with the same typed error until
                # reconnect()
                try:
                    self._sock.close()
                except OSError:
                    pass
                self._sock = None
                self.errors.add()
                raise BrokerUnavailable(str(exc)) from exc
            if reply.get("seq") != self._seq:
                # a desynced stream can never re-pair (brokeripc contract):
                # drop the socket so every later call fails fast typed
                # until reconnect(), instead of reading stale replies
                brokeripc.close_fds(fds)
                try:
                    self._sock.close()
                except OSError:
                    pass
                self._sock = None
                self.errors.add()
                raise BrokerUnavailable(
                    f"protocol desync: reply seq {reply.get('seq')!r} != "
                    f"request {self._seq}; reconnect() required")
        if not reply.get("ok"):
            brokeripc.close_fds(fds)
            self.errors.add()
            raise BrokerError(
                f"broker refused {op}: {reply.get('error', 'unknown')}")
        return reply, fds

    # ------------------------------------------------------- operations

    def node_exists(self, path: str) -> bool:
        with self._cross("node_exists", path=path):
            reply, _ = self._request("node_exists", path=path)
            return bool(reply["exists"])

    def open_node(self, path: str) -> int:
        with self._cross("open_node", path=path):
            reply, fds = self._request("open_node", want_fds=1, path=path)
            if not fds:
                raise BrokerError(
                    f"broker acked open_node {path!r} but passed no fd")
            if len(fds) > 1:
                brokeripc.close_fds(fds[1:])
            return fds[0]

    def read_attr(self, key: str, path: str) -> Optional[bytes]:
        hit = self._ring_lookup("read_attr", path)
        if hit is not None:
            data = hit.get("data")
            return data.encode("latin-1") if data is not None else None
        with self._cross("read_attr", path=path):
            reply, _ = self._request("read_attr", path=path)
            data = reply.get("data")
            return data.encode("latin-1") if data is not None else None

    def read_link(self, path: str) -> Optional[str]:
        hit = self._ring_lookup("read_link", path)
        if hit is not None:
            return hit.get("target")
        with self._cross("read_link", path=path):
            reply, _ = self._request("read_link", path=path)
            return reply.get("target")

    def write_sysfs(self, path: str, data: str) -> None:
        with self._cross("write_sysfs", path=path):
            self._request("write_sysfs", path=path, data=data)

    def probe_config(self, config_path: str) -> int:
        hit = self._ring_lookup("probe_config", config_path)
        if hit is not None:
            return int(hit["verdict"])
        with self._cross("probe_config", path=config_path):
            reply, _ = self._request("probe_config", path=config_path)
            return int(reply["verdict"])

    def probe_node(self, dev_path: str) -> int:
        with self._cross("probe_node", path=dev_path):
            reply, _ = self._request("probe_node", path=dev_path)
            return int(reply["verdict"])

    def chip_alive(self, pci_base_path: str, bdf: str,
                   node_path: Optional[str] = None) -> bool:
        with self._cross("chip_alive", bdf=bdf):
            reply, _ = self._request("chip_alive", pci_base=pci_base_path,
                                     bdf=bdf, node=node_path)
            return bool(reply["alive"])

    def chip_diagnostics(self, pci_base_path: str, bdf: str):
        with self._cross("chip_diagnostics", bdf=bdf):
            reply, _ = self._request("chip_diagnostics",
                                     pci_base=pci_base_path, bdf=bdf)
            return int(reply["bits"]), reply.get("link")

    def revalidate_batch(self, planner, pairs: Sequence[Tuple[str, str]],
                         ) -> None:
        if not pairs:
            return
        from .allocate import AllocationError
        with self._cross("revalidate", members=len(pairs)):
            reply, _ = self._request(
                "revalidate", pci_base=planner.cfg.pci_base_path,
                vendors=sorted(planner._vendor_ok),
                pairs=[[m, g] for m, g in pairs])
            for err in reply.get("errors", ()):
                if err is not None:
                    raise AllocationError(err)

    def run_batch(self, subops: Sequence[dict]) -> List[dict]:
        """ONE round trip for many fd-free sub-operations. Typed partial
        failure end to end: a refused sub rides back as its own {ok:
        False, kind, error} result, and a broker that dies mid-batch
        (kill -9) types EVERY sub-result "unavailable" instead of
        raising through the caller — the caller decides per sub, exactly
        once, and a reconnect() + resubmit after respawn is safe because
        the batch carried only read-only ops."""
        subs = [dict(sub) for sub in subops]
        if not subs:
            return []
        if len(subs) > brokeripc.MAX_BATCH_OPS:
            raise BrokerError(
                f"batch of {len(subs)} sub-ops exceeds MAX_BATCH_OPS "
                f"{brokeripc.MAX_BATCH_OPS}")
        for i, sub in enumerate(subs):
            sub["seq"] = i
        try:
            with self._cross("batch", ops=len(subs)):
                reply, _ = self._request("batch", ops=subs)
        except BrokerUnavailable as exc:
            return [{"ok": False, "seq": i, "kind": "unavailable",
                     "error": str(exc)} for i in range(len(subs))]
        results = reply.get("results") or []
        if len(results) != len(subs):
            self.errors.add()
            raise BrokerError(
                f"broker answered {len(results)} results for "
                f"{len(subs)} batched sub-ops")
        for _ in subs:
            self.batched_ops.add()
        return results

    def stats(self) -> Dict[str, object]:
        out = self.client_stats()
        out["reconnects_total"] = self.reconnects.value
        out["protocol_version"] = self.negotiated_version
        out["ring_attached"] = self._ring is not None
        out["frame_cache_hits_total"] = self._encoder.static_hits
        try:
            with self._cross("stats"):
                reply, _ = self._request("stats")
            out["broker"] = reply.get("broker", {})
        except (BrokerError, brokeripc.BrokerProtocolError):
            out["broker"] = None
        return out

    def shutdown_broker(self) -> None:
        """Ask the broker process to exit cleanly (test teardown)."""
        with self._cross("shutdown"):
            try:
                self._request("shutdown")
            except BrokerUnavailable:
                pass   # already gone — the goal state


# ------------------------------------------------------ privileged side

class PathPolicy:
    """What the broker will touch, derived from one root prefix: device
    nodes only under <root>/dev/{vfio,iommu,accel*}, reads only under
    <root>/sys or <root>/dev, writes only to SYSFS_WRITE_LEAVES under
    <root>/sys. Everything else is refused with a typed error — the
    serving daemon compromising itself must not turn the broker into an
    arbitrary-file oracle."""

    def __init__(self, root: str = "/") -> None:
        self.root = os.path.abspath(root)
        self._dev = [os.path.join(self.root, seg)
                     for seg in DEV_NODE_SEGMENTS]
        self._read_roots = [os.path.join(self.root, "sys"),
                            os.path.join(self.root, "dev")]
        self._sys_root = os.path.join(self.root, "sys")

    @staticmethod
    def _under(path: str, prefix: str, loose: bool = False) -> bool:
        """Component-safe prefix check (`/sys` must not admit
        `/system`); `loose` also accepts name-extension matches
        (`dev/accel` admits `dev/accel0` — the accel nodes are files
        named by index, not a directory)."""
        norm = os.path.normpath(path)
        if norm == prefix or norm.startswith(prefix.rstrip("/") + "/"):
            return True
        return loose and norm.startswith(prefix)

    def check_node(self, path: str) -> None:
        if not any(self._under(path, p, loose=True) for p in self._dev):
            raise BrokerError(
                f"path policy: {path!r} is not a device node under "
                f"{self._dev}")

    def check_read(self, path: str) -> None:
        if not any(self._under(path, p) for p in self._read_roots):
            raise BrokerError(
                f"path policy: {path!r} is outside the readable roots "
                f"{self._read_roots}")

    def check_write(self, path: str) -> None:
        if not self._under(path, self._sys_root):
            raise BrokerError(
                f"path policy: sysfs write target {path!r} is outside "
                f"{self._sys_root}")
        if os.path.basename(path) not in SYSFS_WRITE_LEAVES:
            raise BrokerError(
                f"path policy: write leaf {os.path.basename(path)!r} not "
                f"in {sorted(SYSFS_WRITE_LEAVES)}")

    @staticmethod
    def check_component(name: str, what: str = "bdf") -> None:
        """A device identifier joined under a validated base must be a
        single path-free component — a traversal bdf ('../../etc') would
        otherwise escape the readable roots through the join."""
        if (not name or "/" in name or "\x00" in name
                or name in (".", "..")):
            raise BrokerError(
                f"path policy: {what} {name!r} is not a single path "
                f"component")


class BrokerServer:
    """The privileged broker process body: accept one connection at a
    time on a unix socket (the serving daemon holds exactly one), speak
    brokeripc frames, dispatch through the path policy, and audit every
    crossing. Device nodes opened through the broker are HELD open here
    (``held_fds``) in addition to the duplicate passed to the client —
    the broker keeping its fds across serving-daemon restarts is the
    privilege-separation payoff the acceptance test pins."""

    def __init__(self, socket_path: str, root: str = "/",
                 native_lib_path: Optional[str] = None,
                 enable_ring: bool = True) -> None:
        self.socket_path = socket_path
        self.policy = PathPolicy(root)
        self._health = TpuHealth(native_lib_path)
        # the response ring (round 20): hot read-only results published
        # here after being served (and audited) over the socket, so the
        # daemon's next read of the same key skips the round trip. A
        # kernel without memfd/mmap support just runs ringless.
        self._ring: Optional[brokeripc.RingWriter] = None
        if enable_ring:
            try:
                self._ring = brokeripc.RingWriter()
            except (OSError, ValueError) as exc:
                log.warning("broker: response ring unavailable (%s); "
                            "serving socket-only", exc)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # the one live daemon connection (sequential accept: the serving
        # daemon holds exactly one); stop() closes it so a handler
        # blocked in recv wakes instead of pinning the accept thread
        self._active_conn: Optional[socket.socket] = None
        self._held: Dict[str, int] = {}      # node path -> broker-held fd
        self._counters: Dict[str, int] = {}  # per-op crossing counts
        self._refused = 0
        self._audit: deque = deque(maxlen=AUDIT_RING)
        os.makedirs(os.path.dirname(socket_path) or ".", exist_ok=True)
        try:
            os.unlink(socket_path)
        except OSError:
            pass
        self._listener = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._listener.bind(socket_path)
        self._listener.listen(4)
        # accept() must wake for stop(): a short timeout loop, not a
        # blocking accept, so the in-process test server tears down
        self._listener.settimeout(0.2)
        log.info("broker: listening on %s (root %s, pid %d)",
                 socket_path, self.policy.root, os.getpid())

    # -------------------------------------------------------- lifecycle

    def start(self) -> None:
        """Serve on a background thread (tests / embedded use; the
        standalone process calls serve_forever on its main thread)."""
        self._thread = threading.Thread(target=self.serve_forever,
                                        daemon=True, name="broker-accept")
        self._thread.start()

    def initiate_shutdown(self) -> None:
        """Signal-safe shutdown trigger (the standalone process's SIGTERM
        handler): closing the live sockets is what actually wakes a
        handler blocked in recv — PEP 475 would otherwise retry the read
        forever and the stop flag would never be observed."""
        self._stop.set()
        conn = self._active_conn
        if conn is not None:
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
        try:
            self._listener.close()
        except OSError:
            pass

    def stop(self) -> None:
        self.initiate_shutdown()
        thread = self._thread
        if thread is not None:
            thread.join(timeout=2)
            self._thread = None
        try:
            self._listener.close()
        except OSError:
            pass
        try:
            os.unlink(self.socket_path)
        except OSError:
            pass
        for fd in self._held.values():
            try:
                os.close(fd)
            except OSError:
                pass
        self._held.clear()
        if self._ring is not None:
            self._ring.close()
            self._ring = None

    def serve_forever(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._listener.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            self._active_conn = conn
            try:
                with conn:
                    self._serve_connection(conn)
            finally:
                self._active_conn = None

    def _serve_connection(self, conn: socket.socket) -> None:
        log.info("broker: serving daemon connected")
        # per-connection handshake gate: the documented contract is that
        # a version mismatch is refused BEFORE anything else is served —
        # which only holds if a client that SKIPS hello gets nothing
        helloed = False
        # per-connection NEGOTIATED framing: binary frames are only
        # legal after a v2 hello on THIS connection; replies always
        # mirror the request's framing (so the hello reply is JSON even
        # when the rest of the connection goes binary)
        binary_ok = False
        while not self._stop.is_set():
            try:
                req, extra_fds, was_binary = brokeripc.recv_frame_ex(conn)
            except brokeripc.BrokerConnectionLost:
                # the serving daemon died or restarted: keep running,
                # keep the held fds, go back to accept()
                log.info("broker: serving daemon disconnected; "
                         "holding %d fds", len(self._held))
                return
            except brokeripc.BrokerProtocolError as exc:
                log.warning("broker: protocol error: %s", exc)
                try:
                    brokeripc.send_frame(conn, {
                        "ok": False, "seq": -1, "kind": "protocol",
                        "error": str(exc)})
                except brokeripc.BrokerConnectionLost:
                    pass
                return   # connection unusable after a framing error
            brokeripc.close_fds(extra_fds)   # clients never send fds
            if was_binary and not binary_ok:
                log.warning("broker: binary frame before v2 handshake")
                try:
                    brokeripc.send_frame(conn, {
                        "ok": False, "seq": req.get("seq", -1),
                        "kind": "protocol",
                        "error": "binary framing not negotiated on this "
                                 "connection"})
                except brokeripc.BrokerConnectionLost:
                    pass
                return
            if not helloed and req.get("op") != "hello":
                reply, fds = {
                    "ok": False, "seq": req.get("seq", -1),
                    "kind": "version",
                    "error": "handshake required before any operation"}, []
                self._audit_note(req, False, reply["error"])
            else:
                # broker-side span LINKED to the caller's context (the
                # frame's span field, r17): the privileged process's own
                # flight ring joins the serving daemon's trace — the
                # root span here adopts the caller's trace id, so a
                # fleet trace query over the broker's ring finds the
                # crossing. A pre-r17 frame ({op, seq} only) is NOT
                # malformed context — it just carries none.
                caller = req.get("span") or {}
                link = caller if "trace_id" in caller else None
                with trace.span("broker.serve", link=link,
                                broker_op=str(req.get("op")),
                                caller_op=caller.get("op")):
                    reply, fds = self._dispatch(req)
                if req.get("op") == "hello" and reply.get("ok"):
                    helloed = True
                    binary_ok = int(reply.get("version") or 1) >= 2
            try:
                brokeripc.send_frame(conn, reply, fds=tuple(fds),
                                     binary=was_binary)
            except brokeripc.BrokerConnectionLost:
                return
            finally:
                brokeripc.close_fds(fds)   # ours were dups; client has its own
            if req.get("op") == "shutdown" and reply.get("ok"):
                # only an ACCEPTED shutdown stops the broker: a refused
                # one (no handshake) must not let an arbitrary local
                # process kill the privileged side through the socket
                self._stop.set()
                return

    # --------------------------------------------------------- dispatch

    def _audit_note(self, req: dict, ok: bool, error: str = "") -> None:
        op = str(req.get("op"))
        self._counters[op] = self._counters.get(op, 0) + 1
        if not ok:
            self._refused += 1
        self._audit.append({
            "op": op, "path": req.get("path") or req.get("bdf"),
            "ok": ok, "error": error or None,
            "span": req.get("span"), "ts": time.time()})

    def _ring_publish(self, op: str, path: str, value: dict) -> None:
        ring = self._ring
        if ring is not None:
            ring.publish(brokeripc.ring_key(op, path), value)

    def _dispatch(self, req: dict,
                  in_batch: bool = False) -> Tuple[dict, List[int]]:
        op = req.get("op")
        seq = req.get("seq", -1)
        fds: List[int] = []
        reply: dict = {"ok": True, "seq": seq}
        try:
            if in_batch and op in BATCH_FORBIDDEN:
                raise BrokerError(f"op {op!r} not allowed in a batch")
            if op == "hello":
                version = req.get("version")
                if version not in brokeripc.SUPPORTED_VERSIONS:
                    raise BrokerError(
                        f"protocol version {version!r} "
                        f"unsupported (broker speaks "
                        f"{sorted(brokeripc.SUPPORTED_VERSIONS)})")
                # negotiate DOWN to the client's version: a v1 client
                # keeps JSON framing for the whole connection
                reply["version"] = int(version)
                reply["pid"] = os.getpid()
                if (int(version) >= 2 and req.get("ring")
                        and self._ring is not None):
                    # the one-time ring handover: SCM_RIGHTS used for
                    # actual fd passage, here and open_node only. The
                    # dup is closed after send (server fds always are);
                    # the client's copy keeps the mapping alive.
                    reply["ring"] = True
                    reply["ring_slots"] = self._ring.slots
                    reply["ring_slot_size"] = self._ring.slot_size
                    fds.append(os.dup(self._ring.fd))
            elif op == "node_exists":
                path = str(req["path"])
                self.policy.check_read(path)
                reply["exists"] = os.path.exists(path)
            elif op == "open_node":
                path = str(req["path"])
                self.policy.check_node(path)
                try:
                    fd = os.open(path, os.O_RDWR)
                except OSError as exc:
                    raise BrokerError(f"open_node {path!r}: {exc}") from exc
                # the broker HOLDS its own copy: a serving-daemon crash
                # never drops the device state the broker owns
                prev = self._held.get(path)
                self._held[path] = os.dup(fd)
                if prev is not None:
                    try:
                        os.close(prev)
                    except OSError:
                        pass
                fds.append(fd)
            elif op == "read_attr":
                path = str(req["path"])
                self.policy.check_read(path)
                data: Optional[bytes] = None
                try:
                    with open(path, "rb") as f:
                        data = f.read(256)
                except OSError:
                    data = None
                reply["data"] = (data.decode("latin-1")
                                 if data else None)
                self._ring_publish("read_attr", path,
                                   {"data": reply["data"]})
            elif op == "read_link":
                path = str(req["path"])
                self.policy.check_read(path)
                try:
                    reply["target"] = os.path.basename(os.readlink(path))
                except OSError:
                    reply["target"] = None
                self._ring_publish("read_link", path,
                                   {"target": reply["target"]})
            elif op == "stat_sig":
                # snapshot-revalidation change signature (batch-carried on
                # boot: one crossing stats a whole host's device dirs); a
                # vanished path is a None signature, not an error — the
                # caller treats it as "invalidated, re-read cold"
                path = str(req["path"])
                self.policy.check_read(path)
                try:
                    st = os.stat(path)
                    reply["sig"] = [st.st_mtime_ns, st.st_size]
                except OSError:
                    reply["sig"] = None
            elif op == "write_sysfs":
                path = str(req["path"])
                self.policy.check_write(path)
                try:
                    with open(path, "w", encoding="ascii") as f:
                        f.write(str(req.get("data", "")))
                except OSError as exc:
                    raise BrokerError(
                        f"write_sysfs {path!r}: {exc}") from exc
            elif op == "probe_config":
                path = str(req["path"])
                self.policy.check_read(path)
                reply["verdict"] = self._health.probe_config(path)
                self._ring_publish("probe_config", path,
                                   {"verdict": reply["verdict"]})
            elif op == "probe_node":
                path = str(req["path"])
                self.policy.check_read(path)
                reply["verdict"] = self._health.probe_node(path)
            elif op == "chip_alive":
                base = str(req["pci_base"])
                bdf = str(req["bdf"])
                self.policy.check_read(base)
                self.policy.check_component(bdf)
                node = req.get("node")
                if node is not None:
                    # the node path is probed privileged-side: confine it
                    # like every other read, or the daemon could use the
                    # probe as an arbitrary-file existence oracle
                    self.policy.check_read(str(node))
                reply["alive"] = self._health.chip_alive(
                    base, bdf, node)
            elif op == "chip_diagnostics":
                base = str(req["pci_base"])
                bdf = str(req["bdf"])
                self.policy.check_read(base)
                self.policy.check_component(bdf)
                bits, link = self._health.chip_diagnostics(base, bdf)
                reply["bits"] = bits
                reply["link"] = link
            elif op == "revalidate":
                base = str(req["pci_base"])
                self.policy.check_read(base)
                # normalize configured spellings like the in-process
                # reader does (allocate._vendor_ok_raw accepts both
                # "1ae0" and "0x1ae0"): the sysfs value is stripped of
                # its 0x below, so the configured set must be too — or a
                # cosmetic cfg spelling would fail every spawn-mode
                # Allocate while inproc mode works
                vendors = {
                    v[2:] if v.startswith("0x") else v
                    for v in (str(x).lower()
                              for x in req.get("vendors", ()))}
                pairs = [(str(m), str(g))
                         for m, g in req.get("pairs", ())]
                for member, _group in pairs:
                    self.policy.check_component(member)
                reply["errors"] = [
                    self._revalidate_one(base, m, g, vendors)
                    for m, g in pairs]
            elif op == "batch":
                subs = req.get("ops")
                if not isinstance(subs, list):
                    raise BrokerError("batch requires an ops list")
                if len(subs) > brokeripc.MAX_BATCH_OPS:
                    raise BrokerError(
                        f"batch of {len(subs)} sub-ops exceeds "
                        f"MAX_BATCH_OPS {brokeripc.MAX_BATCH_OPS}")
                # partial-failure semantics: every sub-op dispatches
                # through the SAME policy/audit machinery as a singular
                # crossing (recursive _dispatch appends its own audit
                # entry) and carries its own typed result — one refused
                # sub never poisons the batch
                results = []
                for i, sub in enumerate(subs):
                    if not isinstance(sub, dict):
                        sub = {"op": "invalid", "seq": i}
                    sub_reply, sub_fds = self._dispatch(sub,
                                                        in_batch=True)
                    brokeripc.close_fds(sub_fds)  # barred by policy; belt
                    results.append(sub_reply)
                reply["results"] = results
            elif op == "stats":
                reply["broker"] = {
                    "pid": os.getpid(),
                    "held_fds": len(self._held),
                    "held_paths": sorted(self._held),
                    "ops": dict(self._counters),
                    "refused_total": self._refused,
                    "ring": (self._ring.stats()
                             if self._ring is not None else None),
                    "audit": list(self._audit)[-32:],
                }
            elif op == "shutdown":
                log.info("broker: shutdown requested")
            else:
                raise BrokerError(f"unknown op {op!r}")
        except BrokerError as exc:
            reply = {"ok": False, "seq": seq, "kind": "refused",
                     "error": str(exc)}
            brokeripc.close_fds(fds)
            fds = []
        except Exception as exc:
            # a malformed request field (missing key, wrong shape) from a
            # compromised or version-skewed daemon must degrade to a
            # typed refusal — an uncaught exception here would kill the
            # accept thread, drop every held fd, and wedge all future
            # daemon connects in the dead listener's backlog (the exact
            # DoS the threat model forbids)
            log.warning("broker: bad request %r: %s: %s",
                        op, type(exc).__name__, exc)
            reply = {"ok": False, "seq": seq, "kind": "bad-request",
                     "error": f"{type(exc).__name__}: {exc}"}
            brokeripc.close_fds(fds)
            fds = []
        self._audit_note(req, reply["ok"], reply.get("error", ""))
        return reply, fds

    def _revalidate_one(self, pci_base: str, bdf: str, group: str,
                        vendors: set) -> Optional[str]:
        """One member's TOCTOU revalidation, privileged-side: the same
        facts AllocationPlanner._revalidate_live checks in-process."""
        base = os.path.join(pci_base, bdf)
        try:
            target = os.readlink(os.path.join(base, "iommu_group"))
        except OSError:
            target = ""
        live = target.rsplit("/", 1)[-1] or None
        if live != group:
            return (f"device {bdf}: iommu group changed "
                    f"({group!r} -> {live!r})")
        try:
            with open(os.path.join(base, "vendor"), "rb") as f:
                raw = f.read(64).strip().lower()
        except OSError:
            raw = b""
        vendor = raw.decode("ascii", "replace")
        if vendor.startswith("0x"):
            vendor = vendor[2:]
        if not vendor or vendor not in vendors:
            return f"device {bdf}: vendor {vendor or None!r} is not a TPU"
        return None


# ------------------------------------------------------- health adapter

class BrokeredHealth:
    """TpuHealth-compatible probe surface that forwards the privileged
    reads (config-space probes, node probes, diagnostics) through the
    broker client. lifecycle.PluginManager swaps this in for the plain
    native shim when the daemon runs in spawn mode, so the health hub's
    probe closures cross the privilege boundary without knowing it."""

    def __init__(self, client: _BaseClient,
                 native_lib_path: Optional[str] = None) -> None:
        self._client = client
        # parsing-only helpers (link predicates, libtpu availability)
        # stay local — they touch no privileged state
        self._local = TpuHealth(native_lib_path)

    @property
    def is_native(self) -> bool:
        return self._local.is_native

    def libtpu_available(self) -> bool:
        return self._local.libtpu_available()

    def probe_config(self, config_path: str) -> int:
        return self._client.probe_config(config_path)

    def probe_node(self, dev_path: str) -> int:
        return self._client.probe_node(dev_path)

    def chip_alive(self, pci_base_path: str, bdf: str,
                   node_path: Optional[str] = None) -> bool:
        return self._client.chip_alive(pci_base_path, bdf, node_path)

    def chip_alive_batch(self, pci_base_path: str,
                         items: Sequence[Tuple[str, Optional[str]]],
                         ) -> Dict[str, bool]:
        """A whole probe cycle's chip probes in ONE crossing — healthhub
        detects this method on the shim and coalesces its per-bdf pool
        submissions into one batched crossing per cycle."""
        return self._client.chip_alive_batch(pci_base_path, items)

    def chip_diagnostics(self, pci_base_path: str, bdf: str):
        bits, link = self._client.chip_diagnostics(pci_base_path, bdf)
        return bits, link

    def chip_link_degraded(self, pci_base_path: str, bdf: str) -> bool:
        from .native import link_is_degraded
        return link_is_degraded(
            self.chip_diagnostics(pci_base_path, bdf)[1])

    def chip_error_bits(self, pci_base_path: str, bdf: str) -> int:
        return self.chip_diagnostics(pci_base_path, bdf)[0]


# ------------------------------------------------------------- the seam

_client: Optional[_BaseClient] = None


def seam_read_link(path: str) -> Optional[str]:
    """Basename of a sysfs symlink target, through the privilege seam:
    the spawned broker does the readlink in spawn mode (a read-only
    serving daemon never touches the host tree during prepare — the
    vtpu/dra mdev paths used to read it directly and silently assumed
    access); in-process it is discovery's plain reader, so the existing
    read accounting is unchanged."""
    client = get_client()
    if client.mode == "spawn":
        return client.read_link(path)
    from .discovery import read_link_basename
    return read_link_basename(path)


def seam_read_link_batch(paths: Sequence[str]) -> List[Optional[str]]:
    """Batched seam_read_link: ONE crossing for the whole path list in
    spawn mode (dra's per-partition mdev readlinks used to pay one round
    trip each); in-process it is discovery's plain reader per path, so
    the existing read accounting is unchanged."""
    paths = list(paths)
    if not paths:
        return []
    client = get_client()
    if client.mode == "spawn":
        return client.read_link_batch(paths)
    from .discovery import read_link_basename
    return [read_link_basename(p) for p in paths]


def get_client() -> _BaseClient:
    """The process-global broker seam every privileged access routes
    through. Defaults to an InProcessBroker (lazily built; a benign
    construction race leaves one winner). cli.main replaces it with a
    SocketBrokerClient in spawn mode BEFORE any server starts."""
    global _client
    client = _client
    if client is None:
        client = _client = InProcessBroker()
    return client


def peek_client() -> Optional[_BaseClient]:
    """The installed client WITHOUT instantiating the lazy default —
    discovery's snapshot revalidation runs before any serving surface is
    up and must not be the accidental creator of the process seam."""
    return _client


def set_client(client: Optional[_BaseClient]) -> Optional[_BaseClient]:
    """Install a client (spawn mode, tests); returns the previous one so
    tests can restore it."""
    global _client
    prev, _client = _client, client
    return prev


def reset_client() -> None:
    """Back to the lazy in-process default (test teardown)."""
    global _client
    client, _client = _client, None
    if client is not None:
        client.close()


def health_shim(native_lib_path: Optional[str] = None):
    """The health probe implementation for this process: the plain
    native shim when privileged reads run in-process, a BrokeredHealth
    forwarding through the broker in spawn mode."""
    client = get_client()
    if isinstance(client, SocketBrokerClient):
        return BrokeredHealth(client, native_lib_path)
    return TpuHealth(native_lib_path)


# ---------------------------------------------------------- spawn logic

def socket_live(socket_path: str, timeout_s: float = 1.0) -> bool:
    """True when SOMETHING accepts connections on the socket — used by
    the restart path to tell a wedged-but-alive broker (do NOT spawn a
    duplicate over it) from a dead one (safe to respawn)."""
    sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    sock.settimeout(timeout_s)
    try:
        sock.connect(socket_path)
        return True
    except OSError:
        return False
    finally:
        sock.close()


def spawn_broker(socket_path: str, root: str = "/",
                 native_lib_path: Optional[str] = None,
                 timeout_s: float = 10.0) -> subprocess.Popen:
    """Start the privileged broker as a child process and wait for its
    socket. The caller connects with SocketBrokerClient and installs it
    via set_client. The broker outlives serving-daemon crashes by
    design; it exits on SIGTERM or a shutdown op."""
    argv = [sys.executable, "-m", "tpu_device_plugin.broker",
            "--socket", socket_path, "--root", root]
    if native_lib_path:
        argv += ["--native-lib", native_lib_path]
    # a kill -9'd broker leaves its socket FILE behind; remove it so the
    # bind-wait below observes the NEW broker's socket, not the corpse's
    try:
        os.unlink(socket_path)
    except OSError:
        pass
    proc = subprocess.Popen(argv)
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        # readiness = the broker ACCEPTS a connection, not just that the
        # socket file exists: bind() creates the file before listen()
        # runs, so an existence check can hand the caller a path whose
        # first connect() is refused (seen as a flaky respawn under
        # load). The probe connection is closed without a hello; the
        # broker's accept loop tolerates that as a dead peer.
        if os.path.exists(socket_path):
            probe = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            try:
                probe.settimeout(1.0)
                probe.connect(socket_path)
            except OSError:
                pass
            else:
                return proc
            finally:
                probe.close()
        if proc.poll() is not None:
            raise BrokerUnavailable(
                f"broker process exited rc={proc.returncode} before "
                f"binding {socket_path}")
        time.sleep(0.02)
    proc.terminate()
    raise BrokerUnavailable(
        f"broker did not bind {socket_path} within {timeout_s}s")


def main(argv=None) -> int:
    """``python -m tpu_device_plugin.broker``: the standalone privileged
    process. Deliberately tiny — argparse, one BrokerServer, SIGTERM."""
    import argparse
    import signal

    parser = argparse.ArgumentParser(
        prog="tpu-device-plugin-broker",
        description="Privileged vfio/sysfs/iommufd broker for the "
                    "unprivileged TPU device-plugin daemon.")
    parser.add_argument("--socket", required=True,
                        help="unix socket to serve the broker IPC on")
    parser.add_argument("--root", default="/",
                        help="filesystem root the path policy allows "
                             "(fixture trees in tests)")
    parser.add_argument("--native-lib", default=None,
                        help="path to libtpuhealth.so")
    args = parser.parse_args(argv)
    logging.basicConfig(level=logging.INFO,
                        format="broker %(levelname)s %(message)s")
    server = BrokerServer(args.socket, root=args.root,
                          native_lib_path=args.native_lib)

    def handle(signum, frame):
        server.initiate_shutdown()

    signal.signal(signal.SIGTERM, handle)
    signal.signal(signal.SIGINT, handle)
    server.serve_forever()
    server.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
