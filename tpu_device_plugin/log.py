"""log — structured logging that correlates with the trace plane.

One module owns the daemon's log output shape so every line can be
machine-joined with the flight recorder (trace.py): the formatters ask
the trace plane for the ACTIVE SPAN's attributes on the emitting thread
and append them to every record — a log line emitted inside
``trace.span("dra.prepare.claim", claim_uid=uid)`` carries
``claim_uid=...`` without the call site threading context through its
arguments. Two formats, selected once at startup (cli.build_config):

- default: ``<ts> <LEVEL> <logger>: <message> key=value ...`` —
  the key=value tail is the span context (claim_uid, bdf, resource,
  epoch_id, ...), values quoted only when they contain spaces;
- ``$TDP_LOG_JSON=1`` (or ``--log-json``): one JSON object per line
  with the span context under ``"ctx"`` — fleet log pipelines join
  ``ctx.claim_uid`` against ``/debug/flight?claim=`` directly.

Modules obtain loggers via ``get_logger(__name__)`` (a plain stdlib
logger — the structure lives in the formatter, so third-party/library
records get the same treatment) and tests that capture with caplog see
unformatted records exactly as before.
"""

from __future__ import annotations

import json
import logging
from typing import Any, Dict

__all__ = ["configure", "get_logger", "KeyValueFormatter", "JsonFormatter"]


def get_logger(name: str) -> logging.Logger:
    """The project's logger accessor: a stdlib logger today, but the one
    seam a future adapter (rate limiting, per-module levels) plugs into
    without touching every module again."""
    return logging.getLogger(name)


def _span_context() -> Dict[str, Any]:
    """The active span's attributes on THIS thread (empty when no span is
    open or tracing is disabled). Imported lazily so the logging module
    never participates in an import cycle with trace/epoch."""
    from . import trace
    stack = trace._tls.stack
    if not stack:
        return {}
    return stack[-1].attrs


def _kv(value: Any) -> str:
    text = str(value)
    if not text or any(c in text for c in ' "=\n'):
        return json.dumps(text)
    return text


class KeyValueFormatter(logging.Formatter):
    """``<ts> <LEVEL> <logger>: <msg> key=value ...`` with the active
    span's context appended."""

    def format(self, record: logging.LogRecord) -> str:
        base = (f"{self.formatTime(record)} {record.levelname} "
                f"{record.name}: {record.getMessage()}")
        ctx = _span_context()
        if ctx:
            base += " " + " ".join(
                f"{k}={_kv(v)}" for k, v in sorted(ctx.items()))
        if record.exc_info:
            base += "\n" + self.formatException(record.exc_info)
        return base


class JsonFormatter(logging.Formatter):
    """One JSON object per line; span context under "ctx"."""

    def format(self, record: logging.LogRecord) -> str:
        entry: Dict[str, Any] = {
            "ts": self.formatTime(record),
            "level": record.levelname,
            "logger": record.name,
            "msg": record.getMessage(),
        }
        ctx = _span_context()
        if ctx:
            entry["ctx"] = {k: str(v) for k, v in ctx.items()}
        if record.exc_info:
            entry["exc"] = self.formatException(record.exc_info)
        return json.dumps(entry, sort_keys=True)


_installed_handler: "logging.Handler | None" = None


def configure(level: int = logging.INFO, json_mode: bool = False) -> None:
    """Install the structured handler on the root logger (cli.main).

    basicConfig semantics, deliberately: if the root logger already has
    FOREIGN handlers (pytest's caplog capture, an embedding app), they
    are left untouched — ripping them out would silently break the
    host's capture. Our own handler (tracked) is installed once and
    reconfigured on repeat calls; the level is always applied."""
    global _installed_handler
    root = logging.getLogger()
    formatter = JsonFormatter() if json_mode else KeyValueFormatter()
    if _installed_handler is not None and _installed_handler in root.handlers:
        _installed_handler.setFormatter(formatter)
    elif not root.handlers:
        _installed_handler = logging.StreamHandler()
        _installed_handler.setFormatter(formatter)
        root.addHandler(_installed_handler)
    root.setLevel(level)
