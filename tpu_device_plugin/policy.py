"""policy — the gpu_ext-inspired sandboxed policy hook layer.

Fleet operators tune placement and health behavior today by forking the
daemon. This module makes those decisions operator-loadable instead
(ROADMAP item 1): small Python policy modules, loaded from
``--policy-dir``, run under a restricted-builtins evaluator and hook
three decision points:

  score_allocation(ctx) -> list[str] | None
      Override the GetPreferredAllocation winner. ``ctx`` carries the
      kubelet's available/must-include sets, the requested size, and the
      builtin engine's choice + ICI contiguity score
      (placement.selection_score — the PR 10 engine stays the baseline
      the policy COMPOSES with). Return None to keep the builtin choice;
      a returned list must be a valid allocation (every must-include id,
      exactly `size` ids, all drawn from available+must) or it is
      counted invalid and discarded.

  health_verdict(ctx) -> bool | None
      Override one health source's verdict before it enters the ANDed
      device table (``ctx``: device, healthy, source). None keeps the
      observed verdict. Operators use this to quarantine flapping chips
      harder or to ignore a known-noisy source on specific fleets.

  admit(ctx) -> bool | str | None
      Admission throttle on the attach planes (``ctx``: op
      "prepare"/"allocate", claim/resource identity). None/True admits;
      False or a reason string rejects — the caller surfaces a typed
      rejection, it never crashes the RPC.

  remediate(ctx) -> bool | str | None
      Veto/approve an automated remediation action before the
      remediation engine (remediation.py) applies it (``ctx``: action
      kind, the breached SLO, the target node/knob and its parameters).
      None/True approves; False or a reason string VETOES — the action
      is counted and logged as vetoed, never silently dropped. The same
      deadline + breaker containment applies: a raising or slow
      remediate hook falls back to approving the engine's builtin
      decision.

Misbehaving policies cannot take the daemon down, by construction:

- **sandbox** — policy source is exec'd with a curated builtins table
  (no ``__import__``, no ``open``, no ``getattr``/``eval``/``exec``)
  AND the loader statically rejects any dunder-name access in the
  module's AST — ``().__class__.__base__.__subclasses__()``-style
  object-graph walks, the classic curated-builtins escape, fail at
  LOAD time with PolicyLoadError. The sandbox is a guard rail against
  operator mistakes and casual capability creep, not a substitute for
  reviewing what lands in ``--policy-dir``: policy files come from the
  node's filesystem, which is already a privileged surface.
- **per-hook call deadline** — every invocation is wall-clocked; a
  result that arrives after ``hook_deadline_ms`` is DISCARDED (builtin
  behavior wins), counted, and charged to the breaker. Python cannot
  preempt a hot loop, so the deadline bounds *damage*, not latency of a
  single call — the breaker bounds repetition.
- **circuit breaker** — each hook function carries a
  resilience.CircuitBreaker; raising or slow calls trip it OPEN and the
  engine skips the hook (builtin behavior) until the cooldown's
  half-open probe succeeds.

Decisions are observable: per-hook counters + breaker states on /status
(``policy``) and /metrics (``tdp_policy_*``), and a bounded
recent-decision ring on ``/debug/policy``.

The engine is OPT-IN per process: servers and the DRA driver hold
``policy=None`` by default, and every hot-path consultation starts with
a None/has-hook check — the zero-lock read-path gates run without an
engine and are unaffected. With hooks loaded, a consultation takes the
hook's breaker lock; that is the documented cost of running operator
code on the decision path.

Fault site ``policy.hook`` (raising kind) fires inside the guarded
invocation — an armed error/timeout is indistinguishable from a raising
or slow policy, which is exactly what test_chaos.py scripts.
"""

from __future__ import annotations

import logging
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

from . import faults
from .epoch import AtomicCounter
from .resilience import CircuitBreaker

log = logging.getLogger(__name__)

HOOK_NAMES = ("score_allocation", "health_verdict", "admit", "remediate")
DECISION_RING = 64

# What operator policy code may use. Deliberately small: pure-compute
# builtins only — no import machinery, no I/O, no attribute bypasses
# (getattr/setattr/vars/globals are out: they are the classic sandbox
# escape primitives), no exec/eval/compile.
SAFE_BUILTINS: Dict[str, Any] = {
    "abs": abs, "all": all, "any": any, "bool": bool, "dict": dict,
    "divmod": divmod, "enumerate": enumerate, "filter": filter,
    "float": float, "frozenset": frozenset, "int": int, "len": len,
    "list": list, "map": map, "max": max, "min": min, "range": range,
    "repr": repr, "reversed": reversed, "round": round, "set": set,
    "sorted": sorted, "str": str, "sum": sum, "tuple": tuple, "zip": zip,
    "True": True, "False": False, "None": None,
    "ValueError": ValueError, "KeyError": KeyError, "TypeError": TypeError,
}


class PolicyLoadError(Exception):
    """The policy source failed to load (syntax error, sandbox
    violation at module body, non-callable hook). Loading is fail-loud:
    a daemon must refuse to start with a broken policy rather than run
    silently without it."""


class _Hook:
    """One loaded hook function + its failure containment."""

    __slots__ = ("module", "name", "fn", "breaker", "calls", "errors",
                 "deadline_exceeded", "rejected_open", "overrides")

    def __init__(self, module: str, name: str, fn: Callable,
                 breaker: CircuitBreaker) -> None:
        self.module = module
        self.name = name
        self.fn = fn
        self.breaker = breaker
        self.calls = AtomicCounter()
        self.errors = AtomicCounter()
        self.deadline_exceeded = AtomicCounter()
        self.rejected_open = AtomicCounter()
        self.overrides = AtomicCounter()


class PolicyEngine:
    """Loads policy modules and serves the three decision points.

    Loading happens once at startup (cli.main); after ``load_*`` the
    hook table is immutable, so ``has_hook`` is one dict read and an
    engine with no hooks costs the hot paths one attribute check."""

    def __init__(self, hook_deadline_ms: float = 25.0,
                 breaker_threshold: int = 3,
                 breaker_cooldown_s: float = 30.0,
                 clock: Callable[[], float] = time.monotonic) -> None:
        if hook_deadline_ms <= 0:
            raise ValueError("hook_deadline_ms must be > 0")
        self.hook_deadline_ms = hook_deadline_ms
        self._breaker_threshold = breaker_threshold
        self._breaker_cooldown_s = breaker_cooldown_s
        self._clock = clock
        self._hooks: Dict[str, List[_Hook]] = {n: [] for n in HOOK_NAMES}
        self.modules: List[str] = []
        self.invalid_overrides = AtomicCounter()
        # recent decisions for /debug/policy: C-atomic bounded appends,
        # read by list() copy — no lock on either side
        self._decisions: deque = deque(maxlen=DECISION_RING)

    # ----------------------------------------------------------- loading

    @staticmethod
    def _reject_dunders(module_name: str, source: str) -> None:
        """Static sandbox half: no dunder-name access anywhere in the
        policy AST. Attribute walks like ``().__class__.__base__`` are
        the standard escape out of a curated-builtins exec — pure
        decision functions never need them."""
        import ast
        try:
            tree = ast.parse(source)
        except SyntaxError as exc:
            raise PolicyLoadError(f"policy {module_name}: {exc}") from exc

        def dunder(name: str) -> bool:
            return name.startswith("__") and name.endswith("__")

        for node in ast.walk(tree):
            name = None
            if isinstance(node, ast.Attribute) and dunder(node.attr):
                name = node.attr
            elif isinstance(node, ast.Name) and dunder(node.id):
                name = node.id
            if name is not None:
                raise PolicyLoadError(
                    f"policy {module_name}: dunder access {name!r} at "
                    f"line {node.lineno} is not allowed (sandbox)")

    def load_source(self, module_name: str, source: str) -> None:
        """Compile + exec one policy module under the sandbox and
        register any hook functions it defines."""
        self._reject_dunders(module_name, source)
        try:
            code = compile(source, f"<policy:{module_name}>", "exec")
        except SyntaxError as exc:
            raise PolicyLoadError(f"policy {module_name}: {exc}") from exc
        namespace: Dict[str, Any] = {"__builtins__": dict(SAFE_BUILTINS)}
        try:
            exec(code, namespace)   # noqa: S102 — sandboxed by builtins
        except Exception as exc:
            raise PolicyLoadError(
                f"policy {module_name} failed at load: "
                f"{type(exc).__name__}: {exc}") from exc
        found = 0
        for hook_name in HOOK_NAMES:
            fn = namespace.get(hook_name)
            if fn is None:
                continue
            if not callable(fn):
                raise PolicyLoadError(
                    f"policy {module_name}: {hook_name} is not callable")
            breaker = CircuitBreaker(
                failure_threshold=self._breaker_threshold,
                reset_timeout_s=self._breaker_cooldown_s,
                clock=self._clock,
                name=f"policy.{module_name}.{hook_name}")
            self._hooks[hook_name].append(
                _Hook(module_name, hook_name, fn, breaker))
            found += 1
        if not found:
            raise PolicyLoadError(
                f"policy {module_name}: defines none of {HOOK_NAMES}")
        self.modules.append(module_name)
        log.info("policy: loaded %s (%d hook(s))", module_name, found)

    def load_dir(self, path: str) -> int:
        """Load every ``*.py`` under `path` (sorted; fail-loud on the
        first broken module). Returns the module count."""
        import os
        try:
            entries = sorted(e for e in os.listdir(path)
                             if e.endswith(".py"))
        except OSError as exc:
            raise PolicyLoadError(f"policy dir {path!r}: {exc}") from exc
        for entry in entries:
            with open(os.path.join(path, entry), "r",
                      encoding="utf-8") as f:
                self.load_source(entry.removesuffix(".py"), f.read())
        return len(entries)

    def has_hook(self, hook_name: str) -> bool:
        return bool(self._hooks.get(hook_name))

    # --------------------------------------------------------- invocation

    def _invoke(self, hook_name: str, ctx: dict,
                ) -> "tuple[Optional[Any], Optional[_Hook]]":
        """Run the hook chain for one decision; the FIRST non-None
        result wins and STOPS the chain (later hooks' results could
        never apply, so charging callers their latency would be pure
        waste). Raising, slow, or breaker-open hooks contribute nothing
        (builtin behavior); every outcome is counted. Returns
        (value, winning hook) — the CALLER credits the winner's
        override counter only when the value actually changed behavior
        (a policy answering 'keep builtin' is not an override)."""
        for hook in self._hooks[hook_name]:
            if not hook.breaker.allow():
                hook.rejected_open.add()
                continue
            hook.calls.add()
            t0 = self._clock()
            try:
                # the fault point rides INSIDE the guarded call: an
                # armed error/timeout is a raising policy, exactly
                faults.fire("policy.hook", hook=hook_name,
                            module=hook.module)
                value = hook.fn(dict(ctx))
                elapsed_ms = (self._clock() - t0) * 1e3
            except Exception as exc:
                hook.errors.add()
                hook.breaker.record_failure()
                log.warning("policy %s.%s raised: %s (builtin behavior "
                            "kept)", hook.module, hook_name, exc)
                continue
            if elapsed_ms > self.hook_deadline_ms:
                # post-hoc deadline: the result is discarded, the slow
                # call charged to the breaker — Python cannot preempt
                # the call itself, but repetition is bounded
                hook.deadline_exceeded.add()
                hook.breaker.record_failure()
                log.warning("policy %s.%s exceeded deadline "
                            "(%.1f ms > %g ms); result discarded",
                            hook.module, hook_name, elapsed_ms,
                            self.hook_deadline_ms)
                continue
            hook.breaker.record_success()
            if value is not None:
                return value, hook
        return None, None

    def _note_decision(self, hook_name: str, ctx: dict,
                       outcome: str, detail: object = None) -> None:
        self._decisions.append({
            "hook": hook_name, "outcome": outcome, "detail": detail,
            "ctx": {k: v for k, v in ctx.items()
                    if isinstance(v, (str, int, float, bool))},
            "ts": time.time()})

    # ------------------------------------------------------ decision API

    def score_allocation(self, ctx: dict) -> Optional[List[str]]:
        """A validated override of the preferred-allocation choice, or
        None (builtin wins). Invalid overrides are counted and dropped."""
        if not self.has_hook("score_allocation"):
            return None
        # validation inputs are snapshotted BEFORE the hook runs: the
        # hook receives a shallow ctx copy whose LISTS it could mutate,
        # and validating against post-mutation state would let a policy
        # smuggle a nonexistent device past the validator
        must = list(ctx.get("must_include", ()))
        size = int(ctx.get("size", 0))
        legal = set(ctx.get("available", ())) | set(must)
        value, winner = self._invoke("score_allocation", ctx)
        if value is None:
            return None
        try:
            ids = [str(x) for x in value]
        except TypeError:
            ids = None
        if (ids is None or len(ids) != size or len(set(ids)) != len(ids)
                or not set(ids) <= legal or not set(must) <= set(ids)):
            self.invalid_overrides.add()
            self._note_decision("score_allocation", ctx, "invalid",
                                detail=repr(value)[:120])
            log.warning("policy: score_allocation override %r is not a "
                        "valid allocation (size=%d, must=%s); builtin "
                        "choice kept", value, size, must)
            return None
        winner.overrides.add()
        self._note_decision("score_allocation", ctx, "override",
                            detail=ids)
        return ids

    def health_verdict(self, device: str, healthy: bool,
                       source: str) -> bool:
        """One source's verdict after policy; the observed verdict when
        no hook overrides."""
        if not self.has_hook("health_verdict"):
            return healthy
        ctx = {"device": device, "healthy": healthy, "source": source}
        value, winner = self._invoke("health_verdict", ctx)
        if value is None or bool(value) == healthy:
            return healthy
        winner.overrides.add()
        self._note_decision("health_verdict", ctx, "override",
                            detail=bool(value))
        return bool(value)

    def admit(self, ctx: dict) -> Optional[str]:
        """None = admitted; a reason string = rejected (the caller
        surfaces it as a typed rejection)."""
        if not self.has_hook("admit"):
            return None
        value, winner = self._invoke("admit", ctx)
        if value is None or value is True:
            # an explicit True is plain admission — builtin behavior,
            # not an override
            return None
        reason = value if isinstance(value, str) else "rejected by policy"
        winner.overrides.add()
        self._note_decision("admit", ctx, "reject", detail=reason)
        return reason

    def remediate(self, ctx: dict) -> Optional[str]:
        """None = the remediation action is approved; a reason string =
        VETOED (the remediation engine counts and logs the veto, keeps
        the knob untouched). Same first-non-None-wins chain and
        containment as admit()."""
        if not self.has_hook("remediate"):
            return None
        value, winner = self._invoke("remediate", ctx)
        if value is None or value is True:
            return None
        reason = value if isinstance(value, str) else "vetoed by policy"
        winner.overrides.add()
        self._note_decision("remediate", ctx, "veto", detail=reason)
        return reason

    # ----------------------------------------------------------- surface

    def snapshot(self) -> dict:
        """Lock-free /status body: per-hook counters + breaker states
        (AtomicCounter sums and breaker snapshots)."""
        hooks = []
        for name in HOOK_NAMES:
            for hook in self._hooks[name]:
                hooks.append({
                    "hook": name, "module": hook.module,
                    "calls": hook.calls.value,
                    "overrides": hook.overrides.value,
                    "errors": hook.errors.value,
                    "deadline_exceeded": hook.deadline_exceeded.value,
                    "rejected_while_open": hook.rejected_open.value,
                    "breaker": hook.breaker.snapshot(),
                })
        return {"modules": list(self.modules),
                "hook_deadline_ms": self.hook_deadline_ms,
                "invalid_overrides": self.invalid_overrides.value,
                "hooks": hooks}

    def debug(self) -> dict:
        """The /debug/policy body: the snapshot plus the bounded
        recent-decision ring (C-atomic deque copy)."""
        out = self.snapshot()
        out["recent_decisions"] = list(self._decisions)
        return out
