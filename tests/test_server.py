"""End-to-end plugin server tests against a fake kubelet.

Goes beyond the reference's fake-stream harness
(generic_device_plugin_test.go:55-62): a real gRPC Registration server plays
kubelet, the plugin serves on a real unix socket, and health transitions are
induced by deleting/creating actual device nodes.
"""

import os
import threading
import time

import grpc
import pytest

from tests.fakehost import FakeChip, FakeHost, FakeKubelet
from tpu_device_plugin import kubeletapi as api
from tpu_device_plugin.config import Config
from tpu_device_plugin.discovery import discover_passthrough
from tpu_device_plugin.kubeletapi import pb
from tpu_device_plugin.server import TpuDevicePlugin


@pytest.fixture
def rig(short_root):
    """FakeHost + fake kubelet Registration server + started plugin."""
    host = FakeHost(short_root)
    for i, (g, n) in enumerate([("11", 0), ("11", 0), ("12", 1), ("12", 1)]):
        host.add_chip(FakeChip(f"0000:00:{4 + i:02x}.0", iommu_group=g, numa_node=n))
    # short probe cadence: the native probe now also observes group nodes, so
    # recovery after a node reappears is bounded by health_poll_s
    from dataclasses import replace
    cfg = replace(Config().with_root(host.root), health_poll_s=0.2)
    os.makedirs(cfg.device_plugin_path, exist_ok=True)
    kubelet = FakeKubelet(cfg.kubelet_socket)
    registry, generations = discover_passthrough(cfg)
    plugin = TpuDevicePlugin(cfg, "v4", registry,
                             registry.devices_by_model["0062"],
                             torus_dims=generations["0062"].host_topology)
    plugin.start()
    yield host, cfg, kubelet, plugin
    plugin.stop()
    kubelet.stop()


def _wait(pred, timeout=5.0, interval=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return False


def test_start_registers_with_kubelet(rig):
    host, cfg, kubelet, plugin = rig
    assert kubelet.wait_for(1, timeout=5)
    req = kubelet.registrations[0]
    assert req.resource_name == "cloud-tpus.google.com/v4"
    assert req.version == "v1beta1"
    assert req.endpoint == os.path.basename(plugin.socket_path)
    assert req.options.get_preferred_allocation_available is True
    assert os.path.exists(plugin.socket_path)


def test_list_and_watch_health_transitions(rig):
    host, cfg, kubelet, plugin = rig
    updates = []
    done = threading.Event()

    def consume():
        with grpc.insecure_channel(f"unix://{plugin.socket_path}") as ch:
            stub = api.DevicePluginStub(ch)
            try:
                for resp in stub.ListAndWatch(pb.Empty()):
                    updates.append({d.ID: d.health for d in resp.devices})
                    done.set()
            except grpc.RpcError:
                pass

    t = threading.Thread(target=consume, daemon=True)
    t.start()
    assert _wait(lambda: len(updates) >= 1)
    assert set(updates[0].values()) == {"Healthy"}
    assert len(updates[0]) == 4

    # kill group 12's vfio node -> chips 06/07 go Unhealthy
    host.remove_vfio_group("12")
    assert _wait(lambda: len(updates) >= 2 and
                 updates[-1]["0000:00:06.0"] == "Unhealthy")
    assert updates[-1]["0000:00:07.0"] == "Unhealthy"
    assert updates[-1]["0000:00:04.0"] == "Healthy"

    # node comes back -> Healthy again
    with open(os.path.join(host.devfs, "vfio", "12"), "w") as f:
        f.write("")
    assert _wait(lambda: updates[-1]["0000:00:06.0"] == "Healthy")


def test_list_and_watch_client_cancel_frees_worker(rig):
    """The event-driven stream sleeps on the condvar with no timeout; a
    client cancel must wake it via the RPC-termination callback so the
    worker thread is freed (not pinned until the next health event)."""
    host, cfg, kubelet, plugin = rig
    before = {t.name for t in threading.enumerate()}
    calls = []
    for i in range(3):
        ch = grpc.insecure_channel(f"unix://{plugin.socket_path}")
        call = api.DevicePluginStub(ch).ListAndWatch(pb.Empty())
        next(call)  # initial list delivered; stream now parked on condvar
        calls.append((ch, call))
    for ch, call in calls:
        call.cancel()
        ch.close()
    # the freed workers must be able to serve new RPCs: the pool has 8
    # threads, so burn through 8 fresh streams to prove none stayed pinned
    for i in range(8):
        with grpc.insecure_channel(f"unix://{plugin.socket_path}") as ch:
            call = api.DevicePluginStub(ch).ListAndWatch(pb.Empty())
            assert len(next(call).devices) == 4
            call.cancel()
    assert _wait(
        lambda: len({t.name for t in threading.enumerate()} - before) <= 8)


def test_allocate_and_preferred_over_socket(rig):
    host, cfg, kubelet, plugin = rig
    with grpc.insecure_channel(f"unix://{plugin.socket_path}") as ch:
        stub = api.DevicePluginStub(ch)
        pref = stub.GetPreferredAllocation(
            pb.PreferredAllocationRequest(container_requests=[
                pb.ContainerPreferredAllocationRequest(
                    available_deviceIDs=["0000:00:04.0", "0000:00:07.0",
                                         "0000:00:05.0", "0000:00:06.0"],
                    allocation_size=2)]),
            timeout=5)
        picked = list(pref.container_responses[0].deviceIDs)
        assert picked == ["0000:00:04.0", "0000:00:05.0"]

        resp = stub.Allocate(
            pb.AllocateRequest(container_requests=[
                pb.ContainerAllocateRequest(devices_ids=picked)]),
            timeout=5)
        creps = resp.container_responses[0]
        assert creps.envs["PCI_RESOURCE_CLOUD_TPUS_GOOGLE_COM_V4"] == \
            "0000:00:04.0,0000:00:05.0"
        assert [d.container_path for d in creps.devices] == \
            ["/dev/vfio/vfio", "/dev/vfio/11"]


def test_allocate_unknown_device_is_invalid_argument(rig):
    host, cfg, kubelet, plugin = rig
    with grpc.insecure_channel(f"unix://{plugin.socket_path}") as ch:
        stub = api.DevicePluginStub(ch)
        with pytest.raises(grpc.RpcError) as exc_info:
            stub.Allocate(
                pb.AllocateRequest(container_requests=[
                    pb.ContainerAllocateRequest(devices_ids=["0000:00:99.0"])]),
                timeout=5)
        assert exc_info.value.code() == grpc.StatusCode.INVALID_ARGUMENT


def test_must_include_too_large_is_invalid_argument(rig):
    host, cfg, kubelet, plugin = rig
    with grpc.insecure_channel(f"unix://{plugin.socket_path}") as ch:
        stub = api.DevicePluginStub(ch)
        with pytest.raises(grpc.RpcError) as exc_info:
            stub.GetPreferredAllocation(
                pb.PreferredAllocationRequest(container_requests=[
                    pb.ContainerPreferredAllocationRequest(
                        available_deviceIDs=["0000:00:04.0", "0000:00:05.0"],
                        must_include_deviceIDs=["0000:00:04.0", "0000:00:05.0"],
                        allocation_size=1)]),
                timeout=5)
        assert exc_info.value.code() == grpc.StatusCode.INVALID_ARGUMENT


def test_kubelet_restart_triggers_reregistration(rig):
    host, cfg, kubelet, plugin = rig
    assert kubelet.wait_for(1, timeout=5)
    # kubelet restart wipes the device-plugin dir: remove the plugin's socket
    os.unlink(plugin.socket_path)
    assert kubelet.wait_for(2, timeout=10), "plugin did not re-register"
    assert len(kubelet.registrations) == 2
    assert _wait(lambda: os.path.exists(plugin.socket_path))
    # plugin is serving again on the fresh socket
    with grpc.insecure_channel(f"unix://{plugin.socket_path}") as ch:
        stub = api.DevicePluginStub(ch)
        opts = stub.GetDevicePluginOptions(pb.Empty(), timeout=5)
        assert opts.get_preferred_allocation_available is True


def test_stop_removes_socket(rig):
    host, cfg, kubelet, plugin = rig
    assert os.path.exists(plugin.socket_path)
    plugin.stop()
    assert not os.path.exists(plugin.socket_path)


def test_allocate_rejects_other_models_bdf(short_root):
    """The v5e plugin must refuse a v4 BDF even though both live in the same
    registry (the reference's global map would hand it out)."""
    host = FakeHost(short_root)
    host.add_chip(FakeChip("0000:00:04.0", device_id="0062", iommu_group="11"))
    host.add_chip(FakeChip("0000:01:00.0", device_id="0063", iommu_group="21"))
    from dataclasses import replace
    cfg = replace(Config().with_root(host.root), health_poll_s=60)
    os.makedirs(cfg.device_plugin_path, exist_ok=True)
    kubelet = FakeKubelet(cfg.kubelet_socket)
    registry, generations = discover_passthrough(cfg)
    plugin = TpuDevicePlugin(cfg, "v5e", registry,
                             registry.devices_by_model["0063"])
    plugin.start()
    try:
        with grpc.insecure_channel(f"unix://{plugin.socket_path}") as ch:
            stub = api.DevicePluginStub(ch)
            with pytest.raises(grpc.RpcError) as exc_info:
                stub.Allocate(
                    pb.AllocateRequest(container_requests=[
                        pb.ContainerAllocateRequest(
                            devices_ids=["0000:00:04.0"])]),
                    timeout=5)
            assert exc_info.value.code() == grpc.StatusCode.INVALID_ARGUMENT
            # its own chip still allocates fine
            resp = stub.Allocate(
                pb.AllocateRequest(container_requests=[
                    pb.ContainerAllocateRequest(devices_ids=["0000:01:00.0"])]),
                timeout=5)
            assert resp.container_responses[0].devices
    finally:
        plugin.stop()
        kubelet.stop()
