"""ApiClient unit tests: pool/retry semantics and URL handling.

The keep-alive pool's failure model is load-bearing (duplicate apiserver
writes on a wrong retry; spurious failures on a right one withheld), so
the legs are pinned with fake connections rather than a live server —
tests/test_dra.py covers the live HTTP/1.1 reuse behavior.
"""
import http.client

import pytest

from tpu_device_plugin.kubeapi import ApiClient, ApiError


class FakeResponse:
    def __init__(self, status=200, data=b"{}", will_close=False):
        self.status = status
        self._data = data
        self.will_close = will_close

    def read(self):
        return self._data


class FakeConn:
    """Scripted connection: raises `error` on request, else responds."""

    def __init__(self, error=None, status=200, data=b"ok"):
        self.error = error
        self.status = status
        self.data = data
        self.requests = []
        self.closed = False

    def request(self, method, path, body=None, headers=None):
        self.requests.append((method, path))
        if self.error is not None:
            raise self.error

    def getresponse(self):
        return FakeResponse(self.status, self.data)

    def close(self):
        self.closed = True


def client():
    return ApiClient("http://example.invalid:1", token_path="/nonexistent")


def test_stale_reused_connection_retries_on_brand_new_conn(monkeypatch):
    """A stale-signature failure on a REUSED conn retries exactly once on
    a brand-new connection — never on another pool member (a second stale
    keep-alive after an apiserver restart would fail a request a fresh
    connection serves)."""
    c = client()
    stale = FakeConn(error=BrokenPipeError("server idled out"))
    fresh = FakeConn(data=b"payload")
    monkeypatch.setattr(c, "_get_conn", lambda: (stale, True))
    monkeypatch.setattr(c, "_new_conn", lambda: fresh)
    assert c.request("/x") == b"payload"
    assert stale.closed
    assert fresh.requests == [("GET", "/x")]


def test_fresh_connection_failure_does_not_retry(monkeypatch):
    """The one-attempt contract for fresh connections is kept: retrying
    would mask a genuinely down server and double every timeout."""
    c = client()
    fresh = FakeConn(error=BrokenPipeError("down"))
    calls = []
    news = []
    monkeypatch.setattr(c, "_get_conn",
                        lambda: (calls.append(1) or fresh, False))
    monkeypatch.setattr(c, "_new_conn", lambda: news.append(1) or FakeConn())
    with pytest.raises(ApiError):
        c.request("/x")
    assert len(calls) == 1
    assert news == []   # the retry leg (_new_conn) was never taken


def test_response_timeout_never_retries_a_write(monkeypatch):
    """TimeoutError on a reused conn is NOT a stale-keep-alive signature:
    the server may have processed the request, and replaying a POST would
    duplicate the write. It surfaces as ApiError without retry."""
    c = client()
    conn = FakeConn(error=TimeoutError("read timed out"))
    news = []
    monkeypatch.setattr(c, "_get_conn", lambda: (conn, True))
    monkeypatch.setattr(c, "_new_conn", lambda: news.append(1) or FakeConn())
    with pytest.raises(ApiError):
        c.request("/slices", method="POST", body=b"{}")
    assert news == []          # no second attempt


class FakeConnResponsePhaseError(FakeConn):
    """Send succeeds; the failure happens at getresponse() — the server
    may have processed the request."""

    def request(self, method, path, body=None, headers=None):
        self.requests.append((method, path))   # send phase succeeds

    def getresponse(self):
        raise self.error


def test_response_phase_reset_never_retries_a_write(monkeypatch):
    """A ConnectionResetError AFTER the request was sent may mean the
    server processed it (restart mid-response): replaying a POST would
    duplicate the write, so only GET retries in the response phase."""
    c = client()
    conn = FakeConnResponsePhaseError(error=ConnectionResetError("reset"))
    news = []
    monkeypatch.setattr(c, "_get_conn", lambda: (conn, True))
    monkeypatch.setattr(c, "_new_conn", lambda: news.append(1) or FakeConn())
    with pytest.raises(ApiError):
        c.request("/slices", method="POST", body=b"{}")
    assert news == []          # POST: no second attempt
    # ...but a GET retries: its replay cannot duplicate a write
    conn2 = FakeConnResponsePhaseError(error=ConnectionResetError("reset"))
    fresh = FakeConn(data=b"payload")
    monkeypatch.setattr(c, "_get_conn", lambda: (conn2, True))
    monkeypatch.setattr(c, "_new_conn", lambda: fresh)
    assert c.request("/slices") == b"payload"


def test_redirect_is_an_apierror_not_a_body(monkeypatch):
    """http.client does not follow redirects (urllib did): a 3xx must
    surface as ApiError, never as a successful HTML body that get_json
    would feed to json.loads."""
    c = client()
    conn = FakeConn(status=302, data=b"<html>moved</html>")
    monkeypatch.setattr(c, "_get_conn", lambda: (conn, False))
    with pytest.raises(ApiError) as exc_info:
        c.request("/x")
    assert exc_info.value.code == 302


def test_http_exception_wrapped_as_apierror(monkeypatch):
    """IncompleteRead and friends must surface as ApiError (the callers'
    exception contract), not leak as raw HTTPException."""
    c = client()
    conn = FakeConn(error=http.client.IncompleteRead(b"partial"))
    monkeypatch.setattr(c, "_get_conn", lambda: (conn, False))
    with pytest.raises(ApiError):
        c.request("/x")


def test_http_error_status_carries_code(monkeypatch):
    c = client()
    conn = FakeConn(status=404, data=b"not found")
    monkeypatch.setattr(c, "_get_conn", lambda: (conn, False))
    with pytest.raises(ApiError) as exc_info:
        c.request("/x")
    assert exc_info.value.code == 404
    assert "not found" in str(exc_info.value)


def test_server_path_prefix_is_preserved(monkeypatch):
    """--api-server https://host:6443/apiproxy must hit
    /apiproxy/apis/..., matching what the pre-pool urllib client sent."""
    c = ApiClient("http://host:1/apiproxy/", token_path="/nonexistent")
    conn = FakeConn()
    monkeypatch.setattr(c, "_get_conn", lambda: (conn, False))
    c.request("/apis/resource.k8s.io")
    assert conn.requests == [("GET", "/apiproxy/apis/resource.k8s.io")]


def test_stale_retry_is_exactly_once(monkeypatch):
    """When the brand-new retry connection ALSO fails with a stale
    signature, the request surfaces as ApiError — there is never a third
    attempt (the retry loop is (0, 1), not open-ended)."""
    c = client()
    stale = FakeConn(error=BrokenPipeError("idled out"))
    fresh = FakeConn(error=BrokenPipeError("really down"))
    news = []
    monkeypatch.setattr(c, "_get_conn", lambda: (stale, True))
    monkeypatch.setattr(c, "_new_conn", lambda: news.append(1) or fresh)
    with pytest.raises(ApiError):
        c.request("/x")
    assert len(news) == 1                       # exactly one retry leg
    assert stale.closed and fresh.closed


def test_timeout_on_reused_get_does_not_retry(monkeypatch):
    """TimeoutError is outside _RETRYABLE_STALE for EVERY method — even a
    GET on a reused pool member: a response-read timeout means the server
    may still be processing, and hammering it with a replay doubles its
    load exactly when it is slowest (the hazard documented at
    kubeapi.py:30)."""
    c = client()
    conn = FakeConn(error=TimeoutError("read timed out"))
    news = []
    monkeypatch.setattr(c, "_get_conn", lambda: (conn, True))
    monkeypatch.setattr(c, "_new_conn", lambda: news.append(1) or FakeConn())
    with pytest.raises(ApiError):
        c.request("/x")                          # GET
    assert news == []


# ------------------------------------------------------- circuit breaker


def test_breaker_trips_after_consecutive_transport_failures(monkeypatch):
    """Five consecutive transport failures open the breaker; the next
    request fails fast WITHOUT touching the connection pool."""
    c = client()
    attempts = []
    monkeypatch.setattr(
        c, "_get_conn",
        lambda: (attempts.append(1) or FakeConn(error=ConnectionRefusedError(
            "down")), False))
    for _ in range(c.breaker.failure_threshold):
        with pytest.raises(ApiError):
            c.request("/x")
    assert c.breaker.snapshot()["state"] == "open"
    before = len(attempts)
    with pytest.raises(ApiError, match="circuit breaker open"):
        c.request("/x")
    assert len(attempts) == before               # no network attempt


def test_breaker_counts_5xx_as_failure_but_4xx_as_success(monkeypatch):
    from tpu_device_plugin.resilience import CircuitBreaker
    c = ApiClient("http://example.invalid:1", token_path="/nonexistent",
                  breaker=CircuitBreaker(failure_threshold=2,
                                         reset_timeout_s=60.0))
    monkeypatch.setattr(c, "_get_conn",
                        lambda: (FakeConn(status=500, data=b"boom"), False))
    with pytest.raises(ApiError):
        c.request("/x")
    assert c.breaker.snapshot()["consecutive_failures"] == 1
    # a 404 means the apiserver answered: the streak resets
    monkeypatch.setattr(c, "_get_conn",
                        lambda: (FakeConn(status=404, data=b"nf"), False))
    with pytest.raises(ApiError):
        c.request("/x")
    assert c.breaker.snapshot()["consecutive_failures"] == 0
    assert c.breaker.snapshot()["state"] == "closed"


def test_breaker_half_open_probe_recovers(monkeypatch):
    """After the cooldown, exactly one probe goes through; its success
    closes the breaker for everyone."""
    from conftest import FakeClock
    from tpu_device_plugin.resilience import CircuitBreaker

    clock = FakeClock()
    c = ApiClient("http://example.invalid:1", token_path="/nonexistent",
                  breaker=CircuitBreaker(failure_threshold=1,
                                         reset_timeout_s=10.0, clock=clock))
    monkeypatch.setattr(c, "_get_conn",
                        lambda: (FakeConn(error=ConnectionRefusedError()),
                                 False))
    with pytest.raises(ApiError):
        c.request("/x")
    assert c.breaker.snapshot()["state"] == "open"
    clock.now = 10.0
    monkeypatch.setattr(c, "_get_conn",
                        lambda: (FakeConn(data=b"recovered"), False))
    assert c.request("/x") == b"recovered"
    assert c.breaker.snapshot()["state"] == "closed"


def test_pool_keeps_bounded_idle_connections():
    from tpu_device_plugin.kubeapi import MAX_IDLE_CONNECTIONS
    c = client()
    conns = [FakeConn() for _ in range(MAX_IDLE_CONNECTIONS + 2)]
    for conn in conns:
        c._put_conn(conn)
    assert len(c._idle) == MAX_IDLE_CONNECTIONS
    assert sum(1 for x in conns if x.closed) == 2  # overflow closed
