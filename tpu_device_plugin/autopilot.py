"""Continuous fleet autopilot — the overlapping-storm soak driver (ISSUE 12).

Real fleets never settle: boot storms land while claims churn, chips
fall off the bus mid-migration, rolling upgrades overlap defrag waves.
The PR 9-11 storms each exercised ONE shape at a time with a quiet
fleet around it; this module runs them ALL at once, for as long as
asked, against the watch-stream fabric — and checks the soak invariants
CONTINUOUSLY (fleetsim.fleet_invariants), not only at the end:

  - CLAIM STORMS: worker pools attach + detach claim batches on random
    nodes (the 100k-claim-event engine of the r14 soak);
  - MULTI-HOST SLICES: placement-engine claims prepared across nodes,
    torn down, residue-audited (exactly-once multiclaim commits);
  - FLIP WAVES: health flip storms whose publishes must coalesce;
  - HOT-UNPLUGS: surprise removals orphan claims, the orphans are
    cleaned up kubelet-style, the chip replugs and readmits;
  - DEFRAG WAVES: advisor proposals applied via the PR 7 migration
    handoff (the cross-node flight-recorder claim story);
  - ROLLING UPGRADES: drain → driver rebuild against the same
    checkpoint → restore, in waves (claims must survive every wave);
  - BOOT STORMS: republish waves across node groups.

Chaos rides on top: the fabric's watch-stream chaos (breaks, duplicate
deliveries, stalls — FleetApiServer.arm_watch_chaos) plus the
`kubeapi.watch` / `kubeapi.watch.dup` / `kubeapi.watch.stale` fault
sites fire THROUGHOUT a run with `watch_faults=True`, so every
convergence claim is measured under the event-driven, always-degrading
conditions the ISSUE names.

Concurrency model: one try-acquired lock per node serializes the
disruptive ops on that node (upgrade's driver swap, unplug's device
removal) against claim batches, while storms overlap freely ACROSS
nodes; multi-node ops (multiclaim, defrag, upgrade waves) additionally
serialize on one fleet lock and take their node locks in index order —
a static lock order, no deadlocks. These are soak-harness locks, not
daemon locks: the daemon's own concurrency is exactly what the storms
exercise.

Used by `bench.py --autopilot` (docs/bench_autopilot_r14.json), the CI
autopilot smoke leg, and `make soak-autopilot`.
"""

from __future__ import annotations

import logging
import random
import threading
import time
from concurrent import futures
from dataclasses import dataclass
from typing import Dict, List, Optional

from . import faults
from . import placement
from . import trace
from .fleetsim import FleetSim, fleet_invariants

log = logging.getLogger(__name__)


@dataclass
class AutopilotConfig:
    """Knobs for one soak run. The defaults are the CI smoke shape
    (N=8, ~60 s); `bench.py --autopilot` scales them to the r14
    acceptance run (256 nodes, ≥100k claim events)."""
    nodes: int = 8
    devices_per_node: int = 4
    duration_s: float = 60.0
    # run until BOTH the duration elapsed and this many claim events
    # (prepares + unprepares + orphans) landed; 0 = duration-bound only
    claim_event_target: int = 0
    max_wall_s: float = 0.0          # 0 = duration_s * 6 + 120
    seed: int = 1337
    latency_s: float = 0.0
    max_inflight: int = 0
    # storm worker pools (0 disables a storm type)
    claim_workers: int = 4
    claims_per_batch: int = 4
    multiclaim_workers: int = 1
    flip_workers: int = 1
    unplug_workers: int = 1
    migration_workers: int = 1
    defrag_workers: int = 1
    upgrade_workers: int = 1
    upgrade_wave_size: int = 2
    boot_workers: int = 1
    boot_wave_size: int = 4
    pinned_per_nodes: int = 4        # one long-lived claim per K nodes
    invariant_interval_s: float = 2.0
    # watch plane + chaos
    watch: bool = True
    watch_resync_s: float = 10.0
    watch_poll_s: float = 0.5
    # IDLE-COST knobs, scaled with fleet size: a stream re-establishes
    # every watch_timeout_s and every idle stream emits a bookmark per
    # bookmark_interval_s — at 256 nodes the N=8 defaults would spend
    # the whole GIL on rotation/bookmark churn (128 TCP setups/s + 512
    # bookmark parses/s) instead of claim events
    watch_timeout_s: float = 2.0
    bookmark_interval_s: float = 0.5
    watch_chaos: bool = True         # fabric-side break/dup/stall
    watch_chaos_break_p: float = 0.02
    watch_chaos_dup_p: float = 0.05
    watch_chaos_stall_s: float = 0.0
    watch_faults: bool = True        # kubeapi.watch* fault sites
    watch_fault_p: float = 0.02
    shapes: tuple = ("1x2", "2x2")   # multiclaim shapes
    # self-heal drill (ISSUE 16): after the storm quiesces, a RAMPED
    # delay fault burns a publish-RTT SLO against one victim node; the
    # report's selfheal_story must show the whole closed loop — breach
    # latches, the remediation engine acts (policy-approved, audited),
    # good traffic recovers the burn, the knobs roll back — all
    # reconstructed from ONE fleet-trace query on the breach exemplar
    selfheal: bool = False
    selfheal_fault_delay_s: float = 0.4
    selfheal_fault_jitter_s: float = 0.05
    selfheal_fault_ramp_s: float = 2.0
    # sharded-scheduler drill (ISSUE 17): after quiesce, N partitioned
    # FleetSchedulers place a concurrent claim wave over THIS fleet's
    # fabric through the optimistic CAS commit path, the cross-
    # scheduler exactly-once audit must hold, and every drill claim is
    # released back (zero residue). 0 disables the leg.
    sharded_schedulers: int = 2
    sharded_claims: int = 8


class FleetAutopilot:
    """Drive a FleetSim through overlapping storms with continuous
    invariant checking. run() returns the soak report dict; failures
    are REPORTED (report["ok"] is False with the violations), and also
    raised at the end unless raise_on_violation=False."""

    def __init__(self, cfg: AutopilotConfig,
                 sim: Optional[FleetSim] = None) -> None:
        self.cfg = cfg
        self._own_sim = sim is None
        self.sim = sim or FleetSim(
            n_nodes=cfg.nodes, devices_per_node=cfg.devices_per_node,
            latency_s=cfg.latency_s, max_inflight=cfg.max_inflight,
            seed=cfg.seed, watch=cfg.watch,
            watch_resync_s=cfg.watch_resync_s,
            watch_poll_s=cfg.watch_poll_s,
            watch_timeout_s=cfg.watch_timeout_s,
            bookmark_interval_s=cfg.bookmark_interval_s)
        self._stop_evt = threading.Event()
        self._threads: List[threading.Thread] = []
        # harness locks (see the module docstring's concurrency model)
        self._node_locks = [threading.Lock() for _ in self.sim.nodes]
        self._fleet_lock = threading.Lock()
        self._lock = threading.Lock()          # counters + shared state
        self.counters: Dict[str, int] = {
            "claim_events": 0, "prepares": 0, "unprepares": 0,
            "claim_errors_retried": 0, "claim_errors_final": 0,
            "multiclaims_placed": 0, "multiclaims_unplaceable": 0,
            "multiclaims_rolled_back": 0, "flip_storms": 0,
            "unplugs": 0, "orphans": 0, "orphans_cleaned": 0,
            "readmits": 0, "migrations": 0, "migrations_skipped": 0,
            "defrag_moves": 0, "defrag_skipped": 0,
            "defrag_recoveries": 0, "upgrades": 0, "republish_waves": 0,
            "invariant_checks": 0,
        }
        self._wave_seq = 0
        self._pinned: Dict[str, str] = {}      # uid -> node name
        self._torn_down: List[str] = []        # multiclaim uids torn down
        self.violations: List[str] = []
        self._story: Optional[dict] = None     # one migrated claim's spans

    # ------------------------------------------------------------ helpers

    def _count(self, **deltas: int) -> None:
        with self._lock:
            for key, d in deltas.items():
                self.counters[key] += d

    def _next_wave(self) -> int:
        with self._lock:
            self._wave_seq += 1
            return self._wave_seq

    def _running(self) -> bool:
        return not self._stop_evt.is_set()

    def _pick_node(self, rng: random.Random):
        i = rng.randrange(len(self.sim.nodes))
        return i, self.sim.nodes[i]

    def _try_node(self, i: int) -> bool:
        return self._node_locks[i].acquire(blocking=False)

    def _release_node(self, i: int) -> None:
        self._node_locks[i].release()

    def _spawn(self, fn, name: str, *args) -> None:
        def guarded() -> None:
            try:
                fn(*args)
            except Exception as exc:
                # a dead storm worker IS a soak failure: recording it
                # as a violation keeps the report honest (a silently
                # ended upgrade storm would otherwise leave ok=True on
                # the strength of its earlier waves)
                log.exception("autopilot: worker %s died", name)
                with self._lock:
                    self.violations.append(f"worker {name} died: {exc!r}")

        thread = threading.Thread(target=guarded, daemon=True,
                                  name=f"autopilot-{name}")
        self._threads.append(thread)
        thread.start()

    def _retry_claims(self, op, uids: List[str],
                      attempts: int = 3) -> List[str]:
        """The shared per-claim retry contract every storm uses: run a
        fleet claim op (attach/detach) until each claim's error clears
        or `attempts` rounds pass, counting retries and persistent
        failures. Returns the claims that SUCCEEDED — stragglers were
        counted `claim_errors_final` and stay wherever the op left
        them; callers must never pretend they completed."""
        succeeded: List[str] = []
        pending = list(uids)
        for _attempt in range(attempts):
            resp = op(pending)
            failed = [u for u in pending if resp.claims[u].error]
            failed_set = set(failed)
            succeeded += [u for u in pending if u not in failed_set]
            if not failed:
                return succeeded
            self._count(claim_errors_retried=len(failed))
            pending = failed
            time.sleep(0.01)
        self._count(claim_errors_final=len(pending))
        return succeeded

    def stop(self) -> None:
        self._stop_evt.set()
        for thread in self._threads:
            thread.join(timeout=30)

    # ------------------------------------------------------------- storms

    def _claim_worker(self, wid: int) -> None:
        cfg = self.cfg
        rng = random.Random((cfg.seed << 8) ^ wid)
        while self._running():
            i, node = self._pick_node(rng)
            if not self._try_node(i):
                time.sleep(0.002)
                continue
            try:
                uids = node.register_claims(cfg.claims_per_batch,
                                            wave=self._next_wave())
                succeeded = self._retry_claims(node.attach, uids)
                if succeeded:
                    self._count(prepares=len(succeeded),
                                claim_events=len(succeeded))
                done: List[str] = []
                if succeeded:
                    done = self._retry_claims(node.detach, succeeded)
                    self._count(unprepares=len(done),
                                claim_events=len(done))
                # deregister only claims the node no longer holds
                # prepared: never-attached ones and clean detaches. A
                # detach straggler stays in the fabric registry so the
                # checkpoint/fabric agreement invariant keeps seeing a
                # consistent pair instead of a phantom "lost claim".
                for uid in uids:
                    if uid not in succeeded or uid in done:
                        self.sim.apiserver.remove_claim("fleet", uid)
            finally:
                self._release_node(i)

    def _multiclaim_worker(self, wid: int) -> None:
        cfg = self.cfg
        rng = random.Random((cfg.seed << 9) ^ wid)
        while self._running():
            shape = rng.choice(cfg.shapes)
            uid = f"mc-{self._next_wave()}"
            with self._fleet_lock:
                res = self.sim.prepare_slice(shape, uid, best_effort=True)
                if res.get("placed"):
                    shards = res["shards"]
                    self._count(
                        multiclaims_placed=1,
                        prepares=len(shards), claim_events=len(shards))
                    # tear straight back down (the storm's job is churn;
                    # capacity pinning is the pinned claims' job)
                    by_name = self.sim._node_by_name()
                    all_clean = True
                    for node_name, _raws in shards:
                        sub = f"{uid}-{node_name}"
                        resp = by_name[node_name].detach([sub])
                        if resp.claims[sub].error:
                            # leave the sub-claim registered: its
                            # checkpoint entry survives, and the residue
                            # audit must not expect a torn-down uid
                            all_clean = False
                            continue
                        self._count(unprepares=1, claim_events=1)
                        self.sim.apiserver.remove_claim("fleet", sub)
                    if all_clean:
                        with self._lock:
                            self._torn_down.append(uid)
                elif res.get("rolled_back"):
                    self._count(multiclaims_rolled_back=1)
                    with self._lock:
                        self._torn_down.append(uid)
                else:
                    self._count(multiclaims_unplaceable=1)
            time.sleep(rng.uniform(0.01, 0.1))

    def _flip_worker(self, wid: int) -> None:
        rng = random.Random((self.cfg.seed << 10) ^ wid)
        while self._running():
            i, node = self._pick_node(rng)
            if not self._try_node(i):
                time.sleep(0.002)
                continue
            try:
                node.flip_storm(rng.randrange(2, 6))
                self._count(flip_storms=1)
            finally:
                self._release_node(i)
            time.sleep(rng.uniform(0.01, 0.1))

    def _unplug_worker(self, wid: int) -> None:
        rng = random.Random((self.cfg.seed << 11) ^ wid)
        while self._running():
            i, node = self._pick_node(rng)
            if not self._try_node(i):
                time.sleep(0.002)
                continue
            try:
                bdf = rng.choice(node.bdfs)
                on_device = [
                    uid for uid, entry in list(
                        node.driver._checkpoint.items())
                    if bdf in entry.get("device_raws", ())
                    and "orphaned" not in entry]
                node.driver.on_devices_gone([(bdf, on_device)])
                self._count(unplugs=1, orphans=len(on_device),
                            claim_events=len(on_device))
                # kubelet-style cleanup of the orphaned claims, then the
                # replug readmission (same registry = same identity).
                # Only claims whose detach SUCCEEDED count as cleaned /
                # leave the fabric — a failed unprepare keeps both its
                # checkpoint entry and its fabric record, so the quiesce
                # orphan check points at a real leak, not at counters
                # that already claimed the cleanup happened
                if on_device:
                    cleaned = self._retry_claims(node.detach, on_device)
                    for uid in cleaned:
                        self.sim.apiserver.remove_claim("fleet", uid)
                    with self._lock:
                        for uid in cleaned:
                            self._pinned.pop(uid, None)
                    self._count(orphans_cleaned=len(cleaned))
                node.driver.set_inventory(node.driver.registry,
                                          node.driver.generations)
                node.driver.publish_resource_slices()
                self._count(readmits=1)
            finally:
                self._release_node(i)
            time.sleep(rng.uniform(0.05, 0.25))

    def _migration_worker(self, wid: int) -> None:
        """VMI migration storm: move a long-lived (pinned) claim to a
        different node through the PR 7 handoff machinery — unprepare at
        the source emits the durable record, the destination's prepare
        validates it (claim UID + allocation generation). The first
        completed migration's /debug/flight-shaped claim story (spans
        from BOTH nodes' drivers) is captured into the soak report."""
        rng = random.Random((self.cfg.seed << 15) ^ wid)
        by_name = self.sim._node_by_name()
        while self._running():
            time.sleep(rng.uniform(0.1, 0.4))
            with self._lock:
                pinned = list(self._pinned.items())
            if not pinned:
                continue
            uid, src_name = rng.choice(pinned)
            src = by_name.get(src_name)
            others = [n for n in self.sim.nodes if n.name != src_name]
            if src is None or not others:
                continue
            dst = rng.choice(others)
            with self._fleet_lock:
                entry = dict(src.driver._checkpoint).get(uid)
                free = sorted(dst.host_view().free)
                if entry is None or not free:
                    self._count(migrations_skipped=1)
                    continue
                mig = {"claim": uid,
                       "devices": list(entry.get("device_raws", ())),
                       "target_devices": free[:max(
                           1, len(entry.get("device_raws", ())))]}
                locks = sorted({self.sim.nodes.index(src),
                                self.sim.nodes.index(dst)})
                for li in locks:
                    self._node_locks[li].acquire()
                try:
                    moved = self._apply_one_migration(
                        src, dst, mig, counter="migrations")
                finally:
                    for li in reversed(locks):
                        self._node_locks[li].release()
                if not moved:
                    self._count(migrations_skipped=1)

    def _defrag_worker(self, wid: int) -> None:
        cfg = self.cfg
        rng = random.Random((cfg.seed << 12) ^ wid)
        by_name = self.sim._node_by_name()
        while self._running():
            time.sleep(rng.uniform(0.05, 0.3))
            with self._fleet_lock:
                # propose over a bounded node sample: a 256-node fleet's
                # full cross-product proposal is not the point here
                sample = rng.sample(self.sim.nodes,
                                    min(8, len(self.sim.nodes)))
                try:
                    prop = placement.propose_defrag(
                        placement.parse_shape(rng.choice(cfg.shapes)),
                        [n.host_view() for n in sample])
                except Exception:
                    continue
                moves = [m for m in prop.get("migrations", ())
                         if m.get("target_node") is not None]
                if not moves or prop.get("placeable"):
                    self._count(defrag_skipped=1)
                    continue
                mig = moves[0]
                src = by_name[mig["source_node"]]
                dst = by_name[mig["target_node"]]
                locks = sorted({self.sim.nodes.index(src),
                                self.sim.nodes.index(dst)})
                for li in locks:
                    self._node_locks[li].acquire()
                try:
                    if not self._apply_one_migration(src, dst, mig):
                        self._count(defrag_skipped=1)
                finally:
                    for li in reversed(locks):
                        self._node_locks[li].release()

    def _apply_one_migration(self, src, dst, mig: dict,
                             counter: str = "defrag_moves") -> bool:
        uid = mig["claim"]
        resp = src.detach([uid])
        if resp.claims[uid].error:
            return False
        record = src.driver.export_handoff(uid)
        names = dst.host_view().names
        try:
            devices = [{"device": names[r]}
                       for r in mig["target_devices"]]
        except KeyError:
            devices = None
        if devices is not None:
            self.sim.apiserver.add_claim(
                "fleet", uid, uid, dst.driver.driver_name, devices)
            if record is not None:
                dst.driver.import_handoff(record)
            resp = dst.attach([uid])
            if not resp.claims[uid].error:
                self._count(prepares=1, unprepares=1, claim_events=2,
                            **{counter: 1})
                capture = False
                with self._lock:
                    if uid in self._pinned:
                        self._pinned[uid] = dst.name
                    # the report's sample story must SPAN node
                    # boundaries (prepare on A, unprepare, prepare
                    # on B) — intra-node defrag moves don't qualify
                    if self._story is None and src.name != dst.name:
                        capture = True
                if capture:
                    story = self._fleet_trace_story(uid, src, dst)
                    if story is not None:
                        with self._lock:
                            if self._story is None:
                                self._story = story
                return True
        # recovery: the destination refused (churn won the race) — put
        # the claim back at the source so nothing is lost
        return self._migration_recover(src, uid, mig)

    def _fleet_trace_story(self, uid: str, src, dst):
        """Reconstruct the migrated claim's cross-node story PURELY from
        the fleet trace query (fleetplace.FleetFlight — the exact
        /debug/fleet/trace?trace= body): the destination checkpoint
        entry names the trace that originally placed the claim, and one
        trace= query must replay prepare → unprepare/handoff →
        destination-prepare across both hosts. Returns None when the
        trace does not (yet) span both nodes — the capturer retries on
        a later migration."""
        tp = (dict(dst.driver._checkpoint).get(uid) or {}) \
            .get("traceparent")
        ctx = trace.parse_traceparent(tp) if tp else None
        if ctx is None:
            return None
        waterfall = self.sim.fleet_flight().trace(ctx["trace_id"])
        nodes = set(waterfall["nodes"])
        if not {src.name, dst.name} <= nodes:
            return None
        return {
            "claim": uid, "source": src.name, "target": dst.name,
            "trace_id": ctx["trace_id"],
            "endpoint": f"/debug/fleet/trace?trace={ctx['trace_id']}",
            "nodes": waterfall["nodes"],
            "spans": len(waterfall["spans"]),
            "ops": waterfall["ops"],
        }

    def _selfheal_drill(self):
        """The ISSUE 16 closed loop, end-to-end against the quiesced
        fleet: a RAMPED delay fault on the victim's API path burns a
        publish-RTT SLO → the breach latches with an exemplar → the
        remediation engine (policy-gated) backs the victim's pacer off,
        sheds admission, and — the exemplar attributing to the victim —
        biases placement away from it → good traffic dilutes the burn
        below target → the latched recovery rolls every knob back.
        Returns the story dict; missing links go to self.violations."""
        from . import slo
        from .policy import PolicyEngine
        from .remediation import RemediationEngine
        cfg = self.cfg
        victim = self.sim.nodes[0]
        flight = self.sim.fleet_flight()
        scheduler = self.sim.scheduler(watch=False)
        engine = slo.SLOEngine([slo.Objective(
            "publish_rtt", "tdp_kubeapi_rtt_ms", threshold_ms=100.0,
            target=0.99, fast_window_s=60.0, slow_window_s=300.0)])
        policy = PolicyEngine()
        # an operator hook that APPROVES but proves the gate ran (its
        # call counter lands in the story)
        policy.load_source("selfheal_ops",
                           "def remediate(ctx):\n    return None\n")
        rem = RemediationEngine(
            pacer=victim.driver.pacer, scheduler=scheduler,
            policy=policy, fleet_flight=flight,
            cooldown_s=0.5, node_hits_threshold=1)
        engine.subscribe(rem.on_transition)
        story = {"victim": victim.name}
        # quiesce the watch plane first: its steady drip of good-RTT
        # relists would eat the count-limited fault fires AND dilute
        # the fast window before the breach can latch (parallel stops —
        # a serial march of reflector joins is minutes at 256 nodes)
        from concurrent import futures as _futures
        with _futures.ThreadPoolExecutor(
                max_workers=min(32, len(self.sim.nodes)),
                thread_name_prefix="selfheal-quiesce") as pool:
            list(pool.map(
                lambda n: n.driver.stop_watch_reconciler(),
                self.sim.nodes))

        def bad(msg):
            with self._lock:
                self.violations.append(f"selfheal: {msg}")

        victim.driver.publish_resource_slices()     # good baseline RTTs
        engine.evaluate()
        faults.arm("kubeapi.request", kind="delay", count=8,
                   delay_s=cfg.selfheal_fault_delay_s,
                   jitter_s=cfg.selfheal_fault_jitter_s,
                   ramp_s=cfg.selfheal_fault_ramp_s)
        try:
            # spread the bad publishes over the ramp: early fires sleep
            # a sub-threshold sliver, late ones the full delay — the
            # burn RISES instead of stepping
            for _ in range(6):
                victim.driver.publish_resource_slices()
                time.sleep(cfg.selfheal_fault_ramp_s / 5)
        finally:
            faults.disarm("kubeapi.request")
        time.sleep(1.1)                     # past the engine sample gap
        rec = engine.evaluate()["publish_rtt"]
        story["burn_at_breach"] = rec["burn_rate_fast"]
        story["breached"] = rec["breached"]
        tid = (rec.get("exemplar") or {}).get("trace_id")
        story["trace_id"] = tid
        story["endpoint"] = f"/debug/fleet/trace?trace={tid}"
        if not rec["breached"] or not tid:
            bad(f"breach did not latch (burn={rec['burn_rate_fast']}, "
                f"exemplar={tid})")
            return story
        tick = rem.tick()
        story["actions"] = tick["actions"]
        snap = rem.snapshot()
        story["active_actions"] = [
            {"action": a["action"], "target": a["target"]}
            for a in snap["active_actions"]]
        story["policy_remediate_calls"] = sum(
            h["calls"] for h in policy.snapshot()["hooks"]
            if h["hook"] == "remediate")
        if tick["actions"] == 0:
            bad("breach latched but no remediation action applied")
        if victim.driver.pacer.snapshot().get("backoff_floor_ms", 0) <= 0:
            bad("pacer backoff floor not set on the victim")
        if victim.name not in scheduler.biased_nodes():
            bad(f"victim {victim.name} not placement-biased "
                f"(attribution failed; nodes seen: {snap['node_hits']})")
        # recovery by dilution: enough good publishes shrink the windows'
        # error rate below target — the latched recovery needs the SLOW
        # burn under its threshold, not the incident to slide out
        for _ in range(40):
            victim.driver.publish_resource_slices()
        time.sleep(1.1)
        deadline = time.monotonic() + 30.0
        while engine.snapshot()["recoveries_total"] == 0 \
                and time.monotonic() < deadline:
            for _ in range(20):
                victim.driver.publish_resource_slices()
            time.sleep(1.1)
            engine.evaluate()
        rec = engine.evaluate()["publish_rtt"]
        story["burn_at_recovery"] = rec["burn_rate_fast"]
        story["recovered"] = not rec["breached"]
        if rec["breached"]:
            bad(f"burn did not recover (fast={rec['burn_rate_fast']}, "
                f"slow={rec['burn_rate_slow']})")
            return story
        tick = rem.tick()
        story["rollbacks"] = tick["rollbacks"]
        if tick["rollbacks"] == 0:
            bad("recovery latched but no knob rolled back")
        if victim.driver.pacer.snapshot().get("backoff_floor_ms", 0) != 0:
            bad("pacer backoff floor still set after rollback")
        if victim.name in scheduler.biased_nodes():
            bad("victim still placement-biased after rollback")
        story["counters"] = {
            k: v for k, v in rem.snapshot().items()
            if isinstance(v, int) and k.endswith("_total")}
        # THE acceptance gate: one fleet-trace query on the breach
        # exemplar replays the whole loop — the slow publish on the
        # victim, the remediation actions, the rollbacks
        waterfall = flight.trace(tid)
        story["nodes"] = waterfall["nodes"]
        story["ops"] = waterfall["ops"]
        story["spans"] = len(waterfall["spans"])
        for op, what in (("kubeapi.request", "the slow request"),
                         ("remediation.action", "the corrective action"),
                         ("remediation.rollback", "the rollback")):
            if op not in waterfall["ops"]:
                bad(f"one-query waterfall missing {what} ({op}); "
                    f"has {waterfall['ops']}")
        if victim.name not in waterfall["nodes"]:
            bad(f"one-query waterfall not attributed to the victim; "
                f"nodes={waterfall['nodes']}")
        return story

    def _sharded_drill(self) -> dict:
        """Post-quiesce sharded-scheduler leg: N partitioned watch-fed
        schedulers race a claim wave onto the quiesced fleet through
        the CAS commit path, the cross-scheduler audit proves <=1
        commit per claim uid, and every placement is released (the
        drill must leave the fleet exactly as it found it)."""
        from . import fleetplace
        cfg = self.cfg
        n = cfg.sharded_schedulers
        scheds = [self.sim.scheduler(
            watch=True, shard_index=i, shard_count=n, partition=True,
            wave_max=max(2, cfg.sharded_claims // n))
            for i in range(n)]
        story = {"schedulers": n, "claims": cfg.sharded_claims}
        try:
            for s in scheds:
                s.start()
            for s in scheds:
                s.wait_synced(timeout_s=30)
            results: List[List[dict]] = [[] for _ in range(n)]

            def work(i: int) -> None:
                s = scheds[i]
                for j in range(i, cfg.sharded_claims, n):
                    s.submit("1x2", f"soak-shard-{j:04d}")
                results[i] = s.drain()

            threads: List[threading.Thread] = []
            for i in range(n):
                t = threading.Thread(target=work, args=(i,),
                                     daemon=True,
                                     name=f"autopilot-shard-{i}")
                self._threads.append(t)   # stop() reaps stragglers
                threads.append(t)
                t.start()
            for t in threads:
                t.join(timeout=60)
            flat = [r for shard in results for r in shard]
            placed = [r for r in flat if r.get("placed")]
            # the storm's own multiclaims share this fabric, so the
            # fleet-level fabric set comparison is out of reach here —
            # per-scheduler logs, the cross-scheduler duplicate check
            # and the fabric's CAS placement log still must hold
            audit = fleetplace.fleet_audit(
                scheds,
                placement_audit=self.sim.apiserver.placement_audit())
            for r in placed:
                self.sim.release_subclaims(
                    [(f"{r['uid']}-{node}", node)
                     for node, _raws in r["shards"]])
            residue = sorted(
                line for r in flat
                for line in self.sim.slice_residue(r["uid"]))
            story.update({
                "decided": len(flat),
                "placed": len(placed),
                "conflicts": sum(
                    s.stats["commit_conflicts_total"].value
                    for s in scheds),
                "replans": sum(s.stats["replans_total"].value
                               for s in scheds),
                "waves": sum(s.stats["decision_waves_total"].value
                             for s in scheds),
                "exactly_once": audit["exactly_once"],
                "residue": residue,
            })
            if len(flat) != cfg.sharded_claims:
                self.violations.append(
                    f"sharded drill decided {len(flat)} of "
                    f"{cfg.sharded_claims} claims")
            if not audit["exactly_once"]:
                self.violations.append(
                    "sharded drill: cross-scheduler exactly-once audit "
                    f"failed: {audit['cross_scheduler_duplicates']}")
            if residue:
                self.violations.append(
                    f"sharded drill left residue: {residue}")
        finally:
            for s in scheds:
                try:
                    s.stop()
                except Exception:
                    log.exception("autopilot: sharded drill stop")
        return story

    def _migration_recover(self, src, uid: str, mig: dict) -> bool:
        self.sim.apiserver.add_claim(
            "fleet", uid, uid, src.driver.driver_name,
            [{"device": src.host_view().names[r]}
             for r in mig["devices"]])
        back = src.attach([uid])
        if back.claims[uid].error:
            with self._lock:
                self.violations.append(
                    f"migration lost claim {uid}: "
                    f"{back.claims[uid].error}")
        else:
            self._count(defrag_recoveries=1)
        return False

    def _upgrade_worker(self, wid: int) -> None:
        cfg = self.cfg
        rng = random.Random((cfg.seed << 13) ^ wid)
        while self._running():
            time.sleep(rng.uniform(0.2, 0.8))
            start = rng.randrange(len(self.sim.nodes))
            # dedupe: a wave wider than the fleet wraps onto the same
            # indices, and acquiring a non-reentrant node lock twice
            # would deadlock this worker INSIDE the fleet lock
            wave = sorted({(start + k) % len(self.sim.nodes)
                           for k in range(cfg.upgrade_wave_size)})
            with self._fleet_lock:
                for i in wave:
                    self._node_locks[i].acquire()
                try:
                    for i in wave:
                        node = self.sim.nodes[i]
                        node.drain()
                        node.upgrade()     # asserts claims survived
                        node.restore()
                        self._count(upgrades=1)
                finally:
                    for i in reversed(wave):
                        self._node_locks[i].release()

    def _boot_worker(self, wid: int) -> None:
        cfg = self.cfg
        rng = random.Random((cfg.seed << 14) ^ wid)
        while self._running():
            time.sleep(rng.uniform(0.2, 0.8))
            group = rng.sample(self.sim.nodes,
                               min(cfg.boot_wave_size,
                                   len(self.sim.nodes)))
            with futures.ThreadPoolExecutor(
                    max_workers=len(group)) as pool:
                list(pool.map(
                    lambda n: n.driver.publish_resource_slices(), group))
            self._count(republish_waves=1)

    def _invariant_worker(self) -> None:
        while self._running():
            self._stop_evt.wait(timeout=self.cfg.invariant_interval_s)
            with self._lock:
                torn = list(self._torn_down)
            report = fleet_invariants(self.sim, torn_down_multiclaims=torn)
            self._count(invariant_checks=1)
            if not report["ok"]:
                with self._lock:
                    self.violations.extend(report["violations"])
                log.error("autopilot invariants violated: %s",
                          report["violations"])

    # --------------------------------------------------------------- run

    def _pin_claims(self) -> None:
        """Long-lived single-chip claims (defrag material / unplug
        victims), one per cfg.pinned_per_nodes nodes."""
        for i in range(0, len(self.sim.nodes), self.cfg.pinned_per_nodes):
            node = self.sim.nodes[i]
            free = sorted(node.host_view().free)
            if not free:
                continue
            uid = f"pin-{node.name}"
            try:
                node.claim_devices(uid, [free[0]])
            except AssertionError:
                continue
            self._count(prepares=1, claim_events=1)
            with self._lock:
                self._pinned[uid] = node.name

    def _teardown_pinned(self) -> None:
        by_name = self.sim._node_by_name()
        with self._lock:
            pinned = dict(self._pinned)
            self._pinned.clear()
        for uid, node_name in pinned.items():
            node = by_name.get(node_name)
            if node is None:
                continue
            # same contract as the storm workers: the fabric record
            # leaves only with a SUCCESSFUL detach — removing it for a
            # still-prepared claim would manufacture a phantom "lost
            # claim" in the final invariant pass
            if self._retry_claims(node.detach, [uid]):
                self._count(unprepares=1, claim_events=1)
                self.sim.apiserver.remove_claim("fleet", uid)

    def run(self, raise_on_violation: bool = True) -> dict:
        # the owned sim must die even when the storm phase raises —
        # leaked reflector/fabric threads and the tempdir otherwise
        # outlive the failure and fail unrelated later tests through
        # the conftest owned-thread leak guard
        try:
            return self._run(raise_on_violation)
        finally:
            if self._own_sim:
                self.sim.stop()

    def _run(self, raise_on_violation: bool) -> dict:
        cfg = self.cfg
        t0 = time.monotonic()
        max_wall = cfg.max_wall_s or (cfg.duration_s * 6 + 120)
        try:
            boot = self.sim.boot_storm()
            if cfg.watch_chaos:
                self.sim.apiserver.arm_watch_chaos(
                    break_p=cfg.watch_chaos_break_p,
                    dup_p=cfg.watch_chaos_dup_p,
                    stall_s=cfg.watch_chaos_stall_s, seed=cfg.seed)
            if cfg.watch_faults:
                faults.arm("kubeapi.watch", kind="error", count=None,
                           probability=cfg.watch_fault_p)
                faults.arm("kubeapi.watch.dup", kind="drop", count=None,
                           probability=cfg.watch_fault_p * 2)
                faults.arm("kubeapi.watch.stale", kind="drop", count=None,
                           probability=cfg.watch_fault_p / 2)
            self._pin_claims()
            for w in range(cfg.claim_workers):
                self._spawn(self._claim_worker, f"claims-{w}", w)
            for w in range(cfg.multiclaim_workers):
                self._spawn(self._multiclaim_worker, f"mc-{w}", w)
            for w in range(cfg.flip_workers):
                self._spawn(self._flip_worker, f"flips-{w}", w)
            for w in range(cfg.unplug_workers):
                self._spawn(self._unplug_worker, f"unplug-{w}", w)
            for w in range(cfg.migration_workers):
                self._spawn(self._migration_worker, f"migrate-{w}", w)
            for w in range(cfg.defrag_workers):
                self._spawn(self._defrag_worker, f"defrag-{w}", w)
            for w in range(cfg.upgrade_workers):
                self._spawn(self._upgrade_worker, f"upgrade-{w}", w)
            for w in range(cfg.boot_workers):
                self._spawn(self._boot_worker, f"boot-{w}", w)
            self._spawn(self._invariant_worker, "invariants")
            while True:
                elapsed = time.monotonic() - t0
                with self._lock:
                    events = self.counters["claim_events"]
                if elapsed >= max_wall:
                    log.warning("autopilot: max wall %.0fs hit at %d "
                                "claim events", max_wall, events)
                    break
                if elapsed >= cfg.duration_s and (
                        not cfg.claim_event_target
                        or events >= cfg.claim_event_target):
                    break
                time.sleep(0.2)
        finally:
            self.stop()
            if cfg.watch_faults:
                for site in ("kubeapi.watch", "kubeapi.watch.dup",
                             "kubeapi.watch.stale"):
                    faults.disarm(site)
            self.sim.apiserver.disarm_watch_chaos()
        # quiesce: tear down the pinned claims, settle every slice, then
        # the FINAL invariant pass must be green WITH zero orphans left
        self._teardown_pinned()
        self.sim.settle()
        with self._lock:
            torn = list(self._torn_down)
        final = fleet_invariants(self.sim, torn_down_multiclaims=torn)
        self._count(invariant_checks=1)
        converged = False
        try:
            converged = self.sim.assert_converged()
        except AssertionError as exc:
            self.violations.append(f"final convergence: {exc}")
        if not final["ok"]:
            self.violations.extend(final["violations"])
        if final["orphaned_claims"]:
            self.violations.append(
                f"{final['orphaned_claims']} orphaned claims left after "
                f"quiesce (expected 0)")
        # self-heal drill (ISSUE 16): runs against the quiesced fleet so
        # the injected latency burns ONLY the drill's SLO, never the
        # storm's convergence checks above
        selfheal_story = None
        if cfg.selfheal:
            selfheal_story = self._selfheal_drill()
        # sharded-scheduler drill (ISSUE 17): also against the quiesced
        # fleet — its claims must come and go without disturbing the
        # converged state the checks above just proved
        sharded_story = None
        if cfg.sharded_schedulers:
            sharded_story = self._sharded_drill()
        wall_s = time.monotonic() - t0
        report = {
            "config": {
                "nodes": cfg.nodes,
                "devices_per_node": cfg.devices_per_node,
                "duration_s": cfg.duration_s,
                "claim_event_target": cfg.claim_event_target,
                "seed": cfg.seed,
                "watch": cfg.watch,
                "watch_chaos": cfg.watch_chaos,
                "watch_faults": cfg.watch_faults,
                "selfheal": cfg.selfheal,
                "sharded_schedulers": cfg.sharded_schedulers,
            },
            "wall_s": round(wall_s, 1),
            "boot_published_ok": boot["published_ok"],
            "counters": dict(self.counters),
            "violations": list(self.violations),
            "ok": not self.violations and converged,
            "converged": converged,
            "final_invariants": {
                "ok": final["ok"],
                "orphaned_claims": final["orphaned_claims"],
                "prepared_total": final["prepared_total"],
                "exactly_once": final["audit"]["exactly_once"],
                "multiclaim_exactly_once":
                    final["multiclaim"]["exactly_once"],
            },
            "watch": self.sim.watch_totals(),
            "fabric": self.sim.apiserver.snapshot(),
            "faults_fired": {site: n for site, n in faults.stats().items()
                             if site.startswith("kubeapi.watch")},
            "claim_story": self._story,
            "selfheal_story": selfheal_story,
            "sharded": sharded_story,
        }
        if raise_on_violation and not report["ok"]:
            raise AssertionError(
                "autopilot soak failed: " + "; ".join(
                    self.violations or ["not converged"]))
        return report


# ------------------------------------------------- read/repair comparison


def measure_read_repair(n_nodes: int = 16, rounds: int = 10,
                        seed: int = 7) -> dict:
    """Steady-state read/repair fabric reads: guarded-PUT polling vs
    watch-driven convergence (the r14 acceptance comparison).

    Both fleets run `rounds` reconcile ticks of an UNCHANGED inventory —
    the read/repair loop a timer-driven reconciler must run to notice a
    wiped/diverged slice within its interval. The polling fleet pays one
    liveness GET per node per tick; the watch fleet's established
    streams cover wipe detection, so its ticks read nothing (the one-
    time relists that seeded the streams are reported separately as
    `watch_setup_lists`, not hidden in the ratio)."""

    def _tick_reads(sim: FleetSim) -> int:
        before = sim.apiserver.snapshot()["slice_reads_total"]
        for _ in range(rounds):
            for node in sim.nodes:
                node.driver.publish_resource_slices()
        return sim.apiserver.snapshot()["slice_reads_total"] - before

    poll = FleetSim(n_nodes=n_nodes, latency_s=0.0, max_inflight=0,
                    seed=seed, watch=False)
    try:
        poll.boot_storm()
        poll_reads = _tick_reads(poll)
    finally:
        poll.stop()
    watch = FleetSim(n_nodes=n_nodes, latency_s=0.0, max_inflight=0,
                     seed=seed, watch=True, watch_resync_s=60.0,
                     watch_poll_s=0.5, watch_timeout_s=5.0)
    try:
        watch.boot_storm()
        # wait for every node's stream to establish (bounded)
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if all(n.driver._watch_live() for n in watch.nodes):
                break
            time.sleep(0.05)
        setup_lists = watch.apiserver.snapshot()["list_total"]
        watch_reads = _tick_reads(watch)
        # the watch must still HEAL: wipe one slice behind its driver
        victim = watch.nodes[0]
        name = victim.driver.slice_name()
        victim.driver.api.delete(
            f"/apis/resource.k8s.io/v1beta1/resourceslices/{name}")
        deadline = time.monotonic() + 15
        healed = False
        while time.monotonic() < deadline:
            with watch.apiserver._lock:
                healed = name in watch.apiserver.slices
            if healed:
                break
            time.sleep(0.05)
        audit_ok = watch.apiserver.exactly_once_audit()["exactly_once"]
    finally:
        watch.stop()
    return {
        "nodes": n_nodes,
        "rounds": rounds,
        "poll_reads": poll_reads,
        "watch_reads": watch_reads,
        "watch_setup_lists": setup_lists,
        "read_reduction_x": round(poll_reads / max(1, watch_reads), 1),
        "wipe_healed_by_watch": healed,
        "exactly_once": audit_ok,
    }
