#!/usr/bin/env bash
# Real-kubelet e2e (VERDICT r2 next-item #3): run the plugin against an
# actual kubelet in a kind cluster and assert the full resource lifecycle:
#
#   register -> node allocatable cloud-tpus.google.com/v4: 4 -> pod
#   requesting 2 admitted by the devicemanager -> container starts with the
#   VFIO DeviceSpecs mounted and the PCI_RESOURCE env var injected.
#
# The TPU "hardware" is a fixture sysfs/devfs tree (scripts/
# make_fixture_host.py) mounted into the kind node; its /dev entries are
# replaced with real char-device nodes (mknod c 1 3) inside the node so the
# container runtime can actually mount them. Requires: docker, kind, kubectl.
#
# Run locally:  scripts/e2e_kind.sh
# CI: .github/workflows/e2e.yml (nightly + manual dispatch).
set -euo pipefail

CLUSTER=${CLUSTER:-tpu-dp-e2e}
IMG=tpu-kubevirt-device-plugin:e2e
FIXTURE=/tmp/tpu-fixture-e2e
REPO="$(cd "$(dirname "$0")/.." && pwd)"

cleanup() { kind delete cluster --name "$CLUSTER" >/dev/null 2>&1 || true; }
trap cleanup EXIT

echo "--- build image"
docker build -f "$REPO/deployments/container/Dockerfile" -t "$IMG" "$REPO"

echo "--- fixture host tree"
rm -rf "$FIXTURE"
python3 "$REPO/scripts/make_fixture_host.py" "$FIXTURE"

echo "--- kind cluster (fixture mounted into the node)"
cat <<EOF | kind create cluster --name "$CLUSTER" --config=-
kind: Cluster
apiVersion: kind.x-k8s.io/v1alpha4
nodes:
  - role: control-plane
    extraMounts:
      - hostPath: $FIXTURE
        containerPath: $FIXTURE
EOF
kind load docker-image "$IMG" --name "$CLUSTER"
NODE="${CLUSTER}-control-plane"

echo "--- real device nodes for the runtime to mount"
docker exec "$NODE" bash -c '
  set -e
  for f in '"$FIXTURE"'/dev/vfio/vfio '"$FIXTURE"'/dev/vfio/[0-9]* \
           '"$FIXTURE"'/dev/accel* '"$FIXTURE"'/dev/iommu \
           '"$FIXTURE"'/dev/vfio/devices/vfio*; do
    [ -e "$f" ] || continue
    rm -f "$f" && mknod "$f" c 1 3 && chmod 666 "$f"
  done'

echo "--- deploy plugin"
sed "s|IMAGE_PLACEHOLDER|$IMG|; s|FIXTURE_PLACEHOLDER|$FIXTURE|" \
    "$REPO/manifests/e2e/tpu-device-plugin-e2e.yaml" | kubectl apply -f -
kubectl -n kube-system rollout status ds/tpu-device-plugin-e2e --timeout=120s

echo "--- node allocatable"
for i in $(seq 1 30); do
  GOT=$(kubectl get node "$NODE" \
        -o jsonpath='{.status.allocatable.cloud-tpus\.google\.com/v4}' || true)
  [ "$GOT" = "4" ] && break
  sleep 2
done
[ "$GOT" = "4" ] || { echo "FAIL: allocatable v4=$GOT (want 4)"; \
  kubectl -n kube-system logs ds/tpu-device-plugin-e2e --tail=50; exit 1; }
echo "allocatable OK: cloud-tpus.google.com/v4=$GOT"

echo "--- pod admission + device mount + env"
kubectl apply -f "$REPO/manifests/e2e/tpu-consumer-pod.yaml"
kubectl wait --for=condition=Ready pod/tpu-consumer --timeout=120s || {
  kubectl describe pod tpu-consumer; exit 1; }
ENVV=$(kubectl exec tpu-consumer -- sh -c 'env | grep PCI_RESOURCE_CLOUD_TPUS_GOOGLE_COM_V4')
echo "env: $ENVV"
echo "$ENVV" | grep -q "0000:" || { echo "FAIL: no BDFs in env"; exit 1; }
kubectl exec tpu-consumer -- sh -c 'ls /dev/vfio/vfio' >/dev/null
GROUPS_IN_POD=$(kubectl exec tpu-consumer -- sh -c \
  'ls /dev/vfio | grep -E "^[0-9]+$" | wc -l')
[ "$GROUPS_IN_POD" -ge 1 ] || {
  echo "FAIL: no per-IOMMU-group /dev/vfio/<group> node mounted in the pod"
  kubectl exec tpu-consumer -- ls /dev/vfio; exit 1; }
echo "group mounts OK: $GROUPS_IN_POD /dev/vfio/<group> node(s)"
echo "E2E PASS: real kubelet admitted the pod with TPU VFIO devices"
