#!/bin/sh
# Serialized real-TPU validator attempts (round 3).
#
# Protocol (docs/roadmap.md item 1): exactly ONE TPU process at a time, never
# killed — SIGKILLing a mid-claim process wedges the exclusive-claim PJRT
# relay. Attempts fail naturally (~40 min in backend init) in the wedged
# state observed in rounds 1-2. Stop the loop gracefully between attempts:
#     touch /root/repo/.stop_tpu_attempts
# On the first success the packed protocol (train, infer, attn-bench sweep)
# runs inside the same window and the loop stops itself.
set -u
cd /root/repo
LOG=docs/tpu_attempts_r03.log
if [ -f .stop_tpu_attempts ]; then
    # deliberate stop semantics: the launcher rm -f's the sentinel; a stale
    # one here means "stay stopped" — but say so loudly instead of no-opping
    echo "=== sentinel .stop_tpu_attempts present at launch; not starting" \
         "(rm it and relaunch to run) $(date -u +%FT%TZ) ===" >>"$LOG"
fi
N=0
while [ ! -f .stop_tpu_attempts ]; do
    N=$((N + 1))
    echo "=== attempt $N start $(date -u +%FT%TZ) ===" >>"$LOG"
    python -m tpu_device_plugin.validator --steps 20 \
        >docs/validator_tpu_train_r03.json 2>>"$LOG"
    rc=$?
    tail -c 400 docs/validator_tpu_train_r03.json >>"$LOG"
    echo "" >>"$LOG"
    echo "=== attempt $N end rc=$rc $(date -u +%FT%TZ) ===" >>"$LOG"
    if [ "$rc" -eq 0 ]; then
        echo "SUCCESS: running packed protocol (infer + attn-bench)" >>"$LOG"
        python -m tpu_device_plugin.validator --mode infer \
            >docs/validator_tpu_infer_r03.json 2>>"$LOG"
        echo "infer rc=$? $(date -u +%FT%TZ)" >>"$LOG"
        python -m tpu_device_plugin.validator --mode attn-bench \
            --seqs 1024,2048,4096 --blocks 128x128,256x128,128x256 \
            >docs/validator_tpu_attn_r03.json 2>>"$LOG"
        echo "attn-bench rc=$? $(date -u +%FT%TZ)" >>"$LOG"
        touch .stop_tpu_attempts
        break
    fi
    sleep 30
done
echo "=== loop exit $(date -u +%FT%TZ) ===" >>"$LOG"
