"""Kubelet Device Plugin API v1beta1 — messages, constants, gRPC wiring.

The protobuf messages are generated from `proto/deviceplugin_v1beta1.proto`
(`make proto`); the gRPC service/stub wiring is hand-written in `api.py`
because this image ships no grpc codegen plugin. Wire-compatible with the
kubelet's published v1beta1 contract (reference:
vendor/k8s.io/kubelet/pkg/apis/deviceplugin/v1beta1/api.proto).
"""

from . import deviceplugin_v1beta1_pb2 as pb  # noqa: F401
from . import dra_v1beta1_pb2 as drapb  # noqa: F401
from . import pluginregistration_v1_pb2 as regpb  # noqa: F401
from .api import (  # noqa: F401
    API_VERSION,
    DEVICE_PLUGIN_PATH,
    HEALTHY,
    KUBELET_SOCKET,
    RAW_CONTEXT,
    UNHEALTHY,
    DevicePluginServicer,
    DevicePluginStub,
    RawResponse,
    RegistrationServicer,
    RegistrationStub,
    add_device_plugin_servicer,
    add_registration_servicer,
    wants_raw,
)
