"""Allocate(): turn requested BDFs into VFIO DeviceSpecs + KubeVirt env vars.

TPU analogue of the reference's passthrough Allocate
(generic_device_plugin.go:352-444): expand each requested BDF to its whole
IOMMU group, re-validate live sysfs against the discovery-time snapshot
(TOCTOU guard, :388-397), emit `/dev/vfio/vfio` + `/dev/vfio/<group>` (plus
the iommufd trio when `/dev/iommu` exists, :692-716), and set the
`PCI_RESOURCE_...` env var KubeVirt's virt-launcher reads to pick the PCI
devices for the VMI (externalResourceProvider contract).
"""

from __future__ import annotations

import logging
import os
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

from .config import Config
from .discovery import read_id_from_file, read_link_basename
from .kubeletapi import pb
from .naming import sanitize_name
from .registry import Registry, SharedDevice

log = logging.getLogger(__name__)


class AllocationError(Exception):
    """Request references devices this plugin cannot serve (unknown/invalid)."""


def supports_iommufd(cfg: Config) -> bool:
    """iommufd-capable host: /dev/iommu exists (reference :692-701)."""
    return os.path.exists(cfg.dev_path("dev/iommu"))


def vfio_device_node(cfg: Config, bdf: str) -> Optional[str]:
    """`vfioN` cdev name from sysfs `<bdf>/vfio-dev/` (reference :702-716)."""
    vfio_dev_dir = os.path.join(cfg.pci_base_path, bdf, "vfio-dev")
    try:
        entries = sorted(os.listdir(vfio_dev_dir))
    except OSError:
        return None
    for entry in entries:
        if entry.startswith("vfio"):
            return entry
    return None


def discover_shared_devices(cfg: Config) -> List[SharedDevice]:
    """Scan shared-device classes (EGM analogue, reference :120-157).

    Each class entry lists its member chips in a `chip_devices` file
    (`gpu_devices` also accepted so Grace-Hopper-style EGM trees work) and has
    a matching /dev node. Shared devices are injected all-or-nothing.
    """
    out: List[SharedDevice] = []
    for class_dir in cfg.shared_device_classes:
        try:
            entries = sorted(os.listdir(class_dir))
        except OSError:
            continue
        for name in entries:
            members: Optional[Tuple[str, ...]] = None
            for member_file in ("chip_devices", "gpu_devices"):
                path = os.path.join(class_dir, name, member_file)
                try:
                    with open(path, "r", encoding="ascii", errors="replace") as f:
                        members = tuple(l.strip() for l in f if l.strip())
                    break
                except OSError:
                    continue
            if not members:
                continue
            dev_path = cfg.dev_path("dev", name)
            if not os.path.exists(dev_path):
                log.warning("shared device %s has no %s; skipping", name, dev_path)
                continue
            out.append(SharedDevice(name=name, dev_path=dev_path, member_bdfs=members))
    return out


def _revalidate(cfg: Config, bdf: str, expected_group: str) -> None:
    """Live sysfs must still agree with the discovery snapshot (TOCTOU guard).

    Mirrors the reference's re-reads inside Allocate (:388-397): the iommu
    group link must be unchanged and the vendor must still be a TPU.
    """
    base = os.path.join(cfg.pci_base_path, bdf)
    live_group = read_link_basename(os.path.join(base, "iommu_group"))
    if live_group != expected_group:
        raise AllocationError(
            f"device {bdf}: iommu group changed ({expected_group!r} -> {live_group!r})")
    vendor = read_id_from_file(os.path.join(base, "vendor"))
    if vendor is None or vendor.lower() not in cfg.vendor_ids:
        raise AllocationError(f"device {bdf}: vendor {vendor!r} is not a TPU")


@dataclass
class AllocationPlan:
    device_specs: List[pb.DeviceSpec]
    envs: Dict[str, str]
    expanded_bdfs: List[str]


def plan_allocation(
    cfg: Config,
    registry: Registry,
    resource_suffix: str,
    requested_bdfs: Sequence[str],
    shared_devices: Optional[Sequence[SharedDevice]] = None,
    allowed_bdfs: Optional[frozenset] = None,
) -> AllocationPlan:
    """Build the DeviceSpec list + env map for one container request.

    DeviceSpec order matches the reference's: the shared /dev/vfio/vfio
    container node first, then one /dev/vfio/<group> per IOMMU group, then
    iommufd cdevs + /dev/iommu, then qualifying shared devices.

    `allowed_bdfs` scopes the request to the calling plugin's own devices:
    the reference resolves any BDF in its global map, so its v-something
    plugin would allocate another model's GPUs (generic_device_plugin.go:376-380)
    — here a cross-model BDF is an AllocationError.
    """
    iommufd = supports_iommufd(cfg)
    if shared_devices is None:
        shared_devices = discover_shared_devices(cfg)

    specs: List[pb.DeviceSpec] = [
        pb.DeviceSpec(
            host_path=cfg.dev_path("dev/vfio/vfio"),
            container_path="/dev/vfio/vfio",
            permissions="mrw",
        )
    ]
    expanded: List[str] = []
    seen_groups: List[str] = []
    iommufd_specs: List[pb.DeviceSpec] = []
    for bdf in requested_bdfs:
        group = registry.bdf_to_group.get(bdf)
        if group is None:
            raise AllocationError(f"requested device {bdf} is not a known TPU")
        if allowed_bdfs is not None and bdf not in allowed_bdfs:
            raise AllocationError(
                f"requested device {bdf} is not managed by resource "
                f"{resource_suffix!r}")
        if group in seen_groups:
            continue
        seen_groups.append(group)
        for dev in registry.iommu_map[group]:
            _revalidate(cfg, dev.bdf, group)
            expanded.append(dev.bdf)
            if iommufd:
                node = vfio_device_node(cfg, dev.bdf)
                if node is None:
                    # On an iommufd host every vfio-bound device has a cdev;
                    # an unreadable vfio-dev entry would boot the VM with an
                    # incomplete device set — fail fast like the reference
                    # (generic_device_plugin.go:702-716 errors the Allocate).
                    raise AllocationError(
                        f"device {dev.bdf}: iommufd host but no vfio-dev cdev")
                iommufd_specs.append(pb.DeviceSpec(
                    host_path=cfg.dev_path("dev/vfio/devices", node),
                    container_path=f"/dev/vfio/devices/{node}",
                    permissions="mrw",
                ))
        specs.append(pb.DeviceSpec(
            host_path=cfg.dev_path("dev/vfio", group),
            container_path=f"/dev/vfio/{group}",
            permissions="mrw",
        ))
    specs.extend(iommufd_specs)
    if iommufd and seen_groups:
        specs.append(pb.DeviceSpec(
            host_path=cfg.dev_path("dev/iommu"),
            container_path="/dev/iommu",
            permissions="mrw",
        ))

    # Shared devices ride along iff every member chip is in this allocation
    # (all-or-nothing, reference :159-184).
    allocated = set(expanded)
    for shared in shared_devices:
        if shared.member_bdfs and set(shared.member_bdfs) <= allocated:
            specs.append(pb.DeviceSpec(
                host_path=shared.dev_path,
                container_path=f"/dev/{shared.name}",
                permissions="mrw",
            ))
            log.info("allocation includes shared device %s (members %s)",
                     shared.name, ",".join(shared.member_bdfs))

    env_key = f"{cfg.env_prefix}_{sanitize_name(resource_suffix)}"
    envs = {env_key: ",".join(expanded)}
    log.info("allocate %s: groups=%s devices=%s iommufd=%s cdi=%s",
             resource_suffix, seen_groups, expanded, iommufd,
             bool(cfg.cdi_spec_dir))
    return AllocationPlan(device_specs=specs, envs=envs, expanded_bdfs=expanded)


def allocate_response(
    cfg: Config,
    registry: Registry,
    resource_suffix: str,
    request: pb.AllocateRequest,
    cdi_enabled: Optional[bool] = None,
    allowed_bdfs: Optional[frozenset] = None,
) -> pb.AllocateResponse:
    """Full Allocate handler body: one ContainerAllocateResponse per request.

    `cdi_enabled=None` falls back to `bool(cfg.cdi_spec_dir)`; the plugin
    server passes an explicit value reflecting whether this resource's CDI
    spec file was actually written (unresolvable names are worse than none).
    """
    if cdi_enabled is None:
        cdi_enabled = bool(cfg.cdi_spec_dir)
    shared = discover_shared_devices(cfg)
    resp = pb.AllocateResponse()
    for creq in request.container_requests:
        plan = plan_allocation(cfg, registry, resource_suffix,
                               list(creq.devices_ids), shared,
                               allowed_bdfs=allowed_bdfs)
        cresp = pb.ContainerAllocateResponse(
            envs=plan.envs, devices=plan.device_specs)
        if cdi_enabled:
            from .cdi import cdi_device_name
            cresp.cdi_devices.extend(
                pb.CDIDevice(name=cdi_device_name(cfg, bdf))
                for bdf in plan.expanded_bdfs)
        resp.container_responses.append(cresp)
    return resp
