"""weave CLI — deterministic interleaving checking for the lock-free
planes.

    python -m tools.weave                  # quick matrix: every scenario
    python -m tools.weave --twins          # mutation side: twins must FIRE
    python -m tools.weave --scenario NAME  # one scenario (repeatable)
    python -m tools.weave --soak           # deeper budgets (CI soak leg)
    python -m tools.weave --replay CE.json # reproduce a counterexample
    python -m tools.weave --list           # what exists

Exit codes: 0 = every selected scenario held (and every selected twin
fired); 1 = a counterexample was found (or a twin failed to fire — a
checker that cannot fire is a failing test); 2 = usage error.

On failure the counterexample (exact schedule, JSON) is written under
--artifacts (default .weave-artifacts/) for `--replay`.
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import sys
from typing import Dict, List, Optional, Type

from tools.weave.core import (Counterexample, ExploreResult, Scenario,
                              explore, replay)
from tools.weave.scenarios import SCENARIOS, TWINS

# soak multiplies the per-scenario execution budget and relaxes the
# preemption bound by one — the quick matrix stays seconds-fast while
# the soak leg buys schedules the bounded pass prunes (counts of which
# the quick pass REPORTS, never hides)
SOAK_BUDGET_FACTOR = 25
SOAK_EXTRA_PREEMPTIONS = 1


def _budgets(cls: Type[Scenario], soak: bool
             ) -> Dict[str, Optional[int]]:
    budget = cls.max_executions
    bound = cls.preemption_bound
    if soak:
        budget *= SOAK_BUDGET_FACTOR
        if bound is not None:
            bound += SOAK_EXTRA_PREEMPTIONS
    return {"max_executions": budget, "preemption_bound": bound}


def _describe(res: ExploreResult) -> str:
    if res.complete:
        space = f"complete reduced space in {res.executions} execution(s)"
    else:
        space = f"budget-bounded: {res.executions} execution(s)"
    extra = f", {res.bound_pruned} bound-pruned branch(es)" \
        if res.bound_pruned else ""
    return f"{space}, {res.steps_total} step(s){extra}"


def _write_artifact(dirpath: str, ce: Counterexample) -> str:
    os.makedirs(dirpath, exist_ok=True)
    path = os.path.join(dirpath, f"{ce.scenario}.json")
    with open(path, "w", encoding="utf-8") as f:
        f.write(ce.to_json())
    return path


def _run_scenarios(names: List[str], soak: bool,
                   artifacts: str) -> int:
    rc = 0
    for name in names:
        res = explore(SCENARIOS[name](), **_budgets(SCENARIOS[name], soak))
        if res.ok:
            print(f"ok   {name}: {_describe(res)}")
            continue
        rc = 1
        assert res.counterexample is not None
        path = _write_artifact(artifacts, res.counterexample)
        print(f"FAIL {name}: {_describe(res)}")
        print(res.counterexample.render())
        print(f"     counterexample saved: {path}")
        print(f"     reproduce: python -m tools.weave --replay {path}")
    return rc


def _run_twins(names: List[str], soak: bool, artifacts: str) -> int:
    """Mutation testing for the invariants: every twin seeds a real
    concurrency bug and weave MUST find it."""
    rc = 0
    for name in names:
        res = explore(TWINS[name](), **_budgets(TWINS[name], soak))
        if res.counterexample is not None:
            path = _write_artifact(artifacts, res.counterexample)
            print(f"ok   {name}: seeded bug found "
                  f"({res.executions} execution(s)) — {path}")
        else:
            rc = 1
            print(f"FAIL {name}: seeded bug NOT found — the "
                  f"'{TWINS[name].twin_of}' checker cannot fire "
                  f"({_describe(res)})")
    return rc


def _replay(path: str) -> int:
    with open(path, "r", encoding="utf-8") as f:
        ce = Counterexample.from_json(f.read())
    cls = SCENARIOS.get(ce.scenario) or TWINS.get(ce.scenario)
    if cls is None:
        print(f"unknown scenario in counterexample: {ce.scenario!r}",
              file=sys.stderr)
        return 2
    failure = replay(cls(), ce)
    if failure is None:
        print(f"did NOT reproduce: {ce.scenario} ran the recorded "
              f"schedule clean (code changed since capture?)")
        return 1
    print(f"reproduced {ce.scenario}:")
    print(f"  recorded: {ce.failure}")
    print(f"  now:      {failure}")
    print(ce.render())
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.weave",
        description="deterministic interleaving checker "
                    "(see docs/static-analysis.md)")
    ap.add_argument("--scenario", action="append", default=[],
                    help="run one scenario/twin by name (repeatable; "
                         "default: every production scenario)")
    ap.add_argument("--twins", action="store_true",
                    help="run the seeded-bug twins (each MUST fire)")
    ap.add_argument("--soak", action="store_true",
                    help=f"{SOAK_BUDGET_FACTOR}x execution budgets, "
                         f"+{SOAK_EXTRA_PREEMPTIONS} preemption bound")
    ap.add_argument("--replay", metavar="CE_JSON",
                    help="reproduce a saved counterexample")
    ap.add_argument("--list", action="store_true", dest="list_",
                    help="list scenarios and twins")
    ap.add_argument("--artifacts", default=".weave-artifacts",
                    help="directory for counterexample JSON "
                         "(default: %(default)s)")
    ap.add_argument("--verbose", action="store_true",
                    help="keep production log output (default: quiet — "
                         "failure-path scenarios log errors by design)")
    args = ap.parse_args(argv)

    if args.list_:
        print("scenarios:")
        for name, cls in SCENARIOS.items():
            print(f"  {name:28s} {cls.description}")
        print("twins (seeded bugs — must fire):")
        for name, cls in TWINS.items():
            print(f"  {name:28s} mutation of {cls.twin_of}")
        return 0

    if not args.verbose:
        logging.disable(logging.CRITICAL)

    if args.replay:
        return _replay(args.replay)

    scenario_names = []
    twin_names = []
    for name in args.scenario:
        if name in SCENARIOS:
            scenario_names.append(name)
        elif name in TWINS:
            twin_names.append(name)
        else:
            print(f"unknown scenario: {name!r} (see --list)",
                  file=sys.stderr)
            return 2
    if not args.scenario:
        scenario_names = list(SCENARIOS)
        twin_names = list(TWINS) if args.twins else []
    elif args.twins and not twin_names:
        twin_names = list(TWINS)

    rc = 0
    if scenario_names:
        rc |= _run_scenarios(scenario_names, args.soak, args.artifacts)
    if twin_names:
        rc |= _run_twins(twin_names, args.soak, args.artifacts)
    return rc


if __name__ == "__main__":
    sys.exit(main())
