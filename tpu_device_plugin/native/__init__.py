"""ctypes binding for libtpuhealth.so, with a pure-Python fallback.

Role-equivalent of the reference's vendored NVML cgo binding (SURVEY.md §2
#14): the native shim is loaded dynamically at runtime; when the .so is not
present (unit tests, cross-builds) a Python implementation of the same
probes keeps the plugin functional — health checks are I/O-bound, the native
path exists for deployments that must not run probe I/O under the GIL.
"""

from __future__ import annotations

import ctypes
import logging
import os
from typing import Optional

log = logging.getLogger(__name__)

OK = 0
DEAD = 1
MISSING = 2
ERR = -1

# PCI status-register error bits (config offset 0x06) — the passthrough
# analogue of NVML XID events: master data parity error (8), signaled
# target abort (11), received target/master abort (12/13), signaled system
# error (14), detected parity error (15).
PCI_STATUS_ERROR_MASK = 0xF900

_SEARCH_PATHS = (
    os.path.join(os.path.dirname(__file__), "libtpuhealth.so"),
    os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))), "native", "libtpuhealth.so"),
    "libtpuhealth.so",
)


class TpuHealth:
    """Probe API; backed by libtpuhealth.so when loadable, else Python."""

    def __init__(self, lib_path: Optional[str] = None):
        self._lib = None
        self._has_pci_status = False
        self._err_logged: dict = {}  # bdf -> last-logged error bits
        candidates = (lib_path,) if lib_path else _SEARCH_PATHS
        for cand in candidates:
            if cand is None:
                continue
            try:
                lib = ctypes.CDLL(cand)
                if lib.tpuhealth_abi_version() not in (1, 2):
                    log.warning("libtpuhealth %s has unknown ABI; ignoring", cand)
                    continue
                for fn in ("tpuhealth_probe_config", "tpuhealth_probe_node",
                           "tpuhealth_libtpu_available"):
                    getattr(lib, fn).restype = ctypes.c_int
                    if fn != "tpuhealth_libtpu_available":
                        getattr(lib, fn).argtypes = [ctypes.c_char_p]
                # v2 symbol; a v1 shim just uses the Python reader for it
                try:
                    lib.tpuhealth_pci_status.restype = ctypes.c_int
                    lib.tpuhealth_pci_status.argtypes = [ctypes.c_char_p]
                    self._has_pci_status = True
                except AttributeError:
                    self._has_pci_status = False
                self._lib = lib
                log.info("loaded native libtpuhealth from %s", cand)
                break
            except (OSError, AttributeError):
                # unloadable path, or a foreign .so without our symbols —
                # degrade to the Python fallback rather than crash startup
                continue
        if self._lib is None:
            log.info("libtpuhealth.so not found; using Python probe fallback")

    @property
    def is_native(self) -> bool:
        return self._lib is not None

    def probe_config(self, config_path: str) -> int:
        """PCI config-space liveness: 0xFFFF/unreadable vendor id == dead."""
        if self._lib is not None:
            return self._lib.tpuhealth_probe_config(config_path.encode())
        try:
            with open(config_path, "rb") as f:
                data = f.read(2)
        except FileNotFoundError:
            return MISSING
        except OSError:
            return ERR
        if len(data) != 2:
            return DEAD
        vendor = data[0] | (data[1] << 8)
        return DEAD if vendor in (0xFFFF, 0x0000) else OK

    def probe_node(self, dev_path: str) -> int:
        if self._lib is not None:
            return self._lib.tpuhealth_probe_node(dev_path.encode())
        if not os.path.exists(dev_path):
            return MISSING
        return OK

    def libtpu_available(self) -> bool:
        if self._lib is not None:
            return bool(self._lib.tpuhealth_libtpu_available())
        return False

    def pci_status(self, config_path: str) -> Optional[int]:
        """Raw PCI status register (config offset 6), or None if unreadable."""
        if self._lib is not None and self._has_pci_status:
            value = self._lib.tpuhealth_pci_status(config_path.encode())
            return None if value < 0 else value
        try:
            with open(config_path, "rb") as f:
                f.seek(6)
                data = f.read(2)
        except OSError:
            return None
        if len(data) != 2:
            return None
        return data[0] | (data[1] << 8)

    def chip_error_bits(self, pci_base_path: str, bdf: str) -> int:
        """Latched PCI error bits for one chip (0 = clean/unreadable).

        The XID-events analogue: parity/SERR/abort bits latch on bus errors
        even while the chip is vfio-bound. Diagnostic, not a liveness veto —
        the bits can be sticky from boot-time bus probing."""
        status = self.pci_status(os.path.join(pci_base_path, bdf, "config"))
        if status is None or status == 0xFFFF:
            # all-FF is the no-response artifact of a chip off the bus
            # (probe_config's DEAD case), not real latched error bits
            return 0
        return status & PCI_STATUS_ERROR_MASK

    def chip_alive(self, pci_base_path: str, bdf: str,
                   node_path: Optional[str] = None) -> bool:
        """Composite liveness for one chip (what HealthMonitor polls).

        ANDs two independent native probes: PCI config space (a fallen-off
        chip reads all-FF) and, when the chip has an associated device node
        (`/dev/vfio/<group>`, `/dev/accelN`, mdev sysfs dir), its presence via
        `probe_node` — so a vanished node flips health through the native
        source even when the inotify watcher is degraded (the reference's
        NVML XID watch plays this role, generic_vgpu_device_plugin.go:387-433).
        """
        status = self.probe_config(os.path.join(pci_base_path, bdf, "config"))
        if status == MISSING:
            # Fixture trees have no config file; absence of the whole device
            # dir is the real death signal there.
            alive = os.path.isdir(os.path.join(pci_base_path, bdf))
        else:
            alive = status == OK
        if alive and node_path is not None:
            alive = self.probe_node(node_path) == OK
        if alive:
            # surface latched bus errors without vetoing; log on change only
            bits = self.chip_error_bits(pci_base_path, bdf)
            if bits != self._err_logged.get(bdf, 0):
                self._err_logged[bdf] = bits
                if bits:
                    log.warning("chip %s: PCI status error bits 0x%04x "
                                "latched (diagnostic, not vetoing health)",
                                bdf, bits)
        return alive
