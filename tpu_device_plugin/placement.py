"""Slice placement engine — multi-host ICI slices, fragmentation, defrag.

ROADMAP item 3, the cluster half of topology.py's single-host sub-box
problem. A guest's `jax.Mesh`/`PartitionSpec` sharding (SNIPPETS.md
[1]-[3]) needs the hardware slice to MATCH the mesh shape: four chips on
one ICI ring run XLA collectives over ICI, four stragglers fall back to
PCIe/DCN. This module models slice shapes as tilings of host-local tori
and answers three questions a fleet scheduler (or its simulator,
fleetsim.py) keeps asking:

1. **Where does shape S go?** `plan_slice` places an axis-aligned mesh:
   on ONE host as a free sub-box of the host torus (any axis
   orientation), or across SEVERAL hosts as a grid of fully-free host
   tori — the physical TPU model, where multi-host ICI only exists
   between whole host blocks (a v4 pod is a stack of 2x2x1 host cubes;
   a v5e pod a grid of 2x4 trays). Placements carry a contiguity
   score (1.0 = one perfect box/tiling); `best_effort=True` degrades
   to scattered free chips so callers can measure HOW bad a naive
   placement is instead of just failing.

2. **How fragmented is this host?** `fragmentation` scores a host view:
   `1 - largest_placeable_subbox / free_chips`. 0.0 means every free
   chip is reachable through one box (nothing to defrag); 0.75 on an
   8-chip host means four free chips of which no two are adjacent. A
   DEPARTED chip (hot-unplugged, lifecycle GONE) counts TOWARD
   fragmentation — its hole splits boxes — but is never free capacity
   and never a migration target (ROADMAP item 4 follow-on).

3. **What would make S placeable?** `propose_defrag`: when S is
   unplaceable but free capacity suffices, pick the candidate box
   blocked by the FEWEST claims (departed/unhealthy holes disqualify a
   box — no migration can empty them) and propose moving exactly those
   claims to free slots outside the box. The proposal rides the PR 7
   migration-handoff machinery: each migration is an unprepare (handoff
   record emitted) + re-prepare at the destination, applied by
   fleetsim.FleetSim.apply_defrag and advertised per-node via
   /debug/defrag (docs/design.md "Slice placement" documents the
   proposal format).

Everything here is PURE COMPUTE over immutable inputs: `HostView` is a
frozen snapshot built from an inventory epoch + a checkpoint copy, so
placement scoring can run inside the zero-lock read-path gate
(tests/test_epoch.py pins `placement.score` at 0 registered-lock
acquisitions) and fragmentation can be recomputed at epoch-publish time
with readers never locking (dra.fragmentation_stats).
"""

from __future__ import annotations

import itertools
import logging
from dataclasses import dataclass
from typing import (Any, Dict, FrozenSet, Iterator, List, Mapping,
                    Optional, Sequence, Set, Tuple)

from .topology import Coords, _boxes

log = logging.getLogger(__name__)

__all__ = ["HostView", "SlicePlan", "ShapeError", "parse_shape",
           "orientations", "selection_score", "largest_fit",
           "scatter_score", "cyclic_cover", "mesh_score",
           "fragmentation", "plan_slice", "propose_defrag"]

# Shape sanity bounds. No shipping TPU torus axis exceeds double digits
# and no slice exceeds a few thousand chips; a request like
# "4294967296x2" is a typo (or an attack on _boxes' O(dims^2) per-axis
# interval table), not a slice. Rejecting it typed at parse time keeps
# every downstream planner free of degenerate-box special cases.
MAX_SHAPE_AXIS = 1024
MAX_SHAPE_VOLUME = 1 << 16


class ShapeError(ValueError):
    """A slice-shape string/tuple that cannot describe a real mesh:
    non-integer, empty, zero/negative axis, or axis/volume overflow.
    Subclasses ValueError so existing 400-mapping handlers keep
    working."""


def parse_shape(text: object) -> Coords:
    """"2x2x1" / "4" / [2, 2] → validated dims tuple (every axis >= 1,
    bounded by MAX_SHAPE_AXIS / MAX_SHAPE_VOLUME). Raises ShapeError
    (a ValueError) on anything degenerate — zero, negative, or
    overflow axes must fail HERE, not plan a degenerate box."""
    try:
        if isinstance(text, (tuple, list)):
            if any(isinstance(d, float) and not d.is_integer()
                   for d in text):
                raise ValueError("fractional axis")
            dims = tuple(int(d) for d in text)
        else:
            dims = tuple(int(p) for p in str(text).lower().split("x")
                         if p != "")
    except (TypeError, ValueError):
        raise ShapeError(f"invalid slice shape {text!r}: want NxN[xN] "
                         f"with integer axes") from None
    if not dims or any(d < 1 for d in dims):
        raise ShapeError(f"invalid slice shape {text!r}: want NxN[xN] with "
                         f"every axis >= 1")
    if any(d > MAX_SHAPE_AXIS for d in dims):
        raise ShapeError(f"invalid slice shape {text!r}: axis exceeds "
                         f"{MAX_SHAPE_AXIS}")
    vol = 1
    for d in dims:
        vol *= d
    if vol > MAX_SHAPE_VOLUME:
        raise ShapeError(f"invalid slice shape {text!r}: volume {vol} "
                         f"exceeds {MAX_SHAPE_VOLUME}")
    return dims


def volume(dims: Coords) -> int:
    v = 1
    for d in dims:
        v *= d
    return v


def orientations(shape: Coords, ndims: int) -> Tuple[Coords, ...]:
    """Distinct axis-assignments of `shape` onto an `ndims`-d torus.

    A mesh is orientation-free on the hardware (XLA renumbers axes), so a
    1x4 request may land as 4x1; shapes with fewer axes than the torus
    pad with 1s. A shape with MORE axes than the torus only fits if the
    extra axes are 1 (a 2x2x1 request on a 2D v5e tray is just 2x2)."""
    shape = tuple(d for d in shape if d > 1) or (1,)
    if len(shape) > ndims:
        return ()
    padded = shape + (1,) * (ndims - len(shape))
    return tuple(sorted(set(itertools.permutations(padded))))


def selection_score(dims: Optional[Coords],
                    coords: Sequence[Optional[Coords]]) -> float:
    """ICI contiguity of a chosen chip set: size / minimal-covering-box
    volume. 1.0 = the selection IS an axis-aligned box (one ICI ring /
    torus tile); lower = stragglers whose collectives leave the ICI
    mesh. 0.0 when the torus is unmodeled or any chip has no coords."""
    if not dims or not coords or any(c is None for c in coords):
        return 0.0
    pts = [c for c in coords if c is not None]
    if any(len(c) != len(dims) for c in pts):
        return 0.0
    cover = 1
    for axis in range(len(dims)):
        lo = min(c[axis] for c in pts)
        hi = max(c[axis] for c in pts)
        cover *= hi - lo + 1
    return round(len(set(pts)) / cover, 4) if cover else 0.0


def largest_fit(dims: Coords, avail: FrozenSet[Coords]) -> int:
    """Volume of the largest axis-aligned sub-box of `dims` whose every
    coordinate is in `avail` — the core of the fragmentation score and
    the best-fit tie-break."""
    largest = 0
    for vol, _box, boxset in _boxes(dims):
        if vol > len(avail):
            break          # volume-sorted: nothing larger can fit
        if vol > largest and boxset <= avail:
            largest = vol
    return largest


def scatter_score(shards: Sequence[Tuple[Coords, Sequence[Coords]]],
                  need: int, max_host_volume: int) -> float:
    """Contiguity of a scattered multi-shard pick: per-shard
    selection_score weighted by size, penalized by the host count in
    excess of a perfect tiling's. Shared by plan_slice's best-effort
    fallback and the bench's naive baseline so the engine-vs-naive
    comparison can never drift onto two scoring formulas."""
    weighted = sum(selection_score(dims, list(coords)) * len(coords)
                   for dims, coords in shards)
    min_hosts = max(1, -(-need // max_host_volume))
    return round((weighted / need) * (min_hosts / len(shards)), 4)


@dataclass(frozen=True)
class HostView:
    """Immutable placement snapshot of one host's torus for one
    generation. Built by the DRA driver (DraDriver.host_views) from the
    current inventory epoch + a C-atomic checkpoint copy; fleetsim
    assembles one per node.

      coords    raw id -> host-local torus coords (placed chips only)
      names     raw id -> published ResourceSlice device name
      free      raws allocatable right now (healthy, unclaimed, present)
      departed  raws hot-unplugged (lifecycle GONE): a hole that counts
                toward fragmentation but can never be freed or targeted
      claims    claim uid -> raws it occupies (migratable blockers)
      host_coords  this host's slot on the POD-LEVEL host grid (None =
                unknown): pod wrap-around ICI links join neighboring
                host tori into larger meshes, so a multi-host plan over
                coordinate-bearing hosts is contiguous only when the
                chosen hosts tile a (wrap-aware) box of the host grid
    """

    node: str
    dims: Coords
    coords: Mapping[str, Coords]
    names: Mapping[str, str]
    free: FrozenSet[str]
    departed: FrozenSet[str]
    claims: Mapping[str, Tuple[str, ...]]
    host_coords: Optional[Coords] = None

    def free_coords(self) -> FrozenSet[Coords]:
        return frozenset(self.coords[r] for r in self.free
                         if r in self.coords)

    def claim_of(self) -> Dict[str, str]:
        """raw -> occupying claim uid (inverse of `claims`)."""
        return {raw: uid for uid, raws in self.claims.items()
                for raw in raws}

    def raw_at(self) -> Dict[Coords, str]:
        return {c: raw for raw, c in self.coords.items()}


def fragmentation(view: HostView) -> Dict[str, Any]:
    """The per-host fragmentation record /status + /metrics publish.

    score = 1 - largest_placeable_subbox / free. 0.0 when free capacity
    is one contiguous box (or the host is full — nothing to place,
    nothing fragmented). Departed holes lower `largest_free_box` without
    adding free capacity, so a hot-unplug RAISES the score (its slot is
    unusable until replug) — the defrag advisor reads the same record.
    """
    free_coords = view.free_coords()
    free = len(free_coords)
    largest = largest_fit(view.dims, free_coords) if free else 0
    score = 0.0 if free == 0 else round(1.0 - largest / free, 4)
    return {
        "chips": len(view.coords),
        "free": free,
        "departed": len(view.departed),
        "largest_free_box": largest,
        "fragmentation": score,
    }


class FragAggregate:
    """Incrementally-maintained rollup of per-host fragmentation
    records (ISSUE 17): the cluster_fragmentation totals for one
    generation, updated by add/remove deltas as watch events flip
    single hosts — O(1) per delta instead of re-reducing every host's
    record per decision. `largest_free_box` keeps a multiset of
    per-host values (a counted histogram), so removing the current
    maximum finds the runner-up without a fleet scan. Pure bookkeeping
    — single-writer, no locks; publication is the caller's problem."""

    __slots__ = ("hosts", "chips", "free", "departed", "frag_sum",
                 "fully_free_hosts", "_box_counts")

    def __init__(self) -> None:
        self.hosts = 0
        self.chips = 0
        self.free = 0
        self.departed = 0
        self.frag_sum = 0.0
        self.fully_free_hosts = 0
        self._box_counts: Dict[int, int] = {}

    def add(self, record: Dict[str, Any], fully_free: bool) -> None:
        self.hosts += 1
        self.chips += record["chips"]
        self.free += record["free"]
        self.departed += record["departed"]
        self.frag_sum += record["fragmentation"]
        self.fully_free_hosts += bool(fully_free)
        box = record["largest_free_box"]
        self._box_counts[box] = self._box_counts.get(box, 0) + 1

    def remove(self, record: Dict[str, Any], fully_free: bool) -> None:
        self.hosts -= 1
        self.chips -= record["chips"]
        self.free -= record["free"]
        self.departed -= record["departed"]
        self.frag_sum -= record["fragmentation"]
        self.fully_free_hosts -= bool(fully_free)
        box = record["largest_free_box"]
        left = self._box_counts.get(box, 0) - 1
        if left > 0:
            self._box_counts[box] = left
        else:
            self._box_counts.pop(box, None)

    def largest_free_box(self) -> int:
        return max(self._box_counts, default=0)

    def rollup(self, largest_free_mesh: int = 0) -> Dict[str, Any]:
        """The exact cluster_fragmentation per-generation record shape
        (the mesh term is the caller's — it is a cross-host property no
        per-host delta can maintain)."""
        largest_box = self.largest_free_box()
        largest = max(largest_box, largest_free_mesh)
        return {
            "hosts": self.hosts,
            "chips": self.chips,
            "free": self.free,
            "departed": self.departed,
            "fully_free_hosts": self.fully_free_hosts,
            "largest_free_box": largest_box,
            "largest_free_mesh": largest_free_mesh,
            "fragmentation": 0.0 if self.free == 0
            else round(1.0 - largest / self.free, 4),
            "mean_host_fragmentation": round(
                self.frag_sum / max(1, self.hosts), 4),
        }


def _cyclic_span(values: Sequence[int], dim: int) -> int:
    """Length of the shortest wrap-aware interval on a ring of size
    `dim` covering `values` — the 1-D building block of cyclic_cover.
    On a pod axis with wrap-around ICI, hosts {0, dim-1} are adjacent:
    their span is 2, not dim."""
    pts = sorted(set(v % dim for v in values))
    if len(pts) >= dim:
        return dim
    # the minimal covering interval is the ring minus the largest gap
    largest_gap = max(
        (b - a for a, b in zip(pts, pts[1:])),
        default=0)
    largest_gap = max(largest_gap, pts[0] + dim - pts[-1])
    return dim - largest_gap + 1 if largest_gap else 1


def cyclic_cover(points: Sequence[Coords], pod_dims: Coords) -> int:
    """Minimal wrap-aware covering-box volume of host-grid `points` on
    the pod torus `pod_dims` — the cross-host analogue of
    selection_score's covering box, with per-axis wrap-around because
    pod-level ICI links close each host-grid axis into a ring."""
    cover = 1
    for axis, dim in enumerate(pod_dims):
        cover *= _cyclic_span([p[axis] for p in points], dim)
    return cover


def mesh_score(points: Sequence[Coords], pod_dims: Coords) -> float:
    """Inter-host ICI contiguity of a chosen host set: hosts / minimal
    wrap-aware covering box. 1.0 = the hosts tile one (possibly
    wrapped) box of the pod grid, so every cross-host hop rides a real
    pod-level ICI link; lower = host stragglers whose collectives
    leave the mesh. 0.0 when any host's grid slot is unknown."""
    if not points or any(p is None for p in points):
        return 0.0
    if any(len(p) != len(pod_dims) for p in points):
        return 0.0
    cover = cyclic_cover(points, pod_dims)
    return round(len(set(points)) / cover, 4) if cover else 0.0


@dataclass(frozen=True)
class SlicePlan:
    """One placement decision: per-host shards + how contiguous it is."""

    shape: Coords
    shards: Tuple[Tuple[str, Tuple[str, ...]], ...]   # (node, raws)
    score: float
    hosts: int

    def devices(self) -> List[Tuple[str, str]]:
        return [(node, raw) for node, raws in self.shards for raw in raws]


def _host_boxes(view: HostView, shape: Coords
                ) -> Iterator[Tuple[Tuple[str, ...], FrozenSet[Coords]]]:
    """Candidate placements of `shape` on one host: (raws, boxset) for
    every free axis-aligned box matching any orientation of the shape,
    in deterministic (orientation, position) order."""
    wanted = set(orientations(shape, len(view.dims)))
    if not wanted:
        return
    free_coords = view.free_coords()
    raw_at = view.raw_at()
    for vol, box, boxset in _boxes(view.dims):
        if vol != volume(shape):
            continue
        lengths = tuple(length for _start, length in box)
        if lengths not in wanted:
            continue
        if boxset <= free_coords:
            yield tuple(raw_at[c] for c in sorted(boxset)), boxset


def _single_host_plan(shape: Coords, views: Sequence[HostView]
                      ) -> Optional[SlicePlan]:
    """Best free sub-box across hosts: avoid breaking a PRISTINE
    (fully-free) host first — a whole torus is cross-host mesh capacity
    the fleet scheduler can tile larger slices from, and one stray
    chip destroys it (ISSUE 14) — then best-fit by post-placement
    fragmentation (leave the tightest host tightest), node name as the
    deterministic tie-break."""
    best: Optional[Tuple[tuple, SlicePlan]] = None
    for view in views:
        free_coords = view.free_coords()
        pristine = int(len(free_coords) == volume(view.dims)
                       and not view.departed)
        for raws, boxset in _host_boxes(view, shape):
            remaining = free_coords - boxset
            frag_after = 0.0 if not remaining \
                else 1.0 - largest_fit(view.dims, remaining) / len(remaining)
            key = (pristine, round(frag_after, 6), len(view.free),
                   view.node, sorted(boxset))
            if best is None or key < best[0]:
                best = (key, SlicePlan(shape=shape,
                                       shards=((view.node, raws),),
                                       score=1.0, hosts=1))
    return best[1] if best else None


def _whole_torus_shard(view: HostView) -> Tuple[str, Tuple[str, ...]]:
    return (view.node, tuple(raw for _c, raw in sorted(
        (c, raw) for raw, c in view.coords.items())))


def _mesh_window(counts: Coords, candidates: Sequence[HostView],
                 pod_dims: Coords) -> Optional[List[HostView]]:
    """A counts-shaped window of fully-free hosts on the pod grid,
    wrap-around allowed per axis (pod-level wrap links close each host
    axis into a ring). Deterministic: windows scanned in start order,
    hosts returned in window (row-major) order."""
    if any(c > p for c, p in zip(counts, pod_dims)):
        return None
    at: Dict[Coords, HostView] = {}
    for v in candidates:
        if v.host_coords is not None \
                and len(v.host_coords) == len(pod_dims):
            at[tuple(v.host_coords)] = v
    if len(at) < volume(counts):
        return None
    seen: Set[FrozenSet[Coords]] = set()
    for start in itertools.product(*[range(p) for p in pod_dims]):
        cells = tuple(itertools.product(
            *[tuple((s + k) % p for k in range(c))
              for s, c, p in zip(start, counts, pod_dims)]))
        key = frozenset(cells)
        if key in seen:
            continue          # full-axis windows repeat under rotation
        seen.add(key)
        if all(c in at for c in cells):
            return [at[c] for c in cells]
    return None


def _multi_host_plan(shape: Coords, views: Sequence[HostView],
                     pod_dims: Optional[Coords] = None
                     ) -> Optional[SlicePlan]:
    """Tile `shape` as a grid of FULLY-FREE host tori — the physical TPU
    model: cross-host ICI links join whole host blocks, so a multi-host
    slice is only a mesh when every member host contributes its complete
    torus (v4: 2x2x1 cubes; v5e: 2x4 trays).

    When the caller names the pod grid (`pod_dims`), a contiguous
    multi-host plan must come from COORDINATE-BEARING hosts tiling a
    wrap-aware window of that grid — a host pair with no known
    pod-level ICI link between them is not a mesh, however free both
    tori are, and a coordinate-less host (mid-rollout daemon) cannot
    PROVE adjacency, so it never joins a score-1.0 mesh (best_effort's
    scatter tiers still reach it). The pod grid must model the SAME
    axes as the host torus: a rank-mismatched `pod_dims` (a 2-D grid
    over 3-D v4/v5p host cubes) cannot prove adjacency either, so that
    generation forms no contiguous multi-host plan rather than
    silently reverting to the legacy claim — model a 3-D pod for 3-D
    hosts. With `pod_dims` unmodeled the legacy behavior holds:
    inter-host edges unknown, any whole-tori set scores 1.0."""
    by_dims: Dict[Coords, List[HostView]] = {}
    for view in views:
        full = view.free_coords()
        if len(full) == volume(view.dims) and not view.departed:
            by_dims.setdefault(view.dims, []).append(view)
    mesh_aware = pod_dims is not None
    for dims, candidates in sorted(by_dims.items()):
        if mesh_aware:
            if len(pod_dims) != len(dims):
                continue   # rank-mismatched pod model: unprovable
            pool = [v for v in candidates if v.host_coords is not None
                    and len(v.host_coords) == len(pod_dims)]
        else:
            pool = candidates
        for oriented in orientations(shape, len(dims)):
            if any(s % d for s, d in zip(oriented, dims)):
                continue
            counts = tuple(s // d for s, d in zip(oriented, dims))
            n_hosts = volume(counts)
            if n_hosts < 2 or n_hosts > len(pool):
                continue
            if mesh_aware:
                window = _mesh_window(counts, pool, pod_dims)
                if window is None:
                    continue   # free tori exist but no contiguous mesh
                chosen = window
            else:
                chosen = sorted(pool, key=lambda v: v.node)[:n_hosts]
            return SlicePlan(
                shape=shape,
                shards=tuple(_whole_torus_shard(v) for v in chosen),
                score=1.0, hosts=n_hosts)
    return None


def _mesh_scatter_plan(shape: Coords, views: Sequence[HostView],
                       pod_dims: Coords) -> Optional[SlicePlan]:
    """Best-effort cross-host fallback BETWEEN the contiguous mesh and
    the raw chip scatter: whole free tori chosen greedily by pod-grid
    closeness when no contiguous window exists. Scored honestly by
    mesh_score — some cross-host hops leave the pod ICI mesh."""
    by_dims: Dict[Coords, List[HostView]] = {}
    for view in views:
        if view.host_coords is None or len(view.host_coords) != len(pod_dims):
            continue
        if len(view.free_coords()) == volume(view.dims) \
                and not view.departed:
            by_dims.setdefault(view.dims, []).append(view)
    best: Optional[SlicePlan] = None
    for dims, candidates in sorted(by_dims.items()):
        for oriented in orientations(shape, len(dims)):
            if any(s % d for s, d in zip(oriented, dims)):
                continue
            n_hosts = volume(tuple(s // d
                                   for s, d in zip(oriented, dims)))
            if n_hosts < 2 or n_hosts > len(candidates):
                continue
            # greedy: seed at each candidate, grow by minimal cyclic
            # cover; keep the best-scoring seed (deterministic order)
            for seed in sorted(candidates, key=lambda v: v.node):
                chosen = [seed]
                pool = [v for v in candidates if v is not seed]
                while len(chosen) < n_hosts:
                    pick = min(pool, key=lambda v: (cyclic_cover(
                        [c.host_coords for c in chosen] + [v.host_coords],
                        pod_dims), v.node))
                    chosen.append(pick)
                    pool.remove(pick)
                score = mesh_score([v.host_coords for v in chosen],
                                   pod_dims)
                plan = SlicePlan(
                    shape=shape,
                    shards=tuple(_whole_torus_shard(v) for v in chosen),
                    score=score, hosts=n_hosts)
                if best is None or plan.score > best.score:
                    best = plan
                if best.score == 1.0:
                    return best
    return best


def _scatter_plan(shape: Coords, views: Sequence[HostView]
                  ) -> Optional[SlicePlan]:
    """Best-effort fallback: fill from the freest hosts in coordinate
    order — the 'four stragglers' a topology-blind allocator produces.
    Scored honestly so benches can compare against the planner."""
    need = volume(shape)
    ordered = sorted(views, key=lambda v: (-len(v.free), v.node))
    shards: List[Tuple[str, Tuple[str, ...]]] = []
    scored: List[Tuple[Coords, List[Coords]]] = []
    taken = 0
    for view in ordered:
        if taken >= need:
            break
        free_sorted = sorted(
            (view.coords[r], r) for r in view.free if r in view.coords)
        raws = tuple(r for _c, r in free_sorted[:need - taken])
        if not raws:
            continue
        shards.append((view.node, raws))
        scored.append((view.dims, [view.coords[r] for r in raws]))
        taken += len(raws)
    if taken < need:
        return None
    # a scatter that crossed more hosts than a perfect tiling would is
    # penalized by the host ratio: cross-host traffic leaves ICI entirely
    score = scatter_score(scored, need,
                          max(volume(v.dims) for v in views))
    return SlicePlan(shape=shape, shards=tuple(shards), score=score,
                     hosts=len(shards))


def plan_slice(shape: Coords, views: Sequence[HostView],
               best_effort: bool = False,
               pod_dims: Optional[Coords] = None) -> Optional[SlicePlan]:
    """Place `shape` across `views`.

    Contiguous placements only (score 1.0): one host sub-box, else a
    whole-torus multi-host tiling — wrap-aware-contiguous on the pod
    host grid when `pod_dims` + HostView.host_coords model the
    pod-level ICI links. `best_effort=True` adds the degraded tiers
    (score < 1.0): first whole free tori chosen by pod-grid closeness
    (mesh_score), then the raw chip scatter — so callers can
    place-and-measure instead of failing. The bench's naive baseline
    and the fleetsim storms use it. Returns None when nothing fits.
    """
    if not views:
        return None
    plan = _single_host_plan(shape, views)
    if plan is None:
        plan = _multi_host_plan(shape, views, pod_dims=pod_dims)
    if plan is None and best_effort and pod_dims is not None:
        plan = _mesh_scatter_plan(shape, views, pod_dims)
    if plan is None and best_effort:
        plan = _scatter_plan(shape, views)
    return plan


# ------------------------------------------------------------------ defrag


def _box_candidates(shape: Coords, view: HostView
                    ) -> Iterator[Tuple[FrozenSet[Coords], FrozenSet[str]]]:
    """Defrag target candidates on one host: boxes of the shape whose
    every slot is free or claim-held. A box containing a DEPARTED hole
    (no silicon to migrate onto) or an unhealthy/untracked occupant (no
    claim to move) can never be emptied — skip it."""
    wanted = set(orientations(shape, len(view.dims)))
    if not wanted:
        return
    free_coords = view.free_coords()
    raw_at = view.raw_at()
    claim_of = view.claim_of()
    departed_coords = {view.coords[r] for r in view.departed
                       if r in view.coords}
    for vol, box, boxset in _boxes(view.dims):
        if vol != volume(shape):
            continue
        if tuple(length for _s, length in box) not in wanted:
            continue
        if boxset & departed_coords:
            continue
        blockers: Set[str] = set()
        feasible = True
        for c in boxset:
            if c in free_coords:
                continue
            uid = claim_of.get(raw_at.get(c, ""))
            if uid is None:
                feasible = False    # unhealthy / untracked occupant
                break
            blockers.add(uid)
        if feasible:
            yield boxset, frozenset(blockers)


def _destination(view: HostView, n: int, exclude: FrozenSet[Coords],
                 reserved: Set[Tuple[str, Coords]]
                 ) -> Optional[Tuple[str, ...]]:
    """`n` free slots on `view` outside `exclude` coords and not already
    `reserved` by an earlier migration of the same proposal — preferring
    a contiguous box of the migrated claim's size so defrag does not
    trade one ragged tenant for another."""
    avail = {c for c in view.free_coords() - exclude
             if (view.node, c) not in reserved}
    if len(avail) < n:
        return None
    raw_at = view.raw_at()
    chosen = None
    for vol, _box, boxset in _boxes(view.dims):
        if vol > n:
            break
        if vol == n and boxset <= avail:
            chosen = sorted(boxset)
            break
    if chosen is None:         # no exact-size contiguous box: scatter
        chosen = sorted(avail)[:n]
    for c in chosen:
        reserved.add((view.node, c))
    return tuple(raw_at[c] for c in chosen)


def propose_defrag(shape: Coords, views: Sequence[HostView]
                   ) -> Dict[str, Any]:
    """The defrag advisory (docs/design.md "Slice placement" documents
    this format):

      {"shape": [...], "placeable": bool, "satisfiable": bool,
       "free_total": n, "target": {"node", "devices": [raw...]} | None,
       "migrations": [{"claim", "source_node", "devices": [raw...],
                       "target_node" | None, "target_devices" | None}],
       "moves": n}

    placeable: a contiguous plan already exists (nothing to do).
    satisfiable: total free capacity across views covers the shape —
    when False the advisory still names the minimal evictions (with
    target_node None = "off these hosts"), because an operator with
    capacity elsewhere can act on it.
    """
    shape = parse_shape(shape)
    need = volume(shape)
    free_total = sum(len(v.free) for v in views)
    out: Dict[str, Any] = {
        "shape": list(shape),
        "placeable": False,
        "satisfiable": free_total >= need,
        "free_total": free_total,
        "target": None,
        "migrations": [],
        "moves": 0,
    }
    if plan_slice(shape, views) is not None:
        out["placeable"] = True
        return out
    # Candidates ordered by minimal moves (fewest blocking claims, then
    # fewest chips, then node/box for determinism). Each is then checked
    # for DESTINATION feasibility — a smaller eviction set whose claims
    # have nowhere to land loses to a slightly larger one that fully
    # resolves; when nothing fully resolves, the minimal candidate is
    # still advised with target_node None ("off these hosts").
    candidates = sorted(
        ((len(blockers),
          sum(len(view.claims[uid]) for uid in blockers),
          view.node, sorted(boxset), view, boxset, blockers)
         for view in views
         for boxset, blockers in _box_candidates(shape, view)),
        key=lambda c: c[:4])
    if not candidates:
        return out
    by_free = sorted(views, key=lambda v: (-len(v.free), v.node))
    best_partial = None
    for _n, _chips, _node, _box, view, boxset, blockers in candidates:
        reserved: Set[Tuple[str, Coords]] = set()
        migrations: List[Dict[str, Any]] = []
        resolved = True
        for uid in sorted(blockers):
            raws = view.claims[uid]
            migration: Dict[str, Any] = {
                "claim": uid,
                "source_node": view.node,
                "devices": sorted(raws),
                "target_node": None,
                "target_devices": None,
            }
            for cand in by_free:
                exclude = boxset if cand.node == view.node else frozenset()
                dest = _destination(cand, len(raws), exclude, reserved)
                if dest is not None:
                    migration["target_node"] = cand.node
                    migration["target_devices"] = list(dest)
                    break
            else:
                resolved = False
            migrations.append(migration)
        result = dict(out)
        result["target"] = {
            "node": view.node,
            "devices": sorted(view.raw_at()[c] for c in boxset)}
        result["migrations"] = migrations
        result["moves"] = len(migrations)
        if resolved:
            return result
        if best_partial is None:
            best_partial = result
    return best_partial if best_partial is not None else out
