"""Device lifecycle chaos scenarios (`make chaos-lifecycle`).

The hard production transitions the base chaos suite (test_chaos.py)
does not cover, driven deterministically — every event is injected
synchronously through the FSM/driver seams (or through the armed fault
sites `pci.hotunplug` / `pci.replug` / `migration.handoff`), never by
racing wall-clock sleeps:

  1. **unplug-while-allocated** — PCIe surprise removal of a chip with a
     prepared claim: the claim is orphaned (durably, in the checkpoint),
     the guest-visible removal is recorded, the device leaves the
     published ResourceSlice/by_name entirely, and the epoch bump
     retires precompiled fragments by construction.
  2. **unplug-during-prepare** — the device departs between the claim
     fetch and planning: the prepare fails per-claim with the typed
     "departed" error, leaking neither a CDI spec nor a checkpoint
     entry.
  3. **replug-identity-swap** — the slot comes back with different
     silicon (serial mismatch, or an armed `pci.replug`): readmitted as
     a NEW identity, counted, and the orphaned claim never reattaches;
     a same-serial replug readmits cleanly.
  4. **migration source-crash-mid-handoff** — the handoff record is
     durable exactly-once across injected `migration.handoff` /
     `checkpoint.write` failures and a source daemon crash at any point;
     the destination validates claim UID + allocation generation before
     preparing.
  5. **old→new checkpoint upgrade** — a v0 (bare-map) checkpoint loads
     with claims intact, the daemon re-serves prepared claims without an
     apiserver round-trip, and a FUTURE-version checkpoint refuses to
     load with a typed error instead of being silently truncated.
"""

from __future__ import annotations

import json
import os
import shutil
import tempfile

import pytest

from tests.fakehost import FakeChip, FakeHost
from tests.test_dra import FakeApiServer
from tpu_device_plugin import faults
from tpu_device_plugin.config import Config
from tpu_device_plugin.discovery import discover, read_serial
from tpu_device_plugin.dra import (CHECKPOINT_VERSION, CheckpointVersionError,
                                   DraDriver, slice_device_name)
from tpu_device_plugin.kubeapi import ApiClient
from tpu_device_plugin.kubeletapi import drapb
from tpu_device_plugin.lifecycle_fsm import (ABSENT, ALLOCATED, BOUND,
                                             DETACHING, GONE, PRESENT,
                                             DeviceLifecycle)

SEED = int(os.environ.get("TDP_CHAOS_SEED", "1337"))


@pytest.fixture(autouse=True)
def clean_faults():
    faults.reset()
    faults.seed(SEED)
    yield
    faults.reset()


@pytest.fixture()
def apiserver():
    s = FakeApiServer()
    yield s
    s.stop()


def bdf(i: int) -> str:
    return f"0000:00:{4 + i:02x}.0"


def chip_name(i: int) -> str:
    return slice_device_name(bdf(i))


def make_host(root, serials=True):
    h = FakeHost(root)
    for i in range(4):
        h.add_chip(FakeChip(bdf(i), device_id="0063",
                            iommu_group=str(11 + i), numa_node=i // 2,
                            serial=f"serial-{i}" if serials else None))
    cfg = Config().with_root(root)
    os.makedirs(cfg.device_plugin_path, exist_ok=True)
    return h, cfg


@pytest.fixture()
def host():
    root = tempfile.mkdtemp(prefix="tdplc-")
    yield make_host(root)
    shutil.rmtree(root, ignore_errors=True)


def make_driver(cfg, apiserver, node="node-a"):
    registry, generations = discover(cfg)
    api = (ApiClient(apiserver.url, token_path="/nonexistent-token")
           if apiserver is not None else None)
    return DraDriver(cfg, registry, generations, node_name=node, api=api)


def make_stack(cfg, apiserver, node="node-a"):
    """Driver + attached lifecycle FSM with the inventory admitted (the
    production wiring cli.py + PluginManager._sync_lifecycle perform,
    driven synchronously)."""
    driver = make_driver(cfg, apiserver, node=node)
    fsm = DeviceLifecycle(
        serial_reader=lambda raw: read_serial(cfg.pci_base_path, raw))
    driver.attach_lifecycle(fsm)
    sync_fsm(fsm, cfg)
    return driver, fsm


def sync_fsm(fsm, cfg):
    registry, _ = discover(cfg)
    fsm.sync_inventory({d.bdf: read_serial(cfg.pci_base_path, d.bdf)
                        for d in registry.all_devices()})


def prepare(driver, uid, ns="ns", name=None):
    return driver.NodePrepareResources(
        drapb.NodePrepareResourcesRequest(claims=[
            drapb.Claim(namespace=ns, name=name or uid, uid=uid)]), None)


def unprepare(driver, uid, ns="ns", name=None):
    return driver.NodeUnprepareResources(
        drapb.NodeUnprepareResourcesRequest(claims=[
            drapb.Claim(namespace=ns, name=name or uid, uid=uid)]), None)


def reload_driver(driver, cfg, apiserver, node="node-a"):
    """Daemon crash/upgrade: stop (drains the checkpoint writer) and
    bring up a fresh instance over the same state directories."""
    driver.stop()
    return make_driver(cfg, apiserver, node=node)


# ------------------------------------------------ 1. unplug-while-allocated


def test_unplug_while_allocated_orphans_claim(host, apiserver):
    h, cfg = host
    driver, fsm = make_stack(cfg, apiserver)
    apiserver.add_claim("ns", "c1", "uid-1", driver.driver_name,
                        [{"device": chip_name(0)}], generation=3)
    assert prepare(driver, "uid-1", name="c1").claims["uid-1"].error == ""
    assert fsm.state_of(bdf(0)) == ALLOCATED
    ep0 = driver._inventory_snapshot()

    # PCIe surprise removal observed by the health plane
    shutil.rmtree(os.path.join(h.pci, bdf(0)))
    h.remove_vfio_group("11")
    fsm.note_fs_event(bdf(0), False)

    assert fsm.state_of(bdf(0)) == GONE
    st = fsm.stats()
    assert st["claims_orphaned_total"] == 1
    assert st["transitions"].get("allocated->gone") == 1
    removal = st["surprise_removals"][0]
    assert removal["device"] == bdf(0) and removal["claims"] == ["uid-1"]
    # the claim is orphaned, the device left the published inventory
    assert driver.orphaned_claims() == ["uid-1"]
    ep1 = driver._inventory_snapshot()
    assert ep1.epoch_id > ep0.epoch_id           # fragments retired with it
    assert chip_name(0) not in ep1.by_name
    assert chip_name(0) in ep1.departed
    assert driver.departed_devices() == [bdf(0)]
    names = {d["name"] for d in driver.build_slice()["spec"]["devices"]}
    assert chip_name(0) not in names and len(names) == 3
    # a NEW claim allocated to the departed device fails with the typed
    # error, not a generic stale-slice guess
    apiserver.add_claim("ns", "c2", "uid-2", driver.driver_name,
                        [{"device": chip_name(0)}])
    assert "departed" in prepare(driver, "uid-2",
                                 name="c2").claims["uid-2"].error
    # the orphan mark is durable: a daemon restart still reports it and
    # the prepared claim count is unchanged (exactly-once, no silent drop)
    driver2 = reload_driver(driver, cfg, apiserver)
    assert driver2.orphaned_claims() == ["uid-1"]
    assert driver2.prepared_claim_count() == 1
    entry = driver2._checkpoint["uid-1"]
    assert entry["orphaned"]["device"] == bdf(0)
    assert entry["device_raws"] == [bdf(0)]
    # an orphaned claim's unprepare emits NO handoff (nothing coherent to
    # take over) but still deletes cleanly
    assert unprepare(driver2, "uid-1", name="c1").claims["uid-1"].error == ""
    assert driver2.prepared_claim_count() == 0
    assert driver2.export_handoff("uid-1") is None
    driver2.stop()


def test_injected_hotunplug_fault_forces_surprise_removal(host, apiserver):
    """`pci.hotunplug` inverts presence evidence: no fs mutation needed,
    and checkpoint semantics stay exactly-once under the injected fault."""
    _, cfg = host
    driver, fsm = make_stack(cfg, apiserver)
    apiserver.add_claim("ns", "c1", "uid-1", driver.driver_name,
                        [{"device": chip_name(1)}])
    assert prepare(driver, "uid-1", name="c1").claims["uid-1"].error == ""
    with faults.injected("pci.hotunplug", kind="drop", count=1):
        # evidence says present; the armed fault makes it read as removal
        fsm.note_fs_event(bdf(1), True)
    assert fsm.state_of(bdf(1)) == GONE
    assert fsm.stats()["claims_orphaned_total"] == 1
    assert driver.orphaned_claims() == ["uid-1"]
    # budget exhausted: the next sync readmits the (really present) chip
    sync_fsm(fsm, cfg)
    assert fsm.state_of(bdf(1)) == BOUND
    # exactly-once: one claim, orphan mark durable, no duplicates
    driver2 = reload_driver(driver, cfg, apiserver)
    assert driver2.prepared_claim_count() == 1
    assert driver2.orphaned_claims() == ["uid-1"]
    driver2.stop()


# ------------------------------------------------ 2. unplug-during-prepare


def test_unplug_during_prepare_fails_claim_cleanly(host, apiserver):
    _, cfg = host
    driver, fsm = make_stack(cfg, apiserver)
    apiserver.add_claim("ns", "c1", "uid-1", driver.driver_name,
                        [{"device": chip_name(0)}])
    real_fetch = driver._allocation_results

    def fetch_then_unplug(claim):
        out = real_fetch(claim)
        # the chip departs between the apiserver fetch and planning —
        # injected synchronously at the seam, no timing race
        fsm.note_fs_event(bdf(0), False)
        return out

    driver._allocation_results = fetch_then_unplug
    resp = prepare(driver, "uid-1", name="c1")
    driver._allocation_results = real_fetch
    assert "departed" in resp.claims["uid-1"].error
    # nothing leaked: no checkpoint entry, no CDI spec, and the on-disk
    # checkpoint converges to empty (stop drains the writer)
    assert driver.prepared_claim_count() == 0
    assert not os.path.exists(driver._claim_spec_path("uid-1"))
    driver2 = reload_driver(driver, cfg, apiserver)
    assert driver2.prepared_claim_count() == 0
    assert driver2.orphan_specs_removed == 0
    driver2.stop()


# ------------------------------------------------ 3. replug-identity-swap


def test_replug_identity_swap_keeps_claims_orphaned(host, apiserver):
    h, cfg = host
    driver, fsm = make_stack(cfg, apiserver)
    apiserver.add_claim("ns", "c1", "uid-1", driver.driver_name,
                        [{"device": chip_name(0)}])
    assert prepare(driver, "uid-1", name="c1").claims["uid-1"].error == ""

    # unplug (vfio node loss), then the SLOT returns with NEW silicon
    fsm.note_fs_event(bdf(0), False)
    assert fsm.state_of(bdf(0)) == GONE
    with open(os.path.join(h.pci, bdf(0), "serial_number"), "w") as f:
        f.write("serial-SWAPPED\n")
    fsm.note_fs_event(bdf(0), True)

    st = fsm.stats()
    assert fsm.state_of(bdf(0)) == BOUND         # readmitted, new identity
    assert st["identity_swaps_total"] == 1
    assert st["transitions"].get("gone->replugged") == 1
    assert st["transitions"].get("replugged->present") == 1
    # the orphaned claim never reattaches to the impostor silicon
    assert driver.orphaned_claims() == ["uid-1"]
    # rediscovery readmits the slot into the DRA inventory (departed
    # mark clears) — claims against the OLD identity stay orphaned
    driver.set_inventory(*discover(cfg))
    assert driver.departed_devices() == []
    assert chip_name(0) in driver._by_name
    assert driver.orphaned_claims() == ["uid-1"]

    # contrast: a same-serial replug of another chip is NOT a swap
    fsm.note_fs_event(bdf(1), False)
    fsm.note_fs_event(bdf(1), True)
    st = fsm.stats()
    assert fsm.state_of(bdf(1)) == BOUND
    assert st["identity_swaps_total"] == 1       # unchanged
    driver.stop()


def test_injected_replug_fault_forces_identity_swap(host, apiserver):
    """`pci.replug` makes a same-serial replug read as an identity swap."""
    _, cfg = host
    _, fsm = make_stack(cfg, apiserver)
    fsm.note_fs_event(bdf(2), False)
    with faults.injected("pci.replug", kind="drop", count=1):
        fsm.note_fs_event(bdf(2), True)
    assert fsm.state_of(bdf(2)) == BOUND
    assert fsm.stats()["identity_swaps_total"] == 1


# ------------------------------------ 4. migration source-crash-mid-handoff


def test_migration_handoff_survives_source_crash_and_validates(host,
                                                               apiserver):
    _, cfg = host
    src, fsm = make_stack(cfg, apiserver, node="node-a")
    apiserver.add_claim("ns", "vm-claim", "uid-mig", src.driver_name,
                        [{"device": chip_name(0)}], generation=7)
    assert prepare(src, "uid-mig", name="vm-claim").claims["uid-mig"] \
        .error == ""
    assert src._checkpoint["uid-mig"]["generation"] == 7
    assert fsm.state_of(bdf(0)) == ALLOCATED

    # (a) the handoff emit itself fails: per-claim error BEFORE any state
    # mutates — claim, spec and FSM state survive for the retry
    with faults.injected("migration.handoff", count=1):
        resp = unprepare(src, "uid-mig", name="vm-claim")
    assert "injected" in resp.claims["uid-mig"].error
    assert src.prepared_claim_count() == 1
    assert os.path.exists(src._claim_spec_path("uid-mig"))
    assert src.export_handoff("uid-mig") is None

    # (b) the commit carrying deletion+handoff fails: both roll back
    # together — never a durable handoff for a claim still checkpointed
    with faults.injected("checkpoint.write", count=1):
        resp = unprepare(src, "uid-mig", name="vm-claim")
    assert resp.claims["uid-mig"].error != ""
    assert src.prepared_claim_count() == 1
    assert src.export_handoff("uid-mig") is None

    # (c) source crashes (restart): the claim was never unprepared, the
    # retry now emits the handoff durably — exactly once
    src2 = reload_driver(src, cfg, apiserver, node="node-a")
    src2.attach_lifecycle(fsm)   # daemon restart re-wires the host FSM
    assert src2.prepared_claim_count() == 1
    assert unprepare(src2, "uid-mig",
                     name="vm-claim").claims["uid-mig"].error == ""
    record = src2.export_handoff("uid-mig")
    assert record is not None
    assert record["generation"] == 7
    assert record["devices"] == [chip_name(0)]
    assert record["source_node"] == "node-a"
    assert fsm.state_of(bdf(0)) == BOUND          # detach completed
    assert fsm.stats()["transitions"].get("allocated->detaching") == 1
    assert fsm.stats()["transitions"].get("detaching->bound") == 1

    # (d) source crashes AFTER the emit: the record is checkpointed
    src3 = reload_driver(src2, cfg, apiserver, node="node-a")
    assert src3.export_handoff("uid-mig") == record
    with open(src3.checkpoint_path) as f:
        on_disk = json.load(f)
    assert on_disk["version"] == CHECKPOINT_VERSION
    assert "uid-mig" in on_disk["handoffs"]

    # (e) destination validates the record against the LIVE claim
    dest_root = tempfile.mkdtemp(prefix="tdplc-dest-")
    try:
        _, dest_cfg = make_host(dest_root)
        dest, _dest_fsm = make_stack(dest_cfg, apiserver, node="node-b")
        dest.import_handoff(record)
        # the claim was re-allocated since the source released it
        # (generation moved): the prepare is refused with a typed error
        # AND the stale record is evicted — generations are monotonic,
        # so it could never validate again
        apiserver.add_claim("ns", "vm-claim", "uid-mig", dest.driver_name,
                            [{"device": chip_name(0)}], generation=8)
        resp = prepare(dest, "uid-mig", name="vm-claim")
        assert "handoff generation" in resp.claims["uid-mig"].error
        assert dest.prepared_claim_count() == 0
        # the kubelet retry prepares from the LIVE allocation (the stale
        # handoff no longer blocks the claim forever); nothing was
        # handed off
        resp = prepare(dest, "uid-mig", name="vm-claim")
        assert resp.claims["uid-mig"].error == ""
        assert dest.checkpoint_stats()["handoffs_completed_total"] == 0
        # clean migration: a matching-generation handoff completes once
        assert unprepare(dest, "uid-mig",
                         name="vm-claim").claims["uid-mig"].error == ""
        dest.import_handoff(record)
        apiserver.add_claim("ns", "vm-claim", "uid-mig", dest.driver_name,
                            [{"device": chip_name(0)}], generation=7)
        resp = prepare(dest, "uid-mig", name="vm-claim")
        assert resp.claims["uid-mig"].error == ""
        stats = dest.checkpoint_stats()
        assert stats["handoffs_completed_total"] == 1
        # idempotent kubelet retry: no double-complete
        resp = prepare(dest, "uid-mig", name="vm-claim")
        assert resp.claims["uid-mig"].error == ""
        assert dest.checkpoint_stats()["handoffs_completed_total"] == 1
        dest.stop()
    finally:
        shutil.rmtree(dest_root, ignore_errors=True)
    assert src3.checkpoint_stats()["handoffs_emitted_total"] == 0  # fresh
    src3.stop()


def test_handoff_wrong_uid_rejected(host, apiserver):
    _, cfg = host
    driver, _fsm = make_stack(cfg, apiserver)
    apiserver.add_claim("ns", "c1", "uid-1", driver.driver_name,
                        [{"device": chip_name(0)}], generation=1)
    driver.import_handoff({"uid": "uid-1", "generation": 1})
    # staged under uid-1; a claim with a different uid never sees it, and
    # tampering the record's uid after staging is caught at prepare
    with driver._lock:
        driver._incoming_handoffs["uid-1"]["uid"] = "uid-EVIL"
    resp = prepare(driver, "uid-1", name="c1")
    assert "handoff record is for claim uid" in resp.claims["uid-1"].error
    driver.stop()


def test_round_trip_migration_retires_source_handoff(host, apiserver):
    """A claim migrating BACK to its source retires the stale handoff
    record in the same group commit as the new prepare."""
    _, cfg = host
    driver, _fsm = make_stack(cfg, apiserver)
    apiserver.add_claim("ns", "c1", "uid-1", driver.driver_name,
                        [{"device": chip_name(0)}], generation=1)
    assert prepare(driver, "uid-1", name="c1").claims["uid-1"].error == ""
    assert unprepare(driver, "uid-1", name="c1").claims["uid-1"].error == ""
    assert driver.export_handoff("uid-1") is not None
    # ... migrates back:
    assert prepare(driver, "uid-1", name="c1").claims["uid-1"].error == ""
    assert driver.export_handoff("uid-1") is None
    driver2 = reload_driver(driver, cfg, apiserver)
    assert driver2.export_handoff("uid-1") is None   # durably retired
    assert driver2.prepared_claim_count() == 1
    driver2.stop()


# ------------------------------------------- 5. old→new checkpoint upgrade


def _seed_v0_checkpoint(cfg, apiserver):
    """Materialize a pre-upgrade (v0, bare-map) checkpoint + claim spec
    exactly as an old daemon would have left them."""
    driver = make_driver(cfg, apiserver)     # paths only; never started
    spec_path = driver._claim_spec_path("uid-old")
    entry = {
        "name": "c-old", "namespace": "ns", "spec_path": spec_path,
        "devices": [{"request_names": ["tpu"], "pool_name": "node-a",
                     "device_name": chip_name(0),
                     "cdi_device_ids": [driver._claim_cdi_id("uid-old")]}],
    }
    os.makedirs(os.path.dirname(driver.checkpoint_path), exist_ok=True)
    with open(driver.checkpoint_path, "w") as f:
        json.dump({"uid-old": entry}, f)
    os.makedirs(driver.cdi_dir, exist_ok=True)
    with open(spec_path, "w") as f:
        json.dump({"cdiVersion": "0.6.0", "devices": []}, f)
    return spec_path


def test_v0_checkpoint_upgrade_claims_survive(host, apiserver):
    _, cfg = host
    spec_path = _seed_v0_checkpoint(cfg, apiserver)
    driver = make_driver(cfg, apiserver)     # the UPGRADED daemon boots
    assert driver.prepared_claim_count() == 1
    assert driver.orphan_specs_removed == 0  # the spec has an owner
    assert os.path.exists(spec_path)
    # prepared claims are restored BEFORE any kubelet traffic: the echo
    # path answers without one apiserver round-trip
    before = len(apiserver.requests)
    resp = prepare(driver, "uid-old", name="c-old")
    assert resp.claims["uid-old"].error == ""
    assert [d.device_name for d in resp.claims["uid-old"].devices] \
        == [chip_name(0)]
    assert not any("/resourceclaims/" in path
                   for _, path in apiserver.requests[before:])
    # the next commit rewrites the file at the CURRENT schema version
    assert unprepare(driver, "uid-old",
                     name="c-old").claims["uid-old"].error == ""
    with open(driver.checkpoint_path) as f:
        on_disk = json.load(f)
    assert on_disk["version"] == CHECKPOINT_VERSION
    assert on_disk["claims"] == {}
    assert "uid-old" in on_disk["handoffs"]  # v1 feature, post-upgrade
    driver.stop()


def test_current_schema_round_trips(host, apiserver):
    _, cfg = host
    driver, _fsm = make_stack(cfg, apiserver)
    for i, uid in enumerate(["uid-a", "uid-b"]):
        apiserver.add_claim("ns", uid, uid, driver.driver_name,
                            [{"device": chip_name(i)}], generation=2)
        assert prepare(driver, uid).claims[uid].error == ""
    driver2 = reload_driver(driver, cfg, apiserver)
    assert driver2.prepared_claim_count() == 2
    for i, uid in enumerate(["uid-a", "uid-b"]):
        entry = driver2._checkpoint[uid]
        assert entry["device_raws"] == [bdf(i)]
        assert entry["generation"] == 2
    driver2.stop()


def test_future_version_checkpoint_refuses_to_load(host, apiserver):
    _, cfg = host
    probe = make_driver(cfg, apiserver)      # resolves paths
    path = probe.checkpoint_path
    os.makedirs(os.path.dirname(path), exist_ok=True)
    future = {"version": CHECKPOINT_VERSION + 1,
              "claims": {"uid-x": {"spec_path": "/nope", "devices": [],
                                   "from_the_future": True}}}
    with open(path, "w") as f:
        json.dump(future, f)
    with pytest.raises(CheckpointVersionError):
        make_driver(cfg, apiserver)
    # refusing means NOT corrupting: the file is byte-identical after
    with open(path) as f:
        assert json.load(f) == future
    # malformed version fields refuse too (never guessed at)
    with open(path, "w") as f:
        json.dump({"version": "banana"}, f)
    with pytest.raises(CheckpointVersionError):
        make_driver(cfg, apiserver)


def test_orphan_spec_sweep_on_startup(host, apiserver):
    """Satellite: a crash between spec write and checkpoint commit leaks
    a claim spec no checkpoint entry owns — swept (and counted) at the
    next startup; foreign files in the CDI dir are untouched."""
    _, cfg = host
    probe = make_driver(cfg, apiserver)
    os.makedirs(probe.cdi_dir, exist_ok=True)
    stray = probe._claim_spec_path("uid-stray")
    with open(stray, "w") as f:
        json.dump({"cdiVersion": "0.6.0"}, f)
    foreign = os.path.join(probe.cdi_dir, "unrelated.json")
    with open(foreign, "w") as f:
        f.write("{}")
    driver = make_driver(cfg, apiserver)
    assert driver.orphan_specs_removed == 1
    assert not os.path.exists(stray)
    assert os.path.exists(foreign)
    assert driver.checkpoint_stats()["orphan_specs_removed"] == 1


def test_restart_replays_claim_marks_into_fresh_fsm(host, apiserver):
    """A daemon restart builds a FRESH FSM; attach_lifecycle must replay
    the checkpoint's claim marks into it, or a post-restart hot-unplug
    of an allocated device would orphan nothing."""
    _, cfg = host
    driver, _fsm = make_stack(cfg, apiserver)
    apiserver.add_claim("ns", "c1", "uid-1", driver.driver_name,
                        [{"device": chip_name(0)}])
    assert prepare(driver, "uid-1", name="c1").claims["uid-1"].error == ""
    # full restart: fresh driver AND fresh FSM (in production both die)
    driver2 = reload_driver(driver, cfg, apiserver)
    fsm2 = DeviceLifecycle(
        serial_reader=lambda raw: read_serial(cfg.pci_base_path, raw))
    driver2.attach_lifecycle(fsm2)
    sync_fsm(fsm2, cfg)
    assert fsm2.state_of(bdf(0)) == ALLOCATED     # marks replayed
    fsm2.note_fs_event(bdf(0), False)
    assert driver2.orphaned_claims() == ["uid-1"]
    assert fsm2.stats()["claims_orphaned_total"] == 1
    driver2.stop()


def test_unplug_while_daemon_down_orphans_at_startup_sync(host, apiserver):
    """The chip is pulled while the daemon is down: the first inventory
    sync of the new incarnation discovers the gap and orphans the
    restored claim marks."""
    h, cfg = host
    driver, _fsm = make_stack(cfg, apiserver)
    apiserver.add_claim("ns", "c1", "uid-1", driver.driver_name,
                        [{"device": chip_name(0)}])
    assert prepare(driver, "uid-1", name="c1").claims["uid-1"].error == ""
    driver.stop()                                  # daemon goes down
    shutil.rmtree(os.path.join(h.pci, bdf(0)))     # chip pulled meanwhile
    h.remove_vfio_group("11")
    driver2 = make_driver(cfg, apiserver)          # daemon comes back
    fsm2 = DeviceLifecycle(
        serial_reader=lambda raw: read_serial(cfg.pci_base_path, raw))
    driver2.attach_lifecycle(fsm2)
    sync_fsm(fsm2, cfg)                            # sees only 3 chips
    assert driver2.orphaned_claims() == ["uid-1"]
    st = fsm2.stats()
    assert st["claims_orphaned_total"] == 1
    assert st["surprise_removals"][0]["device"] == bdf(0)
    driver2.stop()


def test_vfio_flap_with_sysfs_present_is_health_not_unplug(host, apiserver):
    """Corroboration: a /dev/vfio node flap while the chip is still
    enumerated in sysfs is a recoverable HEALTH event (the health plane
    prunes/restores it) — never a hot-unplug, never an orphaned claim.
    This is the contract verify-drive and the chaos flap suite pin."""
    h, cfg = host
    driver = make_driver(cfg, apiserver)
    fsm = DeviceLifecycle(
        serial_reader=lambda raw: read_serial(cfg.pci_base_path, raw),
        presence_reader=lambda raw: os.path.isdir(
            os.path.join(h.pci, raw)))
    driver.attach_lifecycle(fsm)
    sync_fsm(fsm, cfg)
    apiserver.add_claim("ns", "c1", "uid-1", driver.driver_name,
                        [{"device": chip_name(0)}])
    assert prepare(driver, "uid-1", name="c1").claims["uid-1"].error == ""
    # vfio node lost; sysfs dir still there -> NOT gone
    h.remove_vfio_group("11")
    fsm.note_fs_event(bdf(0), False)
    assert fsm.state_of(bdf(0)) == ALLOCATED
    assert fsm.stats()["claims_orphaned_total"] == 0
    assert driver.orphaned_claims() == []
    assert chip_name(0) in driver._by_name
    # the same holds for the sync path (inventory drops the unbound chip
    # but sysfs still enumerates it): demoted, not orphaned
    fsm.note_allocated(bdf(1), "uid-x")
    fsm.sync_inventory({b: None for b in (bdf(0), bdf(2), bdf(3))})
    assert fsm.state_of(bdf(1)) == ALLOCATED    # claims pin it
    assert fsm.stats()["claims_orphaned_total"] == 0
    # sysfs dir REMOVED too -> now it is a hot-unplug
    shutil.rmtree(os.path.join(h.pci, bdf(0)))
    fsm.note_fs_event(bdf(0), False)
    assert fsm.state_of(bdf(0)) == GONE
    assert driver.orphaned_claims() == ["uid-1"]
    driver.stop()


# --------------------------------------------------- FSM unit invariants


def test_fsm_transition_table_and_counters():
    fsm = DeviceLifecycle()
    fsm.sync_inventory({"d0": "s0"})
    assert fsm.state_of("d0") == BOUND
    t = fsm.stats()["transitions"]
    assert t == {"absent->present": 1, "present->bound": 1}
    # invalid transition: counted, state unchanged, never raises
    fsm.note_released("d0", "no-claim")      # bound, nothing to release
    fsm._records["d0"].state = BOUND
    assert not fsm._transition_locked(fsm._records["d0"], GONE) or True
    fsm.note_fs_event("unknown-device", False)   # untracked: ignored
    assert fsm.stats()["devices"] == 1


def test_fsm_detach_cycle():
    fsm = DeviceLifecycle()
    fsm.sync_inventory({"d0": "s0"})
    fsm.note_allocated("d0", "u1")
    fsm.note_allocated("d0", "u2")           # two claims share the device
    assert fsm.state_of("d0") == ALLOCATED
    fsm.note_detaching("d0", "u1")
    assert fsm.state_of("d0") == DETACHING
    fsm.note_released("d0", "u1")
    assert fsm.state_of("d0") == DETACHING   # u2 still holds it
    fsm.note_released("d0", "u2")
    assert fsm.state_of("d0") == BOUND


def test_fsm_lockfree_alloc_queue_drains_on_sync():
    """The classic Allocate path's C-atomic queue marks devices
    allocated on the next writer-side event; with no tracked claim the
    next sync demotes them back to bound (grants are unobservable)."""
    fsm = DeviceLifecycle()
    fsm.sync_inventory({"d0": None, "d1": None})
    fsm.note_allocation_event(["d0"])        # lock-free producer
    assert fsm.state_of("d0") == BOUND       # not drained yet (stats is
    assert "allocated" not in fsm.stats()["states"]  # lock-free too)
    fsm.note_allocated("d1", "u1")           # any writer-side call drains
    assert fsm.state_of("d0") == ALLOCATED
    fsm.sync_inventory({"d0": None, "d1": None})
    assert fsm.state_of("d0") == BOUND       # anonymous grant demoted
    assert fsm.state_of("d1") == ALLOCATED   # claim-tracked: kept


def test_fsm_multi_device_removal_batches_gone_delivery():
    """A switch-level removal delivers ONE batched gone event — one
    epoch publish + one slice republish downstream, not one per chip."""
    fsm = DeviceLifecycle()
    fsm.sync_inventory({"d0": None, "d1": None, "d2": None})
    batches = []
    fsm.on_devices_gone = lambda events: batches.append(sorted(events))
    fsm.sync_inventory({})
    assert batches == [[("d0", []), ("d1", []), ("d2", [])]]


def test_fsm_new_claim_during_detach_is_tracked():
    """A claim prepared while another claim's detach is in flight must
    be tracked, or a later hot-unplug would fail to orphan it."""
    fsm = DeviceLifecycle()
    fsm.sync_inventory({"d0": None})
    fsm.note_allocated("d0", "A")
    fsm.note_allocated("d0", "B")
    fsm.note_detaching("d0", "A")
    fsm.note_released("d0", "A")
    assert fsm.state_of("d0") == DETACHING      # B still holds the device
    fsm.note_allocated("d0", "C")               # new claim mid-detach
    assert fsm.state_of("d0") == ALLOCATED
    gone = []
    fsm.on_devices_gone = lambda ev: gone.extend(ev)
    fsm.note_fs_event("d0", False)
    assert gone == [("d0", ["B", "C"])]


def test_fsm_unbind_rebind_cycle_keeps_device_usable():
    """Administrative vfio unbind demotes to PRESENT; a later rebind
    promotes back to BOUND so new claim marks are accepted again."""
    fsm = DeviceLifecycle(presence_reader=lambda raw: True)
    fsm.sync_inventory({"d0": None})
    fsm.sync_inventory({})              # unbound, still enumerated
    assert fsm.state_of("d0") == PRESENT
    assert fsm.stats()["claims_orphaned_total"] == 0
    fsm.sync_inventory({"d0": None})    # rebound
    assert fsm.state_of("d0") == BOUND
    fsm.note_allocated("d0", "u1")
    assert fsm.state_of("d0") == ALLOCATED


def test_fsm_gone_before_admission_and_absent_sync():
    fsm = DeviceLifecycle()
    fsm.sync_inventory({"d0": "s0", "d1": "s1"})
    fsm.note_allocated("d1", "u1")
    gone_events = []
    fsm.on_devices_gone = lambda events: gone_events.extend(events)
    # d1 absent from the next sysfs truth: gone + orphaned via callback
    fsm.sync_inventory({"d0": "s0"})
    assert fsm.state_of("d1") == GONE
    assert gone_events == [("d1", ["u1"])]
    assert fsm.stats()["claims_orphaned_total"] == 1
    # returns with the same serial: readmitted quietly
    fsm.sync_inventory({"d0": "s0", "d1": "s1"})
    assert fsm.state_of("d1") == BOUND
    assert fsm.stats()["identity_swaps_total"] == 0
    # returns (after another loss) with a different serial: swap. The
    # second loss fires the gone hook too — with NO orphans (the driver
    # still drops the device from its slice)
    fsm.sync_inventory({"d0": "s0"})
    assert gone_events[-1] == ("d1", [])
    fsm.sync_inventory({"d0": "s0", "d1": "s1-NEW"})
    assert fsm.state_of("d1") == BOUND
    assert fsm.stats()["identity_swaps_total"] == 1
    assert fsm.state_of("never-seen") == ABSENT
