"""Grand integration: every feature composed against ONE real daemon.

Passthrough + mdev + logical partitions + CDI + labeler feature file +
metrics + incremental rediscovery + drain + clean shutdown, driven through
the actual `python -m tpu_device_plugin` process the DaemonSet runs — the
closest this repo gets to a cluster e2e without a kubelet.
"""

import json
import os
import signal
import subprocess
import sys
import time
import urllib.request

import grpc
import pytest

from tests.fakehost import FakeChip, FakeHost, FakeKubelet
from tests.test_dra import FakeApiServer
from tpu_device_plugin import kubeletapi as api
from tpu_device_plugin.config import Config
from tpu_device_plugin.dra import slice_device_name
from tpu_device_plugin.kubeletapi import draapi, drapb, pb

PORT = 18099


def _get(path):
    with urllib.request.urlopen(f"http://127.0.0.1:{PORT}{path}",
                                timeout=2) as r:
        return r.read().decode()


def _wait(pred, timeout=10):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            if pred():
                return True
        except (OSError, KeyError, IndexError, StopIteration):
            pass
        time.sleep(0.1)
    return False


def _stub(cfg, sock_name):
    sock = os.path.join(cfg.device_plugin_path, sock_name)
    ch = grpc.insecure_channel(f"unix://{sock}")
    return ch, api.DevicePluginStub(ch)


def test_everything_composes(short_root, tmp_path):
    host = FakeHost(short_root)
    # two vfio-bound v4 chips (passthrough), one accel-owned v4 chip
    # (per-core logical partitions), one mdev on a vfio parent
    host.add_chip(FakeChip("0000:00:04.0", iommu_group="11", numa_node=0))
    host.add_chip(FakeChip("0000:00:05.0", iommu_group="12", numa_node=0))
    host.add_chip(FakeChip("0000:00:06.0", iommu_group="13",
                           driver="google-tpu", accel_index=0))
    host.add_mdev("uuid-m", "TPU vhalf", "0000:00:04.0", iommu_group="21")
    pc = tmp_path / "partitions.json"
    pc.write_text(json.dumps({"per_core": True}))
    ff = str(tmp_path / "features.d" / "tpu")
    cfg = Config().with_root(host.root)
    os.makedirs(cfg.device_plugin_path, exist_ok=True)
    kubelet = FakeKubelet(cfg.kubelet_socket)
    apiserver = FakeApiServer()
    proc = subprocess.Popen(
        [sys.executable, "-m", "tpu_device_plugin", "--root", host.root,
         "--partition-config", str(pc),
         "--cdi-spec-dir", str(tmp_path / "cdi"),
         "--feature-file", ff,
         "--rediscovery-seconds", "0.5",
         "--status-port", str(PORT), "--status-host", "127.0.0.1",
         "--dra", "--node-name", "int-node", "--api-server", apiserver.url,
         "--log-json"],
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
    try:
        # 1. three resources register: v4, v4-core, TPU_vhalf
        assert kubelet.wait_for(3, timeout=20)
        assert sorted(kubelet.resource_names) == [
            "cloud-tpus.google.com/TPU_vhalf",
            "cloud-tpus.google.com/v4",
            "cloud-tpus.google.com/v4-core",
        ]

        # 2. labeler feature file reflects the whole inventory
        assert _wait(lambda: os.path.exists(ff))
        facts = dict(l.split("=", 1) for l in open(ff).read().splitlines())
        assert facts["cloud-tpus.google.com/v4.chips"] == "2"
        assert facts["cloud-tpus.google.com/vtpu.TPU_vhalf"] == "1"
        assert facts["cloud-tpus.google.com/vtpu.v4-core"] == "2"

        # 3. passthrough Allocate: CDI names + classic specs + env
        ch, stub = _stub(cfg, "tpukubevirt-v4.sock")
        with ch:
            resp = stub.Allocate(pb.AllocateRequest(container_requests=[
                pb.ContainerAllocateRequest(devices_ids=["0000:00:05.0"])]),
                timeout=5)
            c = resp.container_responses[0]
            assert [d.container_path for d in c.devices] == \
                ["/dev/vfio/vfio", "/dev/vfio/12"]
            assert [x.name for x in c.cdi_devices] == \
                ["cloud-tpus.google.com/tpu=0000:00:05.0"]
            assert c.envs["PCI_RESOURCE_CLOUD_TPUS_GOOGLE_COM_V4"] == \
                "0000:00:05.0"

        # 4. mdev + logical allocations through their own plugins
        ch, stub = _stub(cfg, "tpukubevirt-vtpu-TPU_vhalf.sock")
        with ch:
            resp = stub.Allocate(pb.AllocateRequest(container_requests=[
                pb.ContainerAllocateRequest(devices_ids=["uuid-m"])]),
                timeout=5)
            assert [d.container_path for d in
                    resp.container_responses[0].devices] == \
                ["/dev/vfio/vfio", "/dev/vfio/21"]
        ch, stub = _stub(cfg, "tpukubevirt-vtpu-v4-core.sock")
        with ch:
            resp = stub.Allocate(pb.AllocateRequest(container_requests=[
                pb.ContainerAllocateRequest(
                    devices_ids=["0000:00:06.0-core0"])]), timeout=5)
            assert [d.container_path for d in
                    resp.container_responses[0].devices] == ["/dev/accel0"]

        # 5. observability: counters + recent allocations
        metrics = _get("/metrics")
        assert ('tpu_plugin_allocations_total'
                '{resource="cloud-tpus.google.com/v4"} 1') in metrics
        status = json.loads(_get("/status"))
        v4 = next(p for p in status["plugins"]
                  if p["resource"].endswith("/v4"))
        assert v4["recent_allocations"][0]["devices"] == [["0000:00:05.0"]]

        # 5b. DRA composes with everything above: slice published with the
        # full inventory, claims prepare over the served dra.sock, and the
        # status surface reports it
        assert _wait(lambda: apiserver.slices)
        slice_obj = next(iter(apiserver.slices.values()))
        slice_devs = {d["name"] for d in slice_obj["spec"]["devices"]}
        assert slice_device_name("0000:00:05.0") in slice_devs
        assert slice_device_name("uuid-m") in slice_devs
        apiserver.add_claim("ns1", "c1", "uid-i1", "cloud-tpus.google.com",
                            [{"device": slice_device_name("0000:00:05.0")}])
        dra_sock = os.path.join(cfg.dra_plugins_path,
                                "cloud-tpus.google.com/dra.sock")
        with grpc.insecure_channel(f"unix://{dra_sock}") as dch:
            dresp = draapi.DraPluginStub(dch).NodePrepareResources(
                drapb.NodePrepareResourcesRequest(claims=[
                    drapb.Claim(namespace="ns1", name="c1", uid="uid-i1")]),
                timeout=5)
            assert dresp.claims["uid-i1"].error == ""
        assert "tpu_plugin_dra_prepared_claims 1" in _get("/metrics")
        assert json.loads(_get("/status"))["dra"]["serving"] is True

        # 6. incremental rediscovery: hotplug a v5e chip; ONLY v5e registers
        host.add_chip(FakeChip("0000:01:00.0", device_id="0063",
                               iommu_group="31"))
        assert kubelet.wait_for(4, timeout=15)
        names = kubelet.resource_names
        assert names.count("cloud-tpus.google.com/v4") == 1
        assert names.count("cloud-tpus.google.com/v5e") == 1
        # labeler republished with the new chip
        assert _wait(lambda: "v5e.chips=1" in open(ff).read())
        # DRA slice republished too: new device present, pool generation
        # bumped so the scheduler can tell stale allocations from current
        assert _wait(lambda: slice_device_name("0000:01:00.0") in {
            d["name"]
            for s in apiserver.slices.values()
            for d in s["spec"]["devices"]})
        assert next(iter(apiserver.slices.values()))["spec"]["pool"][
            "generation"] >= 2

        # 7. drain -> every device on every plugin Unhealthy; undrain heals
        proc.send_signal(signal.SIGUSR1)
        assert _wait(lambda: json.loads(_get("/status"))["draining"] and all(
            h == "Unhealthy"
            for p in json.loads(_get("/status"))["plugins"]
            for h in p["devices"].values()))
        proc.send_signal(signal.SIGUSR2)
        assert _wait(lambda: not json.loads(_get("/status"))["draining"] and
                     all(h == "Healthy"
                         for p in json.loads(_get("/status"))["plugins"]
                         for h in p["devices"].values()))

        # 8. clean shutdown: exit 0, sockets gone, JSON logs parse
        proc.send_signal(signal.SIGTERM)
        out, _ = proc.communicate(timeout=15)
        assert proc.returncode == 0, out[-500:]
        assert not any(n.endswith(".sock") and n != "kubelet.sock"
                       for n in os.listdir(cfg.device_plugin_path))
        assert not os.path.exists(dra_sock)
        assert not os.listdir(cfg.dra_registry_path)
        # the slice deliberately SURVIVES shutdown (a DaemonSet restart
        # must not churn scheduler state); only explicit withdraw deletes
        for line in out.splitlines():
            if line.strip():
                json.loads(line)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.communicate()
        kubelet.stop()
        apiserver.stop()
