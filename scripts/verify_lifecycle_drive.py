"""End-to-end drive of the lifecycle survivability layer (PR 7).

Real daemon (cli.main subprocess) with --dra + fast rediscovery against a
fake host; driven as the kubelet would:
  1. prepare a DRA claim over dra.sock (real gRPC)
  2. hot-unplug the chip (sysfs dir + vfio node removed)
  3. assert: claim orphaned on /status, device leaves the ResourceSlice,
     lifecycle counters move, claims_orphaned_total on /metrics
  4. replug the SAME chip -> rediscovery readmits, slice back to 4
"""
import json
import os
import shutil
import subprocess
import sys
import tempfile
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "tests"))

import grpc  # noqa: E402
from fakehost import FakeChip, FakeHost  # noqa: E402
from kubelet_sim import DeviceManagerSim  # noqa: E402
from test_dra import FakeApiServer  # noqa: E402
from tpu_device_plugin.kubeletapi import draapi, drapb  # noqa: E402

root = tempfile.mkdtemp(prefix="vfylc-", dir="/tmp")
fh = FakeHost(root)
for i in range(4):
    fh.add_chip(FakeChip(f"0000:00:{4 + i:02x}.0", device_id="0063",
                         iommu_group=str(10 + i), numa_node=i // 2,
                         serial=f"sn-{i}"))
victim_bdf = "0000:00:04.0"
victim_sysfs = os.path.join(root, "sys/bus/pci/devices", victim_bdf)
victim_backup = os.path.join(root, "victim-backup")
victim_vfio = os.path.join(root, "dev/vfio/10")

os.makedirs(os.path.join(root, "device-plugins"), exist_ok=True)
sim = DeviceManagerSim(os.path.join(root, "device-plugins"))
api = FakeApiServer()
port = 18161
env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu",
           NODE_NAME="node-a")
proc = subprocess.Popen(
    [sys.executable, "-m", "tpu_device_plugin", "--root", root,
     "--dra", "--api-server", api.url, "--status-port", str(port),
     "--health-poll-seconds", "0.3", "--rediscovery-seconds", "0.5", "-v"],
    env=env, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)


def status():
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/status", timeout=2) as r:
        return json.load(r)


def metrics():
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=2) as r:
        return r.read().decode()


def wait_for(pred, what, timeout=30):
    dl = time.time() + timeout
    while time.time() < dl:
        try:
            if pred():
                print(f"OK: {what}")
                return
        except Exception:
            pass
        time.sleep(0.25)
    raise SystemExit(f"FAIL: timeout waiting for {what}")


def slice_names():
    obj = next(iter(api.slices.values()))
    return {d["name"] for d in obj["spec"]["devices"]}


try:
    wait_for(lambda: status(), "daemon up")
    wait_for(lambda: api.slices and len(slice_names()) == 4,
             "ResourceSlice has 4 devices")
    wait_for(lambda: status().get("lifecycle", {}).get("states", {})
             .get("bound") == 4, "lifecycle FSM: 4 devices bound")

    # 1. prepare a claim against the victim over the real DRA socket
    victim_name = "d0000-00-04-0"
    api.add_claim("ns", "vm1", "uid-vm1", "cloud-tpus.google.com",
                  [{"device": victim_name}], generation=5)
    dra_sock = os.path.join(root, "plugins/cloud-tpus.google.com/dra.sock")
    with grpc.insecure_channel(f"unix://{dra_sock}") as ch:
        stub = draapi.DraPluginStub(ch)
        resp = stub.NodePrepareResources(
            drapb.NodePrepareResourcesRequest(claims=[
                drapb.Claim(namespace="ns", name="vm1", uid="uid-vm1")]),
            timeout=10)
    assert resp.claims["uid-vm1"].error == "", resp.claims["uid-vm1"].error
    print("OK: DRA claim prepared over dra.sock")
    wait_for(lambda: status()["lifecycle"]["states"].get("allocated") == 1,
             "FSM: victim allocated")

    # 2. hot-unplug: sysfs dir AND vfio node vanish
    shutil.move(victim_sysfs, victim_backup)
    os.unlink(victim_vfio)

    # 3. orphan + slice drop + counters
    wait_for(lambda: status()["dra"]["orphaned_claims"] == ["uid-vm1"],
             "claim orphaned on /status")
    wait_for(lambda: victim_name not in slice_names()
             and len(slice_names()) == 3, "slice devices -> 3 (departed)")
    wait_for(lambda: status()["dra"]["departed_devices"] == [victim_bdf],
             "departed device listed")
    s = status()["lifecycle"]
    assert s["claims_orphaned_total"] == 1, s
    assert s["transitions"].get("allocated->gone") == 1, s["transitions"]
    assert s["surprise_removals"][0]["device"] == victim_bdf
    print("OK: lifecycle counters (orphaned=1, allocated->gone=1, "
          "surprise removal recorded)")
    m = metrics()
    assert "claims_orphaned_total 1" in m, "claims_orphaned_total not on /metrics"
    assert 'lifecycle_transitions_total{from="allocated",to="gone"} 1' in m
    print("OK: /metrics exposes claims_orphaned_total + "
          "lifecycle_transitions_total{from,to}")

    # 4. replug the same chip: rediscovery readmits, no identity swap
    shutil.move(victim_backup, victim_sysfs)
    with open(victim_vfio, "w"):
        pass
    wait_for(lambda: len(slice_names()) == 4, "slice devices -> 4 after replug")
    wait_for(lambda: status()["dra"]["departed_devices"] == [],
             "departed mark cleared after readmission")
    s = status()["lifecycle"]
    assert s["identity_swaps_total"] == 0, s
    assert s["transitions"].get("gone->replugged") == 1, s["transitions"]
    print("OK: replug readmitted (identity intact, gone->replugged counted)")
    print("LIFECYCLE DRIVE PASS")
finally:
    proc.terminate()
    try:
        proc.wait(timeout=10)
    except subprocess.TimeoutExpired:
        proc.kill()
    api.stop()
    sim.stop()
    shutil.rmtree(root, ignore_errors=True)
