"""Per-generation TPU datasheet peaks: the physics check for every perf claim.

Round 3 published a 289 TFLOP/s bf16 microbench from a chip whose own
`device_kind` said "TPU v5 lite" (peak ~197): the relay-noise-corrupted
timing sailed into BASELINE.md because nothing compared measurements against
what the silicon can do. This table is that comparison. Numbers are the
public Google Cloud TPU datasheet figures (peak dense bf16 TFLOP/s and HBM
bandwidth GB/s per chip); `check()` flags any measurement above
`SUSPECT_FACTOR` x peak as a timing artifact, and the validator refuses to
record such a run as ok (VERDICT r3 item 1).

The reference plugin has no analogue (it runs no compute); this serves the
repo's own north-star metric (BASELINE.md Target): guest-side perf numbers
must be physically honest before they are comparable.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

# A real chip can transiently clock-boost measurement noise a few percent
# above nominal; anything past this factor is a broken estimator, not a
# fast chip.
SUSPECT_FACTOR = 1.05


@dataclass(frozen=True)
class Peak:
    generation: str        # canonical short name: v2/v3/v4/v5e/v5p/v6e
    bf16_tflops: float     # peak dense bf16 TFLOP/s per chip
    hbm_gbps: float        # peak HBM bandwidth GB/s per chip


# Public datasheet values (cloud.google.com/tpu/docs/system-architecture):
# per-chip peak dense bf16 and HBM BW.
PEAKS = {
    "v2": Peak("v2", 45.0, 700.0),
    "v3": Peak("v3", 123.0, 900.0),
    "v4": Peak("v4", 275.0, 1228.0),
    "v5e": Peak("v5e", 197.0, 819.0),
    "v5p": Peak("v5p", 459.0, 2765.0),
    "v6e": Peak("v6e", 918.0, 1640.0),
}


def lookup(device_kind: str) -> Optional[Peak]:
    """Map a PJRT `device_kind` string to its datasheet peak.

    Observed kinds: "TPU v2".."TPU v4", "TPU v5 lite" (v5e), "TPU v5"/"TPU
    v5p" (v5p), "TPU v6 lite"/"TPU v6e" (Trillium). Unknown kinds (CPU,
    future generations) return None — no peak means no physics check, never
    a false veto.
    """
    kind = (device_kind or "").lower()
    if "tpu" not in kind:
        return None
    if "v6" in kind:
        return PEAKS["v6e"]
    if "v5" in kind:
        if "lite" in kind or "v5e" in kind:
            return PEAKS["v5e"]
        return PEAKS["v5p"]
    for gen in ("v4", "v3", "v2"):
        if gen in kind:
            return PEAKS[gen]
    return None


def check(device_kind: str, tflops: float = 0.0, gbps: float = 0.0):
    """Physics-check measurements against the chip's datasheet peak.

    Returns (peak or None, suspect: bool, reason: str). suspect=True means
    a measurement exceeded SUSPECT_FACTOR x peak — the number is a timing
    artifact and must not be recorded as a valid result.
    """
    peak = lookup(device_kind)
    if peak is None:
        return None, False, ""
    reasons = []
    if tflops > SUSPECT_FACTOR * peak.bf16_tflops:
        reasons.append(
            f"measured {tflops:.1f} TFLOP/s > {SUSPECT_FACTOR:g}x the "
            f"{peak.generation} datasheet peak {peak.bf16_tflops:g}")
    if gbps > SUSPECT_FACTOR * peak.hbm_gbps:
        reasons.append(
            f"measured {gbps:.1f} GB/s > {SUSPECT_FACTOR:g}x the "
            f"{peak.generation} datasheet HBM peak {peak.hbm_gbps:g}")
    return peak, bool(reasons), "; ".join(reasons)
