"""Unit tests for the shared backoff/circuit-breaker policy (resilience.py).

These pin the *distributional* contract (decorrelated jitter: every delay
in [base, cap], growth bounded by 3x the previous) with a seeded RNG and
the breaker's full state machine with a fake clock — no sleeping.
"""

import random

import pytest

from conftest import FakeClock
from tpu_device_plugin.resilience import (BackoffPolicy, CircuitBreaker,
                                          CircuitOpen)


# ------------------------------------------------------------- BackoffPolicy


def test_backoff_delays_within_bounds_and_deterministic():
    rng = random.Random(42)
    p = BackoffPolicy(base_s=1.0, cap_s=30.0, rng=rng)
    delays = [p.next_delay() for _ in range(50)]
    assert all(1.0 <= d <= 30.0 for d in delays)
    # decorrelated jitter: each delay is at most 3x its predecessor
    prev = 1.0
    for d in delays:
        assert d <= max(prev * 3.0, 1.0) + 1e-9
        prev = d
    # seeded: the schedule replays exactly
    p2 = BackoffPolicy(base_s=1.0, cap_s=30.0, rng=random.Random(42))
    assert [p2.next_delay() for _ in range(50)] == delays


def test_backoff_grows_under_sustained_failure():
    p = BackoffPolicy(base_s=1.0, cap_s=30.0, rng=random.Random(7))
    delays = [p.next_delay() for _ in range(30)]
    # by the tail of a long failure run, delays should be near the cap far
    # more often than near the base (the whole point of growth)
    assert max(delays[10:]) > 10.0


def test_backoff_reset_returns_to_base():
    p = BackoffPolicy(base_s=1.0, cap_s=30.0, rng=random.Random(7))
    for _ in range(10):
        p.next_delay()
    assert p.attempts == 10
    p.reset()
    assert p.attempts == 0
    assert p.total_attempts == 10          # lifetime counter survives
    assert p.next_delay() <= 3.0           # back to U(base, 3*base)


def test_backoff_rejects_bad_params():
    with pytest.raises(ValueError):
        BackoffPolicy(base_s=0)
    with pytest.raises(ValueError):
        BackoffPolicy(base_s=5.0, cap_s=1.0)


def test_backoff_snapshot_counts():
    p = BackoffPolicy(base_s=0.1, cap_s=1.0, rng=random.Random(1))
    p.next_delay()
    snap = p.snapshot()
    assert snap["attempts"] == 1
    assert snap["total_attempts"] == 1
    assert 0.1 <= snap["current_delay_s"] <= 1.0


# ------------------------------------------------------------ CircuitBreaker


def test_breaker_trips_after_threshold_and_half_opens():
    clock = FakeClock()
    b = CircuitBreaker(failure_threshold=3, reset_timeout_s=10.0, clock=clock)
    assert b.state == "closed"
    for _ in range(2):
        assert b.allow()
        b.record_failure()
    assert b.state == "closed"             # threshold not reached
    assert b.allow()
    b.record_failure()                     # third consecutive failure
    assert b.state == "open"
    assert b.trips == 1
    assert not b.allow()                   # fails fast while open
    clock.advance(10.0)
    assert b.allow()                       # cooldown elapsed: the ONE probe
    assert b.state == "half-open"
    assert not b.allow()                   # second caller is still rejected
    b.record_success()                     # probe succeeded
    assert b.state == "closed"
    assert b.allow()


def test_breaker_failed_probe_reopens_with_fresh_cooldown():
    clock = FakeClock()
    b = CircuitBreaker(failure_threshold=1, reset_timeout_s=5.0, clock=clock)
    b.record_failure()
    assert b.state == "open"
    clock.advance(5.0)
    assert b.allow()                       # half-open probe
    b.record_failure()                     # probe failed
    assert b.state == "open"
    assert b.trips == 2
    clock.advance(4.9)
    assert not b.allow()                   # cooldown restarted at the probe
    clock.advance(0.2)
    assert b.allow()


def test_breaker_success_resets_consecutive_count():
    b = CircuitBreaker(failure_threshold=3)
    b.record_failure()
    b.record_failure()
    b.record_success()                     # streak broken
    b.record_failure()
    b.record_failure()
    assert b.state == "closed"             # never 3 consecutive


def test_breaker_call_wrapper():
    clock = FakeClock()
    b = CircuitBreaker(failure_threshold=1, reset_timeout_s=5.0, clock=clock)

    def boom():
        raise RuntimeError("no")

    with pytest.raises(RuntimeError):
        b.call(boom)
    with pytest.raises(CircuitOpen):
        b.call(lambda: "never runs")
    assert b.rejected == 1
    clock.advance(5.0)
    assert b.call(lambda: "ok") == "ok"    # half-open probe succeeds
    assert b.state == "closed"


def test_breaker_snapshot_shape():
    b = CircuitBreaker(failure_threshold=2, name="t")
    b.record_failure()
    snap = b.snapshot()
    assert snap == {"state": "closed", "consecutive_failures": 1,
                    "trips": 0, "rejected": 0}
