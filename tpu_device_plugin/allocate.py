"""Allocate(): turn requested BDFs into VFIO DeviceSpecs + KubeVirt env vars.

TPU analogue of the reference's passthrough Allocate
(generic_device_plugin.go:352-444): expand each requested BDF to its whole
IOMMU group, re-validate live sysfs against the discovery-time snapshot
(TOCTOU guard, :388-397), emit `/dev/vfio/vfio` + `/dev/vfio/<group>` (plus
the iommufd trio when `/dev/iommu` exists, :692-716), and set the
`PCI_RESOURCE_...` env var KubeVirt's virt-launcher reads to pick the PCI
devices for the VMI (externalResourceProvider contract).
"""

from __future__ import annotations

import logging
import os
import threading
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

from . import lockdep
from .config import Config
from .kubeletapi import pb
from .naming import sanitize_name
from .readcount import WindowRegistry
from .registry import Registry, SharedDevice

log = logging.getLogger(__name__)


class AllocationError(Exception):
    """Request references devices this plugin cannot serve (unknown/invalid)."""


# --- plan-path sysfs accounting (shared machinery: readcount.py) -------------
# Same contract as discovery.count_reads: the attach-path perf-honesty guard
# and `bench.py --attach-burst` assert on sysfs access COUNTS (listdir/
# readlink/exists/attribute-read on the Allocate plan path), because counts —
# unlike wall clock on a shared CPU — are load-insensitive. Windowless calls
# cost one truthiness check.

_plan_registry = WindowRegistry()
_plan_note = _plan_registry.note


def count_plan_reads(confine_thread: bool = False):
    """Count this module's sysfs accesses inside the with-block (nests;
    `confine_thread=True` counts only the opening thread — concurrent
    plan() threads on the gRPC pool would inflate a cross-thread window,
    the same hazard discovery's stats gauge guards against)."""
    return _plan_registry.window(confine_thread)


class LiveAttrReader:
    """Kept-open-fd live reads of small sysfs attributes.

    pread(fd, …, 0) re-runs the attribute's sysfs show() on every call, so
    the read stays LIVE (TOCTOU-guard grade) at stat+fstat+pread cost
    instead of open+read+close. Staleness is detected two ways, because
    the plugin also runs over regular-file roots (tests, --root
    re-rooting) where an unlinked file's fd would otherwise keep serving
    old bytes forever: the PATH's (st_dev, st_ino) identity is compared
    against the cached fd's — catching unlink/replace on any filesystem,
    including ones that report st_nlink >= 1 for open unlinked files
    (9p/overlay, where the previous nlink==0 probe never fired) — and
    pread errors/empty reads catch sysfs inode invalidation. Either falls
    back to a fresh open, so a genuinely new device at the same path is
    still re-validated from scratch.
    get + fstat + pread + stale-path close happen under one lock: a close
    outside it could free the fd NUMBER for reuse by a concurrent open
    while another thread still preads it, silently reading an unrelated
    file.

    read() returns non-empty fresh bytes or None — an empty file is
    reported as None (and never cached), keeping the contract single-faced
    for callers that treat None as "attribute gone".
    """

    def __init__(self) -> None:
        self._fds: Dict[str, int] = {}
        self._lock = lockdep.instrument(
            "allocate.LiveAttrReader._lock", threading.Lock())

    def __del__(self, _close=os.close):
        # _close bound at def time: os.close may already be torn down when
        # a reader is collected at interpreter shutdown
        for fd in getattr(self, "_fds", {}).values():
            try:
                _close(fd)
            except OSError:
                pass

    def read(self, key: str, path: str) -> Optional[bytes]:
        """Fresh non-empty bytes of `path` (cached fd keyed by `key`);
        None if gone/unreadable/empty."""
        with self._lock:
            fd = self._fds.get(key)
            if fd is not None:
                try:
                    st_path = os.stat(path)
                    st_fd = os.fstat(fd)
                    if (st_path.st_dev, st_path.st_ino) \
                            == (st_fd.st_dev, st_fd.st_ino):
                        raw = os.pread(fd, 256, 0)
                        if raw:
                            return raw
                except OSError:
                    pass
                # stale fd (file unlinked/replaced, inode invalidated, or
                # content gone): drop it and reopen
                del self._fds[key]
                try:
                    os.close(fd)
                except OSError:
                    pass
        try:
            fd = os.open(path, os.O_RDONLY)
        except OSError:
            return None
        try:
            raw = os.pread(fd, 256, 0)
        except OSError:
            os.close(fd)
            return None
        if not raw:
            os.close(fd)   # empty attribute: report None, never cache
            return None
        with self._lock:
            prev = self._fds.get(key)
            if prev is None:
                self._fds[key] = fd
                fd = None   # ownership transferred to the cache
        if fd is not None:   # lost the race; another thread cached one
            os.close(fd)
        return raw


def live_mdev_type(reader: LiveAttrReader, cfg: Config, uuid: str) -> str:
    """Live mdev_type/name read (TOCTOU-grade, kept-fd) for Allocate-time
    validation; raises AllocationError when the mdev is gone. Shared by the
    classic vTPU server and the DRA prepare path so the two APIs can never
    validate the same partition differently (reference analogue:
    generic_vgpu_device_plugin.go:216-221)."""
    name_path = os.path.join(cfg.mdev_base_path, uuid, "mdev_type", "name")
    _plan_note(name_path)
    raw = reader.read(uuid, name_path)
    if raw is None:
        # failure path only: one diagnostic open to recover the errno the
        # operator needs (EACCES mount misconfig vs ENOENT gone)
        try:
            with open(name_path, "rb"):
                detail = "empty or unreadable"
        except OSError as exc:
            detail = str(exc)
        raise AllocationError(f"partition {uuid}: mdev vanished ({detail})")
    return raw.decode("ascii", "replace").strip().replace(" ", "_")


def supports_iommufd(cfg: Config) -> bool:
    """iommufd-capable host: /dev/iommu exists (reference :692-701)."""
    path = cfg.dev_path("dev/iommu")
    _plan_note(path)
    return os.path.exists(path)


def vfio_device_node(cfg: Config, bdf: str) -> Optional[str]:
    """`vfioN` cdev name from sysfs `<bdf>/vfio-dev/` (reference :702-716)."""
    vfio_dev_dir = os.path.join(cfg.pci_base_path, bdf, "vfio-dev")
    _plan_note(vfio_dev_dir)
    try:
        entries = sorted(os.listdir(vfio_dev_dir))
    except OSError:
        return None
    for entry in entries:
        if entry.startswith("vfio"):
            return entry
    return None


def discover_shared_devices(cfg: Config) -> List[SharedDevice]:
    """Scan shared-device classes (EGM analogue, reference :120-157).

    Each class entry lists its member chips in a `chip_devices` file
    (`gpu_devices` also accepted so Grace-Hopper-style EGM trees work) and has
    a matching /dev node. Shared devices are injected all-or-nothing.
    """
    out: List[SharedDevice] = []
    for class_dir in cfg.shared_device_classes:
        _plan_note(class_dir)
        try:
            entries = sorted(os.listdir(class_dir))
        except OSError:
            continue
        for name in entries:
            members: Optional[Tuple[str, ...]] = None
            for member_file in ("chip_devices", "gpu_devices"):
                path = os.path.join(class_dir, name, member_file)
                try:
                    with open(path, "r", encoding="ascii", errors="replace") as f:
                        members = tuple(l.strip() for l in f if l.strip())
                    break
                except OSError:
                    continue
            if not members:
                continue
            dev_path = cfg.dev_path("dev", name)
            if not os.path.exists(dev_path):
                log.warning("shared device %s has no %s; skipping", name, dev_path)
                continue
            out.append(SharedDevice(name=name, dev_path=dev_path, member_bdfs=members))
    return out


@dataclass
class AllocationPlan:
    device_specs: List[pb.DeviceSpec]
    envs: Dict[str, str]
    expanded_bdfs: List[str]
    # fully-qualified CDI names for the expanded devices, precomputed in the
    # group fragment (None when the planner predates the fragment, e.g. a
    # hand-built plan in tests); allocate_response falls back to computing
    # them per call
    cdi_names: Optional[List[str]] = None


class _GroupFragment:
    """Precompiled Allocate response fragment for ONE IOMMU group.

    Everything deterministic given (registry snapshot, group, iommufd
    state) is built once and concatenated per request: the member-BDF
    expansion order, the iommufd cdev DeviceSpecs (the per-member
    `vfio-dev/` listdirs are the dominant sysfs cost of a cold plan), and
    the members' CDI names. What is NOT in the fragment, by design: the
    per-member TOCTOU revalidation (group link + vendor), which stays a
    live read on every plan.

    Invalidation: health flaps drop the affected group's fragment through
    `AllocationPlanner.invalidate_fragments` (wired from the same PR-2
    dirty/delta plumbing that hints incremental rediscovery), and an
    iommufd-state flip misses naturally (the flag is part of the fragment).
    Blind spot: a vfio cdev renamed with NO membership change and NO
    health event serves the stale cdev name until a flap or rebuild —
    the same contract as incremental discovery (docs/perf.md).
    """

    __slots__ = ("iommufd", "member_bdfs", "iommufd_specs", "cdi_names")

    def __init__(self, iommufd: bool, member_bdfs: Tuple[str, ...],
                 iommufd_specs: Tuple[pb.DeviceSpec, ...],
                 cdi_names: Tuple[str, ...]):
        self.iommufd = iommufd
        self.member_bdfs = member_bdfs
        self.iommufd_specs = iommufd_specs
        self.cdi_names = cdi_names


class AllocationPlanner:
    """Per-plugin Allocate fast path.

    Plugin servers are rebuilt on every rediscovery signature change
    (lifecycle.py), so anything deterministic given (cfg, registry,
    resource) is precomputed once here: the KubeVirt env-var key, the
    leading /dev/vfio/vfio DeviceSpec, one /dev/vfio/<group> DeviceSpec
    template per IOMMU group, and each device's revalidation paths.

    What stays LIVE, by design: the TOCTOU guard still re-reads every
    allocated device's iommu_group link and vendor id from sysfs on every
    Allocate (reference behavior, generic_device_plugin.go:388-397) — for
    a multi-group request those reads are batched through one pass — and
    the iommufd probe re-stats /dev/iommu (:362,692-701). The vfio cdev
    names and the rest of the per-group response live in a precompiled
    _GroupFragment, invalidated on health flaps (the reference re-listed
    them per Allocate, :702-716). The shared-device (EGM-analogue) scan is
    cached for cfg.shared_scan_ttl_s (0 = the reference's
    rescan-every-Allocate behavior, :366,120-157).

    `allowed_bdfs` (fixed at construction) scopes every request to the
    owning plugin's devices: the reference resolves any BDF in its global
    map, so its v-something plugin would allocate another model's GPUs
    (generic_device_plugin.go:376-380) — here a cross-model BDF is an
    AllocationError. None = unscoped (vTPU parent expansion).
    """

    def __init__(
        self,
        cfg: Config,
        registry: Registry,
        resource_suffix: str,
        allowed_bdfs: Optional[frozenset] = None,
        cdi_enabled: Optional[bool] = None,
    ) -> None:
        self.cfg = cfg
        self.registry = registry
        self.resource_suffix = resource_suffix
        self.allowed_bdfs = allowed_bdfs
        self.cdi_enabled = (bool(cfg.cdi_spec_dir) if cdi_enabled is None
                            else cdi_enabled)
        self.env_key = f"{cfg.env_prefix}_{sanitize_name(resource_suffix)}"
        self._vfio_spec = pb.DeviceSpec(
            host_path=cfg.dev_path("dev/vfio/vfio"),
            container_path="/dev/vfio/vfio",
            permissions="mrw",
        )
        self._group_specs: Dict[str, pb.DeviceSpec] = {
            group: pb.DeviceSpec(
                host_path=cfg.dev_path("dev/vfio", group),
                container_path=f"/dev/vfio/{group}",
                permissions="mrw",
            )
            for group in registry.iommu_map
        }
        self._iommu_spec = pb.DeviceSpec(
            host_path=cfg.dev_path("dev/iommu"),
            container_path="/dev/iommu",
            permissions="mrw",
        )
        # bdf → (iommu_group symlink path, vendor attribute path)
        self._reval_paths: Dict[str, Tuple[str, str]] = {
            bdf: (os.path.join(cfg.pci_base_path, bdf, "iommu_group"),
                  os.path.join(cfg.pci_base_path, bdf, "vendor"))
            for bdf in registry.bdf_to_group
        }
        self._vendor_ok = frozenset(v.lower() for v in cfg.vendor_ids)
        # raw sysfs spellings accepted without the slow-path decode
        self._vendor_ok_raw = frozenset(
            s for v in self._vendor_ok
            for s in (v.encode("ascii"), b"0x" + v.encode("ascii")))
        # live <bdf>/vendor reads for the TOCTOU guard (see LiveAttrReader)
        self._vendor_reader = LiveAttrReader()
        self._shared_cache: Optional[List[SharedDevice]] = None
        self._shared_expires = 0.0
        self._iommufd_cache: Optional[bool] = None
        self._iommufd_expires = 0.0
        # precompiled per-group response fragments (see _GroupFragment);
        # guarded by their own lock — plan() runs on concurrent gRPC worker
        # threads while health listeners invalidate from hub threads
        self._fragments: Dict[str, _GroupFragment] = {}
        self._frag_lock = lockdep.instrument(
            "allocate.AllocationPlanner._frag_lock", threading.Lock())
        # bumped by every invalidation; a build that was in flight when an
        # invalidation landed must not store its (possibly pre-flap)
        # result — see _fragment
        self._frag_epoch = 0
        self.fragment_hits = 0
        self.fragment_misses = 0

    # ------------------------------------------------------ group fragments

    def invalidate_fragments(self, bdfs: Optional[Sequence[str]] = None) -> None:
        """Drop the cached fragments of the groups owning `bdfs` (all
        fragments when None). Wired from the health listeners so a flapped
        device's group is recompiled — cdev names re-listed — on its next
        plan, the same dirty plumbing that hints incremental rediscovery."""
        with self._frag_lock:
            self._frag_epoch += 1
            if bdfs is None:
                self._fragments.clear()
                return
            for bdf in bdfs:
                group = self.registry.bdf_to_group.get(bdf)
                if group is not None:
                    self._fragments.pop(group, None)

    def fragment_stats(self) -> Dict[str, int]:
        with self._frag_lock:
            return {"hits": self.fragment_hits,
                    "misses": self.fragment_misses,
                    "size": len(self._fragments)}

    def _fragment(self, group: str, iommufd: bool) -> _GroupFragment:
        with self._frag_lock:
            frag = self._fragments.get(group)
            if frag is not None and frag.iommufd == iommufd:
                self.fragment_hits += 1
                return frag
            self.fragment_misses += 1
            epoch = self._frag_epoch
        frag = self._build_fragment(group, iommufd)
        with self._frag_lock:
            # an invalidation that landed mid-build may have been aimed at
            # what this build just read (a flap racing the listdir): serve
            # the result but never cache it — the next plan recompiles
            if self._frag_epoch == epoch:
                self._fragments[group] = frag
        return frag

    def _build_fragment(self, group: str, iommufd: bool) -> _GroupFragment:
        from .cdi import cdi_device_name
        cfg = self.cfg
        members = tuple(d.bdf for d in self.registry.iommu_map.get(group, ()))
        iommufd_specs: List[pb.DeviceSpec] = []
        if iommufd:
            for bdf in members:
                node = vfio_device_node(cfg, bdf)
                if node is None:
                    # On an iommufd host every vfio-bound device has a cdev;
                    # an unreadable vfio-dev entry would boot the VM with an
                    # incomplete device set — fail fast like the reference
                    # (generic_device_plugin.go:702-716 errors the Allocate).
                    # Failures are never cached.
                    raise AllocationError(
                        f"device {bdf}: iommufd host but no vfio-dev cdev")
                iommufd_specs.append(pb.DeviceSpec(
                    host_path=cfg.dev_path("dev/vfio/devices", node),
                    container_path=f"/dev/vfio/devices/{node}",
                    permissions="mrw",
                ))
        return _GroupFragment(
            iommufd=iommufd,
            member_bdfs=members,
            iommufd_specs=tuple(iommufd_specs),
            cdi_names=tuple(cdi_device_name(cfg, bdf) for bdf in members))

    def _revalidate_live(self, bdf: str, expected_group: str) -> None:
        """TOCTOU guard (NEVER cached): live sysfs must still agree with the
        discovery snapshot — group link unchanged, vendor still a TPU."""
        paths = self._reval_paths.get(bdf)
        if paths is None:  # device outside this registry snapshot
            base = os.path.join(self.cfg.pci_base_path, bdf)
            paths = (os.path.join(base, "iommu_group"),
                     os.path.join(base, "vendor"))
        glink, vpath = paths
        _plan_note(glink)
        try:
            target = os.readlink(glink)
        except OSError:
            target = ""
        if target.rsplit("/", 1)[-1] != expected_group:
            live = target.rsplit("/", 1)[-1] or None
            raise AllocationError(
                f"device {bdf}: iommu group changed "
                f"({expected_group!r} -> {live!r})")
        _plan_note(vpath)
        raw = self._vendor_reader.read(bdf, vpath)
        if raw is not None and raw.strip().lower() in self._vendor_ok_raw:
            return
        # slow path only to produce the same diagnostic as before
        vendor = (raw.strip().lower().decode("ascii", "replace")
                  if raw is not None else None)
        if vendor is not None and vendor.startswith("0x"):
            vendor = vendor[2:]
        if vendor is None or vendor not in self._vendor_ok:
            raise AllocationError(f"device {bdf}: vendor {vendor!r} is not a TPU")

    def shared_devices(self) -> List[SharedDevice]:
        ttl = getattr(self.cfg, "shared_scan_ttl_s", 0.0)
        now = time.monotonic()
        if self._shared_cache is None or ttl <= 0 or now >= self._shared_expires:
            self._shared_cache = discover_shared_devices(self.cfg)
            self._shared_expires = now + ttl
        return self._shared_cache

    def _iommufd(self) -> bool:
        """supports_iommufd under the same TTL as the shared-device scan:
        /dev/iommu is boot-time host configuration, but ttl=0 (the
        reference behavior, :692-701 stats it per Allocate) keeps the
        per-RPC stat for operators who want it."""
        ttl = getattr(self.cfg, "shared_scan_ttl_s", 0.0)
        now = time.monotonic()
        if self._iommufd_cache is None or ttl <= 0 \
                or now >= self._iommufd_expires:
            self._iommufd_cache = supports_iommufd(self.cfg)
            self._iommufd_expires = now + ttl
        return self._iommufd_cache

    def plan(
        self,
        requested_bdfs: Sequence[str],
        shared_devices: Optional[Sequence[SharedDevice]] = None,
    ) -> AllocationPlan:
        """Build the DeviceSpec list + env map for one container request.

        DeviceSpec order matches the reference's: the shared /dev/vfio/vfio
        container node first, then one /dev/vfio/<group> per IOMMU group,
        then iommufd cdevs + /dev/iommu, then qualifying shared devices.

        The per-group expansion is fragment concatenation (_GroupFragment
        cache) plus ONE batched live-revalidation pass over every member of
        every requested group — the TOCTOU guard is never cached.
        """
        registry = self.registry
        iommufd = self._iommufd()
        if shared_devices is None:
            shared_devices = self.shared_devices()

        # dedup with a set (membership was an O(n^2) list probe across a
        # request's groups) while keeping the reference's spec ordering
        seen_groups: set = set()
        ordered_groups: List[str] = []
        fragments: List[_GroupFragment] = []
        revalidate: List[Tuple[str, str]] = []   # (bdf, group), all groups
        for bdf in requested_bdfs:
            group = registry.bdf_to_group.get(bdf)
            if group is None:
                raise AllocationError(
                    f"requested device {bdf} is not a known TPU")
            if self.allowed_bdfs is not None and bdf not in self.allowed_bdfs:
                raise AllocationError(
                    f"requested device {bdf} is not managed by resource "
                    f"{self.resource_suffix!r}")
            if group in seen_groups:
                continue
            seen_groups.add(group)
            ordered_groups.append(group)
            frag = self._fragment(group, iommufd)
            fragments.append(frag)
            revalidate.extend((m, group) for m in frag.member_bdfs)
        # one batched pass for the whole request (multi-group requests no
        # longer interleave revalidation with response assembly)
        for member, group in revalidate:
            self._revalidate_live(member, group)

        specs: List[pb.DeviceSpec] = [self._vfio_spec]
        expanded: List[str] = []
        cdi_names: List[str] = []
        iommufd_specs: List[pb.DeviceSpec] = []
        for group, frag in zip(ordered_groups, fragments):
            expanded.extend(frag.member_bdfs)
            cdi_names.extend(frag.cdi_names)
            iommufd_specs.extend(frag.iommufd_specs)
            specs.append(self._group_specs[group])
        specs.extend(iommufd_specs)
        if iommufd and ordered_groups:
            specs.append(self._iommu_spec)

        # Shared devices ride along iff every member chip is in this
        # allocation (all-or-nothing, reference :159-184).
        allocated = set(expanded)
        for shared in shared_devices:
            if shared.member_bdfs and set(shared.member_bdfs) <= allocated:
                specs.append(pb.DeviceSpec(
                    host_path=shared.dev_path,
                    container_path=f"/dev/{shared.name}",
                    permissions="mrw",
                ))
                log.info("allocation includes shared device %s (members %s)",
                         shared.name, ",".join(shared.member_bdfs))

        envs = {self.env_key: ",".join(expanded)}
        log.info("allocate %s: groups=%s devices=%s iommufd=%s cdi=%s",
                 self.resource_suffix, ordered_groups, expanded, iommufd,
                 self.cdi_enabled)
        return AllocationPlan(device_specs=specs, envs=envs,
                              expanded_bdfs=expanded, cdi_names=cdi_names)

    def allocate_response(self, request: pb.AllocateRequest) -> pb.AllocateResponse:
        """Full Allocate handler body: one ContainerAllocateResponse per
        container request in the AllocateRequest."""
        shared = self.shared_devices()
        resp = pb.AllocateResponse()
        for creq in request.container_requests:
            plan = self.plan(list(creq.devices_ids), shared)
            cresp = pb.ContainerAllocateResponse(
                envs=plan.envs, devices=plan.device_specs)
            if self.cdi_enabled:
                names = plan.cdi_names
                if names is None:
                    from .cdi import cdi_device_name
                    names = [cdi_device_name(self.cfg, bdf)
                             for bdf in plan.expanded_bdfs]
                cresp.cdi_devices.extend(
                    pb.CDIDevice(name=name) for name in names)
            resp.container_responses.append(cresp)
        return resp


def plan_allocation(
    cfg: Config,
    registry: Registry,
    resource_suffix: str,
    requested_bdfs: Sequence[str],
    shared_devices: Optional[Sequence[SharedDevice]] = None,
    allowed_bdfs: Optional[frozenset] = None,
) -> AllocationPlan:
    """One-shot form of AllocationPlanner.plan (tests, ad-hoc callers).

    Long-lived callers (the plugin servers) hold an AllocationPlanner so the
    per-(cfg, registry) precomputation is paid once, not per RPC.
    """
    planner = AllocationPlanner(cfg, registry, resource_suffix,
                                allowed_bdfs=allowed_bdfs)
    if shared_devices is None:
        shared_devices = discover_shared_devices(cfg)
    return planner.plan(requested_bdfs, shared_devices)


def allocate_response(
    cfg: Config,
    registry: Registry,
    resource_suffix: str,
    request: pb.AllocateRequest,
    cdi_enabled: Optional[bool] = None,
    allowed_bdfs: Optional[frozenset] = None,
) -> pb.AllocateResponse:
    """One-shot form of AllocationPlanner.allocate_response.

    `cdi_enabled=None` falls back to `bool(cfg.cdi_spec_dir)`; the plugin
    server passes an explicit value reflecting whether this resource's CDI
    spec file was actually written (unresolvable names are worse than none).
    """
    planner = AllocationPlanner(cfg, registry, resource_suffix,
                                allowed_bdfs=allowed_bdfs,
                                cdi_enabled=cdi_enabled)
    return planner.allocate_response(request)
