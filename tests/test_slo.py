"""SLO plane tests (tpu_device_plugin/slo.py, ISSUE 15).

Covers the objective math (bucket-exact bad counting at the snapped
threshold), the multi-window burn-rate computation under a synthetic
clock, the breach latch (transition counted + slo.breach flight event
carrying the exemplar trace), the /status + /metrics surfaces, the
crash-dump satellite (histogram snapshots + SLO state in the dumped
JSON, parsed back), config loading fail-loudness, and the LIVE
acceptance drill: an injected latency fault (the new faults kind
"delay") on the kubeapi path provably moves the publish_rtt burn-rate
gauge with an exemplar trace id that resolves on the fleet trace
query."""

import json
import os
import threading

import pytest

from tpu_device_plugin import faults, slo, trace


@pytest.fixture(autouse=True)
def clean_trace():
    trace.reset()
    yield
    trace.reset()
    faults.reset()


def _engine(clock, **kw):
    defaults = dict(threshold_ms=50.0, target=0.99,
                    fast_window_s=60.0, slow_window_s=300.0)
    defaults.update(kw)
    return slo.SLOEngine(
        [slo.Objective("att", "tdp_attach_wall_ms", **defaults)],
        now=lambda: clock[0])


# ------------------------------------------------------------ objective math


def test_bad_counting_snaps_to_the_next_bucket_bound():
    hist = trace.histogram("tdp_attach_wall_ms")
    for v in (1.0, 40.0, 49.0, 51.0, 20000.0):
        hist.observe(v)
    total, bad, bound = slo._counts(hist.snapshot(), 50.0)
    assert (total, bad, bound) == (5, 2, 50.0)     # 51ms + 20s are bad
    # a threshold between bounds snaps UP (45 -> the 50ms bucket)
    _total, bad2, bound2 = slo._counts(hist.snapshot(), 45.0)
    assert (bad2, bound2) == (2, 50.0)
    # beyond the last bound: only +Inf overflow is bad
    _total, bad3, bound3 = slo._counts(hist.snapshot(), 99999.0)
    assert bad3 == 1 and bound3 == float("inf")


def test_objective_validation_and_config_loading_fail_loud(tmp_path):
    with pytest.raises(slo.SLOConfigError):
        slo.Objective("x", "no_such_histogram", 50.0, 0.99).validate()
    with pytest.raises(slo.SLOConfigError):
        slo.Objective("x", "tdp_attach_wall_ms", 50.0, 1.5).validate()
    with pytest.raises(slo.SLOConfigError):
        slo.Objective("x", "tdp_attach_wall_ms", -1.0, 0.99).validate()
    with pytest.raises(slo.SLOConfigError):
        slo.load_objectives("not json at all {")
    with pytest.raises(slo.SLOConfigError):
        slo.load_objectives('[{"name": "a", "bogus_field": 1}]')
    with pytest.raises(slo.SLOConfigError):
        slo.load_objectives(json.dumps([
            {"name": "a", "histogram": "tdp_attach_wall_ms",
             "threshold_ms": 50.0, "target": 0.99},
            {"name": "a", "histogram": "tdp_kubeapi_rtt_ms",
             "threshold_ms": 50.0, "target": 0.99}]))   # duplicate name
    # a valid file loads
    path = tmp_path / "slo.json"
    path.write_text(json.dumps([
        {"name": "mine", "histogram": "tdp_kubeapi_rtt_ms",
         "threshold_ms": 100.0, "target": 0.999, "burn_fast": 10.0}]))
    objs = slo.load_objectives(str(path))
    assert objs[0].name == "mine" and objs[0].burn_fast == 10.0
    # every default objective validates against a registered histogram
    for obj in slo.default_objectives():
        obj.validate()


# ----------------------------------------------------------- burn + breach


def test_burn_rates_windows_and_breach_latch_with_synthetic_clock():
    clock = [1000.0]
    eng = _engine(clock)
    hist = trace.histogram("tdp_attach_wall_ms")
    for _ in range(100):
        hist.observe(1.0)
    eng.evaluate()                                  # baseline sample
    clock[0] += 30
    st = eng.evaluate()["att"]
    assert st["burn_rate_fast"] == 0.0 and not st["breached"]
    # 50 bad of 50 new observations: error rate 1.0 -> burn 100x
    for _ in range(50):
        hist.observe(500.0, exemplar="ab" * 16)
    st = eng.evaluate()["att"]
    assert st["burn_rate_fast"] == pytest.approx(100.0)
    assert st["burn_rate_slow"] == pytest.approx(100.0)
    assert st["breached"] is True
    assert st["exemplar"]["trace_id"] == "ab" * 16
    assert eng.snapshot()["breaches_total"] == 1
    # the breach is a flight-recorder event carrying the exemplar
    evs = trace.snapshot(op="slo.breach")
    assert evs and evs[0]["attrs"]["slo"] == "att"
    assert evs[0]["attrs"]["exemplar_trace"] == "ab" * 16
    # re-evaluating while burning does NOT re-count (latched)
    eng.evaluate()
    assert eng.snapshot()["breaches_total"] == 1
    # a fast-window dip alone does NOT unlatch: the slow window still
    # carries the incident (recovery latches via the slow window only)
    clock[0] += 120
    for _ in range(500):
        hist.observe(1.0)
    eng.evaluate()
    clock[0] += 59
    st = eng.evaluate()["att"]
    assert st["burn_rate_fast"] < 14.4
    assert st["burn_rate_slow"] >= 6.0
    assert st["breached"] is True            # still latched
    assert eng.snapshot()["recoveries_total"] == 0
    # cool: once the incident leaves the SLOW window too -> recovery
    clock[0] += 200
    st = eng.evaluate()["att"]
    assert st["burn_rate_slow"] < 6.0 and st["breached"] is False
    assert eng.snapshot()["recoveries_total"] == 1
    evs = trace.snapshot(op="slo.recovered")
    assert evs and evs[0]["attrs"]["slo"] == "att"
    # a SECOND incident counts a second breach
    for _ in range(200):
        hist.observe(500.0)
    st = eng.evaluate()["att"]
    assert st["breached"] and eng.snapshot()["breaches_total"] == 2


def test_restart_mid_breach_does_not_relatch_from_half_empty_window():
    """Satellite (ISSUE 16): a daemon restart mid-breach hands a FRESH
    engine a histogram carrying lifetime bad counts. The young engine
    must not instantly re-latch from that half-empty window — burn is
    computed from post-restart deltas only, and window_actual reports
    the engine's real (short) coverage. A truly continuing incident
    (new bad deltas) still latches."""
    clock = [1000.0]
    hist = trace.histogram("tdp_attach_wall_ms")
    for _ in range(100):
        hist.observe(1.0)
    for _ in range(100):
        hist.observe(500.0)        # the pre-restart incident: 50% bad
    eng = _engine(clock)           # "restarted": empty sample ring
    st = eng.evaluate()["att"]
    assert st["breached"] is False
    assert st["window_fast_actual_s"] == 0.0   # honest: no history yet
    clock[0] += 5
    st = eng.evaluate()["att"]
    # no post-restart traffic: the lifetime bad counts are NOT burn
    assert st["burn_rate_fast"] == 0.0 and st["burn_rate_slow"] == 0.0
    assert st["breached"] is False
    assert st["window_fast_actual_s"] == pytest.approx(5.0)
    assert eng.snapshot()["breaches_total"] == 0
    # the incident actually continuing (fresh bad deltas) re-latches
    for _ in range(50):
        hist.observe(500.0)
    clock[0] += 5
    st = eng.evaluate()["att"]
    assert st["breached"] is True


def test_latch_does_not_flap_under_oscillating_fault():
    """Hysteresis (ISSUE 16 acceptance): a fault oscillating at the
    fast-window cadence latches ONE breach and holds it — the slow
    window rides through the quiet half-periods, so breaches_total
    counts incidents, not oscillations."""
    clock = [1000.0]
    eng = _engine(clock)           # fast 60s / slow 300s
    hist = trace.histogram("tdp_attach_wall_ms")
    for _ in range(100):
        hist.observe(1.0)
    eng.evaluate()
    # 6 half-periods of 45s: bad burst, quiet, bad burst, quiet ...
    for period in range(6):
        clock[0] += 45
        if period % 2 == 0:
            for _ in range(30):
                hist.observe(500.0)
        else:
            for _ in range(30):
                hist.observe(1.0)
        st = eng.evaluate()["att"]
        if period >= 1:
            assert st["breached"] is True, period   # held, no flap
    snap = eng.snapshot()
    assert snap["breaches_total"] == 1
    assert snap["recoveries_total"] == 0


def test_subscribers_fire_on_breach_and_recovery_transitions():
    """subscribe(): one callback per latched transition, carrying the
    exemplar — the seam remediation.py rides."""
    clock = [1000.0]
    eng = _engine(clock)
    events = []
    eng.subscribe(lambda e: events.append(e))
    eng.subscribe(lambda e: (_ for _ in ()).throw(RuntimeError("bad")))
    hist = trace.histogram("tdp_attach_wall_ms")
    for _ in range(100):
        hist.observe(1.0)
    eng.evaluate()
    clock[0] += 30
    for _ in range(50):
        hist.observe(500.0, exemplar="cd" * 16)
    eng.evaluate()                 # breach (raising subscriber contained)
    assert [e["kind"] for e in events] == ["breach"]
    assert events[0]["slo"] == "att"
    assert events[0]["exemplar"]["trace_id"] == "cd" * 16
    # steady-state burning: no repeat events (latched)
    clock[0] += 10
    eng.evaluate()
    assert len(events) == 1
    # recovery: one "recovered" event once the slow window cools
    clock[0] += 120
    for _ in range(500):
        hist.observe(1.0)
    eng.evaluate()
    clock[0] += 400
    eng.evaluate()
    assert [e["kind"] for e in events] == ["breach", "recovered"]


def test_short_lived_engine_reports_actual_window_honestly():
    clock = [50.0]
    eng = _engine(clock)
    trace.histogram("tdp_attach_wall_ms").observe(1.0)
    eng.evaluate()
    clock[0] += 10                       # engine is 10s old, window 60s
    st = eng.evaluate()["att"]
    assert st["window_fast_actual_s"] == pytest.approx(10.0)


def test_budget_remaining_tracks_lifetime_error_budget():
    clock = [0.0]
    eng = _engine(clock, target=0.9)     # 10% budget
    hist = trace.histogram("tdp_attach_wall_ms")
    for _ in range(95):
        hist.observe(1.0)
    for _ in range(5):
        hist.observe(500.0)
    st = eng.evaluate()["att"]
    # 5% bad of a 10% budget: half the budget left
    assert st["budget_remaining"] == pytest.approx(0.5)


# ---------------------------------------------------------------- surfaces


class _StubManager:
    def __init__(self):
        self.running = threading.Event()
        self.plugins = []
        self.pending = []


def test_status_and_metrics_surfaces_with_exemplar_info():
    from tpu_device_plugin.status import StatusServer
    prev = slo.set_engine(slo.SLOEngine())
    server = StatusServer(_StubManager(), port=0)
    try:
        with trace.span("att.bad", histogram="tdp_attach_wall_ms"):
            tid = trace.current_context()["trace_id"]
            import time
            time.sleep(0.06)             # > the 50ms attach objective
        out = server.status()
        assert set(out["slo"]["objectives"]) == {
            "attach_wall", "prepare_wall", "publish_rtt",
            "watch_convergence"}
        rec = out["slo"]["objectives"]["attach_wall"]
        assert rec["bad_total"] == 1
        assert rec["exemplar"]["trace_id"] == tid
        text = server.metrics()
        assert ('tpu_plugin_slo_burn_rate{slo="attach_wall",'
                'window="fast"}') in text
        assert 'tpu_plugin_slo_bad_total{slo="attach_wall"} 1' in text
        assert (f'tpu_plugin_slo_exemplar_info{{slo="attach_wall",'
                f'trace_id="{tid}"}} 1') in text
        assert "tpu_plugin_slo_evals_total" in text
    finally:
        server._httpd.server_close()
        slo.set_engine(prev)


def test_crash_dump_carries_histograms_and_slo_state(tmp_path):
    """Satellite: the crash/SIGHUP dump includes histogram snapshots and
    the current SLO/burn state alongside the merged ring — parsed back
    from the dumped JSON."""
    engine = slo.SLOEngine()
    engine.attach_to_dumps()
    try:
        with trace.span("crash.attach", histogram="tdp_attach_wall_ms"):
            pass
        path = str(tmp_path / "crash-dump.json")
        assert trace.dump("unit-crash", path=path) == path
        with open(path) as f:
            payload = json.load(f)
        assert any(r["op"] == "crash.attach" for r in payload["spans"])
        hist = payload["histograms"]["tdp_attach_wall_ms"]
        assert hist["count"] == 1
        assert hist["buckets"][-1][1] == 1           # cumulative shape
        slo_state = payload["slo"]
        assert "attach_wall" in slo_state["objectives"]
        assert slo_state["objectives"]["attach_wall"]["target"] == 0.99
        assert slo_state["evals_total"] >= 1
    finally:
        trace.unregister_dump_extra("slo")


# ------------------------------------------------------- the live drill


def test_injected_latency_fault_moves_burn_rate_with_resolvable_exemplar(
        short_root):
    """ACCEPTANCE (live half): an armed kubeapi.request delay fault makes
    real publish RTTs breach the publish_rtt objective — the burn-rate
    gauge moves, a breach latches, and the exemplar trace id resolves to
    the offending request's spans on the fleet-trace query path."""
    from tests.test_dra import FakeApiServer
    from tpu_device_plugin.fleetplace import FleetFlight
    from tpu_device_plugin.kubeapi import ApiClient
    clock = [0.0]
    eng = slo.SLOEngine([slo.Objective(
        "publish_rtt", "tdp_kubeapi_rtt_ms", threshold_ms=100.0,
        target=0.99, fast_window_s=60.0, slow_window_s=300.0)],
        now=lambda: clock[0])
    api = FakeApiServer()
    try:
        client = ApiClient(api.url, token_path="/nonexistent")
        with trace.span("drill.request"):
            client.get_json("/api/v1/nodes/n1")      # fast: good sample
        eng.evaluate()                               # baseline
        clock[0] += 5
        before = eng.evaluate()["publish_rtt"]
        assert before["burn_rate_fast"] == 0.0
        with faults.injected("kubeapi.request", kind="delay", count=3,
                             delay_s=0.15):
            with trace.span("drill.slow-request"):
                tid = trace.current_context()["trace_id"]
                client.get_json("/api/v1/nodes/n1")  # slow: bad sample
        clock[0] += 5
        after = eng.evaluate()["publish_rtt"]
        assert after["burn_rate_fast"] > before["burn_rate_fast"]
        assert after["bad_total"] == before["bad_total"] + 1
        assert after["breached"] is True
        assert after["exemplar"]["trace_id"] == tid
        # the exemplar resolves on the fleet trace plane
        ff = FleetFlight()
        ff.add_local_source("node-a")
        story = ff.trace(after["exemplar"]["trace_id"])
        assert "kubeapi.request" in story["ops"]
        assert "drill.slow-request" in story["ops"]
    finally:
        api.stop()
