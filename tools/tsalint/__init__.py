"""tsalint — project-specific concurrency lint for the threaded daemon.

The daemon grew from a single-threaded loop into a genuinely concurrent
system (shared HealthHub + bounded probe pool, group-committed checkpoint
writer, per-claim-UID locks nested inside pool workers, debounce timers,
~26 lock/thread sites across 8 modules). Generic linters check style;
nothing checked the invariants that keep that concurrency correct. This
package does, statically:

  lock-order-cycle        the static lock-acquisition graph (nested
                          ``with``/".acquire()" sites plus resolvable
                          intra-class and cross-object calls made while a
                          lock is held) must be acyclic
  blocking-under-hot-lock no blocking call (file/socket I/O, sleeps,
                          kube-apiserver requests) inside the designated
                          hot locks: the server device-table lock, the DRA
                          global lock, the checkpoint-writer condition
  counter-lock            every /status and /metrics counter mutation must
                          sit under its owning lock (ownership is declared
                          in config.py)
  fault-site              every ``faults.fire("site")`` call site must be
                          registered in faults._SITE_CATEGORY AND
                          documented in docs/fault-injection.md; registered
                          sites with no production call site are dead and
                          fail too
  thread-lifecycle        every ``threading.Thread(``/``Timer(`` must be
                          daemonized AND be joinable on a stop() path
                          (tracked on an attribute that a stop-like method
                          joins with a timeout, or cancels for a Timer)
  broker-boundary         privileged calls — device-node opens
                          (/dev/vfio, /dev/iommu), sysfs bind/unbind/
                          driver_override writes, config-space reads —
                          only in the whitelisted privilege seams
                          (broker.py, discovery.py, the native shim);
                          everything else must route through
                          broker.get_client()

Findings are pinned in a checked-in baseline (baseline.json) so
pre-existing debt is frozen and only NEW violations fail CI. The runtime
side of the same contract is tpu_device_plugin/lockdep.py
($TDP_LOCKDEP=1). See docs/static-analysis.md.
"""

from .analyzer import Analyzer, Finding, analyze_paths, analyze_sources
from .baseline import diff_against_baseline, load_baseline, save_baseline
from .config import LintConfig, project_config

__all__ = [
    "Analyzer",
    "Finding",
    "LintConfig",
    "analyze_paths",
    "analyze_sources",
    "diff_against_baseline",
    "load_baseline",
    "save_baseline",
    "project_config",
]
