"""Minimal Kubernetes API client — stdlib only, no `kubernetes` package.

Shared by the node labeler (PATCH node labels) and the DRA driver
(ResourceSlice publish, ResourceClaim reads). Authenticates with the pod's
service-account token and trusts the in-cluster CA, exactly like the
labeler always has; the dependency-free stance mirrors the reference's
single-static-binary posture (its only runtime deps are grpc + sysfs,
reference: go.mod:1-12 — it never talks to the API server at all).
"""

from __future__ import annotations

import http.client
import json
import logging
import os
import random
import ssl
import threading
import time
from typing import Callable, Optional
from urllib.parse import urlsplit

from . import epoch as epoch_mod
from . import faults
from . import lockdep
from . import trace
from .resilience import BackoffPolicy, CircuitBreaker

log = logging.getLogger(__name__)

# idle keep-alive connections retained per client; excess connections from
# concurrency bursts are closed on return rather than pooled
MAX_IDLE_CONNECTIONS = 4

# failures whose signature is a stale keep-alive connection the server
# idled out — retried ONCE on a brand-new connection when the failed one
# was a reused pool member. Deliberately NARROW: a response-read timeout
# (TimeoutError) means the server may have processed the request, and
# replaying a POST/PUT there would duplicate apiserver writes, so it is
# wrapped as ApiError without retry like every other transport failure.
_RETRYABLE_STALE = (http.client.BadStatusLine,
                    http.client.CannotSendRequest,
                    http.client.ResponseNotReady, BrokenPipeError,
                    ConnectionResetError, ConnectionAbortedError)

SA_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"

# bounded in-call retries for a 429-throttled GET (reads are idempotent;
# writes go through PublishPacer's re-admission instead). 4 retries at
# the jittered 50-500 ms client-wide backoff rides out a boot-storm
# congestion spike without turning one kubelet RPC into an unbounded wait.
THROTTLED_GET_RETRIES = 4


def in_cluster_server() -> Optional[str]:
    """https://host:port of the API server from the in-cluster env, if any."""
    host = os.environ.get("KUBERNETES_SERVICE_HOST")
    port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
    if not host:
        return None
    return f"https://{host}:{port}"


class ApiError(Exception):
    """HTTP-level API failure carrying the status code (0 = transport)."""

    def __init__(self, message: str, code: int = 0):
        super().__init__(message)
        self.code = code


class ApiClient:
    """Bearer-token REST client for one API server.

    Connections are keep-alive and pooled (up to MAX_IDLE_CONNECTIONS
    idle): a node agent talks to one apiserver for its whole life, and
    per-request TCP+TLS handshakes are both the dominant cost of a DRA
    claim prepare and pointless apiserver load. The pool never blocks —
    a concurrency burst simply opens extra connections and closes them on
    return — so a slow publish cannot stall a claim prepare (the dra.py
    lock-scope rationale). A request that fails at send/first-byte on a
    REUSED connection is retried once on a brand-new one (the server
    idled out the keep-alive); a fresh-connection failure propagates,
    matching the one-attempt behavior this client always had.

    Connections are DIRECT (http.client): HTTP(S)_PROXY env vars, which
    the pre-pool urllib implementation honored, are intentionally not —
    an in-cluster node agent talks straight to its apiserver. A path
    component in the server URL (e.g. an apiserver proxy prefix) is
    preserved and prepended to every request path.
    """

    def __init__(self, server: str,
                 token_path: str = os.path.join(SA_DIR, "token"),
                 ca_path: str = os.path.join(SA_DIR, "ca.crt"),
                 timeout_s: float = 10.0,
                 breaker: Optional[CircuitBreaker] = None):
        self.server = server.rstrip("/")
        self.token_path = token_path
        self.ca_path = ca_path
        self.timeout_s = timeout_s
        split = urlsplit(self.server)
        self._https = split.scheme == "https"
        self._host = split.hostname or self.server
        self._port = split.port
        self._base_path = split.path.rstrip("/")
        self._idle: list = []
        self._pool_lock = lockdep.instrument(
            "kubeapi.ApiClient._pool_lock", threading.Lock())
        # Circuit breaker over the whole client (resilience.py): transport
        # failures and 5xx count as failures, any response < 500 (including
        # 4xx — the server answered) as success. While open, request()
        # fails fast with ApiError instead of burning a connect timeout per
        # call — the callers' own retry loops (lifecycle publish retry, dra
        # republish timer) keep running and land on the half-open probe.
        self.breaker = breaker or CircuitBreaker(
            failure_threshold=5, reset_timeout_s=15.0,
            name=f"kubeapi:{self._host}")
        # brief jittered pause before the single stale-keep-alive retry
        # (below): lets a restarting apiserver finish its listen() instead
        # of immediately eating the one retry the contract allows
        self._stale_backoff = BackoffPolicy(base_s=0.02, cap_s=0.2)
        # jittered client-wide backoff for 429-throttled GETs (below):
        # shared across this client's threads on purpose — when the
        # apiserver sheds load, EVERY reader of this client slows down
        # together instead of each thread independently hammering
        self._throttle_backoff = BackoffPolicy(base_s=0.05, cap_s=0.5)
        # Congestion signals consumed by PublishPacer: 429s (apiserver
        # priority-and-fairness shedding load), the calling thread's
        # last observed RTT (last_rtt_s property), and the thread's last
        # error code. throttled_total is an AtomicCounter (lock-free,
        # exact, client-wide — the /status-style aggregate); everything
        # the pacer classifies from is PER-THREAD (_throttle_tls), so
        # concurrent prepare workers' traffic on the same client can
        # never be misattributed to a publish.
        self.throttled_total = epoch_mod.AtomicCounter()
        self._throttle_tls = threading.local()

    def _new_conn(self) -> http.client.HTTPConnection:
        if self._https:
            # context rebuilt per NEW connection (cheap — pooling makes
            # new connections rare): the projected ca.crt rotates like
            # the token does, and a cached context would pin the old CA,
            # failing every handshake after a cluster CA rotation until
            # pod restart. Established pooled connections are unaffected
            # by rotation (their handshake is done).
            ctx = ssl.create_default_context(
                cafile=self.ca_path if os.path.exists(self.ca_path)
                else None)
            return http.client.HTTPSConnection(
                self._host, self._port, context=ctx,
                timeout=self.timeout_s)
        return http.client.HTTPConnection(
            self._host, self._port, timeout=self.timeout_s)

    def _get_conn(self):
        """→ (connection, was_reused)."""
        with self._pool_lock:
            if self._idle:
                return self._idle.pop(), True
        return self._new_conn(), False

    def _put_conn(self, conn) -> None:
        with self._pool_lock:
            if len(self._idle) < MAX_IDLE_CONNECTIONS:
                self._idle.append(conn)
                return
        conn.close()

    def request(self, path: str, method: str = "GET",
                body: Optional[bytes] = None,
                content_type: Optional[str] = None) -> bytes:
        """Raw request against an API path; raises ApiError on failure.

        Fails fast (without touching the network) while the circuit
        breaker is open; every attempt's outcome feeds the breaker.

        The span (op "kubeapi.request", tdp_kubeapi_rtt_ms) is the
        daemon's apiserver-RTT observability: started inside a claim
        span it inherits the claim_uid, so a prepare stalled on a slow
        ResourceClaim GET is attributable from /debug/flight alone.
        """
        url = self.server + path
        # breaker fast-fail OUTSIDE the span: an open breaker rejects in
        # microseconds, and recording those as RTT samples would collapse
        # tdp_kubeapi_rtt_ms percentiles to ~0 exactly when the apiserver
        # is down — the opposite of what the histogram exists to show
        if not self.breaker.allow():
            raise ApiError(f"{method} {url}: circuit breaker open "
                           f"(apiserver failing; next probe within "
                           f"{self.breaker.reset_timeout_s:.0f}s)",
                           code=0)
        # The 429-GET retry loop sits OUTSIDE the per-attempt span below:
        # the backoff sleeps are client-side waiting, not server RTT, and
        # folding them into tdp_kubeapi_rtt_ms would read seconds for
        # requests the server answered in ~1 ms exactly when the
        # apiserver throttles — the same honesty rule that keeps the
        # breaker fast-fail out of the span. A throttled GET — whose
        # replay cannot duplicate a write — retries behind a client-wide
        # jittered backoff (every reader of this client slows down
        # together); throttled WRITES never retry at this layer — the
        # publish pacer owns their re-admission.
        for attempt in range(THROTTLED_GET_RETRIES + 1):
            try:
                return self._traced_attempt(path, method, body,
                                            content_type, url)
            except ApiError as exc:
                if exc.code == 429 and method == "GET" \
                        and attempt < THROTTLED_GET_RETRIES:
                    time.sleep(self._throttle_backoff.next_delay())
                    continue
                raise
        raise ApiError(f"{method} {url}: throttle retry fell "
                       f"through")  # unreachable

    def _traced_attempt(self, path: str, method: str,
                        body: Optional[bytes],
                        content_type: Optional[str], url: str) -> bytes:
        """One traced wire attempt: its span IS one server round trip
        (tdp_kubeapi_rtt_ms stays an RTT histogram even under throttle
        storms), with breaker + congestion-signal accounting."""
        with trace.span("kubeapi.request", histogram="tdp_kubeapi_rtt_ms",
                        method=method, path=path):
            tls = self._throttle_tls
            t0 = time.monotonic()
            try:
                # fault point "kubeapi.request" (raising): an armed
                # fault fails the request before the wire, as a
                # transport error would
                faults.fire("kubeapi.request", method=method, path=path)
                data = self._request_once(path, method, body,
                                          content_type, url)
            except ApiError as exc:
                tls.rtt = time.monotonic() - t0
                tls.last_code = exc.code
                if exc.code == 429:
                    # apiserver shedding load (priority-and-fairness):
                    # the pacing layer widens its admission window on
                    # this signal; the server ANSWERED, so the breaker
                    # records success like any other 4xx
                    self.throttled_total.add()
                    tls.count = getattr(tls, "count", 0) + 1
                    self.breaker.record_success()
                elif exc.code == 0 or exc.code >= 500:
                    self.breaker.record_failure()
                else:
                    self.breaker.record_success()  # 3xx/4xx: alive
                raise
            except Exception as exc:
                # injected fault of a non-ApiError kind: surface it
                # under the client's one exception contract
                self.breaker.record_failure()
                tls.last_code = 0
                raise ApiError(f"{method} {url}: {exc}") from exc
            tls.rtt = time.monotonic() - t0
            self.breaker.record_success()
            self._stale_backoff.reset()
            self._throttle_backoff.reset()
            return data

    # -- per-thread congestion signals (PublishPacer's classification) ----

    @property
    def last_rtt_s(self) -> float:
        """The CALLING thread's most recent server round-trip time —
        the pacer's slow-RTT signal (per-thread so another worker's
        request can never overwrite the publish's own reading)."""
        return getattr(self._throttle_tls, "rtt", 0.0)

    def thread_throttled_count(self) -> int:
        """429s observed by the CALLING thread's requests."""
        return getattr(self._throttle_tls, "count", 0)

    def reset_thread_error(self) -> None:
        """Clear the calling thread's last-error record (the pacer calls
        this at wave start so a stale code from earlier traffic cannot
        classify this wave)."""
        self._throttle_tls.last_code = None

    def thread_last_error_code(self) -> Optional[int]:
        """HTTP code of the CALLING thread's most recent FAILED request
        (None if none since reset). The pacer classifies a failed wave
        as throttled only when the request that made it give up was a
        429 — a publish whose internal GET drew a retried-away 429 but
        whose PUT then failed 5xx must return to the caller's republish
        machinery, not re-admit."""
        return getattr(self._throttle_tls, "last_code", None)

    def _request_once(self, path: str, method: str, body: Optional[bytes],
                      content_type: Optional[str], url: str) -> bytes:
        """One logical request: pool checkout, send, narrow stale-keep-alive
        retry, status handling. Raises ApiError on any failure."""
        headers = {}
        if content_type:
            headers["Content-Type"] = content_type
        # token re-read per request: in-cluster tokens rotate
        try:
            with open(self.token_path, "r", encoding="ascii") as f:
                headers["Authorization"] = f"Bearer {f.read().strip()}"
        except OSError:
            pass  # no token (e.g. test server without auth)
        for attempt in (0, 1):
            if attempt == 0:
                conn, reused = self._get_conn()
            else:
                # retry leg: ALWAYS a brand-new connection — popping
                # another pool member could hit a second stale keep-alive
                # (apiserver restart with several idle conns) and fail a
                # request a fresh connection would serve
                conn, reused = self._new_conn(), False
            # The SEND phase and the RESPONSE phase have different retry
            # safety: a send-phase failure means the server never got the
            # full request (any method can retry); a response-phase
            # failure means it may have PROCESSED it, so only GET — whose
            # replay cannot duplicate a write — retries there.
            sent = False
            try:
                conn.request(method, self._base_path + path, body=body,
                             headers=headers)
                sent = True
                resp = conn.getresponse()
                data = resp.read()
            except (http.client.HTTPException, OSError) as exc:
                conn.close()
                retry_safe = (not sent) or method == "GET"
                if (attempt == 0 and reused and retry_safe
                        and isinstance(exc, _RETRYABLE_STALE)):
                    # idled-out keep-alive: one fresh retry, after a short
                    # jittered pause (BackoffPolicy; reset on any success)
                    time.sleep(self._stale_backoff.next_delay())
                    continue
                raise ApiError(f"{method} {url}: {exc}") from exc
            if resp.will_close:
                conn.close()
            else:
                self._put_conn(conn)
            if resp.status >= 400:
                detail = data.decode("utf-8", "replace")[:300]
                raise ApiError(
                    f"{method} {url}: HTTP {resp.status} {detail}",
                    code=resp.status)
            if resp.status >= 300:
                # the pre-pool urllib client auto-followed redirects;
                # http.client does not, and silently returning a redirect
                # body would feed HTML into json.loads — surface it as
                # the transport error it is
                raise ApiError(
                    f"{method} {url}: HTTP {resp.status} redirect "
                    f"(redirects unsupported; point --api-server at the "
                    f"final URL)", code=resp.status)
            return data
        raise ApiError(f"{method} {url}: retry fell through")  # unreachable

    # -- JSON convenience wrappers against resource paths ---------------------

    def get_json(self, path: str) -> dict:
        return json.loads(self.request(path))

    def post_json(self, path: str, obj: dict) -> dict:
        return json.loads(self.request(
            path, method="POST", body=json.dumps(obj).encode(),
            content_type="application/json"))

    def put_json(self, path: str, obj: dict) -> dict:
        return json.loads(self.request(
            path, method="PUT", body=json.dumps(obj).encode(),
            content_type="application/json"))

    def delete(self, path: str) -> None:
        self.request(path, method="DELETE")

    def patch_strategic(self, path: str, obj: dict) -> bytes:
        return self.request(
            path, method="PATCH", body=json.dumps(obj).encode(),
            content_type="application/strategic-merge-patch+json")


# ---------------------------------------------------------------- pacing

# Admission-window bounds for PublishPacer. The window starts at the
# configured base (default 0: an unloaded node publishes with zero added
# latency) and adapts: multiplicative increase on a 429 or a slow RTT,
# halving decay on fast successes — AIMD, the same shape TCP and RPCAcc-
# style PCIe RPC pacing use, because the fleet problem is the same: N
# independent senders discovering one server's capacity without a
# coordinator.
PACE_GROW_FLOOR_S = 0.05     # first growth step when the window was ~0
PACE_MAX_WINDOW_S = 2.0      # adaptation ceiling
PACE_SLOW_RTT_S = 0.25       # RTT above this reads as server congestion
PACE_MAX_ATTEMPTS = 8        # throttled-publish retries within one run()


class PublishPacer:
    """Per-client adaptive pacing + coalescing for guarded publishes.

    The fleet congestion shape (ROADMAP item 1 / RPCAcc in PAPERS.md):
    N nodes boot at once and every daemon's guarded ResourceSlice PUT
    lands on the apiserver in the same instant — a thundering herd the
    server answers with 429s, which naive clients retry immediately,
    keeping peak in-flight at N forever. This class bounds that:

    - ADMISSION WINDOW: a publish first waits a jittered delay drawn
      from the current window. The window starts at `base_window_s`
      (default 0 — steady-state single-node publishes pay nothing) and
      adapts on feedback from the ApiClient's congestion signals: a 429
      or a slow RTT doubles it (from PACE_GROW_FLOOR_S when it was ~0),
      a fast success halves it back toward base. Across a fleet the
      jittered, independently-grown windows turn N simultaneous PUTs
      into bounded-rate waves.
    - COALESCING: publishers arriving while a wave is still in its
      admission wait JOIN that wave instead of queueing their own —
      the leader builds the slice body AFTER admission, so the joined
      caller's state rides the same PUT (`publishes_coalesced_total`).
      A health-flip storm inside one daemon becomes one PUT, not one
      per flip.
    - THROTTLE RETRY: a publish the server answered with 429 is retried
      through a re-grown window (bounded by PACE_MAX_ATTEMPTS), so a
      boot storm converges without waiting for the caller's slow
      republish timer. Non-throttle failures return False immediately —
      the existing retry machinery (republish backoff, chaos contracts)
      owns those.

    Exactly-once is untouched: the pacer never replays a publish the
    server may have applied — it only delays, coalesces, and retries
    attempts the server REFUSED (429 = not executed, by definition).

    Counters (`stats`) mutate under `_cond` (tsalint COUNTERS entry);
    admission delays are recorded into the `tdp_pacing_delay_ms`
    histogram (trace.py). `rng` is injectable so fleet simulations are
    deterministic.
    """

    def __init__(self, api: Optional[ApiClient] = None,
                 base_window_s: float = 0.0,
                 max_window_s: float = PACE_MAX_WINDOW_S,
                 slow_rtt_s: float = PACE_SLOW_RTT_S,
                 max_attempts: int = PACE_MAX_ATTEMPTS,
                 rng: Optional[random.Random] = None) -> None:
        self.api = api
        self.base_window_s = max(0.0, base_window_s)
        self.max_window_s = max_window_s
        self.slow_rtt_s = slow_rtt_s
        self.max_attempts = max(1, max_attempts)
        self._rng = rng or random.Random()
        self._cond = lockdep.instrument(
            "kubeapi.PublishPacer._cond", threading.Condition())
        # state machine: idle -> waiting (admission; joinable) ->
        # publishing -> idle. All state below is guarded by _cond.
        self._state = "idle"
        self._window_s = self.base_window_s
        self._wave_seq = 0       # waves opened (leader entered waiting)
        self._done_seq = 0       # waves completed
        self._last_result = False
        self.stats = {
            # publish waves actually sent to the server (leader attempts)
            "publish_waves_total": 0,
            # callers whose state rode another caller's wave
            "publishes_coalesced_total": 0,
            # waves the server answered 429 and the pacer re-admitted
            "publish_throttled_total": 0,
            # admission waits with a non-zero delay
            "pacing_delays_total": 0,
        }

    def snapshot(self) -> dict:
        """Lock-free stats read (fixed-key dict: C-atomic copy + GIL-
        atomic int reads), plus the current admission window — the
        /status surface."""
        out = dict(self.stats)
        out["window_ms"] = round(self._window_s * 1e3, 3)
        return out

    def _wave_start(self) -> None:
        if self.api is not None:
            self.api.reset_thread_error()

    def _wave_throttled(self, ok: bool) -> bool:
        """A FAILED wave is throttled iff the request that made it give
        up answered 429. publish_fn runs synchronously on this thread,
        and the client's last-error record is per-thread and reset at
        wave start — so neither concurrent workers' traffic nor a
        retried-away internal 429 followed by a 5xx PUT can re-admit a
        wave that must return to the caller's republish machinery."""
        if ok or self.api is None:
            return False
        return self.api.thread_last_error_code() == 429

    def _wave_rtt_s(self, wall_s: float) -> float:
        """The slow-RTT adaptation signal: the publish's own last server
        round trip when a client is wired (per-thread last_rtt_s), the
        whole-wave wall otherwise (tests / detached pacers)."""
        if self.api is not None:
            rtt = self.api.last_rtt_s
            if rtt > 0:
                return rtt
        return wall_s

    def _adapt_locked(self, ok: bool, rtt_s: float, throttled: bool) -> None:
        if throttled:
            self._window_s = min(self.max_window_s,
                                 max(PACE_GROW_FLOOR_S, self._window_s * 2))
        elif rtt_s > self.slow_rtt_s:
            self._window_s = min(self.max_window_s,
                                 max(PACE_GROW_FLOOR_S / 2,
                                     self._window_s * 1.5))
        elif ok:
            decayed = self._window_s / 2
            self._window_s = self.base_window_s \
                if decayed < max(self.base_window_s, 1e-3) else decayed

    def run(self, publish_fn: Callable[[], bool]) -> bool:
        """Publish through the pacer; returns publish_fn's result (or a
        completed wave's result when this caller coalesced onto it).

        publish_fn must build the published body from CURRENT state when
        invoked (the DRA driver's `_publish_locked` does): that is what
        makes joining a wave that has not yet built its body correct.
        """
        cond = self._cond
        with cond:
            while True:
                if self._state == "waiting":
                    # a wave is still in its admission wait: our state
                    # will be in the body it builds after admission
                    joined = self._wave_seq
                    self.stats["publishes_coalesced_total"] += 1
                    cond.wait_for(lambda: self._done_seq >= joined)
                    return self._last_result
                if self._state == "publishing":
                    # too late to join (the body may already be built):
                    # wait for the wave to finish, then lead our own
                    cond.wait_for(lambda: self._state != "publishing")
                    continue
                self._state = "waiting"
                self._wave_seq += 1
                break
        ok = False
        try:
            attempt = 0
            while True:
                with cond:
                    window = self._window_s
                    # uniform over the FULL window: a fleet of pacers
                    # with the same window then spreads a simultaneous
                    # storm evenly across it (a [w/2, w] draw would
                    # re-clump every node into the window's second half)
                    delay = self._rng.uniform(0.0, window) \
                        if window > 0 else 0.0
                    if delay > 0:
                        self.stats["pacing_delays_total"] += 1
                        deadline = time.monotonic() + delay
                        while True:
                            remaining = deadline - time.monotonic()
                            if remaining <= 0:
                                break
                            cond.wait(timeout=remaining)
                    self._state = "publishing"
                    self.stats["publish_waves_total"] += 1
                if delay > 0:
                    # 0-delay waves (the unloaded steady state) are not
                    # recorded: they would collapse the histogram's
                    # percentiles to 0 exactly when pacing is idle
                    trace.observe("tdp_pacing_delay_ms", delay * 1e3)
                self._wave_start()
                t0 = time.monotonic()
                ok = publish_fn()
                wall = time.monotonic() - t0
                throttled = self._wave_throttled(ok)
                with cond:
                    self._adapt_locked(ok, self._wave_rtt_s(wall),
                                       throttled)
                    if ok or not throttled \
                            or attempt >= self.max_attempts - 1:
                        return ok
                    # 429: the server refused (never executed) the PUT —
                    # re-admit through the grown window; new arrivals
                    # coalesce onto the retry
                    attempt += 1
                    self.stats["publish_throttled_total"] += 1
                    self._state = "waiting"
        finally:
            with cond:
                self._state = "idle"
                self._done_seq = self._wave_seq
                self._last_result = ok
                cond.notify_all()
