"""Fleet placement control plane — cluster-wide ICI slice scheduler.

PR 10's placement engine (placement.py) plans within ONE daemon's host
view; production TPU fleets place slices across thousands of hosts.
This module is the scheduler-side consumer ROADMAP item 1 names: it
merges every daemon's PUBLISHED host view — the ResourceSlices the
fleet's drivers keep converged through the PR 12 watch plane — into one
cluster placement decision. Like gpu_ext moves GPU policy out of the
fixed driver into operator-extensible programs (PAPERS.md), the
placement decision moves out of the per-host daemon into a control
plane driven by typed selector expressions over the topology attributes
the daemons publish (dra._device_entry: ICI coords, torus dims,
generation, ring/host ids).

Three planes, all reading lock-free snapshots:

1. **Selector engine.** CEL-style typed attribute expressions —
   `topology.generation == "v5e" && topology.ring_size >= 4` — compiled
   ONCE (`compile_selector`; malformed text raises SelectorError at
   compile, never at match) and evaluated over per-device attribute
   views (`device_attrs`). Pure compute over immutable inputs: no
   selector evaluation ever takes a lock. Semantics: an empty selector
   matches everything; a predicate over an unknown attribute or a
   type-mismatched comparison poisons its boolean branch to NO MATCH
   (counted, never raised to callers) — short-circuit `&&`/`||` mean an
   already-decided branch never touches the bad predicate.

2. **Slice cache + fleet views.** `SliceCache` is the scheduler-side
   informer cache: the PR 12 kubeapi.Reflector feeds it (`on_sync` /
   `on_event`, both idempotent under the at-least-once contract), the
   writer swaps an immutable snapshot under its lock, and every reader
   — selector filtering, placement planning, fragmentation accounting —
   consumes the snapshot without locking. `host_views_from_slices`
   parses published topology attributes back into placement.HostView
   grids, overlaying the scheduler's own claim ledger (a scheduler
   knows what IT placed; slices advertise capacity, not tenancy).

3. **FleetScheduler.** Cluster decisions end-to-end: selector-filtered
   views → placement.plan_slice with the POD-LEVEL host grid (cross-
   host wrap-around ICI meshes, mesh_score contiguity) → execution
   through the fleetsim multiclaim fabric — with ONE commit log
   spanning scheduler decision → per-node sub-claims → rollback,
   audited exactly-once cluster-wide (`audit`), every decision a
   flight-recorder span (`fleetplace.schedule`), and fleet-global
   fragmentation rolled up per generation (`cluster_fragmentation`)
   to drive globally-planned defrag waves applied node-by-node through
   the PR 7 migration-handoff machinery.

docs/design.md "Fleet placement control plane" documents the selector
grammar, the cross-host mesh model, and the global defrag sequence.
"""

from __future__ import annotations

import itertools
import json
import logging
import re
import threading
import time
from dataclasses import replace
from types import MappingProxyType
from typing import (Callable, Dict, List, Mapping, Optional, Sequence,
                    Tuple)

from . import lockdep, trace
from .epoch import AtomicCounter
from .placement import HostView, volume

log = logging.getLogger(__name__)

__all__ = ["SelectorError", "CompiledSelector", "compile_selector",
           "device_attrs", "SliceCache", "host_views_from_slices",
           "cluster_fragmentation", "FleetScheduler", "FleetFlight"]


# ====================================================================
# selector engine
# ====================================================================


class SelectorError(ValueError):
    """A selector that cannot compile: bad token, unbalanced parens,
    dangling operator, mixed-type list literal. Raised at COMPILE time
    — a malformed expression must fail loudly when the operator writes
    it, never silently at match time."""


class _EvalMiss(Exception):
    """Internal: a predicate touched an unknown attribute or mismatched
    types. Poisons the enclosing boolean branch to no-match; counted by
    CompiledSelector.matches, never raised to callers."""

    __slots__ = ("kind",)

    def __init__(self, kind: str) -> None:
        self.kind = kind


_TOKEN_RE = re.compile(r"""
    \s*(?:
      (?P<lparen>\() | (?P<rparen>\)) |
      (?P<lbracket>\[) | (?P<rbracket>\]) | (?P<comma>,) |
      (?P<cmp>==|!=|<=|>=|<|>) |
      (?P<andop>&&) | (?P<orop>\|\|) | (?P<notop>!) |
      (?P<string>"(?:[^"\\]|\\.)*"|'(?:[^'\\]|\\.)*') |
      (?P<int>-?\d+\b) |
      (?P<ident>[A-Za-z_][A-Za-z0-9_]*(?:\.[A-Za-z_][A-Za-z0-9_]*)*)
    )""", re.VERBOSE)


def _tokenize(text: str) -> List[Tuple[str, str]]:
    tokens: List[Tuple[str, str]] = []
    pos = 0
    while pos < len(text):
        m = _TOKEN_RE.match(text, pos)
        if m is None or m.end() == m.start():
            rest = text[pos:].lstrip()
            if not rest:
                break
            raise SelectorError(
                f"selector: unrecognized input at {rest[:20]!r}")
        pos = m.end()
        kind = m.lastgroup
        if kind is None:      # trailing whitespace
            continue
        tokens.append((kind, m.group(kind)))
    return tokens


def _type_name(value) -> str:
    # bool before int: isinstance(True, int) holds in Python, but a
    # selector comparing a bool attribute against 1 is a type error
    if isinstance(value, bool):
        return "bool"
    if isinstance(value, int):
        return "int"
    return "string"


_CMP_OPS: Dict[str, Callable] = {
    "==": lambda a, b: a == b,
    "!=": lambda a, b: a != b,
    "<": lambda a, b: a < b,
    "<=": lambda a, b: a <= b,
    ">": lambda a, b: a > b,
    ">=": lambda a, b: a >= b,
}
_ORDER_OPS = {"<", "<=", ">", ">="}

_MISSING = object()


def _camel(name: str) -> str:
    head, *rest = name.split("_")
    return head + "".join(w.capitalize() for w in rest)


def resolve_attr(attrs: Mapping[str, object], ident: str):
    """Selector identifier → published attribute value. `topology.` /
    `device.` prefixes address the same flat attribute map the daemon
    publishes; snake_case falls back to the camelCase the wire uses
    (`topology.ring_size` → `ringSize`). Returns _MISSING when no
    candidate resolves."""
    suffix = ident.split(".", 1)[1] \
        if ident.split(".", 1)[0] in ("topology", "device") \
        and "." in ident else ident
    for cand in (ident, suffix, _camel(suffix)):
        if cand in attrs:
            return attrs[cand]
    return _MISSING


class _Parser:
    """Recursive-descent over the token list; every production returns
    a closure. Value closures: attrs -> python value (raising _EvalMiss
    on unknown attributes). Boolean closures: attrs -> bool."""

    def __init__(self, tokens: List[Tuple[str, str]]) -> None:
        self.tokens = tokens
        self.pos = 0

    def peek(self) -> Optional[Tuple[str, str]]:
        return self.tokens[self.pos] if self.pos < len(self.tokens) \
            else None

    def take(self, kind: Optional[str] = None) -> Tuple[str, str]:
        tok = self.peek()
        if tok is None:
            raise SelectorError("selector: unexpected end of expression")
        if kind is not None and tok[0] != kind:
            raise SelectorError(f"selector: expected {kind}, got "
                                f"{tok[1]!r}")
        self.pos += 1
        return tok

    # ------------------------------------------------------- grammar

    def parse(self) -> Callable:
        fn = self.expr()
        if self.peek() is not None:
            raise SelectorError(
                f"selector: trailing input at {self.peek()[1]!r}")
        return fn

    def expr(self) -> Callable:
        terms = [self.and_()]
        while self.peek() and self.peek()[0] == "orop":
            self.take()
            terms.append(self.and_())
        if len(terms) == 1:
            return terms[0]

        def run_or(attrs, _terms=tuple(terms)):
            for t in _terms:
                if t(attrs):
                    return True
            return False
        return run_or

    def and_(self) -> Callable:
        terms = [self.unary()]
        while self.peek() and self.peek()[0] == "andop":
            self.take()
            terms.append(self.unary())
        if len(terms) == 1:
            return terms[0]

        def run_and(attrs, _terms=tuple(terms)):
            for t in _terms:
                if not t(attrs):
                    return False
            return True
        return run_and

    def unary(self) -> Callable:
        if self.peek() and self.peek()[0] == "notop":
            self.take()
            inner = self.unary()
            return lambda attrs: not inner(attrs)
        return self.primary()

    def primary(self) -> Callable:
        tok = self.peek()
        if tok is None:
            raise SelectorError("selector: unexpected end of expression")
        if tok[0] == "lparen":
            self.take()
            inner = self.expr()
            self.take("rparen")
            return inner
        lhs, lhs_desc = self.operand()
        nxt = self.peek()
        if nxt is not None and nxt[0] == "cmp":
            op = self.take()[1]
            rhs, _rhs_desc = self.operand()
            return self._comparison(lhs, op, rhs)
        if nxt is not None and nxt[0] == "ident" and nxt[1] == "in":
            self.take()
            members = self.list_literal()
            return self._membership(lhs, members)
        # bare operand: must evaluate to a bool attribute/literal

        def run_bare(attrs, _lhs=lhs, _desc=lhs_desc):
            value = _lhs(attrs)
            if not isinstance(value, bool):
                raise _EvalMiss("type_mismatch")
            return value
        return run_bare

    @staticmethod
    def _unquote(text: str) -> str:
        """Decode one string-literal token — shared by the operand and
        list-literal positions so the same quoted token denotes the
        same value in `==` and `in` contexts."""
        return text[1:-1].replace("\\" + text[0], text[0]) \
            .replace("\\\\", "\\")

    def operand(self) -> Tuple[Callable, str]:
        tok = self.take()
        kind, text = tok
        if kind == "string":
            value = self._unquote(text)
            return (lambda attrs, _v=value: _v), f"string {value!r}"
        if kind == "int":
            value = int(text)
            return (lambda attrs, _v=value: _v), f"int {value}"
        if kind == "ident":
            if text in ("true", "false"):
                value = text == "true"
                return (lambda attrs, _v=value: _v), f"bool {text}"
            if text == "in":
                raise SelectorError("selector: 'in' needs a left operand")

            def run_ident(attrs, _name=text):
                value = resolve_attr(attrs, _name)
                if value is _MISSING:
                    raise _EvalMiss("unknown_attribute")
                return value
            return run_ident, f"attribute {text}"
        raise SelectorError(f"selector: expected a value, got {text!r}")

    def list_literal(self) -> Tuple:
        self.take("lbracket")
        members: List = []
        while True:
            tok = self.peek()
            if tok is None:
                raise SelectorError("selector: unterminated list literal")
            if tok[0] == "rbracket":
                self.take()
                break
            if members:
                self.take("comma")
                tok = self.peek()
                if tok is not None and tok[0] == "rbracket":
                    self.take()
                    break
            kind, text = self.take()
            if kind == "string":
                members.append(self._unquote(text))
            elif kind == "int":
                members.append(int(text))
            elif kind == "ident" and text in ("true", "false"):
                members.append(text == "true")
            else:
                raise SelectorError(
                    f"selector: list literals hold literals only, got "
                    f"{text!r}")
        if members and len({_type_name(m) for m in members}) > 1:
            raise SelectorError("selector: mixed-type list literal")
        return tuple(members)

    @staticmethod
    def _comparison(lhs: Callable, op: str, rhs: Callable) -> Callable:
        fn = _CMP_OPS[op]
        ordered = op in _ORDER_OPS

        def run_cmp(attrs):
            a = lhs(attrs)
            b = rhs(attrs)
            ta, tb = _type_name(a), _type_name(b)
            if ta != tb or (ordered and ta == "bool"):
                raise _EvalMiss("type_mismatch")
            return fn(a, b)
        return run_cmp

    @staticmethod
    def _membership(lhs: Callable, members: Tuple) -> Callable:
        member_type = _type_name(members[0]) if members else None

        def run_in(attrs):
            value = lhs(attrs)
            if member_type is not None \
                    and _type_name(value) != member_type:
                raise _EvalMiss("type_mismatch")
            return value in members
        return run_in


class CompiledSelector:
    """One compiled selector: `matches(attrs)` over a per-device
    attribute view. Stateless between calls except the lock-free
    AtomicCounter stats — safe to share across scheduler threads, safe
    inside zero-lock read paths."""

    __slots__ = ("text", "_fn", "stats")

    STAT_KEYS = ("evals_total", "matches_total",
                 "unknown_attribute_total", "type_mismatch_total")

    def __init__(self, text: str, fn: Optional[Callable]) -> None:
        self.text = text
        self._fn = fn
        self.stats = {key: AtomicCounter() for key in self.STAT_KEYS}

    def matches(self, attrs: Mapping[str, object]) -> bool:
        self.stats["evals_total"].add()
        if self._fn is None:          # empty selector: match-all
            self.stats["matches_total"].add()
            return True
        try:
            ok = bool(self._fn(attrs))
        except _EvalMiss as miss:
            self.stats[f"{miss.kind}_total"].add()
            ok = False
        if ok:
            self.stats["matches_total"].add()
        return ok

    def snapshot(self) -> Dict[str, int]:
        return {key: counter.value
                for key, counter in self.stats.items()}


def compile_selector(text: str) -> CompiledSelector:
    """Compile a selector expression ONCE; evaluate many times.
    Raises SelectorError on malformed input — compile is where
    expressions fail, match never raises. An empty/whitespace selector
    compiles to match-all."""
    text = (text or "").strip()
    if not text:
        return CompiledSelector(text, None)
    return CompiledSelector(text, _Parser(_tokenize(text)).parse())


def device_attrs(entry: Mapping) -> Dict[str, object]:
    """Flatten one ResourceSlice device entry's typed attributes
    ({"string"|"int"|"bool": v}, v1beta1 "basic"-nested or v1 flat)
    into the plain {name: value} view selectors evaluate over. The
    device's published name rides along as "name"."""
    basic = entry.get("basic")
    attrs = (basic or {}).get("attributes") if isinstance(basic, Mapping) \
        else entry.get("attributes")
    out: Dict[str, object] = {}
    for name, tv in (attrs or {}).items():
        if not isinstance(tv, Mapping):
            continue
        if "string" in tv:
            out[name] = str(tv["string"])
        elif "bool" in tv:
            out[name] = bool(tv["bool"])
        elif "int" in tv:
            out[name] = int(tv["int"])
    out.setdefault("name", entry.get("name"))
    return out


# ====================================================================
# scheduler-side slice cache (the PR 12 Reflector's consumer)
# ====================================================================


class SliceCache:
    """Informer cache over published ResourceSlices, fed by a
    kubeapi.Reflector (`on_sync` for LIST states, `on_event` for watch
    events — both idempotent, surviving the at-least-once delivery
    contract). The writer (reflector thread) mutates its private dict
    under `_lock` and swaps an IMMUTABLE MappingProxyType snapshot;
    `snapshot()` readers never lock — fleet accounting and selector
    evaluation run against one frozen cluster state."""

    def __init__(self) -> None:
        self._lock = lockdep.instrument(
            "fleetplace.SliceCache._lock", threading.Lock())
        self._by_name: Dict[str, dict] = {}
        self._snap: Mapping[str, dict] = MappingProxyType({})
        self.syncs = AtomicCounter()
        self.events = AtomicCounter()

    def on_sync(self, items: Sequence[dict]) -> None:
        self.syncs.add()
        fresh = {}
        for obj in items or ():
            name = ((obj.get("metadata") or {}).get("name"))
            # real apiserver LIST items omit per-item kind (only the
            # List envelope carries one) — skip an item only when a
            # kind IS present and names something else
            if name and obj.get("kind") in (None, "ResourceSlice"):
                fresh[name] = obj
        with self._lock:
            self._by_name = fresh
            self._snap = MappingProxyType(dict(fresh))

    def on_event(self, evt: dict) -> None:
        self.events.add()
        obj = evt.get("object") or {}
        name = (obj.get("metadata") or {}).get("name")
        if not name:
            return
        with self._lock:
            if evt.get("type") == "DELETED":
                self._by_name.pop(name, None)
            else:
                self._by_name[name] = obj
            self._snap = MappingProxyType(dict(self._by_name))

    def snapshot(self) -> Mapping[str, dict]:
        """Lock-free: one attribute read of an immutable mapping."""
        return self._snap


_AXES = "xyz"


def _axis_attrs(attrs: Mapping[str, object], prefix: str
                ) -> Optional[Tuple[int, ...]]:
    """("iciX","iciY"[,"iciZ"]) / ("torusX",..) / ("hostX",..) →
    coordinate tuple, None when the leading axis is absent."""
    out: List[int] = []
    for axis in _AXES:
        value = attrs.get(f"{prefix}{axis.upper()}")
        if not isinstance(value, int) or isinstance(value, bool):
            break
        out.append(value)
    return tuple(out) if out else None


def host_views_from_slices(
    slices: Mapping[str, dict],
    claims: Mapping[str, Tuple[Tuple[str, str, Tuple[str, ...]], ...]],
) -> Tuple[Dict[str, List[HostView]],
           Dict[Tuple[str, str], Dict[str, Dict[str, object]]]]:
    """Published ResourceSlices + the scheduler's claim ledger → the
    cluster's placement views.

    The ledger maps uid -> ((sub_uid, node, raws), ...): each shard
    carries its NODE-LEVEL claim identity (`<uid>-<node>` at placement
    time, stable across defrag migrations), and the views' claims maps
    are keyed by those sub-uids — the ids the node drivers' checkpoints
    actually hold — so a defrag advisory computed over these views
    names claims the handoff machinery can really unprepare.

    Returns (views_by_generation, attrs_index): one HostView per
    (node, generation) grouped by generation name, plus the per-device
    attribute views ((node, generation) -> bdf -> attrs) selector
    filtering evaluates. Pure compute over the immutable cache
    snapshot: devices without ICI coords or torus dims (partitions,
    pre-topology daemons) are skipped — a scheduler cannot place a mesh
    on chips whose topology it cannot see. Departed chips never appear
    (the daemon prunes them from its slice), so scheduler-side views
    carry no departed holes; per-daemon /status keeps that accounting.
    """
    grids: Dict[Tuple[str, str], dict] = {}
    attrs_index: Dict[Tuple[str, str], Dict[str, Dict[str, object]]] = {}
    # keyed (node, raw): BDFs repeat across hosts — every node
    # enumerates 0000:00:04.0 — so a bare-BDF key would mark one
    # claim's chips busy fleet-wide
    claimed: Dict[Tuple[str, str], str] = {}
    claim_raws: Dict[Tuple[str, str], Dict[str, List[str]]] = {}
    for _uid, shards in claims.items():
        for sub_uid, node, raws in shards:
            for raw in raws:
                claimed[(node, raw)] = sub_uid
    for obj in slices.values():
        spec = obj.get("spec") or {}
        node = spec.get("nodeName")
        if not node:
            continue
        for entry in spec.get("devices") or ():
            attrs = device_attrs(entry)
            generation = attrs.get("generation")
            bdf = attrs.get("bdf")
            coords = _axis_attrs(attrs, "ici")
            dims = _axis_attrs(attrs, "torus")
            if not generation or not bdf or coords is None or dims is None:
                continue
            if len(coords) != len(dims):
                continue
            key = (node, str(generation))
            g = grids.setdefault(key, {
                "dims": dims, "coords": {}, "names": {}, "free": set(),
                "host_coords": _axis_attrs(attrs, "host")})
            g["coords"][bdf] = coords
            g["names"][bdf] = str(attrs.get("name"))
            attrs_index.setdefault(key, {})[bdf] = attrs
            uid = claimed.get((node, bdf))
            if uid is None:
                g["free"].add(bdf)
            else:
                claim_raws.setdefault(key, {}).setdefault(
                    uid, []).append(bdf)
    views: Dict[str, List[HostView]] = {}
    for (node, generation), g in sorted(grids.items()):
        views.setdefault(generation, []).append(HostView(
            node=node, dims=g["dims"],
            coords=g["coords"], names=g["names"],
            free=frozenset(g["free"]), departed=frozenset(),
            claims={uid: tuple(raws) for uid, raws
                    in claim_raws.get((node, generation), {}).items()},
            host_coords=g["host_coords"]))
    return views, attrs_index


def _view_attrs(generation: str, view: HostView, raw: str
                ) -> Dict[str, object]:
    """Synthesize the published attribute view for one chip of a
    driver-side HostView — the same fields dra._device_entry puts on
    the wire, so selector semantics cannot drift between the watch-fed
    and the direct-views scheduler modes."""
    dims = view.dims
    out: Dict[str, object] = {
        "generation": generation,
        "bdf": raw,
        "name": view.names.get(raw, raw),
        "ringSize": max(dims),
        "hostId": view.node,
    }
    coords = view.coords.get(raw)
    if coords is not None:
        for axis, coord in zip(_AXES, coords):
            out[f"ici{axis.upper()}"] = coord
        ring_axis = dims.index(max(dims))
        ring = [str(c) for i, c in enumerate(coords) if i != ring_axis]
        out["ringId"] = "/".join([view.node, generation] + ring)
    for axis, d in zip(_AXES, dims):
        out[f"torus{axis.upper()}"] = d
    if view.host_coords is not None:
        for axis, coord in zip(_AXES, view.host_coords):
            out[f"host{axis.upper()}"] = coord
    return out


# ====================================================================
# fleet-global fragmentation accounting
# ====================================================================


def _largest_free_mesh(views: Sequence[HostView],
                       pod_dims: Optional[Tuple[int, ...]]) -> int:
    """Chips in the largest wrap-aware host-grid window made entirely
    of FULLY-FREE hosts — the biggest cross-host slice placeable right
    now. 0 when the pod grid is unmodeled or fewer than two hosts are
    fully free."""
    from . import placement
    if pod_dims is None:
        return 0
    free_hosts = [v for v in views
                  if v.host_coords is not None
                  and len(v.host_coords) == len(pod_dims)
                  and len(v.free_coords()) == volume(v.dims)
                  and not v.departed]
    if len(free_hosts) < 2:
        return 0
    by_dims: Dict[Tuple[int, ...], List[HostView]] = {}
    for v in free_hosts:
        by_dims.setdefault(v.dims, []).append(v)
    best = 0
    for dims, hosts in by_dims.items():
        host_vol = volume(dims)
        slots = {v.host_coords for v in hosts}
        # windows scanned largest-volume-first so the first hit wins
        shapes = sorted(
            itertools.product(*[range(1, p + 1) for p in pod_dims]),
            key=volume, reverse=True)
        for counts in shapes:
            n = volume(counts)
            # n >= 2: a (1,1) window is a single host, not a mesh —
            # counting it would report cross-host capacity that does
            # not exist (largest_free_box already covers it)
            if n < 2 or n * host_vol <= best or n > len(slots):
                continue
            if placement._mesh_window(counts, hosts, pod_dims) is not None:
                best = n * host_vol
                break
    return best


def cluster_fragmentation(
    views_by_gen: Mapping[str, Sequence[HostView]],
    pod_dims: Optional[Tuple[int, ...]] = None,
) -> Dict[str, dict]:
    """Many hosts' fragmentation records rolled into one cluster curve
    per generation — the record the bench's fragmentation-over-churn
    curves and the defrag planner read. Pure compute over immutable
    views (lock-free by construction):

      hosts/chips/free        cluster totals
      fully_free_hosts        whole tori available for cross-host tiling
      largest_free_box        best single-host contiguous placement
      largest_free_mesh       best cross-host wrap-window placement
      fragmentation           1 - largest_placeable/free (0.0 = one
                              placement reaches every free chip)
      mean_host_fragmentation per-host scores averaged (the per-daemon
                              records' rollup)
    """
    from . import placement
    out: Dict[str, dict] = {}
    for generation, views in sorted(views_by_gen.items()):
        records = [placement.fragmentation(v) for v in views]
        free = sum(r["free"] for r in records)
        largest_box = max((r["largest_free_box"] for r in records),
                          default=0)
        largest_mesh = _largest_free_mesh(views, pod_dims)
        largest = max(largest_box, largest_mesh)
        out[generation] = {
            "hosts": len(views),
            "chips": sum(r["chips"] for r in records),
            "free": free,
            "departed": sum(r["departed"] for r in records),
            "fully_free_hosts": sum(
                1 for v in views
                if len(v.free_coords()) == volume(v.dims)
                and not v.departed),
            "largest_free_box": largest_box,
            "largest_free_mesh": largest_mesh,
            "fragmentation": 0.0 if free == 0
            else round(1.0 - largest / free, 4),
            "mean_host_fragmentation": round(
                sum(r["fragmentation"] for r in records)
                / max(1, len(records)), 4),
        }
    return out


# ====================================================================
# fleet flight collector (the cross-node trace waterfall, ISSUE 15)
# ====================================================================


class FleetFlight:
    """Scheduler-side flight collector: merges per-node ``/debug/flight``
    rings into ONE cross-node, cross-process waterfall for a trace id —
    the ``/debug/fleet/trace?trace=`` body.

    Sources are named fetch callables taking a query dict ({"trace":
    id}) and returning the /debug/flight JSON shape ({"spans": [...]}).
    ``add_http_source`` pulls a real daemon's endpoint over HTTP (the
    production deployment shape); fleetsim builds in-process sources of
    the SAME shape (FleetSim.fleet_flight) — one per node, filtered by
    the ``node`` attribute its driver stamps on every RPC span. A
    source that fails to answer degrades to a per-source error note
    (an incident view must render the nodes that DID answer).

    Merging dedupes by the records' process-unique identity
    ((thread, seq, ts, op) — per-node sources backed by one shared
    in-process recorder overlap by construction), labels every record
    with its node (the span's own ``node`` attr wins over the source
    name), and returns the records time-ordered: the waterfall."""

    def __init__(self) -> None:
        self._sources: Dict[str, Callable[[dict], dict]] = {}

    def add_source(self, name: str,
                   fetch: Callable[[dict], dict]) -> None:
        self._sources[name] = fetch

    def add_http_source(self, name: str, base_url: str,
                        timeout_s: float = 5.0) -> None:
        """Pull `name`'s flight ring from its status endpoint
        (`<base_url>/debug/flight?trace=...`) — the real-deployment
        source shape."""
        import urllib.parse
        import urllib.request

        base = base_url.rstrip("/")

        def fetch(query: dict) -> dict:
            qs = urllib.parse.urlencode(
                {k: v for k, v in query.items() if v is not None})
            with urllib.request.urlopen(
                    f"{base}/debug/flight?{qs}", timeout=timeout_s) as r:
                return json.loads(r.read())
        self.add_source(name, fetch)

    def add_local_source(self, name: str = "local") -> None:
        """THIS process's recorder as a source (the single-daemon
        deployment: /debug/fleet/trace serves the local ring until an
        operator registers the fleet's endpoints)."""
        self.add_source(
            name, lambda query: {"spans": trace.snapshot(
                trace=query.get("trace"))})

    def sources(self) -> List[str]:
        return sorted(self._sources)

    def trace(self, trace_id: str, limit: Optional[int] = None) -> dict:
        """The merged waterfall for one trace id: every source's
        matching records (own trace_id OR span-link match — the
        migration-handoff joins), deduped, node-labeled, time-ordered.
        `limit` keeps the newest N after the merge."""
        merged: List[dict] = []
        seen: set = set()
        errors: Dict[str, str] = {}
        for name, fetch in sorted(self._sources.items()):
            try:
                body = fetch({"trace": trace_id})
            except Exception as exc:
                errors[name] = str(exc)
                continue
            for rec in body.get("spans") or ():
                key = (rec.get("thread"), rec.get("seq"),
                       rec.get("ts"), rec.get("op"))
                if key in seen:
                    continue
                seen.add(key)
                rec = dict(rec)
                rec["node"] = (rec.get("attrs") or {}).get("node") or name
                merged.append(rec)
        merged.sort(key=lambda r: (r.get("ts", 0), r.get("seq", 0)))
        if limit is not None and limit >= 0:
            merged = merged[len(merged) - min(limit, len(merged)):]
        # nodes/ops summarize the RETURNED page (post-limit), so a
        # limited body is internally consistent — never a node with
        # zero spans in the waterfall it headlines
        return {
            "trace": trace_id,
            "spans": merged,
            "nodes": sorted({r["node"] for r in merged}),
            "ops": sorted({r["op"] for r in merged}),
            "sources": len(self._sources),
            "source_errors": errors,
        }


# ====================================================================
# the scheduler
# ====================================================================


class FleetScheduler:
    """Cluster-wide slice scheduler over the published topology.

    Views come from the reflector-fed SliceCache (production shape) or
    a `views_source` callable returning {generation: [HostView]}
    (tests/benches without a watch plane). Decisions execute through an
    `executor` — fleetsim.FleetSim is the reference implementation
    (`execute_plan` / `release_plan` / `apply_defrag`), carrying the
    fabric's cross-node multiclaim records — and EVERY lifecycle step
    lands in one commit log: decision → per-node sub-claims → rollback/
    commit, audited exactly-once by `audit()`. All reads (selector
    filtering, views, fragmentation) are lock-free snapshot reads
    bracketed by lockdep read paths, pinned at zero lock acquisitions
    by tests/test_fleetplace.py."""

    def __init__(self, executor=None,
                 cache: Optional[SliceCache] = None,
                 reflector=None,
                 views_source: Optional[Callable[[], Mapping[
                     str, Sequence[HostView]]]] = None,
                 pod_dims: Optional[Tuple[int, ...]] = None) -> None:
        if cache is None and views_source is None:
            raise ValueError("FleetScheduler needs a SliceCache or a "
                             "views_source")
        self.executor = executor
        self.cache = cache
        self.reflector = reflector
        self._views_source = views_source
        self.pod_dims = tuple(pod_dims) if pod_dims else None
        # claim ledger: uid -> ((sub_uid, node, raws), ...) — each
        # shard carries its node-level claim identity, minted at
        # placement (`<uid>-<node>`) and KEPT across defrag migrations
        # (the node checkpoints know the claim by that id wherever it
        # lives now). Copy-on-write swaps keep readers lock-free (the
        # GIL makes the attribute store atomic).
        self._claims: Dict[str, Tuple] = {}
        # identity-memoized cluster views: both the cache snapshot and
        # the ledger are swapped wholesale (never mutated), so reusing
        # the parse while both references are unchanged is exact —
        # steady-state reads stop re-parsing 2048 device entries per
        # decision at 256 nodes
        self._views_memo: Optional[Tuple] = None
        self._claims_lock = lockdep.instrument(
            "fleetplace.FleetScheduler._claims_lock", threading.Lock())
        # THE commit log: (kind, uid, detail) tuples, append-only.
        # list.append is GIL-atomic; audit() reads a C-atomic copy.
        self._log: List[Tuple[str, str, object]] = []
        self._selectors: Dict[str, CompiledSelector] = {}
        self._selector_lock = lockdep.instrument(
            "fleetplace.FleetScheduler._selector_lock", threading.Lock())
        self.stats = {key: AtomicCounter() for key in (
            "decisions_total", "placed_total", "unplaceable_total",
            "rollbacks_total", "releases_total", "defrag_waves_total",
            "defrag_moves_total", "selector_compile_errors_total",
            "bias_applied_total", "bias_cleared_total",
            "drains_planned_total")}
        # remediation seam: nodes the self-heal plane is steering new
        # placements away from (exemplar->node attribution pinned a
        # host). Copy-on-write frozenset — the zero-lock decision read
        # path reads the reference GIL-atomically; writes (rare, one
        # per remediation action) serialize on _bias_lock.
        self._avoid_nodes: frozenset = frozenset()
        self._bias_lock = lockdep.instrument(
            "fleetplace.FleetScheduler._bias_lock", threading.Lock())

    # ------------------------------------------------------- control

    def start(self) -> None:
        if self.reflector is not None:
            self.reflector.start()

    def stop(self) -> None:
        if self.reflector is not None:
            self.reflector.stop()

    def wait_synced(self, timeout_s: float = 10.0,
                    min_slices: int = 0) -> bool:
        """Block until the reflector's first LIST seeded the cache (and
        at least `min_slices` slices are visible) — the scheduler's
        boot barrier. True on sync, False on timeout."""
        if self.cache is None:
            return True
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if self.cache.syncs.value > 0 \
                    and len(self.cache.snapshot()) >= min_slices:
                return True
            time.sleep(0.02)
        return False

    # ------------------------------------------------- views + selectors

    def selector(self, text: str) -> CompiledSelector:
        """Compile-once cache: one CompiledSelector per expression text,
        its stats accumulating across decisions. Compile failures count
        and re-raise (SelectorError)."""
        text = (text or "").strip()
        compiled = self._selectors.get(text)    # lock-free hit
        if compiled is not None:
            return compiled
        try:
            compiled = compile_selector(text)
        except SelectorError:
            self.stats["selector_compile_errors_total"].add()
            raise
        with self._selector_lock:
            compiled = self._selectors.setdefault(text, compiled)
        return compiled

    def views_by_generation(self) -> Tuple[
            Dict[str, List[HostView]],
            Dict[Tuple[str, str], Dict[str, Dict[str, object]]]]:
        """The merged cluster view: every daemon's published host view
        + the scheduler's own ledger. Lock-free snapshot reads. In
        views_source mode the attribute index is SYNTHESIZED from the
        views with the same fields the daemon publishes, so selectors
        behave identically with or without a watch plane."""
        if self.cache is not None:
            snap = self.cache.snapshot()
            claims = self._claims
            memo = self._views_memo
            if memo is not None and memo[0] is snap \
                    and memo[1] is claims:
                return memo[2], memo[3]
            views, idx = host_views_from_slices(snap, claims)
            self._views_memo = (snap, claims, views, idx)
            return views, idx
        views = {gen: list(vs)
                 for gen, vs in self._views_source().items()}
        attrs_index: Dict[Tuple[str, str],
                          Dict[str, Dict[str, object]]] = {}
        for gen, vs in views.items():
            for view in vs:
                attrs_index[(view.node, gen)] = {
                    raw: _view_attrs(gen, view, raw)
                    for raw in view.coords}
        return views, attrs_index

    @staticmethod
    def _filter_views(views_by_gen: Mapping[str, Sequence[HostView]],
                      attrs_index, compiled: CompiledSelector
                      ) -> Dict[str, List[HostView]]:
        """Per-generation selector filtering: each view's FREE set
        narrows to the chips whose published attributes match; a view
        left with no matching free chip still participates as occupancy
        (its claims can still block boxes) but offers nothing."""
        out: Dict[str, List[HostView]] = {}
        for generation, views in views_by_gen.items():
            filtered: List[HostView] = []
            for view in views:
                index = attrs_index.get((view.node, generation))
                if compiled._fn is None or index is None:
                    filtered.append(view)
                    continue
                keep = frozenset(
                    raw for raw in view.free
                    if compiled.matches(index.get(raw, {})))
                if keep != view.free:
                    view = replace(view, free=keep)
                filtered.append(view)
            out[generation] = filtered
        return out

    def eligible_views(self, selector_text: str = ""
                       ) -> Tuple[List[HostView], CompiledSelector]:
        """Selector-filtered cluster views, flattened across
        generations. Runs inside the `fleetplace.select` read-path
        bracket — zero registered locks, counted."""
        compiled = self.selector(selector_text)
        with lockdep.read_path("fleetplace.select"):
            views_by_gen, attrs_index = self.views_by_generation()
            filtered = self._filter_views(views_by_gen, attrs_index,
                                          compiled)
            avoid = self._avoid_nodes          # GIL-atomic ref read
            out = []
            for views in filtered.values():
                for v in views:
                    if v.free and v.node in avoid:
                        # biased-away host: still occupancy (its claims
                        # keep blocking boxes) but offers no capacity
                        v = replace(v, free=frozenset())
                    out.append(v)
            return out, compiled

    # ---------------------------------------------------- decisions

    def _note(self, kind: str, uid: str, detail=None) -> None:
        self._log.append((kind, uid, detail))

    def schedule(self, shape, uid: str, selector: str = "",
                 best_effort: bool = False,
                 fail_node: Optional[str] = None) -> dict:
        """One cluster placement decision end-to-end: selector-filtered
        views → plan (cross-host mesh aware) → execution through the
        multiclaim fabric — logged decision → sub-claims → rollback/
        commit, spanned on the flight recorder."""
        from . import placement
        shape = placement.parse_shape(shape)
        self.stats["decisions_total"].add()
        with trace.span("fleetplace.schedule", claim_uid=uid,
                        shape="x".join(str(d) for d in shape),
                        selector=selector or ""):
            # the decision's trace id is THE fleet trace handle: shard
            # prepares, broker crossings and later migration handoffs
            # all join it, and every schedule() result returns it so a
            # caller can open /debug/fleet/trace?trace= directly
            ctx = trace.current_context()
            trace_id = ctx["trace_id"] if ctx else None
            views, _compiled = self.eligible_views(selector)
            plan = placement.plan_slice(shape, views,
                                        best_effort=best_effort,
                                        pod_dims=self.pod_dims)
            self._note("decided", uid, {
                "shape": list(shape), "selector": selector or "",
                "shards": None if plan is None
                else [[n, list(r)] for n, r in plan.shards]})
            if plan is None:
                self.stats["unplaceable_total"].add()
                self._note("unplaceable", uid, None)
                trace.event("fleetplace.unplaceable", claim_uid=uid)
                return {"uid": uid, "placed": False,
                        "reason": "unplaceable", "trace_id": trace_id}
            if self.executor is None:
                # plan-only mode (dry runs / what-if): the decision is
                # logged as advisory, never committed
                self._note("advisory", uid, None)
                return {"uid": uid, "placed": True, "advisory": True,
                        "trace_id": trace_id,
                        "score": plan.score, "hosts": plan.hosts,
                        "shards": [(n, list(r)) for n, r in plan.shards]}
            result = self.executor.execute_plan(
                plan, uid, fail_node=fail_node, observer=self._note)
            result.setdefault("trace_id", trace_id)
            if result.get("placed"):
                with self._claims_lock:
                    fresh = dict(self._claims)
                    fresh[uid] = tuple(
                        (f"{uid}-{node}", node, tuple(raws))
                        for node, raws in plan.shards)
                    self._claims = fresh
                self.stats["placed_total"].add()
            else:
                self.stats["rollbacks_total"].add()
            return result

    def release(self, uid: str) -> bool:
        """Release a committed decision's sub-claims node-by-node (the
        tenant went away). Each shard is released by its LEDGER
        identity (sub_uid, current node) — correct even after a defrag
        wave moved the claim to a different host. Logged; the ledger
        swap keeps readers lock-free."""
        shards = self._claims.get(uid)
        if shards is None:
            return False
        with trace.span("fleetplace.release", claim_uid=uid):
            if self.executor is not None:
                self.executor.release_subclaims(
                    [(sub_uid, node) for sub_uid, node, _raws in shards])
            with self._claims_lock:
                fresh = dict(self._claims)
                fresh.pop(uid, None)
                self._claims = fresh
            self._note("released", uid, None)
            self.stats["releases_total"].add()
        return True

    # --------------------------------------- remediation seams (PR 16)

    def bias_away(self, node: str, reason: str = "") -> bool:
        """Steer NEW placements off `node`: its free chips stop being
        offered while its existing claims keep participating as
        occupancy. Idempotent; logged and counted. The remediation
        engine applies this when exemplar->node attribution keeps
        surfacing one host under a burning SLO, and clears it on
        recovery (clear_bias)."""
        with self._bias_lock:
            if node in self._avoid_nodes:
                return False
            self._avoid_nodes = self._avoid_nodes | {node}
        self.stats["bias_applied_total"].add()
        self._note("bias_applied", node, {"reason": reason})
        trace.event("fleetplace.bias_applied", node=node,
                    reason=reason)
        return True

    def clear_bias(self, node: str) -> bool:
        """Rollback of bias_away: the node offers capacity again."""
        with self._bias_lock:
            if node not in self._avoid_nodes:
                return False
            self._avoid_nodes = self._avoid_nodes - {node}
        self.stats["bias_cleared_total"].add()
        self._note("bias_cleared", node, None)
        trace.event("fleetplace.bias_cleared", node=node)
        return True

    def biased_nodes(self) -> Tuple[str, ...]:
        return tuple(sorted(self._avoid_nodes))

    def plan_drain(self, node: str,
                   generation: Optional[str] = None) -> dict:
        """Plan draining every scheduler-placed claim shard off `node`
        through the SAME handoff path a defrag wave uses: the returned
        proposal feeds apply_defrag_wave unchanged (unprepare → durable
        handoff record → re-point fabric claim → import at the
        destination, ledger re-pointed move-by-move).

        Destinations are chosen most-free-first within the node's own
        generation, capacity reserved move-by-move; a shard with no
        destination is advised with target_node None (apply skips it —
        a partial drain is honest, not silent)."""
        views_by_gen, _ = self.views_by_generation()
        if generation is None:
            for gen, views in views_by_gen.items():
                if any(v.node == node for v in views):
                    generation = gen
                    break
        views = views_by_gen.get(generation) or []
        source = next((v for v in views if v.node == node), None)
        migrations: List[dict] = []
        if source is not None:
            targets = sorted(
                (v for v in views
                 if v.node != node and v.node not in self._avoid_nodes),
                key=lambda v: (-len(v.free), v.node))
            reserved: Dict[str, set] = {}
            for uid in sorted(source.claims):
                raws = sorted(source.claims[uid])
                mig = {"claim": uid, "source_node": node,
                       "devices": raws,
                       "target_node": None, "target_devices": None}
                for tv in targets:
                    avail = sorted(tv.free - reserved.get(tv.node,
                                                          set()))
                    if len(avail) >= len(raws):
                        picked = avail[:len(raws)]
                        reserved.setdefault(tv.node,
                                            set()).update(picked)
                        mig["target_node"] = tv.node
                        mig["target_devices"] = picked
                        break
                migrations.append(mig)
        self.stats["drains_planned_total"].add()
        resolved = sum(1 for m in migrations
                       if m["target_node"] is not None)
        self._note("drain_planned", node, {
            "generation": generation, "moves": len(migrations),
            "resolved": resolved})
        return {"node": node, "generation": generation,
                "migrations": migrations,
                "moves": len(migrations), "resolved": resolved}

    # ------------------------------------------------- fragmentation

    def fragmentation(self) -> Dict[str, dict]:
        """Fleet-global fragmentation rollup (cluster curves), read
        lock-free inside the `fleetplace.frag` bracket."""
        with lockdep.read_path("fleetplace.frag"):
            views_by_gen, _ = self.views_by_generation()
            return cluster_fragmentation(views_by_gen,
                                         pod_dims=self.pod_dims)

    def plan_defrag_wave(self, shape, generation: Optional[str] = None,
                         selector: str = "") -> dict:
        """Plan one globally-coordinated defrag wave: the cluster-wide
        advisory (placement.propose_defrag over EVERY host's view, so
        migration targets resolve across the fleet) plus the rollup
        curves before the wave. Raises ValueError (typed, HTTP-400
        shaped) when the named generation has no host view."""
        from . import placement
        shape = placement.parse_shape(shape)
        views_by_gen, attrs_index = self.views_by_generation()
        if generation is None and len(views_by_gen) == 1:
            generation = next(iter(views_by_gen))
        views = views_by_gen.get(generation)
        if not views:
            raise ValueError(
                f"unknown generation {generation!r}; have "
                f"{sorted(views_by_gen)}")
        if selector:
            # filter WITHIN the named generation only: a node serving
            # several generations must not leak its other tori into
            # this advisory as free capacity
            views = self._filter_views(
                {generation: views}, attrs_index,
                self.selector(selector))[generation]
        proposal = placement.propose_defrag(shape, views)
        proposal["generation"] = generation
        proposal["cluster_fragmentation"] = cluster_fragmentation(
            {generation: views}, pod_dims=self.pod_dims)[generation]
        return proposal

    def apply_defrag_wave(self, proposal: dict) -> dict:
        """Apply a planned wave NODE-BY-NODE through the PR 7 handoff
        machinery: migrations grouped by source node, each group one
        executor.apply_defrag call (unprepare → durable handoff record
        → re-point fabric claim → import + validate at destination),
        every move logged and spanned. Returns the wave report."""
        if self.executor is None:
            raise RuntimeError("no executor attached")
        migrations = [m for m in proposal.get("migrations", ())
                      if m.get("target_node") is not None]
        by_source: Dict[str, List[dict]] = {}
        for mig in migrations:
            by_source.setdefault(mig["source_node"], []).append(mig)
        # counted at wave START so a retried wave after a mid-apply
        # failure gets a fresh id in the log
        self.stats["defrag_waves_total"].add()
        wave_id = f"wave-{self.stats['defrag_waves_total'].value}"
        moves = 0
        with trace.span("fleetplace.defrag.wave", wave=wave_id):
            self._note("defrag_wave", wave_id,
                       {"moves_planned": len(migrations)})
            for node in sorted(by_source):
                group = by_source[node]
                with trace.span("fleetplace.defrag.node", node=node,
                                moves=len(group)):
                    # one executor call PER migration: the ledger
                    # re-point and the log entry land immediately after
                    # each completed move, so a failure mid-group
                    # leaves every already-moved claim's ledger shard
                    # naming its REAL new home (a later release then
                    # unprepares the right node)
                    for mig in group:
                        applied = self.executor.apply_defrag(
                            {"migrations": [mig]})
                        moves += applied
                        self._migrate_ledger(mig)
                        self._note("defrag_move", mig["claim"], {
                            "wave": wave_id, "source": node,
                            "target": mig["target_node"]})
                        self.stats["defrag_moves_total"].add()
        return {"wave": wave_id, "moves_planned": len(migrations),
                "moves_applied": moves}

    def _migrate_ledger(self, mig: dict) -> None:
        """Re-point a migrated claim's ledger shard at its new home.
        The advisory names the NODE-LEVEL claim id (the views' claims
        maps are sub-uid-keyed), so resolve it back to its ledger
        parent; the sub-uid itself is KEPT — the destination driver
        imported the handoff under that id, and a later release must
        unprepare by it. A migration of a claim the scheduler never
        placed (a direct/foreign tenant) is a no-op here — the drivers'
        own state is ground truth for those."""
        sub_uid = mig["claim"]
        # resolve AND rebuild under the ledger lock like every other
        # writer: a racing release() popping the parent between a
        # lock-free lookup and the swap would be resurrected by the
        # stale re-insert (permanently busy chips, failing releases)
        with self._claims_lock:
            parent = None
            for uid, shards in self._claims.items():
                if any(s == sub_uid for s, _n, _r in shards):
                    parent = uid
                    break
            if parent is None:
                return
            fresh_shards = tuple(
                (s, mig["target_node"],
                 tuple(mig.get("target_devices") or ()))
                if s == sub_uid else (s, node, raws)
                for s, node, raws in self._claims[parent])
            fresh = dict(self._claims)
            fresh[parent] = fresh_shards
            self._claims = fresh

    # ----------------------------------------------------- the audit

    def audit(self, fabric_audit: Optional[dict] = None) -> dict:
        """Exactly-once over THE commit log — one log spanning scheduler
        decision → per-node sub-claims → rollback/commit, cluster-wide:

          - every uid's first entry is its decision;
          - at most ONE commit per uid, and nothing after it;
          - every abort is clean: each sub-claim prepared since the
            latest decision was rolled back first.

        `fabric_audit` (FleetApiServer.multiclaim_audit()) cross-checks
        the fabric's view: the sets of committed uids must agree — a
        commit only one side knows is a lost or replayed claim."""
        entries = list(self._log)          # C-atomic copy
        by_uid: Dict[str, List[Tuple[str, object]]] = {}
        for kind, uid, detail in entries:
            if kind in ("defrag_wave", "bias_applied", "bias_cleared",
                        "drain_planned"):
                continue
            by_uid.setdefault(uid, []).append((kind, detail))
        duplicated: List[str] = []
        undecided: List[str] = []
        dirty_aborts: List[str] = []
        post_commit: List[str] = []
        committed: List[str] = []
        for uid, seq in sorted(by_uid.items()):
            kinds = [k for k, _d in seq]
            if kinds and kinds[0] not in ("decided", "defrag_move",
                                          "released"):
                undecided.append(uid)
            n_commit = kinds.count("committed")
            if n_commit > 1:
                duplicated.append(uid)
            if n_commit:
                committed.append(uid)
                # a committed claim may later be released or migrated
                # by a defrag wave; anything else after its commit is
                # a replayed decision
                after = kinds[kinds.index("committed") + 1:]
                if any(k not in ("released", "defrag_move")
                       for k in after):
                    post_commit.append(uid)
            prepared: set = set()
            for kind, detail in seq:
                if kind == "decided":
                    prepared = set()
                elif kind == "shard_prepared":
                    prepared.add(detail)
                elif kind == "shard_rolled_back":
                    prepared.discard(detail)
                elif kind == "aborted" and prepared:
                    dirty_aborts.append(uid)
                    break
        out = {
            "decisions_audited": len(by_uid),
            "committed": sorted(committed),
            "duplicated_commits": sorted(duplicated),
            "undecided_commits": sorted(undecided),
            "dirty_aborts": sorted(dirty_aborts),
            "entries_after_commit": sorted(post_commit),
            "exactly_once": not (duplicated or undecided or dirty_aborts
                                 or post_commit),
        }
        if fabric_audit is not None:
            fabric_committed = set(fabric_audit.get("committed") or ())
            ours = set(committed)
            out["fabric_agrees"] = (
                fabric_audit.get("exactly_once", False)
                and fabric_committed == ours)
            out["fabric_only"] = sorted(fabric_committed - ours)
            out["scheduler_only"] = sorted(ours - fabric_committed)
            out["exactly_once"] = (out["exactly_once"]
                                   and out["fabric_agrees"])
        return out

    def snapshot(self) -> dict:
        """Lock-free stats read: AtomicCounter sums + ledger/log sizes
        (GIL-atomic len reads)."""
        out = {key: counter.value for key, counter in self.stats.items()}
        out["biased_nodes"] = list(self.biased_nodes())
        out["claims"] = len(self._claims)
        out["log_entries"] = len(self._log)
        out["selectors_compiled"] = len(self._selectors)
        if self.reflector is not None:
            out["reflector"] = self.reflector.snapshot()
        if self.cache is not None:
            out["cache_slices"] = len(self.cache.snapshot())
            out["cache_syncs"] = self.cache.syncs.value
            out["cache_events"] = self.cache.events.value
        return out
