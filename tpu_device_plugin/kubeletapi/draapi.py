"""Hand-rolled gRPC wiring for the kubelet DRA + plugin-registration APIs.

Same approach as api.py (no grpc codegen plugin in the image): generic
handlers registered under the UPSTREAM service paths. The local descriptor
package for the DRA messages is `dra.v1beta1` (see proto/dra_v1beta1.proto
for why), but the wire method paths below carry the published service names
`v1beta1.DRAPlugin` and `pluginregistration.Registration` — those, plus the
field numbers, ARE the kubelet contract (locked by tests/test_kubeletapi.py).
"""

from __future__ import annotations

import grpc

from . import dra_v1beta1_pb2 as drapb
from . import pluginregistration_v1_pb2 as regpb
from .api import raw_or

# -- kubelet contract constants ------------------------------------------------
DRA_API_VERSION = "v1beta1"
# Every version this driver serves, newest first. Upstream promoted the DRA
# kubelet gRPC API to v1 with messages field-number-identical to v1beta1
# (only the service path changes: v1.DRAPlugin vs v1beta1.DRAPlugin), so one
# servicer + one descriptor set serves both; the kubelet picks the newest
# version it supports from GetInfo.supported_versions. A kubelet that has
# dropped v1beta1 would otherwise strand the driver (VERDICT r3 item 7).
DRA_API_VERSIONS = ("v1", "v1beta1")
# The kubelet watches this directory for registration sockets.
PLUGINS_REGISTRY_PATH = "/var/lib/kubelet/plugins_registry/"
# Per-driver service sockets live under here.
PLUGINS_PATH = "/var/lib/kubelet/plugins/"
DRA_PLUGIN_TYPE = "DRAPlugin"

_DRA_SERVICES = tuple(f"{v}.DRAPlugin" for v in DRA_API_VERSIONS)
_DRA_SERVICE = "v1beta1.DRAPlugin"   # historical default (stub, tests)
_PLUGIN_REGISTRATION_SERVICE = "pluginregistration.Registration"


class DraPluginServicer:
    """Server-side interface for the DRAPlugin service (2 RPCs)."""

    def NodePrepareResources(self, request, context):
        context.abort(grpc.StatusCode.UNIMPLEMENTED, "NodePrepareResources")

    def NodeUnprepareResources(self, request, context):
        context.abort(grpc.StatusCode.UNIMPLEMENTED, "NodeUnprepareResources")


def add_dra_plugin_servicer(server: grpc.Server,
                            servicer: DraPluginServicer) -> None:
    """Register `servicer` under EVERY advertised DRA service path.

    The v1 and v1beta1 messages are field-number-identical, so the same
    deserializers serve both; a kubelet dialing either version reaches the
    same handlers."""
    handlers = {
        "NodePrepareResources": grpc.unary_unary_rpc_method_handler(
            servicer.NodePrepareResources,
            request_deserializer=drapb.NodePrepareResourcesRequest.FromString,
            # RawResponse passthrough (api.py): prepare acks are assembled
            # from pre-serialized per-claim segments on the gRPC path
            response_serializer=raw_or(
                drapb.NodePrepareResourcesResponse.SerializeToString),
        ),
        "NodeUnprepareResources": grpc.unary_unary_rpc_method_handler(
            servicer.NodeUnprepareResources,
            request_deserializer=(
                drapb.NodeUnprepareResourcesRequest.FromString),
            response_serializer=(
                drapb.NodeUnprepareResourcesResponse.SerializeToString),
        ),
    }
    server.add_generic_rpc_handlers(tuple(
        grpc.method_handlers_generic_handler(service, handlers)
        for service in _DRA_SERVICES))


class DraPluginStub:
    """Client stub for the DRAPlugin service (what the kubelet dials).

    `version` selects the service path a specific kubelet generation would
    dial ("v1beta1" default; "v1" for the GA API)."""

    def __init__(self, channel: grpc.Channel, version: str = DRA_API_VERSION):
        service = f"{version}.DRAPlugin"
        self.NodePrepareResources = channel.unary_unary(
            f"/{service}/NodePrepareResources",
            request_serializer=(
                drapb.NodePrepareResourcesRequest.SerializeToString),
            response_deserializer=(
                drapb.NodePrepareResourcesResponse.FromString),
        )
        self.NodeUnprepareResources = channel.unary_unary(
            f"/{service}/NodeUnprepareResources",
            request_serializer=(
                drapb.NodeUnprepareResourcesRequest.SerializeToString),
            response_deserializer=(
                drapb.NodeUnprepareResourcesResponse.FromString),
        )


class PluginRegistrationServicer:
    """Server-side interface for pluginregistration.Registration.

    Served by the PLUGIN on its plugins_registry socket; the kubelet dials
    it (the inverse of the device-plugin flow, where the plugin dials
    kubelet.sock — reference: generic_device_plugin.go:288-309).
    """

    def GetInfo(self, request, context):
        context.abort(grpc.StatusCode.UNIMPLEMENTED, "GetInfo")

    def NotifyRegistrationStatus(self, request, context):
        context.abort(grpc.StatusCode.UNIMPLEMENTED,
                      "NotifyRegistrationStatus")


def add_plugin_registration_servicer(
        server: grpc.Server, servicer: PluginRegistrationServicer) -> None:
    handlers = {
        "GetInfo": grpc.unary_unary_rpc_method_handler(
            servicer.GetInfo,
            request_deserializer=regpb.InfoRequest.FromString,
            response_serializer=regpb.PluginInfo.SerializeToString,
        ),
        "NotifyRegistrationStatus": grpc.unary_unary_rpc_method_handler(
            servicer.NotifyRegistrationStatus,
            request_deserializer=regpb.RegistrationStatus.FromString,
            response_serializer=(
                regpb.RegistrationStatusResponse.SerializeToString),
        ),
    }
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(
            _PLUGIN_REGISTRATION_SERVICE, handlers),))


class PluginRegistrationStub:
    """Client stub for pluginregistration.Registration (fake kubelet in tests)."""

    def __init__(self, channel: grpc.Channel):
        self.GetInfo = channel.unary_unary(
            f"/{_PLUGIN_REGISTRATION_SERVICE}/GetInfo",
            request_serializer=regpb.InfoRequest.SerializeToString,
            response_deserializer=regpb.PluginInfo.FromString,
        )
        self.NotifyRegistrationStatus = channel.unary_unary(
            f"/{_PLUGIN_REGISTRATION_SERVICE}/NotifyRegistrationStatus",
            request_serializer=regpb.RegistrationStatus.SerializeToString,
            response_deserializer=regpb.RegistrationStatusResponse.FromString,
        )
