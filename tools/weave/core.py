"""weave core — a loom-style deterministic schedule explorer.

One scenario = a handful of threads running REAL production code whose
concurrency seams have been virtualized:

- ``threading.Lock/RLock/Condition/Event/Thread`` constructed inside a
  run are replaced by cooperative shims: every acquire/release/wait/
  notify/set/start/join is a *schedule point* where the calling thread
  parks and the controller decides who runs next.
- ``schedcheck.yield_point(...)`` calls in production code (the marked
  C-atomic accesses of the lock-free planes) become schedule points
  the same way.
- ``time.monotonic``/``time.sleep`` read a virtual clock. A timed wait
  never fires while any thread is runnable: when the run quiesces, the
  clock jumps to the earliest pending deadline. An UNTIMED wait that is
  never notified is therefore a detected deadlock — exactly the
  lost-wakeup class of bug.

Exactly one logical thread runs at a time (each parked on its own real
`Event`), so an execution is a deterministic function of the schedule —
the sequence of thread choices. The explorer enumerates schedules with
Flanagan–Godefroid dynamic partial-order reduction (per-step backtrack
sets seeded from the last dependent access to the same object), an
optional preemption bound, and a per-scenario execution budget. A
failing execution yields a counterexample whose schedule replays the
exact interleaving deterministically.
"""

from __future__ import annotations

import _thread
import json
import sys
import threading
import time
import traceback
from typing import (Any, Callable, Dict, List, Optional, Sequence, Set,
                    Tuple)

from tpu_device_plugin import schedcheck

__all__ = [
    "Counterexample", "DeadlockError", "ExploreResult", "Scenario",
    "WeaveError", "WeaveHang", "explore", "replay", "run_once",
    "WeaveLock", "WeaveRLock", "WeaveCondition", "WeaveEvent",
    "WeaveThread",
]

# real primitives, captured before any patching. Controlled threads are
# started with _thread.start_new_thread, NOT threading.Thread, and the
# harness parks them on raw _thread locks: anything from the threading
# module (Thread, Event, even a pre-captured Event CLASS) resolves
# Lock/Condition from the threading namespace at call time, which inside
# a run would hand the harness its own cooperative shims.
_REAL_CURRENT_THREAD = threading.current_thread
_REAL_GET_IDENT = threading.get_ident
_REAL_MONOTONIC = time.monotonic
_REAL_SLEEP = time.sleep


class _Gate:
    """Auto-reset event on a raw C lock — safe to use under the patch."""

    __slots__ = ("_lk",)

    def __init__(self) -> None:
        self._lk = _thread.allocate_lock()
        self._lk.acquire()             # start closed

    def wait(self, timeout: Optional[float] = None) -> bool:
        if timeout is None:
            self._lk.acquire()
            return True
        return self._lk.acquire(True, timeout)

    def set(self) -> None:
        try:
            self._lk.release()
        except RuntimeError:
            pass                       # already open: saturate

    def clear(self) -> None:
        self._lk.acquire(False)        # drain a stale set, never block

# a controlled thread stuck in a REAL blocking call longer than this is
# a harness bug (or un-virtualized blocking in production code) — fail
# loudly with stacks instead of hanging CI
_WATCHDOG_S = 30.0

_MAX_STEPS_DEFAULT = 20_000


class WeaveError(Exception):
    """Scenario/harness error (not an invariant violation)."""


class DeadlockError(WeaveError):
    """No thread runnable, no pending deadline: a lost wakeup."""


class WeaveHang(WeaveError):
    """A controlled thread blocked in real (un-virtualized) code."""


class _ReapSignal(BaseException):
    """Raised inside abandoned threads to unwind them after a verdict."""


# --------------------------------------------------------------- model ops

# op kinds that touch a keyed location but are NOT conflict points for
# the dependency relation (see the dep_log comment in _Run.run_until)
_NONCONFLICT_KINDS = frozenset({"release", "wakeup"})


class _Op:
    """One announced schedule point: what the thread will do next.

    `key`   identifies the shared object (dependency equivalence class).
    `mode`  "r" or "w" — two ops are dependent iff same key and not
            both reads.
    `deadline` — virtual-clock instant at which a blocked op becomes
            enabled (timed waits/sleeps/joins); None = untimed.
    """

    __slots__ = ("kind", "label", "key", "mode", "deadline",
                 "enabled", "execute")

    def __init__(self, kind: str, label: str, key: Optional[int],
                 mode: str = "w",
                 deadline: Optional[float] = None,
                 enabled: Optional[Callable[[], bool]] = None,
                 execute: Optional[Callable[[], None]] = None) -> None:
        self.kind = kind
        self.label = label
        self.key = key
        self.mode = mode
        self.deadline = deadline
        self.enabled = enabled or _always
        self.execute = execute or _noop

    def depends(self, other: "_Op") -> bool:
        if self.key is None or other.key is None:
            return False
        if self.key != other.key:
            return False
        return not (self.mode == "r" and other.mode == "r")

    def __repr__(self) -> str:
        return self.label


def _always() -> bool:
    return True


def _noop() -> None:
    return None


def _name(obj: object) -> str:
    return f"{type(obj).__name__}#{id(obj) & 0xFFFF:04x}"


class _VThread:
    """One controlled logical thread (backed by a real thread that only
    ever runs while the controller has handed it the baton)."""

    def __init__(self, run: "_Run", name: str,
                 fn: Callable[[], None]) -> None:
        self.run = run
        self.name = name
        self.fn = fn
        self.go = _Gate()
        self.pending: Optional[_Op] = None
        self.finished = False
        self.exc: Optional[BaseException] = None
        self.notified = False          # condition wakeup flag
        self.shim: Optional["WeaveThread"] = None   # threading.Thread shim
        self.ident: Optional[int] = None
        self.done = _Gate()      # set when the real thread exits

    def _main(self) -> None:
        # initial park at "begin" WITHOUT signaling the controller: the
        # spawner synchronizes on `pending` becoming visible, and the
        # thread only starts running user code when first scheduled
        self.pending = _Op("begin", f"begin:{self.name}", None)
        self.go.wait()
        self.go.clear()
        self.pending = None
        try:
            if not self.run._reaping:
                self.fn()
        except _ReapSignal:
            pass
        except BaseException as exc:      # noqa: BLE001 — reported as CE
            self.exc = exc
        finally:
            self.finished = True
            self.run._ctrl.set()
            self.done.set()


class _Clock:
    def __init__(self) -> None:
        self.now = 1000.0              # arbitrary epoch, away from zero
        self.advances = 0

    def advance_to(self, t: float) -> None:
        if t > self.now:
            self.now = t
            self.advances += 1


class _Run:
    """One execution: the controller state shared with the shims."""

    def __init__(self, max_steps: int = _MAX_STEPS_DEFAULT) -> None:
        self.clock = _Clock()
        self.threads: List[_VThread] = []
        self._by_real: Dict[int, _VThread] = {}
        self._ctrl = _Gate()
        self.steps: List[Tuple[str, str]] = []       # (thread, op label)
        self.enabled_log: List[Tuple[str, ...]] = []  # per step
        self.dep_log: List[Tuple[int, int, str, str]] = []
        #             (step index, key, mode, thread)
        self.max_steps = max_steps
        self._reaping = False
        self._spawn_seq = 0

    # ---- thread registry

    def spawn(self, name: str, fn: Callable[[], None],
              shim: Optional["WeaveThread"] = None) -> _VThread:
        taken = {t.name for t in self.threads}
        base, uniq = name, name
        n = 2
        while uniq in taken:
            uniq = f"{base}#{n}"
            n += 1
        vt = _VThread(self, uniq, fn)
        vt.shim = shim
        self.threads.append(vt)
        vt.ident = _thread.start_new_thread(vt._main, ())
        self._by_real[vt.ident] = vt
        # wait until the thread parks at its begin announce, so spawn is
        # atomic from the spawner's point of view
        deadline = _REAL_MONOTONIC() + _WATCHDOG_S
        while vt.pending is None and not vt.finished:
            if _REAL_MONOTONIC() > deadline:
                raise WeaveHang(f"thread {uniq} never parked")
            _REAL_SLEEP(0.00005)
        return vt

    def current(self) -> Optional[_VThread]:
        return self._by_real.get(_REAL_GET_IDENT())

    # ---- schedule points (called from controlled threads)

    def schedule(self, op: _Op) -> None:
        vt = self.current()
        if vt is None:
            # main/uncontrolled thread: runs only while every controlled
            # thread is parked — execute the effect directly
            op.execute()
            return
        if self._reaping:
            raise _ReapSignal()
        vt.pending = op
        self._ctrl.set()
        vt.go.wait()
        vt.go.clear()
        if self._reaping:
            vt.pending = None
            raise _ReapSignal()
        pend, vt.pending = vt.pending, None
        if pend is not None:
            pend.execute()

    # ---- controller (runs on the main thread)

    def _step_one(self, vt: _VThread) -> None:
        self._ctrl.clear()
        vt.go.set()
        if not self._ctrl.wait(timeout=_WATCHDOG_S):
            frames = sys._current_frames()
            stacks = []
            for t in self.threads:
                fr = frames.get(t.ident or -1)
                if fr is not None:
                    stacks.append(f"--- {t.name} ---\n" +
                                  "".join(traceback.format_stack(fr)))
            raise WeaveHang(
                "controlled thread blocked in real code:\n" +
                "\n".join(stacks))

    def run_until(self, forced: Sequence[str],
                  stop_when: Optional[Callable[[], bool]] = None) -> None:
        """Drive threads until all are finished (or `stop_when` holds).
        The first len(forced) choices overall are pinned; after that the
        default policy runs — stay on the previous thread while it is
        enabled, else the first enabled by name (run-to-completion,
        which minimizes preemptions)."""
        prev: Optional[str] = None if not self.steps else self.steps[-1][0]
        while True:
            if stop_when is not None and stop_when():
                return
            live = [t for t in self.threads if not t.finished]
            if not live:
                return
            enabled = sorted(
                t.name for t in live
                if t.pending is not None and t.pending.enabled())
            if not enabled:
                deadlines = [t.pending.deadline for t in live
                             if t.pending is not None
                             and t.pending.deadline is not None]
                if not deadlines:
                    blocked = ", ".join(
                        f"{t.name} at {t.pending!r}" for t in live
                        if t.pending is not None)
                    raise DeadlockError(
                        f"deadlock (lost wakeup): no runnable thread, no "
                        f"pending deadline; blocked: {blocked}")
                self.clock.advance_to(min(deadlines))
                continue
            i = len(self.steps)
            if i >= self.max_steps:
                raise WeaveError(
                    f"step budget exceeded ({self.max_steps}): livelock "
                    f"or unbounded loop in scenario")
            if i < len(forced):
                name = forced[i]
                if name not in enabled:
                    raise WeaveError(
                        f"schedule diverged at step {i}: {name!r} not in "
                        f"enabled set {enabled}")
            else:
                name = prev if prev in enabled else enabled[0]
            vt = next(t for t in self.threads if t.name == name)
            op = vt.pending
            assert op is not None
            self.steps.append((name, repr(op)))
            self.enabled_log.append(tuple(enabled))
            # releases and post-notify wakeups are enabledness plumbing,
            # not conflicts: an acquire can never be reordered before the
            # release that enables it, so logging them as dependencies
            # would stop the DPOR backward scan at a step whose pre-state
            # has only the lock holder enabled — hiding the acquire
            # (the true race point) behind it and losing interleavings
            # (e.g. a check/apply TOCTOU split across two crossings).
            if op.key is not None and op.kind not in _NONCONFLICT_KINDS:
                self.dep_log.append((i, op.key, op.mode, name))
            self._step_one(vt)
            prev = name

    def reap(self) -> None:
        """Unwind every still-live thread (post-verdict cleanup: failed
        or deadlocked executions leave threads parked)."""
        self._reaping = True
        for vt in self.threads:
            if not vt.finished:
                vt.go.set()
        for vt in self.threads:
            if not vt.done.wait(timeout=5):
                raise WeaveHang(f"thread {vt.name} would not unwind")


# ------------------------------------------------------------------ shims

_CURRENT_RUN: Optional[_Run] = None


def _run_and_me() -> Tuple[Optional[_Run], Optional[_VThread]]:
    run = _CURRENT_RUN
    if run is None:
        return None, None
    return run, run.current()


class WeaveLock:
    """Cooperative threading.Lock replacement."""

    _reentrant = False

    def __init__(self) -> None:
        self._owner: Optional[_VThread] = None
        self._count = 0
        self._main_held = 0       # held by the (uncontrolled) main thread

    # -- model helpers (controller-atomic: called from op.execute or
    #    enabled() while every other thread is parked)

    def _free_for(self, vt: Optional[_VThread]) -> bool:
        if self._main_held:
            return False
        if self._owner is None:
            return True
        return self._reentrant and self._owner is vt

    def _take(self, vt: Optional[_VThread]) -> None:
        if vt is None:
            self._main_held += 1
            return
        self._owner = vt
        self._count += 1

    def _drop(self, vt: Optional[_VThread]) -> None:
        if vt is None and self._main_held:
            self._main_held -= 1
            return
        if self._owner is not vt or self._count <= 0:
            raise RuntimeError("release of un-acquired weave lock")
        self._count -= 1
        if self._count == 0:
            self._owner = None

    def _release_all(self, vt: _VThread) -> int:
        if self._owner is not vt:
            raise RuntimeError("cannot wait on un-owned lock")
        saved, self._count, self._owner = self._count, 0, None
        return saved

    def _restore(self, vt: _VThread, count: int) -> None:
        self._owner, self._count = vt, count

    # -- threading API

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        run, me = _run_and_me()
        if run is None or me is None:
            if not self._free_for(me):
                raise WeaveError("main thread would block on weave lock")
            self._take(me)
            return True
        if not blocking:
            got: List[bool] = []

            def _try() -> None:
                ok = self._free_for(me)
                if ok:
                    self._take(me)
                got.append(ok)

            run.schedule(_Op("tryacquire", f"tryacquire:{_name(self)}",
                             id(self), execute=_try))
            return got[0]
        run.schedule(_Op(
            "acquire", f"acquire:{_name(self)}", id(self),
            enabled=lambda: self._free_for(me),
            execute=lambda: self._take(me)))
        return True

    def release(self) -> None:
        run, me = _run_and_me()
        if run is None or me is None:
            self._drop(me)
            return
        run.schedule(_Op("release", f"release:{_name(self)}", id(self),
                         execute=lambda: self._drop(me)))

    def locked(self) -> bool:
        return self._owner is not None or bool(self._main_held)

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc: object) -> None:
        self.release()


class WeaveRLock(WeaveLock):
    _reentrant = True


class WeaveCondition(threading.Condition):
    """Cooperative threading.Condition replacement.

    Subclasses the real Condition so `isinstance(x, threading.Condition)`
    dispatch (lockdep.instrument's proxy selection) keeps working; every
    inherited behavior is overridden and the base __init__ is NOT called
    (its real RLock would be dead weight)."""

    def __init__(self, lock: Optional[WeaveLock] = None) -> None:
        self._wlock = lock if lock is not None else WeaveRLock()
        self._cond_waiters: List[_VThread] = []

    # lock passthrough

    def acquire(self, *args: Any, **kwargs: Any) -> bool:
        return self._wlock.acquire(*args, **kwargs)

    def release(self) -> None:
        self._wlock.release()

    def __enter__(self) -> bool:
        return self._wlock.acquire()

    def __exit__(self, *exc: object) -> None:
        self._wlock.release()

    # condition protocol: wait = three schedule points — release+park
    # ("wait"), wake eligibility ("wakeup": notified or timed out), then
    # a normal contended reacquire

    def wait(self, timeout: Optional[float] = None) -> bool:
        run, me = _run_and_me()
        if run is None or me is None:
            raise WeaveError("main thread cannot wait on weave condition")
        if self._wlock._owner is not me:
            raise RuntimeError("cannot wait on un-acquired lock")
        saved = [0]

        def _exec_wait() -> None:
            saved[0] = self._wlock._release_all(me)
            me.notified = False
            self._cond_waiters.append(me)

        run.schedule(_Op("wait", f"wait:{_name(self)}", id(self._wlock),
                         execute=_exec_wait))
        deadline = (run.clock.now + timeout) if timeout is not None else None
        timed_out = [False]

        def _exec_wake() -> None:
            timed_out[0] = not me.notified
            if me in self._cond_waiters:
                self._cond_waiters.remove(me)

        run.schedule(_Op(
            "wakeup", f"wakeup:{_name(self)}", id(self._wlock),
            deadline=deadline,
            enabled=lambda: me.notified or (
                deadline is not None and run.clock.now >= deadline),
            execute=_exec_wake))
        run.schedule(_Op(
            "reacquire", f"reacquire:{_name(self)}", id(self._wlock),
            enabled=lambda: self._wlock._free_for(me),
            execute=lambda: self._wlock._restore(me, saved[0])))
        return not timed_out[0]

    def wait_for(self, predicate: Callable[[], bool],
                 timeout: Optional[float] = None) -> bool:
        run, _me = _run_and_me()
        endtime: Optional[float] = None
        result = predicate()
        while not result:
            if timeout is not None:
                assert run is not None
                if endtime is None:
                    endtime = run.clock.now + timeout
                waittime = endtime - run.clock.now
                if waittime <= 0:
                    break
                self.wait(waittime)
            else:
                self.wait()
            result = predicate()
        return result

    def notify(self, n: int = 1) -> None:
        def _exec() -> None:
            woken = 0
            for vt in self._cond_waiters:
                if not vt.notified:
                    vt.notified = True
                    woken += 1
                    if woken >= n:
                        break

        run, me = _run_and_me()
        if run is None or me is None:
            _exec()
            return
        if self._wlock._owner is not me:
            raise RuntimeError("cannot notify on un-acquired lock")
        run.schedule(_Op("notify", f"notify:{_name(self)}",
                         id(self._wlock), execute=_exec))

    def notify_all(self) -> None:
        self.notify(1_000_000)


class WeaveEvent:
    """Cooperative threading.Event replacement."""

    def __init__(self) -> None:
        self._flag = False

    def is_set(self) -> bool:
        return self._flag

    def set(self) -> None:
        run, me = _run_and_me()
        if run is None or me is None:
            self._flag = True
            return

        def _exec() -> None:
            self._flag = True

        run.schedule(_Op("evset", f"evset:{_name(self)}", id(self),
                         execute=_exec))

    def clear(self) -> None:
        run, me = _run_and_me()
        if run is None or me is None:
            self._flag = False
            return

        def _exec() -> None:
            self._flag = False

        run.schedule(_Op("evclear", f"evclear:{_name(self)}", id(self),
                         execute=_exec))

    def wait(self, timeout: Optional[float] = None) -> bool:
        run, me = _run_and_me()
        if run is None or me is None:
            if not self._flag:
                raise WeaveError("main thread would block on weave event")
            return True
        deadline = (run.clock.now + timeout) if timeout is not None else None
        run.schedule(_Op(
            "evwait", f"evwait:{_name(self)}", id(self),
            deadline=deadline,
            enabled=lambda: self._flag or (
                deadline is not None and run.clock.now >= deadline)))
        return self._flag


class WeaveThread:
    """Cooperative threading.Thread replacement: threads production code
    spawns inside a run become controlled threads."""

    def __init__(self, group: None = None,
                 target: Optional[Callable[..., Any]] = None,
                 name: Optional[str] = None,
                 args: Tuple[Any, ...] = (),
                 kwargs: Optional[Dict[str, Any]] = None,
                 daemon: Optional[bool] = None) -> None:
        self._target = target
        self._args = args
        self._kwargs = kwargs or {}
        self._requested_name = name
        self.daemon = bool(daemon)
        self._vt: Optional[_VThread] = None

    @property
    def name(self) -> str:
        if self._vt is not None:
            return self._vt.name
        return self._requested_name or "unstarted"

    def start(self) -> None:
        run, me = _run_and_me()
        if run is None:
            raise WeaveError("weave thread started outside a run")
        name = self._requested_name
        if name is None:
            run._spawn_seq += 1
            name = f"spawned-{run._spawn_seq}"

        def body() -> None:
            if self._target is not None:
                self._target(*self._args, **self._kwargs)

        if me is None:
            self._vt = run.spawn(name, body, shim=self)
            return

        def _exec() -> None:
            self._vt = run.spawn(name, body, shim=self)

        run.schedule(_Op("spawn", f"spawn:{name}", None, execute=_exec))

    def is_alive(self) -> bool:
        vt = self._vt
        return vt is not None and not vt.finished

    @property
    def ident(self) -> Optional[int]:
        vt = self._vt
        return vt.ident if vt is not None else None

    def join(self, timeout: Optional[float] = None) -> None:
        run, me = _run_and_me()
        vt = self._vt
        if vt is None:
            return
        if run is None or me is None:
            raise WeaveError("main thread cannot join a weave thread; "
                             "the controller drains it")
        deadline = (run.clock.now + timeout) if timeout is not None else None
        run.schedule(_Op(
            "join", f"join:{vt.name}", None, deadline=deadline,
            enabled=lambda: vt.finished or (
                deadline is not None and run.clock.now >= deadline)))


class _FakeThread:
    """current_thread() stand-in for controlled threads with no
    threading.Thread shim (the scenario's own threads)."""

    def __init__(self, vt: _VThread) -> None:
        self._vt = vt
        self.name = vt.name
        self.daemon = True

    @property
    def ident(self) -> Optional[int]:
        return self._vt.ident

    def is_alive(self) -> bool:
        return not self._vt.finished


def _weave_current_thread() -> Any:
    run = _CURRENT_RUN
    if run is not None:
        vt = run.current()
        if vt is not None:
            if vt.shim is not None:
                return vt.shim
            fake = getattr(vt, "fake", None)
            if fake is None:
                fake = vt.fake = _FakeThread(vt)
            return fake
    return _REAL_CURRENT_THREAD()


def _weave_monotonic() -> float:
    run = _CURRENT_RUN
    if run is not None:
        return run.clock.now
    return _REAL_MONOTONIC()


def _weave_sleep(seconds: float) -> None:
    run = _CURRENT_RUN
    if run is None:
        _REAL_SLEEP(seconds)
        return
    me = run.current()
    if me is None:
        run.clock.advance_to(run.clock.now + seconds)
        return
    deadline = run.clock.now + max(seconds, 0.0)
    run.schedule(_Op(
        "sleep", f"sleep:{seconds:g}", None, deadline=deadline,
        enabled=lambda: run.clock.now >= deadline))


def _yield_hook(label: str, obj: Optional[object], mode: str,
                key: Optional[str] = None) -> None:
    run = _CURRENT_RUN
    if run is None:
        return
    me = run.current()
    if me is None:
        return
    if key is not None:
        loc = hash(("yp-key", key)) | 1
    elif obj is not None:
        loc = id(obj)
    else:
        loc = hash(("yp-label", label)) | 1
    run.schedule(_Op("yp", f"yp:{label}", loc, mode=mode))


class _Patch:
    """Swap the concurrency seams for shims for the duration of a run."""

    def __init__(self, run: _Run) -> None:
        self.run = run
        self._saved: Dict[str, Any] = {}

    def __enter__(self) -> "_Patch":
        global _CURRENT_RUN
        if _CURRENT_RUN is not None:
            raise WeaveError("nested weave runs are not supported")
        self._saved = {
            "Lock": threading.Lock, "RLock": threading.RLock,
            "Condition": threading.Condition, "Event": threading.Event,
            "Thread": threading.Thread,
            "current_thread": threading.current_thread,
            "monotonic": time.monotonic, "sleep": time.sleep,
        }
        threading.Lock = WeaveLock                  # type: ignore[misc]
        threading.RLock = WeaveRLock                # type: ignore[misc]
        threading.Condition = WeaveCondition        # type: ignore[misc]
        threading.Event = WeaveEvent                # type: ignore[misc]
        threading.Thread = WeaveThread              # type: ignore[misc]
        threading.current_thread = _weave_current_thread
        time.monotonic = _weave_monotonic
        time.sleep = _weave_sleep
        _CURRENT_RUN = self.run
        schedcheck.install(_yield_hook)
        return self

    def __exit__(self, *exc: object) -> None:
        global _CURRENT_RUN
        schedcheck.uninstall()
        _CURRENT_RUN = None
        threading.Lock = self._saved["Lock"]
        threading.RLock = self._saved["RLock"]
        threading.Condition = self._saved["Condition"]
        threading.Event = self._saved["Event"]
        threading.Thread = self._saved["Thread"]
        threading.current_thread = self._saved["current_thread"]
        time.monotonic = self._saved["monotonic"]
        time.sleep = self._saved["sleep"]


# -------------------------------------------------------------- scenarios

class Scenario:
    """Subclass and override:

    - ``setup(self) -> state``: construct the objects under test (the
      patched constructors are active — locks/conditions built here are
      cooperative).
    - ``threads(self, state) -> [(name, fn), ...]``: the racing thread
      bodies (2–4).
    - ``invariant(self, state, run)``: raise AssertionError on
      violation; runs after every complete execution. ``run`` exposes
      ``clock`` (with ``.advances``) and ``steps``.
    - ``drain(self, state)`` (optional): runs on the controller thread
      once the scenario threads finish — stop flags for background
      threads production code spawned; they are then scheduled to
      completion before the invariant runs.
    """

    name = "scenario"
    description = ""
    max_executions = 2000
    preemption_bound: Optional[int] = None
    max_steps = _MAX_STEPS_DEFAULT

    def setup(self) -> Any:
        raise NotImplementedError

    def threads(self, state: Any) -> List[Tuple[str, Callable[[], None]]]:
        raise NotImplementedError

    def invariant(self, state: Any, run: _Run) -> None:
        raise NotImplementedError

    def drain(self, state: Any) -> None:
        return None


class Counterexample:
    def __init__(self, scenario: str, schedule: List[str],
                 steps: List[Tuple[str, str]], failure: str) -> None:
        self.scenario = scenario
        self.schedule = schedule
        self.steps = steps
        self.failure = failure

    def to_json(self) -> str:
        return json.dumps({
            "scenario": self.scenario,
            "schedule": self.schedule,
            "steps": [list(s) for s in self.steps],
            "failure": self.failure,
        }, indent=2)

    @staticmethod
    def from_json(text: str) -> "Counterexample":
        d = json.loads(text)
        return Counterexample(
            d["scenario"], list(d["schedule"]),
            [(s[0], s[1]) for s in d.get("steps", [])],
            d.get("failure", ""))

    def render(self) -> str:
        lines = [f"counterexample: {self.scenario}",
                 f"  failure: {self.failure}",
                 "  schedule (step: thread  op):"]
        for i, (name, op) in enumerate(self.steps):
            lines.append(f"    {i:4d}: {name:<14s} {op}")
        return "\n".join(lines)


class ExploreResult:
    def __init__(self, scenario: str) -> None:
        self.scenario = scenario
        self.executions = 0
        self.steps_total = 0
        self.complete = False          # full reduced space explored
        self.bound_pruned = 0          # choices pruned by preemption bound
        self.counterexample: Optional[Counterexample] = None

    @property
    def ok(self) -> bool:
        return self.counterexample is None

    def render(self) -> str:
        status = "ok" if self.ok else "FAIL"
        if not self.ok:
            space = "stopped at first counterexample;"
        elif self.complete:
            space = "complete"
        else:
            space = "budget-bounded"
        extra = (f", {self.bound_pruned} choice(s) pruned by preemption "
                 f"bound" if self.bound_pruned else "")
        return (f"{self.scenario}: {status} — {self.executions} "
                f"execution(s), {self.steps_total} step(s), "
                f"{space} exploration{extra}")


def _execute(scenario: Scenario,
             forced: Sequence[str]) -> Tuple[_Run, Optional[str]]:
    """One deterministic execution under the forced schedule prefix.
    Returns (run, failure_text or None)."""
    run = _Run(max_steps=scenario.max_steps)
    failure: Optional[str] = None
    with _Patch(run):
        try:
            state = scenario.setup()
            svts = [run.spawn(tname, fn)
                    for tname, fn in scenario.threads(state)]
            try:
                run.run_until(
                    forced,
                    stop_when=lambda: all(t.finished for t in svts))
                scenario.drain(state)
                run.run_until(forced)
            except DeadlockError as exc:
                failure = str(exc)
            if failure is None:
                for vt in run.threads:
                    if vt.exc is not None:
                        tb = "".join(traceback.format_exception(
                            type(vt.exc), vt.exc,
                            vt.exc.__traceback__)).strip()
                        failure = f"thread {vt.name} raised: {tb}"
                        break
            if failure is None:
                try:
                    scenario.invariant(state, run)
                except AssertionError as exc:
                    failure = f"invariant violated: {exc}"
        finally:
            run.reap()
    return run, failure


def run_once(scenario: Scenario,
             schedule: Sequence[str]) -> Tuple[_Run, Optional[str]]:
    """Replay one exact schedule (counterexample reproduction)."""
    return _execute(scenario, list(schedule))


def replay(scenario: Scenario, ce: Counterexample) -> Optional[str]:
    """Re-run a counterexample's schedule; returns the reproduced
    failure text (None = did not reproduce)."""
    _run, failure = run_once(scenario, ce.schedule)
    return failure


# -------------------------------------------------------------- explorer

class _Node:
    """Per-depth exploration record (persists across executions)."""

    __slots__ = ("enabled", "chosen", "backtrack", "done", "preempts",
                 "label")

    def __init__(self, enabled: Tuple[str, ...], chosen: str,
                 preempts: int, label: str) -> None:
        self.enabled = enabled
        self.chosen = chosen
        self.backtrack: Set[str] = {chosen}
        self.done: Set[str] = {chosen}
        self.preempts = preempts       # preemptions along prefix incl. this
        self.label = label             # the chosen op (repr) at this depth


def _is_preemption(prev: Optional[str], choice: str,
                   enabled: Tuple[str, ...],
                   prev_label: Optional[str]) -> bool:
    """A switch counts against the preemption bound only when it takes
    the scheduler away from a thread that could have continued AND that
    thread had started running its body — switching after a `begin`
    step orders thread starts (real-scheduler nondeterminism), it does
    not preempt any user code."""
    return (prev is not None and prev != choice and prev in enabled
            and not (prev_label or "").startswith("begin:"))


def explore(scenario: Scenario,
            max_executions: Optional[int] = None,
            preemption_bound: Optional[int] = None) -> ExploreResult:
    """DPOR exploration of the scenario's schedule space.

    Runs executions until the reduced space is exhausted (``complete``)
    or the execution budget is spent. The first failing execution stops
    exploration and becomes the counterexample."""
    budget = max_executions if max_executions is not None \
        else scenario.max_executions
    bound = preemption_bound if preemption_bound is not None \
        else scenario.preemption_bound
    result = ExploreResult(scenario.name)
    nodes: List[_Node] = []
    forced: List[str] = []

    while True:
        run, failure = _execute(scenario, forced)
        result.executions += 1
        result.steps_total += len(run.steps)

        # a re-branched node's label is stale until its forced execution
        # runs — refresh from the steps actually taken this round
        for i in range(min(len(nodes), len(run.steps))):
            nodes[i].label = run.steps[i][1]

        # append fresh nodes for the suffix this execution discovered
        for i in range(len(nodes), len(run.steps)):
            tname, label = run.steps[i]
            enabled = run.enabled_log[i]
            prev = run.steps[i - 1][0] if i else None
            prev_label = run.steps[i - 1][1] if i else None
            base = nodes[i - 1].preempts if i else 0
            nodes.append(_Node(
                enabled, tname,
                base + int(_is_preemption(prev, tname, enabled,
                                          prev_label)),
                label))

        # DPOR: seed backtrack sets from the last dependent access
        last_by_key: Dict[int, List[Tuple[int, str, str]]] = {}
        for i, key, mode, tname in run.dep_log:
            hist = last_by_key.setdefault(key, [])
            for j, jmode, jname in reversed(hist):
                if jname == tname:
                    continue
                if jmode == "r" and mode == "r":
                    continue
                if tname in nodes[j].enabled:
                    nodes[j].backtrack.add(tname)
                else:
                    nodes[j].backtrack.update(nodes[j].enabled)
                break
            hist.append((i, mode, tname))

        if failure is not None:
            result.counterexample = Counterexample(
                scenario.name, [tname for tname, _ in run.steps],
                run.steps, failure)
            return result

        if result.executions >= budget:
            return result

        # deepest node with an unexplored, bound-feasible backtrack choice
        pick: Optional[Tuple[int, str]] = None
        for i in range(len(nodes) - 1, -1, -1):
            node = nodes[i]
            cands = sorted((node.backtrack & set(node.enabled))
                           - node.done)
            for c in cands:
                if bound is not None:
                    prev = nodes[i - 1].chosen if i else None
                    prev_label = nodes[i - 1].label if i else None
                    base = nodes[i - 1].preempts if i else 0
                    if base + int(_is_preemption(prev, c, node.enabled,
                                                 prev_label)) > bound:
                        node.done.add(c)
                        result.bound_pruned += 1
                        continue
                pick = (i, c)
                break
            if pick is not None:
                break
        if pick is None:
            result.complete = True
            return result
        depth, choice = pick
        node = nodes[depth]
        node.chosen = choice
        node.done.add(choice)
        prev = nodes[depth - 1].chosen if depth else None
        prev_label = nodes[depth - 1].label if depth else None
        base = nodes[depth - 1].preempts if depth else 0
        node.preempts = base + int(
            _is_preemption(prev, choice, node.enabled, prev_label))
        del nodes[depth + 1:]
        forced = [nodes[k].chosen for k in range(depth + 1)]
