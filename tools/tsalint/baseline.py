"""Baseline handling: freeze pre-existing debt, fail only NEW findings.

Keys are line-free (`rule|path|qualname|detail`) so unrelated edits that
shift line numbers never thaw or spuriously match an entry. The baseline
is checked in; `--update-baseline` is the only way it changes, which makes
every new entry reviewable in the diff.
"""

from __future__ import annotations

import json
from typing import Dict, List, Sequence, Tuple

from .analyzer import Finding

BASELINE_VERSION = 1


def load_baseline(path: str) -> Dict[str, str]:
    """{finding key: human message} — empty when the file is absent."""
    try:
        with open(path, "r", encoding="utf-8") as f:
            data = json.load(f)
    except FileNotFoundError:
        return {}
    if not isinstance(data, dict) or data.get("version") != BASELINE_VERSION:
        raise ValueError(f"{path}: unrecognized baseline format")
    entries = data.get("findings")
    if not isinstance(entries, dict):
        raise ValueError(f"{path}: baseline 'findings' must be an object")
    return {str(k): str(v) for k, v in entries.items()}


def save_baseline(path: str, findings: Sequence[Finding]) -> None:
    payload = {
        "version": BASELINE_VERSION,
        "findings": {f.key: f.message for f in
                     sorted(findings, key=lambda f: f.key)},
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=1, sort_keys=True)
        f.write("\n")


def diff_against_baseline(
        findings: Sequence[Finding],
        baseline: Dict[str, str]) -> Tuple[List[Finding], List[str]]:
    """(new findings not in the baseline, stale baseline keys that no
    longer fire — resolved debt worth deleting from the file)."""
    current = {f.key for f in findings}
    new = [f for f in findings if f.key not in baseline]
    stale = sorted(k for k in baseline if k not in current)
    return new, stale
