"""Pre-serialized hot-response correctness (ISSUE 13, transport endgame).

The byte plane (epoch.encode_delimited + the epoch-keyed segment caches
in allocate.py / server.py / dra.py) must be INVISIBLE on the wire: a
response assembled from cached byte segments has to parse back identical
to the proto the message path would have built — across an epoch bump, a
health flip, a multi-container request, and a policy-hook override (the
policy path must bypass the byte cache, never serve a stale winner).
"""

import os
import shutil
import tempfile

import pytest

from tests.fakehost import FakeChip, FakeHost
from tpu_device_plugin import kubeletapi as api
from tpu_device_plugin.config import Config
from tpu_device_plugin.discovery import discover_passthrough
from tpu_device_plugin.kubeletapi import drapb, pb
from tpu_device_plugin.server import TpuDevicePlugin

RAW = api.RAW_CONTEXT


@pytest.fixture()
def rig():
    root = tempfile.mkdtemp(prefix="tdpbytes-")
    host = FakeHost(root)
    for i in range(4):
        host.add_chip(FakeChip(f"0000:00:{4 + i:02x}.0",
                               iommu_group=str(11 + i),
                               vfio_dev=f"vfio{i}", numa_node=i // 2))
    host.enable_iommufd()
    cfg = Config().with_root(root)
    os.makedirs(cfg.device_plugin_path, exist_ok=True)
    registry, generations = discover_passthrough(cfg)
    plugin = TpuDevicePlugin(cfg, "v4", registry,
                             registry.devices_by_model["0062"],
                             torus_dims=generations["0062"].host_topology,
                             cdi_enabled=True)
    yield host, cfg, registry, generations, plugin
    shutil.rmtree(root, ignore_errors=True)


def _alloc_req(ids):
    return pb.AllocateRequest(container_requests=[
        pb.ContainerAllocateRequest(devices_ids=ids)])


def _fresh_allocate(plugin, req):
    """The freshly-built proto the byte path must be indistinguishable
    from: the planner's message path at the SAME epoch."""
    return plugin._planner.allocate_response(
        req, epoch=plugin._store.current.epoch_id)


# ------------------------------------------------------------ Allocate


def test_allocate_bytes_parse_identical_to_fresh_proto(rig):
    _, _, registry, _, plugin = rig
    ids = sorted(registry.bdf_to_group)
    req = _alloc_req(ids[:2])
    raw = plugin.Allocate(req, RAW)
    assert isinstance(raw, api.RawResponse)
    parsed = pb.AllocateResponse.FromString(raw.data)
    assert parsed == _fresh_allocate(plugin, req)
    # the parse-path direct call serves the same bytes
    assert plugin.Allocate(req, None) == parsed
    # the response carries everything the reference contract needs
    cresp = parsed.container_responses[0]
    assert cresp.envs and cresp.devices and cresp.cdi_devices


def test_allocate_bytes_identical_across_epoch_bump_and_health_flip(rig):
    host, _, registry, _, plugin = rig
    ids = sorted(registry.bdf_to_group)
    req = _alloc_req(ids[:2])
    before = pb.AllocateResponse.FromString(plugin.Allocate(req, RAW).data)
    ep0 = plugin._store.current.epoch_id
    # health flip: down then up — two epoch publishes, fragment caches
    # retired by construction (epoch-keyed)
    plugin.set_devices_health([ids[0]], False, source="t")
    plugin.set_devices_health([ids[0]], True, source="t")
    assert plugin._store.current.epoch_id == ep0 + 2
    after = pb.AllocateResponse.FromString(plugin.Allocate(req, RAW).data)
    assert after == before == _fresh_allocate(plugin, req)


def test_allocate_bytes_multi_container_coalesced(rig):
    """The coalesced multi-container fast path: one request, two
    containers — parse-identical to the per-container message path AND
    one privilege crossing for the whole request."""
    from tpu_device_plugin import broker

    _, _, registry, _, plugin = rig
    ids = sorted(registry.bdf_to_group)
    req = pb.AllocateRequest(container_requests=[
        pb.ContainerAllocateRequest(devices_ids=ids[:2]),
        pb.ContainerAllocateRequest(devices_ids=ids[2:4])])
    expected = _fresh_allocate(plugin, req)
    before = broker.get_client().client_stats()["crossings_total"]
    raw = plugin.Allocate(req, RAW)
    crossings = (broker.get_client().client_stats()["crossings_total"]
                 - before)
    assert pb.AllocateResponse.FromString(raw.data) == expected
    assert len(expected.container_responses) == 2
    assert crossings == 1, \
        f"multi-container Allocate paid {crossings} crossings (want 1: " \
        f"the coalesced batched revalidation)"


def test_allocate_warm_path_reuses_bytes_and_serializes_nothing(rig):
    _, _, registry, _, plugin = rig
    ids = sorted(registry.bdf_to_group)
    req = _alloc_req(ids[:2])
    plugin.Allocate(req, RAW)          # warm (fragment builds serialize)
    r0 = plugin._alloc_bytes_reused.value
    s0 = plugin._alloc_serializations.value
    for _ in range(3):
        plugin.Allocate(req, RAW)
    assert plugin._alloc_bytes_reused.value - r0 == 3
    assert plugin._alloc_serializations.value - s0 == 0


# ------------------------------------------- GetPreferredAllocation


def _pref_req(ids, size=2):
    return pb.PreferredAllocationRequest(container_requests=[
        pb.ContainerPreferredAllocationRequest(
            available_deviceIDs=ids, allocation_size=size)])


def test_pref_bytes_parse_identical_and_reused_across_epoch_bump(rig):
    _, _, registry, _, plugin = rig
    ids = sorted(registry.bdf_to_group)
    req = _pref_req(ids)
    first = plugin.GetPreferredAllocation(req, None)     # miss: serializes
    r0 = plugin._alloc_bytes_reused.value
    raw = plugin.GetPreferredAllocation(req, RAW)        # warm: byte memo
    assert isinstance(raw, api.RawResponse)
    assert pb.PreferredAllocationResponse.FromString(raw.data) == first
    assert plugin._alloc_bytes_reused.value == r0 + 1
    # epoch bump retires the memo wholesale; the recomputed answer (the
    # scan is pure in availability/size, health is not an input) still
    # parses identical
    plugin.set_devices_health([ids[0]], False, source="t")
    misses0 = plugin._pref_misses.value
    again = plugin.GetPreferredAllocation(req, RAW)
    assert plugin._pref_misses.value == misses0 + 1
    assert pb.PreferredAllocationResponse.FromString(again.data) == first


def test_policy_override_bypasses_pref_byte_cache(rig):
    """The hazard: the memo holds the BUILTIN answer's bytes; with a
    scoring hook loaded, a memo hit must never short-circuit past the
    policy — the override is serialized fresh, the cached builtin bytes
    are never served, and the bytes-reused counter does not move."""
    from tests.test_policy import engine_with

    _, cfg, registry, generations, _ = rig
    engine = engine_with(
        "def score_allocation(ctx):\n"
        "    ranked = sorted(ctx['available'], reverse=True)\n"
        "    return ranked[:ctx['size']]\n")
    plugin = TpuDevicePlugin(cfg, "v4", registry,
                             registry.devices_by_model["0062"],
                             torus_dims=generations["0062"].host_topology,
                             policy=engine)
    ids = sorted(registry.bdf_to_group)
    req = _pref_req(ids)
    want = sorted(ids, reverse=True)[:2]
    first = plugin.GetPreferredAllocation(req, RAW)
    assert list(pb.PreferredAllocationResponse.FromString(first.data)
                .container_responses[0].deviceIDs) == want
    # the memo now holds the builtin answer (+ its bytes) for this key —
    # prove the SECOND call (a memo hit) still serves the override
    key = next(iter(plugin._pref_cache))
    builtin_ids = plugin._pref_cache[key][0]
    assert list(builtin_ids) != want
    r0 = plugin._alloc_bytes_reused.value
    second = plugin.GetPreferredAllocation(req, RAW)
    assert list(pb.PreferredAllocationResponse.FromString(second.data)
                .container_responses[0].deviceIDs) == want
    assert plugin._alloc_bytes_reused.value == r0, \
        "a policy-overridden answer must never count as byte reuse"


# -------------------------------------------------------- ListAndWatch


def test_lw_raw_send_is_the_epoch_payload(rig):
    _, _, _, _, plugin = rig
    ep = plugin._store.current
    raw = plugin._lw_response(ep, raw=True)
    assert isinstance(raw, api.RawResponse)
    assert raw.data == ep.lw_payload
    assert (pb.ListAndWatchResponse.FromString(raw.data)
            == plugin._lw_response(ep))


# ------------------------------------------------- DRA prepare acks


def test_dra_prepare_ack_bytes_parse_identical_and_reused():
    from tests.test_dra import FakeApiServer, make_driver

    root = tempfile.mkdtemp(prefix="tdpdraack-")
    apiserver = FakeApiServer()
    try:
        host = FakeHost(root)
        for i in range(2):
            host.add_chip(FakeChip(f"0000:00:{4 + i:02x}.0",
                                   device_id="0063",
                                   iommu_group=str(11 + i)))
        cfg = Config().with_root(root)
        os.makedirs(cfg.device_plugin_path, exist_ok=True)
        driver = make_driver(cfg, apiserver)
        from tpu_device_plugin.dra import slice_device_name
        name = slice_device_name("0000:00:04.0")
        apiserver.add_claim("ns", "c1", "uid-1", driver.driver_name,
                            [{"device": name}])
        claim = drapb.Claim(namespace="ns", name="c1", uid="uid-1")
        req = drapb.NodePrepareResourcesRequest(claims=[claim])

        first = driver.NodePrepareResources(req, None)
        assert first.claims["uid-1"].error == ""
        assert len(first.claims["uid-1"].devices) == 1
        # the freshly-built proto the ack bytes must match
        entry = driver._checkpoint["uid-1"]
        expected = drapb.NodePrepareResourcesResponse()
        expected.claims["uid-1"].devices.extend(
            drapb.Device(**d) for d in entry["devices"])
        assert first == expected

        # idempotent kubelet retry: the ack segment is REUSED (counted)
        r0 = driver._ack_bytes_reused.value
        s0 = driver._ack_serializations.value
        raw = driver.NodePrepareResources(req, RAW)
        assert isinstance(raw, api.RawResponse)
        assert (drapb.NodePrepareResourcesResponse.FromString(raw.data)
                == expected)
        assert driver._ack_bytes_reused.value == r0 + 1
        assert driver._ack_serializations.value == s0

        # a failed claim's error ack rides the same assembly
        bad = drapb.Claim(namespace="ns", name="nope", uid="uid-missing")
        both = driver.NodePrepareResources(
            drapb.NodePrepareResourcesRequest(claims=[claim, bad]), RAW)
        parsed = drapb.NodePrepareResourcesResponse.FromString(both.data)
        assert parsed.claims["uid-1"] == expected.claims["uid-1"]
        assert parsed.claims["uid-missing"].error != ""

        # unprepare retires the cached segment with the entry
        driver.NodeUnprepareResources(
            drapb.NodeUnprepareResourcesRequest(claims=[claim]), None)
        assert "uid-1" not in driver._ack_cache
        driver.stop()
    finally:
        apiserver.stop()
        shutil.rmtree(root, ignore_errors=True)
