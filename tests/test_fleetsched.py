"""Sharded fleet scheduler tests (ISSUE 17).

Batched decision waves (one snapshot, one sorted pass, ONE fabric
commit round per wave — with the PR 4 group-commit firing rules: full
wave, expired window, or a lone claim committing immediately), the
optimistic-concurrency CAS commit (a stale observation is a counted
CLEAN abort: nothing registered, prepares unwound, zero residue), the
two-scheduler race for the last ICI-contiguous window (exactly one
commits; the loser replans onto the next-best window with an honestly
lower contiguity score, its whole story — plan → conflict-abort →
replan → commit — on ONE trace id), the 410-relist unchanged-identity
skip (the ISSUE 17 bugfix: a relist must not reparse the unchanged
fleet), the cross-scheduler exactly-once audit, and the zero-lock
read-path gate extended through the FragAccountant.
"""

import threading
import time

import pytest

from tpu_device_plugin import faults, fleetplace, lockdep, trace
from tpu_device_plugin.fleetplace import (
    FleetScheduler, FragAccountant, SliceCache, fleet_audit)
from tpu_device_plugin.fleetsim import (
    SyntheticFleet, synthetic_slice_objects)
from tpu_device_plugin.placement import SlicePlan, parse_shape


def _bdf(j):
    return f"0000:{j:02x}:00.0"


def _fill(fleet, uid, node, chip_indexes, shape):
    """Consume exact chips through the fabric's CAS path (observed
    gen 0: first write wins) so every scheduler's watch cache sees
    the occupancy."""
    plan = SlicePlan(shape=parse_shape(shape),
                     shards=((node, tuple(_bdf(j)
                                          for j in chip_indexes)),),
                     score=1.0, hosts=1)
    res = fleet.execute_plan(plan, uid, observed={node: 0})
    assert res["placed"], res
    return res


def _wait(predicate, timeout_s=5.0, msg="condition"):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if predicate():
            return
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {msg}")


def _free_by_node(sched):
    views, _sel = sched.eligible_views()
    return {v.node: len(v.free) for v in views}


# ------------------------------------------------- decision waves


def test_wave_batches_one_fabric_commit_round():
    """A wave of k claims costs ONE snapshot, ONE planning pass and
    ONE fabric commit round — not k of each."""
    fleet = SyntheticFleet(8, devices_per_node=8)
    try:
        sched = fleet.scheduler(wave_max=16)
        sched.start()
        assert sched.wait_synced(10.0)
        with fleet.apiserver._lock:
            rounds0 = fleet.apiserver.stats["commit_rounds_total"]
        for j in range(8):
            sched.submit("1x2", f"wave-{j}")
        results = sched.pump(force=True)
        assert len(results) == 8
        assert all(r["placed"] for r in results)
        assert sched.stats["decision_waves_total"].value == 1
        with fleet.apiserver._lock:
            rounds = fleet.apiserver.stats["commit_rounds_total"]
        assert rounds - rounds0 == 1, \
            f"8-claim wave cost {rounds - rounds0} commit rounds"
        assert all(r["latency_ms"] >= 0 for r in results)
        audit = fleet_audit(
            [sched], fabric_audit=fleet.apiserver.multiclaim_audit(),
            placement_audit=fleet.apiserver.placement_audit(),
            checkpoint_audit=fleet.checkpoint_audit())
        assert audit["exactly_once"], audit
    finally:
        fleet.stop()


def test_wave_waits_for_company_until_full_or_window():
    """Two queued claims inside a young wave window do NOT fire; the
    wave fires when it fills to wave_max."""
    fleet = SyntheticFleet(4, devices_per_node=8)
    try:
        sched = fleet.scheduler(wave_max=4, wave_window_s=60.0)
        sched.start()
        assert sched.wait_synced(10.0)
        sched.submit("1x2", "early-0")
        sched.submit("1x2", "early-1")
        assert sched.pump() == []          # not lone, not full, young
        sched.submit("1x2", "early-2")
        sched.submit("1x2", "early-3")     # hits wave_max
        results = sched.pump()
        assert len(results) == 4
        assert all(r["placed"] for r in results)
        assert sched.stats["decision_waves_total"].value == 1
    finally:
        fleet.stop()


def test_lone_claim_commits_immediately():
    """The PR 4 lone-claim rule at the scheduler tier: a single queued
    claim never waits out the wave window."""
    fleet = SyntheticFleet(2, devices_per_node=8)
    try:
        sched = fleet.scheduler(wave_max=64, wave_window_s=60.0)
        sched.start()
        assert sched.wait_synced(10.0)
        sched.submit("1x2", "lone")
        results = sched.pump()             # NOT forced
        assert [r["uid"] for r in results] == ["lone"]
        assert results[0]["placed"]
        assert sched.stats["decision_waves_total"].value == 1
    finally:
        fleet.stop()


# ------------------------------------------- optimistic concurrency


def test_stale_observed_commit_is_counted_clean_abort():
    """The fabric-side CAS contract: a commit whose observed placement
    generation is stale is refused atomically — counted, nothing
    registered, prepares unwound, zero residue — and reports the live
    generations so the caller can replan."""
    fleet = SyntheticFleet(2, devices_per_node=8)
    try:
        _fill(fleet, "holder", "node-0000", (0, 1), "1x2")
        plan = SlicePlan(shape=parse_shape("1x2"),
                         shards=(("node-0000", (_bdf(2), _bdf(3))),),
                         score=1.0, hosts=1)
        res = fleet.execute_plan(plan, "stale", observed={"node-0000": 0})
        assert not res["placed"]
        assert res["conflict"]
        assert res["conflicts"] == ["node-0000"]
        assert res["placement_gens"] == {"node-0000": 1}
        assert res["residue"] == []
        assert fleet.slice_residue("stale") == []
        with fleet.apiserver._lock:
            assert fleet.apiserver.stats["placement_conflicts_total"] == 1
        for name, audit in fleet.audits().items():
            assert audit["exactly_once"], (name, audit)
    finally:
        fleet.stop()


@pytest.mark.parametrize("faulted", [False, True])
def test_two_schedulers_race_for_last_contiguous_window(faulted):
    """ISSUE 17 satellite: two schedulers race for the LAST perfectly
    contiguous 2x2 window. Exactly one commits it; the loser's abort
    is clean (no residue on the contested node, no orphaned
    sub-claims) and its replan lands the next-best window with an
    honestly LOWER contiguity score — the whole story (plan →
    conflict-abort → replan → commit) on ONE trace id. The `faulted`
    leg repeats the race with the chaos registry armed on the
    apiserver transport."""
    trace.reset()
    faults.reset()
    fleet = SyntheticFleet(4, devices_per_node=8,
                           commit_crossing_s=0.05)
    try:
        s1 = fleet.scheduler(partition=False)
        s2 = fleet.scheduler(partition=False)
        for s in (s1, s2):
            s.start()
        for s in (s1, s2):
            assert s.wait_synced(10.0)
        # node-0000 keeps ONE contiguous 2x2 (cols 0-1 of its 2x4
        # torus); node-0001 keeps 4 free chips in cols 0 and 2 — a
        # 2x2 only best-effort, never contiguous; the rest is full
        _fill(fleet, "fill-n0", "node-0000", (2, 3, 6, 7), "1x4")
        _fill(fleet, "fill-frag", "node-0001", (1, 5, 3, 7), "1x4")
        _fill(fleet, "fill-n2", "node-0002", tuple(range(8)), "2x4")
        _fill(fleet, "fill-n3", "node-0003", tuple(range(8)), "2x4")
        want = {"node-0000": 4, "node-0001": 4,
                "node-0002": 0, "node-0003": 0}
        _wait(lambda: _free_by_node(s1) == want
              and _free_by_node(s2) == want, msg="fill convergence")
        if faulted:
            faults.arm("kubeapi.request", kind="error", count=2)
        barrier = threading.Barrier(2)
        res = {}

        def go(sched, uid):
            barrier.wait()
            res[uid] = sched.schedule("2x2", uid, best_effort=True)

        threads = [threading.Thread(target=go, args=args)
                   for args in ((s1, "race-a"), (s2, "race-b"))]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        ra, rb = res["race-a"], res["race-b"]
        assert ra["placed"] and rb["placed"], (ra, rb)
        scores = sorted([ra["score"], rb["score"]])
        assert scores[1] == 1.0, "someone must win the pristine window"
        assert scores[0] < 1.0, \
            "loser must land the next-best window at a LOWER score"
        winner, loser = (ra, rb) if ra["score"] == 1.0 else (rb, ra)
        winner_node = winner["shards"][0][0]
        loser_node = loser["shards"][0][0]
        assert winner_node == "node-0000"
        assert loser_node == "node-0001"
        conflicts = (s1.stats["commit_conflicts_total"].value
                     + s2.stats["commit_conflicts_total"].value)
        replans = (s1.stats["replans_total"].value
                   + s2.stats["replans_total"].value)
        assert conflicts >= 1 and replans >= 1, (conflicts, replans)
        # the loser left NOTHING behind on the contested node
        residue = fleet.slice_residue(loser["uid"])
        assert all(winner_node not in entry for entry in residue), \
            residue
        # satellite: the conflicted claim's waterfall on ONE trace id
        ops = {s_["op"] for s_ in trace.snapshot(trace=loser["trace_id"])}
        for needed in ("fleetplace.schedule", "fleetplace.conflict_abort",
                       "fleetplace.replan", "fleetplace.commit"):
            assert needed in ops, (needed, sorted(ops))
        # the fills committed through the fabric out-of-band, so the
        # scheduler-vs-fabric SET comparison cannot hold here — the
        # placement/checkpoint legs and the fabric's own audit can
        audit = fleet_audit(
            [s1, s2],
            placement_audit=fleet.apiserver.placement_audit(),
            checkpoint_audit=fleet.checkpoint_audit())
        assert audit["exactly_once"], audit
        assert audit["cross_scheduler_duplicates"] == []
        assert fleet.apiserver.multiclaim_audit()["exactly_once"]
    finally:
        fleet.stop()
        faults.reset()


# --------------------------------------------- 410-relist skip (bugfix)


def test_relist_unchanged_slices_skip_delta_application():
    """ISSUE 17 bugfix regression: after a 410-compaction relist, a
    slice whose resourceVersion/generation identity is unchanged is
    SKIPPED — counted — instead of reparsed; only the slices that
    actually moved pay the recompute."""
    objs, pod_dims = synthetic_slice_objects(8, devices_per_node=4)
    for i, obj in enumerate(objs):
        obj["metadata"]["resourceVersion"] = str(i + 1)
    fresh = {o["metadata"]["name"]: o for o in objs}
    acc = FragAccountant(pod_dims=pod_dims)
    acc.on_sync(fresh)
    assert acc.stats["slice_reparses_total"].value == 8
    assert acc.stats["relist_unchanged_skips_total"].value == 0
    # identical relist: ALL skipped, NOTHING reparsed
    acc.on_sync(fresh)
    assert acc.stats["relist_unchanged_skips_total"].value == 8
    assert acc.stats["slice_reparses_total"].value == 8
    # one slice moved between compactions: exactly one reparse
    moved = dict(fresh)
    bumped = dict(moved["node-0003-slice"])
    bumped["metadata"] = dict(bumped["metadata"],
                              resourceVersion="99")
    moved["node-0003-slice"] = bumped
    acc.on_sync(moved)
    assert acc.stats["slice_reparses_total"].value == 9
    assert acc.stats["relist_unchanged_skips_total"].value == 15
    # duplicate watch delivery hits the same identity skip
    acc.on_event({"type": "MODIFIED", "object": bumped})
    assert acc.stats["slice_reparses_total"].value == 9
    assert acc.stats["relist_unchanged_skips_total"].value == 16


# ------------------------------------------------- cross-scheduler audit


def test_fleet_audit_flags_cross_scheduler_duplicate_commit():
    """A claim uid committing on TWO schedulers is the violation the
    fleet-level audit exists for — per-scheduler logs can each look
    clean while the union is wrong."""
    cache1, cache2 = SliceCache(), SliceCache()
    s1 = FleetScheduler(cache=cache1)
    s2 = FleetScheduler(cache=cache2)
    for s in (s1, s2):
        s._note("decided", "dup", None)
        s._note("committed", "dup", None)
    audit = fleet_audit([s1, s2])
    assert audit["cross_scheduler_duplicates"] == ["dup"]
    assert not audit["exactly_once"]
    # each scheduler ALONE audits clean — only the union catches it
    assert all(a["exactly_once"] for a in audit["per_scheduler"])


# --------------------------------------------- zero-lock read gates


def test_fleet_reads_stay_zero_lock_through_accountant():
    """The ISSUE 14 zero-lock read gate survives the ISSUE 17
    accountant: after a sync AND applied watch deltas, selector
    evaluation and fragmentation reads still acquire zero registered
    locks (they run on the accountant's published snapshots)."""
    objs, pod_dims = synthetic_slice_objects(4, devices_per_node=4)
    for i, obj in enumerate(objs):
        obj["metadata"]["resourceVersion"] = str(i + 1)
    with lockdep.scoped():
        cache = SliceCache(pod_dims=pod_dims)
        cache.on_sync(objs)
        sched = FleetScheduler(cache=cache, pod_dims=pod_dims)
        # watch deltas land through the accountant's O(1) apply path
        flip = dict(objs[0])
        flip["metadata"] = dict(flip["metadata"], resourceVersion="50")
        cache.on_event({"type": "MODIFIED", "object": flip})
        assert cache.accountant.stats[
            "frag_delta_applies_total"].value >= 1
        lockdep.reset()
        for _ in range(5):
            views, _sel = sched.eligible_views()
            assert len(views) == 4
            frag = sched.fragmentation()
            assert frag
        stats = lockdep.path_stats()
        for path in ("fleetplace.select", "fleetplace.frag"):
            rec = stats[path]
            assert rec["calls"] >= 5, stats
            assert rec["lock_acquisitions"] == 0, \
                f"{path} acquired {rec['lock_acquisitions']} locks"
