"""Immutable copy-on-write epochs — the daemon's lock-free read plane.

PRs 2-5 made the daemon concurrent but left every hot read path paying
lock traffic: a warm Allocate took 11 registered-lock acquisitions
(fragment lock x4, vendor-reader lock x4, device-table condition x2, memo
lock x1, measured pre-refactor) and /status took the device-table
condition while assembling its dict. This module inverts the ownership:

- a single WRITER (the discovery/health reconciler) builds a frozen
  `Epoch` — device table, effective health verdicts, the pre-serialized
  ListAndWatch payload — and publishes it with ONE atomic reference swap;
- READERS (`Allocate`, `GetPreferredAllocation`, ListAndWatch payload
  assembly, `/status`, DRA prepare planning) grab the current epoch
  pointer and never acquire a registered lock in steady state. Caches
  that used to need explicit invalidation (the GetPreferredAllocation
  memo, the per-IOMMU-group Allocate fragments) are keyed by epoch id
  instead — invalidated by construction, no listener plumbing.

Immutability is enforced three ways: the dataclasses are frozen, their
mappings are `MappingProxyType` views, and tsalint's `epoch-mutation`
rule fails the build on any attribute/dict write to an epoch outside
this module's builders (docs/static-analysis.md).

Atomicity contract (CPython): attribute reads/writes, `dict.get`,
single-key `dict` stores, `len()`, `dict(d)` / `list(d.values())` copies
and `deque.append` are single-bytecode / C-level operations under the
GIL — the reader side leans on exactly these, nothing subtler. The
free-threaded build would need the stores to become real atomics; the
seam is `EpochStore.publish`.

What still locks, by design (docs/perf.md "what still locks"):
- the writer: epoch builds + publishes serialize on the store's internal
  condition (`epoch.EpochStore._cond` — also what ListAndWatch waiters
  park on; a parked waiter holds nothing, lockdep suspends it);
- genuinely mutating paths: claim checkpoint commits (`dra._lock`,
  `dra._ckpt_cond`), the health-listener delivery chain
  (`server._listener_lock`), and the LiveAttrReader SLOW path (fd
  open/replace — its steady-state pread is lock-free, allocate.py).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from types import MappingProxyType
from typing import (Any, Callable, Dict, FrozenSet, List, Mapping,
                    Optional, Sequence, Tuple)

from . import lockdep, schedcheck

__all__ = ["AtomicCounter", "Epoch", "EpochStore", "InventoryEpoch",
           "build_inventory_epoch", "build_server_epoch",
           "encode_delimited", "encode_varint"]

_EMPTY_MAP: Mapping = MappingProxyType({})


# --- pre-serialized response assembly (round 15) -----------------------------
# The ListAndWatch payload proved the pattern: serialize once at publish
# time, reuse the bytes per send. Extending it to Allocate /
# GetPreferredAllocation / DRA prepare acks needs one protobuf wire fact:
# a length-delimited field record is self-contained, and concatenating
# records of a repeated/map field yields the same parse as building the
# message whole. These two helpers are the entire assembly vocabulary —
# epoch-keyed caches hold serialized sub-message bytes, and the hot path
# concatenates records instead of re-building + re-serializing protos
# (tests/test_preserialized.py pins parse-identity against fresh builds).

def encode_varint(n: int) -> bytes:
    """Protobuf base-128 varint encoding of a non-negative int."""
    out = bytearray()
    while True:
        bit = n & 0x7F
        n >>= 7
        if n:
            out.append(bit | 0x80)
        else:
            out.append(bit)
            return bytes(out)


def encode_delimited(field_number: int, payload: bytes) -> bytes:
    """One length-delimited (wire type 2) field record: tag + length +
    payload. `payload` is serialized sub-message bytes or UTF-8 string
    bytes — the two length-delimited kinds the response planes use."""
    return (encode_varint((field_number << 3) | 2)
            + encode_varint(len(payload)) + payload)


class AtomicCounter:
    """Lock-free EXACT monotonic counter for hot-path stats.

    Sharded per thread: each thread increments its own one-element cell
    (single-owner, so `cell[0] += 1` is exact), and `value` sums a
    C-atomic `list()` snapshot of the cells. Cells only grow and are
    never removed, so two successive `value` reads can never go
    backwards — a Prometheus scrape sees a true counter (a plain
    store-last-total design can park a STALE total when the last racing
    store loses, and a counter decrease reads as a process restart to
    rate()). Cost: add() is a thread-local hit + int increment; value is
    O(threads ever seen), read only on /status//metrics. Zero lock
    acquisitions either way — the lockdep read-path gate pins it
    (tests/test_epoch.py).
    """

    __slots__ = ("_cells", "_local", "_start")

    def __init__(self, start: int = 0) -> None:
        self._start = start
        self._cells: List[List[int]] = []
        self._local = threading.local()

    def add(self) -> None:
        """Count one event. O(1): a thread-local hit + int increment —
        the cross-cell sum is paid only by `value` readers (/status,
        /metrics), never by the hot path."""
        cell = getattr(self._local, "cell", None)
        if cell is None:
            cell = self._local.cell = [0]
            schedcheck.yield_point("epoch.counter.adopt", obj=self)
            self._cells.append(cell)   # C-atomic list append
        schedcheck.yield_point("epoch.counter.bump", obj=self)
        cell[0] += 1                   # owner-thread only: exact

    @property
    def value(self) -> int:
        schedcheck.yield_point("epoch.counter.snapshot", obj=self, mode="r")
        return self._start + sum(c[0] for c in list(self._cells))


@dataclass(frozen=True)
class Epoch:
    """One immutable generation of a plugin server's read-plane state.

    Built ONLY by `build_server_epoch` (tsalint's epoch-mutation rule
    enforces that nothing outside epoch.py writes to a published epoch).

      epoch_id       — monotonic per-store generation; caches key on it
      device_health  — device id -> "Healthy"/"Unhealthy" (the ANDed
                       effective verdict; read-only mapping view)
      lw_payload     — the fully-serialized ListAndWatchResponse bytes;
                       stream sends parse this once instead of
                       deep-copying every pb.Device under a lock
    """

    epoch_id: int
    device_health: Mapping[str, str] = _EMPTY_MAP
    lw_payload: bytes = b""


@dataclass(frozen=True)
class InventoryEpoch:
    """The DRA driver's read-plane generation (prepare planning + slice
    builds read this; only `set_inventory`/`apply_health` publish).

      by_name        — published device name -> (kind, group, obj)
      planners       — generation name -> AllocationPlanner
      parent_planner — the vfio-backed-partition passthrough planner
      unhealthy      — raw ids pruned from the published ResourceSlice
      departed       — raw ids REMOVED from by_name by hot-unplug
                       (lifecycle GONE): distinct from unhealthy so a
                       prepare against one can say "device departed"
                       instead of "stale ResourceSlice", and /status can
                       report the difference
    """

    epoch_id: int
    by_name: Mapping[str, Tuple[str, str, Any]] = _EMPTY_MAP
    planners: Mapping[str, Any] = _EMPTY_MAP
    parent_planner: Any = None
    unhealthy: FrozenSet[str] = field(default_factory=frozenset)
    departed: FrozenSet[str] = field(default_factory=frozenset)


def build_server_epoch(epoch_id: int,
                       rows: Sequence[Tuple[str, int]],
                       health_sources: Mapping[str, Mapping[str, bool]]
                       ) -> Epoch:
    """The plugin-server epoch builder (the only place server epochs are
    born). `rows` is the static (device id, NUMA node) table fixed for
    the server's lifetime; `health_sources` is the writer-owned per-source
    verdict map — a device is Healthy iff ALL its sources agree (the
    fs-watcher/native-probe AND from server.set_devices_health)."""
    from . import kubeletapi as api
    from .kubeletapi import pb

    health: Dict[str, str] = {}
    devices = []
    for dev_id, numa_node in rows:
        sources = health_sources.get(dev_id)
        state = api.HEALTHY if (not sources or all(sources.values())) \
            else api.UNHEALTHY
        health[dev_id] = state
        devices.append(pb.Device(
            ID=dev_id, health=state,
            topology=pb.TopologyInfo(nodes=[pb.NUMANode(ID=numa_node)])))
    payload = pb.ListAndWatchResponse(devices=devices).SerializeToString()
    return Epoch(epoch_id=epoch_id,
                 device_health=MappingProxyType(health),
                 lw_payload=payload)


def build_inventory_epoch(epoch_id: int,
                          by_name: Mapping[str, Tuple[str, str, Any]],
                          planners: Mapping[str, Any],
                          parent_planner: Any,
                          unhealthy: FrozenSet[str],
                          departed: FrozenSet[str] = frozenset()
                          ) -> InventoryEpoch:
    """The DRA inventory-epoch builder. The mappings are snapshotted into
    read-only views here so a writer that keeps mutating its working dict
    after publish cannot reach readers."""
    return InventoryEpoch(
        epoch_id=epoch_id,
        by_name=MappingProxyType(dict(by_name)),
        planners=MappingProxyType(dict(planners)),
        parent_planner=parent_planner,
        unhealthy=frozenset(unhealthy),
        departed=frozenset(departed))


class EpochStore:
    """Atomic publish/subscribe point for one epoch sequence.

    `current` is a plain attribute read — the whole reader contract.
    Writers serialize on the internal condition (`with store.lock():`)
    and publish with `publish_locked`; ListAndWatch waiters park on the
    same condition via `wait_for` and observe the epoch id change (the
    notify_all replaces the old per-server device-table condvar fan-out).
    `publishes` counts successful swaps — the generation counter /status
    and /metrics surface.
    """

    def __init__(self, initial: Any = None) -> None:
        # one shared lockdep name for every store instance (server + DRA):
        # stores are never nested, so any store->store edge flags as a
        # self-inversion — the same convention as dra's per-claim locks
        self._cond = lockdep.instrument(
            "epoch.EpochStore._cond", threading.Condition())
        self.current: Any = initial if initial is not None else Epoch(0)
        self.publishes = AtomicCounter()
        # parked wait_for callers (ListAndWatch streams, fleet-sim
        # subscribers): mutated under _cond, read lock-free (GIL-atomic
        # int) — the mass-churn wakeup tests and the fleet bench use it
        # to know every subscriber is parked before firing a flip
        self.waiters = 0

    def lock(self) -> threading.Condition:
        """The writer-side critical section: `with store.lock(): ...`.
        Epoch builds inside it must stay pure compute — the blocking-call
        vocabulary under `epoch.EpochStore._cond` is lint-enforced."""
        return self._cond

    def publish_locked(self, ep: Any) -> Any:
        """Swap the current epoch and wake every waiter. Caller holds
        `lock()`; the swap itself is one attribute store, so readers on
        other threads switch epochs atomically."""
        schedcheck.yield_point("epoch.publish.swap", obj=self)
        self.current = ep
        self.publishes.add()
        self._cond.notify_all()
        return ep

    def publish(self, ep: Any) -> Any:
        with self._cond:
            return self.publish_locked(ep)

    def wait_for(self, predicate: Callable[[], bool],
                 timeout: Optional[float] = None) -> bool:
        """Park until `predicate()` (checked under the store condition).
        Waiters hold nothing while parked — lockdep suspends the hold."""
        with self._cond:
            self.waiters += 1
            try:
                return self._cond.wait_for(predicate, timeout)
            finally:
                self.waiters -= 1

    def poke(self) -> None:
        """Wake waiters without publishing (teardown, RPC termination)."""
        with self._cond:
            self._cond.notify_all()
