"""Minimal Kubernetes API client — stdlib only, no `kubernetes` package.

Shared by the node labeler (PATCH node labels) and the DRA driver
(ResourceSlice publish, ResourceClaim reads). Authenticates with the pod's
service-account token and trusts the in-cluster CA, exactly like the
labeler always has; the dependency-free stance mirrors the reference's
single-static-binary posture (its only runtime deps are grpc + sysfs,
reference: go.mod:1-12 — it never talks to the API server at all).
"""

from __future__ import annotations

import http.client
import json
import logging
import os
import ssl
import threading
import time
from typing import Optional
from urllib.parse import urlsplit

from . import faults
from . import lockdep
from . import trace
from .resilience import BackoffPolicy, CircuitBreaker

log = logging.getLogger(__name__)

# idle keep-alive connections retained per client; excess connections from
# concurrency bursts are closed on return rather than pooled
MAX_IDLE_CONNECTIONS = 4

# failures whose signature is a stale keep-alive connection the server
# idled out — retried ONCE on a brand-new connection when the failed one
# was a reused pool member. Deliberately NARROW: a response-read timeout
# (TimeoutError) means the server may have processed the request, and
# replaying a POST/PUT there would duplicate apiserver writes, so it is
# wrapped as ApiError without retry like every other transport failure.
_RETRYABLE_STALE = (http.client.BadStatusLine,
                    http.client.CannotSendRequest,
                    http.client.ResponseNotReady, BrokenPipeError,
                    ConnectionResetError, ConnectionAbortedError)

SA_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"


def in_cluster_server() -> Optional[str]:
    """https://host:port of the API server from the in-cluster env, if any."""
    host = os.environ.get("KUBERNETES_SERVICE_HOST")
    port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
    if not host:
        return None
    return f"https://{host}:{port}"


class ApiError(Exception):
    """HTTP-level API failure carrying the status code (0 = transport)."""

    def __init__(self, message: str, code: int = 0):
        super().__init__(message)
        self.code = code


class ApiClient:
    """Bearer-token REST client for one API server.

    Connections are keep-alive and pooled (up to MAX_IDLE_CONNECTIONS
    idle): a node agent talks to one apiserver for its whole life, and
    per-request TCP+TLS handshakes are both the dominant cost of a DRA
    claim prepare and pointless apiserver load. The pool never blocks —
    a concurrency burst simply opens extra connections and closes them on
    return — so a slow publish cannot stall a claim prepare (the dra.py
    lock-scope rationale). A request that fails at send/first-byte on a
    REUSED connection is retried once on a brand-new one (the server
    idled out the keep-alive); a fresh-connection failure propagates,
    matching the one-attempt behavior this client always had.

    Connections are DIRECT (http.client): HTTP(S)_PROXY env vars, which
    the pre-pool urllib implementation honored, are intentionally not —
    an in-cluster node agent talks straight to its apiserver. A path
    component in the server URL (e.g. an apiserver proxy prefix) is
    preserved and prepended to every request path.
    """

    def __init__(self, server: str,
                 token_path: str = os.path.join(SA_DIR, "token"),
                 ca_path: str = os.path.join(SA_DIR, "ca.crt"),
                 timeout_s: float = 10.0,
                 breaker: Optional[CircuitBreaker] = None):
        self.server = server.rstrip("/")
        self.token_path = token_path
        self.ca_path = ca_path
        self.timeout_s = timeout_s
        split = urlsplit(self.server)
        self._https = split.scheme == "https"
        self._host = split.hostname or self.server
        self._port = split.port
        self._base_path = split.path.rstrip("/")
        self._idle: list = []
        self._pool_lock = lockdep.instrument(
            "kubeapi.ApiClient._pool_lock", threading.Lock())
        # Circuit breaker over the whole client (resilience.py): transport
        # failures and 5xx count as failures, any response < 500 (including
        # 4xx — the server answered) as success. While open, request()
        # fails fast with ApiError instead of burning a connect timeout per
        # call — the callers' own retry loops (lifecycle publish retry, dra
        # republish timer) keep running and land on the half-open probe.
        self.breaker = breaker or CircuitBreaker(
            failure_threshold=5, reset_timeout_s=15.0,
            name=f"kubeapi:{self._host}")
        # brief jittered pause before the single stale-keep-alive retry
        # (below): lets a restarting apiserver finish its listen() instead
        # of immediately eating the one retry the contract allows
        self._stale_backoff = BackoffPolicy(base_s=0.02, cap_s=0.2)

    def _new_conn(self) -> http.client.HTTPConnection:
        if self._https:
            # context rebuilt per NEW connection (cheap — pooling makes
            # new connections rare): the projected ca.crt rotates like
            # the token does, and a cached context would pin the old CA,
            # failing every handshake after a cluster CA rotation until
            # pod restart. Established pooled connections are unaffected
            # by rotation (their handshake is done).
            ctx = ssl.create_default_context(
                cafile=self.ca_path if os.path.exists(self.ca_path)
                else None)
            return http.client.HTTPSConnection(
                self._host, self._port, context=ctx,
                timeout=self.timeout_s)
        return http.client.HTTPConnection(
            self._host, self._port, timeout=self.timeout_s)

    def _get_conn(self):
        """→ (connection, was_reused)."""
        with self._pool_lock:
            if self._idle:
                return self._idle.pop(), True
        return self._new_conn(), False

    def _put_conn(self, conn) -> None:
        with self._pool_lock:
            if len(self._idle) < MAX_IDLE_CONNECTIONS:
                self._idle.append(conn)
                return
        conn.close()

    def request(self, path: str, method: str = "GET",
                body: Optional[bytes] = None,
                content_type: Optional[str] = None) -> bytes:
        """Raw request against an API path; raises ApiError on failure.

        Fails fast (without touching the network) while the circuit
        breaker is open; every attempt's outcome feeds the breaker.

        The span (op "kubeapi.request", tdp_kubeapi_rtt_ms) is the
        daemon's apiserver-RTT observability: started inside a claim
        span it inherits the claim_uid, so a prepare stalled on a slow
        ResourceClaim GET is attributable from /debug/flight alone.
        """
        url = self.server + path
        # breaker fast-fail OUTSIDE the span: an open breaker rejects in
        # microseconds, and recording those as RTT samples would collapse
        # tdp_kubeapi_rtt_ms percentiles to ~0 exactly when the apiserver
        # is down — the opposite of what the histogram exists to show
        if not self.breaker.allow():
            raise ApiError(f"{method} {url}: circuit breaker open "
                           f"(apiserver failing; next probe within "
                           f"{self.breaker.reset_timeout_s:.0f}s)",
                           code=0)
        with trace.span("kubeapi.request", histogram="tdp_kubeapi_rtt_ms",
                        method=method, path=path):
            try:
                # fault point "kubeapi.request" (raising): an armed fault
                # fails the request before the wire, as a transport error
                # would
                faults.fire("kubeapi.request", method=method, path=path)
                data = self._request_once(path, method, body, content_type,
                                          url)
            except ApiError as exc:
                if exc.code == 0 or exc.code >= 500:
                    self.breaker.record_failure()
                else:
                    self.breaker.record_success()  # 3xx/4xx: alive
                raise
            except Exception as exc:
                # injected fault of a non-ApiError kind: surface it under
                # the client's one exception contract
                self.breaker.record_failure()
                raise ApiError(f"{method} {url}: {exc}") from exc
            self.breaker.record_success()
            self._stale_backoff.reset()
            return data

    def _request_once(self, path: str, method: str, body: Optional[bytes],
                      content_type: Optional[str], url: str) -> bytes:
        """One logical request: pool checkout, send, narrow stale-keep-alive
        retry, status handling. Raises ApiError on any failure."""
        headers = {}
        if content_type:
            headers["Content-Type"] = content_type
        # token re-read per request: in-cluster tokens rotate
        try:
            with open(self.token_path, "r", encoding="ascii") as f:
                headers["Authorization"] = f"Bearer {f.read().strip()}"
        except OSError:
            pass  # no token (e.g. test server without auth)
        for attempt in (0, 1):
            if attempt == 0:
                conn, reused = self._get_conn()
            else:
                # retry leg: ALWAYS a brand-new connection — popping
                # another pool member could hit a second stale keep-alive
                # (apiserver restart with several idle conns) and fail a
                # request a fresh connection would serve
                conn, reused = self._new_conn(), False
            # The SEND phase and the RESPONSE phase have different retry
            # safety: a send-phase failure means the server never got the
            # full request (any method can retry); a response-phase
            # failure means it may have PROCESSED it, so only GET — whose
            # replay cannot duplicate a write — retries there.
            sent = False
            try:
                conn.request(method, self._base_path + path, body=body,
                             headers=headers)
                sent = True
                resp = conn.getresponse()
                data = resp.read()
            except (http.client.HTTPException, OSError) as exc:
                conn.close()
                retry_safe = (not sent) or method == "GET"
                if (attempt == 0 and reused and retry_safe
                        and isinstance(exc, _RETRYABLE_STALE)):
                    # idled-out keep-alive: one fresh retry, after a short
                    # jittered pause (BackoffPolicy; reset on any success)
                    time.sleep(self._stale_backoff.next_delay())
                    continue
                raise ApiError(f"{method} {url}: {exc}") from exc
            if resp.will_close:
                conn.close()
            else:
                self._put_conn(conn)
            if resp.status >= 400:
                detail = data.decode("utf-8", "replace")[:300]
                raise ApiError(
                    f"{method} {url}: HTTP {resp.status} {detail}",
                    code=resp.status)
            if resp.status >= 300:
                # the pre-pool urllib client auto-followed redirects;
                # http.client does not, and silently returning a redirect
                # body would feed HTML into json.loads — surface it as
                # the transport error it is
                raise ApiError(
                    f"{method} {url}: HTTP {resp.status} redirect "
                    f"(redirects unsupported; point --api-server at the "
                    f"final URL)", code=resp.status)
            return data
        raise ApiError(f"{method} {url}: retry fell through")  # unreachable

    # -- JSON convenience wrappers against resource paths ---------------------

    def get_json(self, path: str) -> dict:
        return json.loads(self.request(path))

    def post_json(self, path: str, obj: dict) -> dict:
        return json.loads(self.request(
            path, method="POST", body=json.dumps(obj).encode(),
            content_type="application/json"))

    def put_json(self, path: str, obj: dict) -> dict:
        return json.loads(self.request(
            path, method="PUT", body=json.dumps(obj).encode(),
            content_type="application/json"))

    def delete(self, path: str) -> None:
        self.request(path, method="DELETE")

    def patch_strategic(self, path: str, obj: dict) -> bytes:
        return self.request(
            path, method="PATCH", body=json.dumps(obj).encode(),
            content_type="application/strategic-merge-patch+json")
