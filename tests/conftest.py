"""Test env: force JAX onto a virtual 8-device CPU mesh before any jax import.

Exception: TDP_TPU_TESTS=1 leaves the platform un-pinned so the `-m tpu`
Mosaic-compile gate (tests/test_tpu_gate.py) can claim the real chip. Use it
only for that file — running the whole suite that way would put every jax
test in contention for the single exclusive-claim TPU:

    TDP_TPU_TESTS=1 python -m pytest tests/test_tpu_gate.py -v
"""

import os
import shutil
import sys
import tempfile

import pytest

_want_tpu = os.environ.get("TDP_TPU_TESTS") == "1"
if not _want_tpu:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

# Some environments force-register an out-of-process TPU PJRT plugin from
# sitecustomize, overriding JAX_PLATFORMS; initializing it would contend for
# the (single) real chip from every test process. Pin the config to CPU
# before any backend initialization.
if not _want_tpu:
    try:
        import jax

        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Runtime lockdep (tpu_device_plugin/lockdep.py): with TDP_LOCKDEP=1 the
# whole suite doubles as a race detector — every registered lock records
# its acquisition order and hold times, and the session FAILS on any
# observed lock-order inversion, cycle, or watched-lock long hold, plus on
# leaked daemon threads. Enabled HERE, before any tpu_device_plugin module
# is imported, because module-level locks (faults._lock) are instrumented
# at import time.
_lockdep_on = os.environ.get("TDP_LOCKDEP") == "1"
if _lockdep_on:
    from tpu_device_plugin import lockdep as _lockdep

    _lockdep.enable()

# thread-name prefixes owned by this codebase: anything with one of these
# names still alive at session end (after a settle window) was leaked by
# an owner whose stop() path lost it
_OWNED_THREAD_PREFIXES = (
    "healthhub", "dra-prepare", "dra-ckpt", "dra-reserve", "restart-",
    "plugin-start", "status-http", "health-", "dp-", "reflector-",
    "autopilot-",
)


def _leaked_threads(settle_s: float = 5.0):
    """Our named threads still alive after up to `settle_s` of grace (join
    timeouts in stop() paths are bounded; give stragglers that long)."""
    import threading
    import time

    deadline = time.monotonic() + settle_s
    while True:
        leaked = [t for t in threading.enumerate()
                  if t.is_alive()
                  and t.name.startswith(_OWNED_THREAD_PREFIXES)]
        if not leaked or time.monotonic() >= deadline:
            return leaked
        time.sleep(0.1)


# weave smoke gate (docs/static-analysis.md "Deterministic interleaving
# checking"): a sub-second slice of the schedule-exploration matrix runs
# at session end so a regressed lock-free invariant — or a checker that
# can no longer fire — fails the DEFAULT tier-1 run, not only the
# dedicated CI weave job. The full matrix is `make weave`.
_WEAVE_SMOKE_SCENARIOS = (
    "epoch-publish-waiter",     # complete reduced space in 2 executions
    "ring-seqlock",             # seqlock torn-read guard, ~60 executions
    "placement-cas-race",       # CAS single-winner, 3 executions
    "breaker-half-open-probe",  # half-open single-probe, 3 executions
)
_WEAVE_SMOKE_TWIN = "twin-epoch-publish-no-notify"   # must FIRE


def _weave_smoke_problems():
    from tools.weave.core import explore
    from tools.weave.scenarios import SCENARIOS, TWINS

    problems = []
    for name in _WEAVE_SMOKE_SCENARIOS:
        res = explore(SCENARIOS[name]())
        if not res.ok:
            assert res.counterexample is not None
            problems.append(
                f"weave smoke: {name} found a counterexample "
                f"({res.counterexample.failure}); replay via "
                f"`python -m tools.weave --scenario {name}`")
    twin = explore(TWINS[_WEAVE_SMOKE_TWIN]())
    if twin.counterexample is None:
        problems.append(
            f"weave smoke: {_WEAVE_SMOKE_TWIN} did NOT fire — the "
            f"lost-wakeup checker is dead (mutation test)")
    return problems


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "tpu: needs a real TPU backend (TDP_TPU_TESTS=1)")
    config.addinivalue_line(
        "markers", "slow: long randomized chaos soak (TDP_CHAOS_SOAK=1; "
                   "run via `make chaos-soak`)")


def pytest_sessionfinish(session, exitstatus):
    """Fail the run on lockdep violations / thread leaks (TDP_LOCKDEP=1).

    Without TDP_LOCKDEP the leak scan still runs and prints, so a leak
    regression is visible in any tier-1 log even before the dedicated CI
    lockdep job catches it."""
    problems = []      # enforced only under TDP_LOCKDEP=1
    enforced = []      # enforced in EVERY run (weave smoke gate)
    leaked = _leaked_threads()
    if leaked:
        problems.append(
            "thread leak: " + ", ".join(sorted(t.name for t in leaked))
            + " still alive at session end (stop() paths must join)")
    if os.environ.get("TDP_WEAVE_SMOKE") != "0":
        try:
            enforced.extend(_weave_smoke_problems())
        except Exception as exc:   # a broken explorer is a failure too
            enforced.append(f"weave smoke: explorer crashed: {exc!r}")
    if _lockdep_on:
        rep = _lockdep.report()
        violations = rep.violations()
        print("\n" + rep.render(stacks=bool(violations)))
        problems.extend(violations)
    if problems or enforced:
        print("\nconcurrency gate FAILED:")
        for p in problems + enforced:
            print("  " + p)
        if _lockdep_on or enforced:
            session.exitstatus = 1
        else:
            print("  (TDP_LOCKDEP not set: reported, not enforced)")


class FakeClock:
    """Injectable monotonic clock for CircuitBreaker tests — advance time
    without sleeping (used by test_resilience.py and test_kubeapi.py)."""

    def __init__(self):
        self.now = 0.0

    def __call__(self):
        return self.now

    def advance(self, s):
        self.now += s


@pytest.fixture
def short_root():
    """A short tmpdir for fixtures that bind unix sockets: pytest's tmp_path
    can push socket paths past the kernel's 107-char sun_path limit."""
    root = tempfile.mkdtemp(prefix="tdp-")
    yield root
    shutil.rmtree(root, ignore_errors=True)
