"""DRA (Dynamic Resource Allocation) driver — the successor kubelet API.

The device-plugin API (server.py / vtpu.py) advertises opaque counted
resources; DRA instead publishes every chip/partition as a structured
device in a `ResourceSlice` (attributes: generation, NUMA node, ICI torus
coordinates, IOMMU group), lets the *scheduler* allocate specific devices
against `ResourceClaims`, and has the kubelet call this node-local driver
to prepare them. That moves topology-aware placement from our
GetPreferredAllocation heuristic (topology.py) into cluster-wide CEL
selectors over the published ICI attributes — the long-term home for
slice-aware scheduling.

The reference plugin predates DRA entirely (its nearest analogues:
registration generic_device_plugin.go:288-309, Allocate :352-444); NVIDIA
ships DRA support as the separate k8s-dra-driver-gpu project. Here it is a
third server inside the same binary, sharing discovery, the
AllocationPlanner (TOCTOU revalidation, IOMMU-group expansion, iommufd,
shared-device injection) and the CDI writer with the device-plugin path, so
a cluster can run either API — or both during migration — from one
DaemonSet.

Flow:
  1. `publish_resource_slices()` — POST/PUT one ResourceSlice for this node
     (stdlib ApiClient; pool generation bumps on inventory change).
  2. kubelet discovers the registration socket under plugins_registry/ and
     calls GetInfo (pluginregistration/v1) → we answer type=DRAPlugin.
  3. Scheduler allocates claim → kubelet calls NodePrepareResources
     (dra/v1beta1): we fetch the ResourceClaim's allocation from the API
     server, plan device nodes exactly like Allocate would, write ONE
     per-claim CDI spec carrying deviceNodes + the KubeVirt
     PCI_RESOURCE_* env contract, checkpoint it, and return the CDI id.
  4. NodeUnprepareResources removes the spec + checkpoint entry.
Prepare/unprepare are idempotent across kubelet and driver restarts (the
checkpoint file is the source of truth, like upstream DRA drivers).
"""

from __future__ import annotations

import collections
import hashlib
import itertools
import json
import os
import re
import tempfile
import threading
import time
from concurrent import futures
from contextlib import contextmanager
from typing import Deque, Dict, List, Optional, Sequence, Tuple

import grpc

from . import broker as broker_mod
from . import epoch as epoch_mod
from . import faults
from . import lockdep
from . import placement
from . import trace
from .log import get_logger
from .allocate import (AllocationError, AllocationPlanner, LiveAttrReader,
                       live_mdev_type)
from .config import Config
from .kubeapi import ApiClient, ApiError, PublishPacer, Reflector
from .resilience import BackoffPolicy
from .kubeletapi import RawResponse, draapi, drapb, regpb, wants_raw
from .naming import GenerationInfo, sanitize_name
from .registry import Registry, TpuDevice, TpuPartition

log = get_logger(__name__)

RESOURCE_API = "/apis/resource.k8s.io/v1beta1"   # fallback when undiscoverable
# REST versions this driver can speak, newest first. v1 flattens the
# v1beta1 device entry (attributes directly on the device, no "basic"
# wrapper); v1beta2 already uses the flattened v1 shape (it is
# schema-identical to v1 for everything this driver touches, covering a
# k8s 1.33 apiserver with v1beta1 disabled before v1 exists). The served
# version is discovered from the API group document at first use so an
# apiserver that dropped v1beta1 does not strand the driver
# (VERDICT r3 item 7).
RESOURCE_API_VERSIONS = ("v1", "v1beta2", "v1beta1")
CDI_VERSION = "0.6.0"
# retry cadence CAP for a health-triggered republish that failed (transient
# apiserver blip / resourceVersion conflict). The actual delay is drawn by
# a decorrelated-jitter BackoffPolicy (resilience.py) between
# HEALTH_REPUBLISH_BASE_S and this cap, so a fleet of nodes that lost the
# apiserver together does not republish in lockstep when it returns.
HEALTH_REPUBLISH_RETRY_S = 30.0
HEALTH_REPUBLISH_BASE_S = 5.0
# Distinct CDI class from cdi.py's per-chip "tpu" kind: claim devices are
# composite (all of a claim's nodes + env in one entry) and live in
# per-claim spec files created/removed at prepare/unprepare time.
CDI_CLAIM_CLASS = "claim"
# Group-commit coalescing cap for the checkpoint writer (see
# _checkpoint_writer_loop): once woken, the writer holds the commit while
# other attach tasks are still in flight — their mutations ride the same
# atomic write — but never longer than this, bounding any one claim's ACK
# delay. A lone prepare pays ~zero extra latency: its flush drops the
# in-flight count to 0 and the writer commits immediately. 10 ms merges a
# worker-pool wave's completions with the next wave's (a 32-claim burst at
# 8 workers lands in <= 4 writes, measured); against a VM-boot-scale
# attach path the worst-case ACK delay it can add is negligible.
CHECKPOINT_COMMIT_WINDOW_S = 0.010
# Idle exit for the group-commit writer thread: with nothing dirty for
# this long the thread returns instead of parking on the condvar forever.
# Safe because EVERY producer (_checkpoint_flush / _checkpoint_mark_dirty)
# calls _ensure_checkpoint_writer_locked first — the next mutation
# respawns it — and a driver dropped without stop() (tests, embedders)
# then sheds its writer instead of leaking one per driver lifetime.
CHECKPOINT_WRITER_IDLE_S = 2.0
# ---- checkpoint schema versioning (daemon upgrade under live allocations)
# v0 (pre-lifecycle): the bare {uid: entry} claim map, no version key.
# v1: {"version": 1, "claims": {...}, "handoffs": {...}} — claim entries
# additionally carry the devices' raw ids and the claim's allocation
# generation; "handoffs" holds the migration records NodeUnprepareResources
# emits. Forward migrations live in _CKPT_MIGRATIONS; a checkpoint from a
# NEWER daemon refuses to load (CheckpointVersionError) instead of being
# silently truncated and then overwritten by the next group commit.
CHECKPOINT_VERSION = 1
# migration handoff records retained on the source: bounded so a node that
# unprepares thousands of claims over its lifetime cannot grow the
# checkpoint without bound (oldest dropped first; a consumed or
# re-prepared claim's record is dropped eagerly)
HANDOFF_MAX_RECORDS = 64


class CheckpointVersionError(RuntimeError):
    """The on-disk checkpoint was written by a NEWER daemon than this
    binary. Refusing to start is the only safe move: loading would drop
    fields the newer schema relies on and the next group commit would
    overwrite (corrupt) the file — a rollback must ship a binary that
    speaks the schema, not eat the node's claim state."""


class HandoffValidationError(AllocationError):
    """A migration handoff record failed validation against the live
    ResourceClaim (UID or allocation-generation mismatch): the claim was
    deleted/re-allocated since the source emitted the record, so
    preparing from it would attach the pod to stale devices."""


def _ckpt_v0_to_v1(data: dict) -> dict:
    """v0 → v1: wrap the bare uid→entry map. Entries gain no mandatory
    fields (device_raws / generation / orphaned are all optional), so
    pre-upgrade claims keep working; they just lack lifecycle metadata
    until re-prepared."""
    claims = {uid: entry for uid, entry in data.items()
              if isinstance(entry, dict)}
    return {"version": 1, "claims": claims, "handoffs": {}}


# version N -> migration producing version N+1; applied in sequence by
# _load_checkpoint until CHECKPOINT_VERSION is reached
_CKPT_MIGRATIONS = {0: _ckpt_v0_to_v1}


def slice_device_name(raw: str) -> str:
    """DNS-label device name for a ResourceSlice entry.

    BDFs ("0000:00:04.0") and mdev UUIDs contain characters outside the
    [a-z0-9-] label alphabet; the mapping must stay injective enough to
    invert via the name→object map built at publish time.
    """
    name = re.sub(r"[^a-z0-9-]", "-", raw.lower())
    name = name.strip("-") or "dev"
    if not name[0].isalpha():
        name = "d" + name
    return name[:63]


def _dump_compact(obj: dict) -> str:
    """The one serialization for driver state files: compact separators
    (no indent, no space after ':' or ','). At 1024 claims the indent=1
    form the checkpoint used through PR 8 paid ~35% more bytes per group
    commit — pure fsync'd whitespace (the perf-honesty size bound pins
    the compact form). sort_keys keeps writes byte-stable for diffing."""
    return json.dumps(obj, separators=(",", ":"), sort_keys=True)


def _atomic_write_text(path: str, text: str) -> None:
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path) or ".",
                               suffix=".tmp")
    try:
        with os.fdopen(fd, "w", encoding="utf-8") as f:
            f.write(text)
        os.replace(tmp, path)
    except OSError:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def _atomic_write_json(path: str, obj: dict) -> None:
    _atomic_write_text(path, _dump_compact(obj))


class DraDriver(draapi.DraPluginServicer, draapi.PluginRegistrationServicer):
    """Node-local DRA driver sharing the plugin's discovery snapshot."""

    def __init__(
        self,
        cfg: Config,
        registry: Registry,
        generations: Dict[str, GenerationInfo],
        node_name: Optional[str] = None,
        api: Optional[ApiClient] = None,
        driver_name: Optional[str] = None,
        policy=None,
        remediation=None,
    ) -> None:
        self.cfg = cfg
        self.node_name = node_name or os.environ.get("NODE_NAME") or "node"
        self.api = api
        # Optional policy.PolicyEngine: the prepare plane consults its
        # admit hook per claim (a rejection is that claim's typed error,
        # never the RPC's); None costs one attribute check
        self._policy = policy
        # Optional remediation.RemediationEngine: its admission throttle
        # (armed only while an SLO burns) sheds prepares above the token
        # rate with a typed per-claim error — same retry contract as a
        # policy rejection; None costs one attribute check
        self._remediation = remediation
        self.driver_name = driver_name or cfg.resource_namespace
        self._driver_fs = sanitize_name(self.driver_name).lower().replace(
            "_", "-")
        self.driver_dir = os.path.join(cfg.dra_plugins_path, self.driver_name)
        self.dra_socket_path = os.path.join(self.driver_dir, "dra.sock")
        self.registration_socket_path = os.path.join(
            cfg.dra_registry_path, f"{self._driver_fs}-reg.sock")
        self.checkpoint_path = os.path.join(self.driver_dir, "checkpoint.json")
        self.cdi_dir = cfg.cdi_spec_dir or os.path.join(
            cfg.root_path, "var/run/cdi")
        self.registered = threading.Event()
        self.registration_error: Optional[str] = None
        self._lock = lockdep.instrument(
            "dra.DraDriver._lock", threading.Lock())
        # serializes server bring-up/teardown against the hub-triggered
        # re-serve (see attach_health_hub / _restart_serving)
        self._serve_lock = lockdep.instrument(
            "dra.DraDriver._serve_lock", threading.Lock())
        # the hub-triggered re-serve runner; event-paced so stop() can wake
        # a mid-backoff sleep, tracked so stop() can join it (timeout)
        self._reserve_thread: Optional[threading.Thread] = None
        self._stopping = threading.Event()
        self._health_hub = None
        self._health_sub = None
        self._dra_server: Optional[grpc.Server] = None
        self._reg_server: Optional[grpc.Server] = None
        self._node_uid: Optional[str] = None
        # raw ids (BDF / partition uuid) currently Unhealthy per the plugin
        # servers' ANDed health verdict; such devices are pruned from the
        # published ResourceSlice so a DRA-only scheduler can never allocate
        # dead hardware (parity with the classic path's one-ListAndWatch-send
        # propagation, server.py set_devices_health). Keyed by raw id so the
        # set survives set_inventory() swaps. WRITER-owned (mutated under
        # _lock); readers see the frozenset published into the epoch.
        self._unhealthy: set = set()
        # The read plane (epoch.py): prepare planning, slice builds and
        # /status read `self._inv_store.current` — an immutable
        # InventoryEpoch (by_name, planners, parent planner, unhealthy
        # set) — and never take _lock. set_inventory/apply_health are the
        # only publishers (under _lock).
        self._inv_store = epoch_mod.EpochStore(
            initial=epoch_mod.InventoryEpoch(0))
        self._republish_timer: Optional[threading.Timer] = None
        # jittered delay for the self-armed republish retry; reset by any
        # successful publish. Chaos tests inject a seeded/faster policy.
        self.republish_backoff = BackoffPolicy(
            base_s=HEALTH_REPUBLISH_BASE_S, cap_s=HEALTH_REPUBLISH_RETRY_S)
        self._stopped = False
        self._resource_version_cache: Optional[str] = None
        # Last successful slice write: {rv, generation, projection, version}.
        # Lets a health-only change publish as ONE guarded PUT keyed by the
        # locally-tracked pool generation (generation+1 under the cached
        # resourceVersion) instead of the whole GET+diff+PUT read-modify-
        # write; any interleaved writer surfaces as a 409 and falls back.
        # Guarded by _publish_lock (only _publish_locked touches it).
        self._last_publish: Optional[dict] = None
        # delta vs full publish counters for /status + /metrics.
        # watch_read_skips counts unchanged-projection publishes that
        # skipped their liveness GET because a live watch stream covers
        # the wipe-detection the GET existed for (ISSUE 12) — the
        # steady-state read/repair churn the watch plane removes.
        self.publish_stats = {"full": 0, "delta": 0, "delta_conflicts": 0,
                              "watch_read_skips": 0}
        # serializes slice publishes against each other AND against
        # stop(withdraw_slice=True): an in-flight retry publish racing the
        # withdraw could otherwise POST the slice back after the delete
        self._publish_lock = lockdep.instrument(
            "dra.DraDriver._publish_lock", threading.Lock())
        # Fleet-scale publish pacing + coalescing (kubeapi.PublishPacer):
        # every publish_resource_slices goes through it. With the default
        # base window 0 an uncongested publish pays nothing; under an
        # apiserver 429/latency storm the jittered admission window opens
        # and concurrent publish requests coalesce into waves. Sits
        # OUTSIDE _publish_lock so coalescers meet in the pacer instead
        # of queueing on the lock.
        self.pacer = PublishPacer(
            api=api,
            base_window_s=getattr(cfg, "publish_pace_base_s", 0.0),
            max_window_s=getattr(cfg, "publish_pace_max_s", 2.0))
        # ---- watch-driven slice convergence (ISSUE 12) -------------------
        # An informer-style reflector (kubeapi.Reflector) over the
        # resourceslices collection replaces the read/repair churn: a
        # slice wiped or mutated behind our back is OBSERVED as a watch
        # event and repaired through the normal guarded-write path,
        # instead of being discovered by periodic liveness GETs. Started
        # explicitly (start_watch_reconciler — cli.main / fleetsim wire
        # it); None = the pre-watch polling behavior, unchanged. The
        # reflector degrades to paced-relist polling by itself when the
        # apiserver loses (or never had) watch support — typed, counted,
        # /status-visible, never a hang.
        self._slice_watch: Optional[Reflector] = None
        # repairs triggered by watch observations (lock-free owned,
        # like the trace-plane counters)
        self.watch_repairs = epoch_mod.AtomicCounter()
        # Watch observations of a wipe/divergence arriving while a
        # publish holds _publish_lock are DEFERRED (acting on evidence
        # read against a half-updated window is wrong, but FORGETTING
        # it would leave the wipe unhealed until the resync backstop):
        # the reflector thread bumps _watch_deferred_seq, and while it
        # is ahead of _watch_deferred_ack the unchanged-projection
        # publish pays its classic liveness GET instead of taking the
        # watch_read_skips fast path. The ack advances only to the seq
        # captured BEFORE a publish that SUCCEEDED — a failed attempt
        # keeps the deferral for the republish retry, and evidence
        # arriving mid-publish outruns the ack and forces another GET.
        # GIL-atomic ints: seq has one writer (the reflector thread),
        # ack has one writer (the publish path under _publish_lock).
        self._watch_deferred_seq = 0
        self._watch_deferred_ack = 0
        # True once this driver has successfully published its slice at
        # least once — the watch reconciler must not "repair" a slice
        # that was never published (boot is the publisher's job)
        self._has_published = False
        # highest pool generation this driver ever published (process
        # lifetime): a repair that RECREATES a wiped slice continues the
        # sequence instead of resetting to 1 — a reset would make old
        # allocations look newer than the live pool AND replay already-
        # used generations into the fabric's exactly-once write audit
        self._last_generation = 0
        # name-stability records (see _assign_slice_names), persisted
        # beside the claim checkpoint so neither an inventory swap nor a
        # driver restart (DaemonSet upgrade) can re-point a published name
        # under a live claim
        self.sticky_names_path = os.path.join(self.driver_dir,
                                              "sticky-names.json")
        # serializes sticky-name writers (the write itself runs outside
        # the global lock; see _save_sticky_names)
        self._sticky_save_lock = lockdep.instrument(
            "dra.DraDriver._sticky_save_lock", threading.Lock())
        self._sticky_suffixed, self._label_owners = self._load_sticky_names()
        # live mdev_type/name reads for the prepare-path TOCTOU check
        self._mdev_name_reader = LiveAttrReader()
        # ---- attach plane (burst throughput) --------------------------------
        # Per-claim-UID locks: two kubelet retries of the SAME claim
        # serialize (prepare/unprepare stay idempotent and can never
        # interleave), while different claims never queue behind each
        # other's API-server fetch or sysfs reads. Entries are refcounted
        # away so a node-recovery storm cannot grow the map unboundedly.
        self._claim_locks: Dict[str, list] = {}   # uid -> [lock, refcount]
        self._claim_locks_lock = lockdep.instrument(
            "dra.DraDriver._claim_locks_lock", threading.Lock())
        # bounded pool fanning a multi-claim NodePrepareResources /
        # NodeUnprepareResources out (threads spawn lazily on first submit)
        self.prepare_workers = max(1, getattr(cfg, "prepare_workers", 4))
        self._prepare_pool = futures.ThreadPoolExecutor(
            max_workers=self.prepare_workers,
            thread_name_prefix="dra-prepare")
        # ---- group-committed checkpoint durability --------------------------
        # One writer thread coalesces concurrently-completed claim
        # mutations into one atomic checkpoint write per commit; each
        # prepare/unprepare blocks on the flush barrier until its entry is
        # durable before ACKing (exactly-once preserved: never ACK before
        # it is on disk). All state below is guarded by _ckpt_cond.
        self._ckpt_cond = lockdep.instrument(
            "dra.DraDriver._ckpt_cond", threading.Condition())
        self._ckpt_dirty_gen = 0      # bumped per mutation
        self._ckpt_result_gen = 0     # covered by a COMPLETED write attempt
        self._ckpt_durable_gen = 0    # covered by a SUCCESSFUL write
        self._ckpt_error: Optional[BaseException] = None  # last attempt's
        # failed-attempt generation intervals (gen_lo, gen_hi, err]: a
        # waiter whose target landed inside a FAILED commit must raise
        # that attempt's error even if a LATER successful retry (covering
        # other claims' rollbacks plus this still-present entry) advanced
        # _ckpt_durable_gen past its target first — the claim was told
        # nothing durable happened, so ACKing off the retry would be a
        # silent ACK the rollback then immediately un-commits. Bounded:
        # waiters scan on the wake that follows each publish, so stale
        # intervals die within one scheduling quantum.
        self._ckpt_failures: Deque[tuple] = collections.deque(maxlen=64)
        self._ckpt_pending_claims = 0  # mutations since the last write
        self._ckpt_thread: Optional[threading.Thread] = None
        self._ckpt_stopped = False
        self._attach_active = 0       # claim tasks not yet at their barrier
        self._prepare_inflight = 0    # claim tasks in flight (status gauge)
        self._checkpoint_bytes = 0    # size of the last committed write
        self.checkpoint_commit_window_s = CHECKPOINT_COMMIT_WINDOW_S
        self.checkpoint_stats_counters = {
            # atomic checkpoint file writes vs claim mutations made durable
            # by them: commits << claims under a burst is the win
            "checkpoint_commits_total": 0,
            "checkpoint_claims_coalesced_total": 0,
        }
        # ---- lifecycle survivability -----------------------------------
        # raw id -> published name of devices REMOVED from the inventory
        # by hot-unplug (apply_gone); cleared when rediscovery readmits
        # the raw id. Writer-owned (mutated under _lock); the published
        # epoch carries the name frozenset for the prepare path.
        self._departed: Dict[str, str] = {}
        # raw id -> (generation name, ici coords) captured AT departure:
        # the fragmentation view must keep counting the gone chip's torus
        # hole (ISSUE 10 satellite) even after rediscovery swaps in a
        # registry that no longer knows the device. Lifecycle matches
        # _departed exactly (written in apply_gone, pruned with it in
        # set_inventory).
        self._departed_meta: Dict[str, tuple] = {}
        # migration handoff counters; mutated under _lock, read lock-free
        # by checkpoint_stats (fixed keys, C-atomic dict copy)
        self.handoff_stats = {
            "handoffs_emitted_total": 0,
            "handoffs_completed_total": 0,
        }
        # handoff records staged by import_handoff for the destination's
        # next prepare of that claim UID (in-memory: the record's source
        # of truth is the SOURCE node's checkpoint)
        self._incoming_handoffs: Dict[str, dict] = {}
        # ---- prepare-ack byte plane (round 15) --------------------------
        # uid -> (devices-list object, serialized NodePrepareResourceResponse
        # payload). A prepared claim's ack is deterministic given its
        # checkpoint entry's devices list, so the segment is serialized
        # ONCE and reused by every kubelet retry; invalidation is BY
        # CONSTRUCTION via object identity — any path that changes a
        # claim's devices builds a NEW list (the orphan-mark swap copies
        # the entry but keeps the list: the ack is still correct), and
        # unprepare pops the cache with the entry. In-memory only (the
        # JSON checkpoint stays bytes-free); single-key dict ops are
        # GIL-atomic, so pool workers never lock here.
        self._ack_cache: Dict[str, Tuple[object, bytes]] = {}
        self._ack_bytes_reused = epoch_mod.AtomicCounter()
        self._ack_serializations = epoch_mod.AtomicCounter()
        # host lifecycle FSM (lifecycle_fsm.DeviceLifecycle), attached by
        # cli.py via attach_lifecycle; None when running DRA standalone
        self._lifecycle = None
        # ---- slice placement / fragmentation (placement.py) -------------
        # per-generation fragmentation records, recomputed by the WRITER
        # on every inventory-epoch publish and once per checkpoint GROUP
        # COMMIT (claim mutations coalesce with the write itself), and
        # swapped wholesale — /status and /metrics read the attribute
        # with zero locks (the /status gate pins it). The counters
        # mutate under _lock (tsalint COUNTERS ownership).
        self._fragmentation: Dict[str, dict] = {}
        self.placement_stats = {
            "frag_recomputes_total": 0,
            "defrag_proposals_total": 0,
            "defrag_unsatisfiable_total": 0,
        }
        # set_inventory() (below) recomputes fragmentation from the claim
        # map; at construction the checkpoint is not loaded yet, so start
        # empty and recompute again once it is
        self._checkpoint: Dict[str, dict] = {}
        self.set_inventory(registry, generations)
        loaded = self._load_checkpoint()
        self._checkpoint = loaded["claims"]
        # migration handoff records this node emitted, persisted in the
        # checkpoint so a source-daemon crash/upgrade between unprepare
        # and the destination's prepare cannot lose the handoff
        self._handoffs: Dict[str, dict] = loaded["handoffs"]
        # startup orphan sweep: claim-spec files whose UID the loaded
        # checkpoint does not know (crash between spec write and
        # checkpoint commit) are deleted, not leaked forever
        self.orphan_specs_removed = self._sweep_orphan_specs()
        # restored claims occupy slots: fragmentation must see them
        self._recompute_fragmentation()
        # warm byte plane: pre-serialize every restored claim's ack NOW,
        # before the kubelet reconnects — its post-restart
        # NodePrepareResources replays then hit the byte cache instead of
        # paying first-touch serialization during the restart storm
        self.warm_ack_cache()

    # ---------------------------------------------------------- inventory

    @staticmethod
    def _assign_slice_names(raws, sticky=frozenset(),
                            owners=None) -> Dict[str, str]:
        """raw id → collision-safe DNS-label name.

        slice_device_name() is lossy (lowercasing + non-[a-z0-9-] collapse
        + 63-char truncation), so two distinct raw ids can map to one label
        — silently overwriting the earlier device in _by_name and
        publishing duplicate names in one ResourceSlice, after which a
        prepare could hand out the WRONG device. Every member of a
        colliding label group gets a digest suffix — including the first,
        so a device's published name is a pure function of the raw id set's
        collisions, never of iteration order (an order-dependent plain
        label could be inherited by a DIFFERENT device after an inventory
        swap, silently re-pointing old claims).

        Two sticky records close the across-swap/restart holes, both
        persisted in sticky-names.json beside the claim checkpoint and
        kept for the driver's installed lifetime:

        - `sticky` raws are suffixed unconditionally: once a raw id has
          ever been published digest-suffixed, a later swap that removes
          the rest of its collision group must NOT flip the survivor back
          to the plain label, or a ResourceClaim allocated under the old
          suffixed name would fail the _plan_devices lookup on a
          post-swap prepare retry.
        - `owners` maps each plain label ever published to the raw id it
          named. A DIFFERENT raw id arriving later with the same
          sanitized label (whether or not the two ever coexist) must not
          take the plain label — an old claim against it would silently
          resolve to the WRONG device. Non-owners are suffixed; the
          recorded owner keeps the plain label whenever present, even
          inside a live collision group (its claims predate the
          collision). A collision among raws with NO recorded owner
          suffixes every member, including the first — deterministic in
          the raw id set, never in iteration order."""
        owners = owners or {}
        labels: Dict[str, List[str]] = {}
        for raw in raws:
            labels.setdefault(slice_device_name(raw), []).append(raw)
        names: Dict[str, str] = {}
        for label, members in labels.items():
            owner = owners.get(label)
            if owner is None and len(members) == 1 \
                    and members[0] not in sticky:
                plain_raw = members[0]
            elif owner in members and owner not in sticky:
                plain_raw = owner
            else:
                plain_raw = None
            for raw in members:
                if raw == plain_raw:
                    names[raw] = label
                    continue
                digest = hashlib.sha256(
                    raw.encode("utf-8", "replace")).hexdigest()[:8]
                names[raw] = f"{label[:63 - 9]}-{digest}"
            if len(members) > 1:
                log.warning("DRA: device name collision on %r; publishing "
                            "%s", label,
                            sorted(names[r] for r in members))
        return names

    @staticmethod
    def _raw_id(kind: str, obj) -> str:
        return obj.bdf if kind == "chip" else obj.uuid

    def set_inventory(self, registry: Registry,
                      generations: Dict[str, GenerationInfo]) -> None:
        """Swap the discovery snapshot (rediscovery path): build the new
        name map + planners into locals, then publish ONE immutable
        InventoryEpoch — readers switch atomically, mid-flight prepares
        finish against the epoch they started with."""
        sticky_dirty = False
        with self._lock:
            self.registry = registry
            self.generations = generations
            entries: List[Tuple[str, str, str, object]] = []  # raw,kind,grp,obj
            planners: Dict[str, AllocationPlanner] = {}
            for model, devs in sorted(registry.devices_by_model.items()):
                info = generations.get(model)
                gen = info.name if info else f"tpu-{model}"
                if gen not in planners:
                    # message path only (prepare consumes plan() specs
                    # for the CDI spec file): no byte records
                    planners[gen] = AllocationPlanner(
                        self.cfg, registry, gen, byte_records=False)
                entries.extend((d.bdf, "chip", gen, d) for d in devs)
            for type_name, parts in sorted(registry.partitions_by_type.items()):
                entries.extend((p.uuid, "partition", type_name, p)
                               for p in parts)
            names = self._assign_slice_names(
                [raw for raw, *_ in entries], self._sticky_suffixed,
                self._label_owners)
            suffixed = {raw for raw, name in names.items()
                        if name != slice_device_name(raw)}
            owned = {name: raw for raw, name in names.items()
                     if raw not in suffixed}
            if (not suffixed <= self._sticky_suffixed
                    or any(self._label_owners.get(lb) != rw
                           for lb, rw in owned.items())):
                self._sticky_suffixed |= suffixed
                self._label_owners.update(owned)
                sticky_dirty = True
            by_name: Dict[str, Tuple[str, str, object]] = {
                names[raw]: (kind, group, obj)
                for raw, kind, group, obj in entries}
            # devices that left the inventory take their health state along
            self._unhealthy &= set(names)
            # a departed (hot-unplugged) raw id that rediscovery readmits
            # sheds its departed mark — replug reconciliation happened
            # upstream in the lifecycle FSM before it re-entered the
            # registry
            self._departed = {raw: name
                              for raw, name in self._departed.items()
                              if raw not in names}
            self._departed_meta = {raw: meta
                                   for raw, meta in
                                   self._departed_meta.items()
                                   if raw in self._departed}
            self._inv_store.publish(epoch_mod.build_inventory_epoch(
                self._inv_store.current.epoch_id + 1, by_name, planners,
                # vfio-backed logical partitions ride their parent's planner
                AllocationPlanner(self.cfg, registry, "vtpu-parent",
                                  byte_records=False),
                frozenset(self._unhealthy),
                frozenset(self._departed.values())))
            self._recompute_fragmentation_locked()
        if sticky_dirty:
            # file I/O stays OUTSIDE the global lock (a slow disk must not
            # stall claim prepares / slice builds); _save_sticky_names
            # re-snapshots the CURRENT sets under the lock per write, so
            # racing savers converge on the newest state
            self._save_sticky_names()

    def _device_entry(self, name: str, kind: str, group_name: str,
                      obj, version: str = "v1beta1",
                      info=None) -> dict:
        if kind == "chip":
            d: TpuDevice = obj
            attrs = {
                "type": {"string": "passthrough"},
                "generation": {"string": group_name},
                "bdf": {"string": d.bdf},
                "iommuGroup": {"string": d.iommu_group},
                "numaNode": {"int": d.numa_node},
            }
            if d.accel_index is not None:
                attrs["accelIndex"] = {"int": d.accel_index}
            if d.ici_coords is not None:
                for axis, coord in zip("xyz", d.ici_coords):
                    attrs[f"ici{axis.upper()}"] = {"int": coord}
            # Published ICI topology (the PR 10 follow-on): torus dims,
            # ring/host ids and the pod-grid slot give fleet-side
            # selectors (fleetplace.py) real fields to match against —
            # `topology.ring_size >= 4`, `topology.host_id == ...` —
            # and let the cluster scheduler rebuild this host's
            # placement grid from the slice alone.
            if info is not None and d.ici_coords is not None:
                dims = tuple(info.host_topology)
                for axis, dim in zip("xyz", dims):
                    attrs[f"torus{axis.upper()}"] = {"int": dim}
                attrs["ringSize"] = {"int": max(dims)}
                attrs["hostId"] = {"string": self.node_name}
                # the chip's wrap-around ICI ring on the host torus:
                # its coordinates with the longest axis projected out
                ring_axis = dims.index(max(dims))
                ring = [str(c) for i, c in enumerate(d.ici_coords)
                        if i != ring_axis]
                attrs["ringId"] = {"string": "/".join(
                    [self.node_name, group_name] + ring)}
            if self.cfg.host_coords is not None:
                for axis, coord in zip("xyz", self.cfg.host_coords):
                    attrs[f"host{axis.upper()}"] = {"int": int(coord)}
        else:
            p: TpuPartition = obj
            attrs = {
                "type": {"string": "partition"},
                "partitionType": {"string": group_name},
                "parentBdf": {"string": p.parent_bdf},
                "numaNode": {"int": p.numa_node},
                "provider": {"string": p.provider},
            }
            if p.accel_index is not None:
                attrs["accelIndex"] = {"int": p.accel_index}
        # v1beta1 wraps attributes in "basic"; v1 (and the shape-identical
        # v1beta2) flatten them onto the device entry. Same attribute value
        # encoding either way.
        if version == "v1beta1":
            return {"name": name, "basic": {"attributes": attrs}}
        return {"name": name, "attributes": attrs}

    def build_slice(self, pool_generation: int = 1,
                    version: Optional[str] = None) -> dict:
        """The ResourceSlice object for this node's HEALTHY inventory.

        Unhealthy devices are pruned, not attribute-marked: a scheduler
        needs no CEL opt-in to avoid dead hardware, matching the classic
        path where an Unhealthy device simply stops being allocatable.
        """
        version = version or self.resource_api_version()
        # read the inventory epoch, no lock: the slice body is a pure
        # function of one immutable snapshot
        ep = self._inv_store.current
        infos = {info.name: info for info in self.generations.values()}
        devices = [self._device_entry(name, kind, group_name, obj,
                                      version,
                                      info=infos.get(group_name))
                   for name, (kind, group_name, obj)
                   in ep.by_name.items()
                   if self._raw_id(kind, obj) not in ep.unhealthy]
        slice_obj = {
            "apiVersion": f"resource.k8s.io/{version}",
            "kind": "ResourceSlice",
            "metadata": {"name": self.slice_name()},
            "spec": {
                "driver": self.driver_name,
                "nodeName": self.node_name,
                "pool": {
                    "name": self.node_name,
                    "generation": pool_generation,
                    "resourceSliceCount": 1,
                },
                "devices": devices,
            },
        }
        owner = self._node_owner_ref()
        if owner is not None:
            slice_obj["metadata"]["ownerReferences"] = [owner]
        return slice_obj

    def slice_name(self) -> str:
        return slice_device_name(f"{self.node_name}-{self._driver_fs}")

    # ----------------------------------------------------- API versioning

    def resource_api_version(self) -> str:
        """The newest resource.k8s.io version both sides speak.

        Discovered once from the group document (GET /apis/resource.k8s.io)
        and cached; a discovery failure falls back to v1beta1 WITHOUT
        caching, so a transient apiserver blip at boot cannot pin an old
        version for the process lifetime.
        """
        if self._resource_version_cache is not None:
            return self._resource_version_cache
        if self.api is None:
            return RESOURCE_API_VERSIONS[-1]
        try:
            group = self.api.get_json("/apis/resource.k8s.io")
            served = {v.get("version")
                      for v in (group.get("versions") or [])
                      if isinstance(v, dict)}
        except (ApiError, ValueError) as exc:
            log.debug("DRA: resource.k8s.io discovery failed (%s); "
                      "assuming v1beta1 this call", exc)
            return RESOURCE_API_VERSIONS[-1]
        for version in RESOURCE_API_VERSIONS:
            if version in served:
                self._resource_version_cache = version
                log.info("DRA: serving resource.k8s.io/%s", version)
                return version
        # group exists but serves none of ours: stay on the fallback and
        # keep retrying discovery (an upgrade may add a known version)
        log.warning("DRA: apiserver serves resource.k8s.io versions %s, "
                    "none known to this driver; using v1beta1", sorted(served))
        return RESOURCE_API_VERSIONS[-1]

    def _resource_api(self) -> str:
        return f"/apis/resource.k8s.io/{self.resource_api_version()}"

    def _note_api_404(self) -> None:
        """A 404 from a versioned mutation/fetch may mean the cached group
        version was dropped by a control-plane upgrade (the daemon outlives
        apiservers). Clear the cache so the next operation re-discovers —
        a false invalidation (object genuinely absent) only costs one
        discovery GET."""
        if self._resource_version_cache is not None:
            log.info("DRA: 404 on resource.k8s.io/%s; will re-discover the "
                     "served version", self._resource_version_cache)
            self._resource_version_cache = None

    # ---------------------------------------------------------------- health

    def apply_health(self, transitions: Dict[str, bool]) -> bool:
        """Plugin-server health transitions ({raw id: healthy}) → slice.

        Wired as the plugin servers' health_listener (cli.py): the same
        ANDed fs+probe verdict that flips a device Unhealthy on the
        ListAndWatch stream prunes it from (or restores it to) the
        published ResourceSlice, bumping the pool generation. Returns True
        when the slice changed (and a republish was attempted).
        """
        with self._lock:
            before = set(self._unhealthy)
            ep = self._inv_store.current
            known = {self._raw_id(kind, obj)
                     for kind, _, obj in ep.by_name.values()}
            for raw, healthy in transitions.items():
                if raw not in known:
                    continue
                if healthy:
                    self._unhealthy.discard(raw)
                else:
                    self._unhealthy.add(raw)
            # ids whose EFFECTIVE verdict moved — the listener re-delivers
            # unchanged snapshots by design (server.py), and those must
            # cost nothing here: no epoch publish (each publish also
            # retires concurrently-built fragment caches), no inventory
            # walks. A real flip publishes the next epoch, which is ALSO
            # what invalidates every planner's precompiled fragments —
            # plan() keys its cache on the epoch id, so the per-planner
            # invalidate-listener plumbing is gone.
            changed = bool(before ^ self._unhealthy)
            if changed:
                dead = sorted(self._unhealthy)
                self._inv_store.publish(epoch_mod.build_inventory_epoch(
                    ep.epoch_id + 1, ep.by_name, ep.planners,
                    ep.parent_planner, frozenset(self._unhealthy),
                    ep.departed))
                self._recompute_fragmentation_locked()
        if not changed:
            return False
        log.warning("DRA: health transition; unhealthy devices now %s",
                    dead or "none")
        if not self.publish_resource_slices():
            # unlike inventory publishes (retried by the PluginManager run
            # loop), nothing re-fires a health transition — a dropped
            # republish would leave a dead device allocatable until some
            # unrelated change. Self-arm a retry.
            self._arm_republish_retry()
        return True

    def _arm_republish_retry(self) -> None:
        # without an API client publish_resource_slices always returns
        # False — a retry can never accomplish anything, it would just
        # re-arm and log "no API client" every 30 s forever
        if self.api is None:
            return
        with self._lock:
            # a stopped driver must never re-arm: an in-flight retry racing
            # stop(withdraw_slice=True) would POST the slice back for a
            # driver that no longer exists
            if self._republish_timer is not None or self._stopped:
                return
            t = threading.Timer(self.republish_backoff.next_delay(),
                                self._republish_retry)
            t.daemon = True
            self._republish_timer = t
        t.start()

    def _republish_retry(self) -> None:
        with self._lock:
            self._republish_timer = None
            if self._stopped:
                return
        if not self.publish_resource_slices():
            self._arm_republish_retry()

    # ---------------------------- watch-driven convergence (ISSUE 12)

    def start_watch_reconciler(
            self, resync_interval_s: float = 300.0,
            poll_interval_s: float = 30.0,
            watch_timeout_s: float = 30.0,
            backoff=None) -> bool:
        """Move slice read/repair onto watch-driven convergence.

        A reflector list+watches the resourceslices collection; every
        observation of OUR slice is checked against the desired
        projection, and a divergence (wiped, mutated by another writer)
        is repaired through publish_resource_slices — the guarded-write
        path, so exactly-once is untouched. While the stream is live the
        publish path also skips its unchanged-projection liveness GET
        (`publish_stats["watch_read_skips"]`): wipe detection is the
        watch's job now. The periodic resync relist is the missed-event
        backstop, and the reflector's own degradation ladder (paced
        relist polling) covers fabrics without watch support. Returns
        False without an API client (nothing to watch)."""
        if self.api is None:
            return False
        if self._slice_watch is not None:
            return True
        self._slice_watch = Reflector(
            # callable path + on_list_404: a control-plane upgrade that
            # drops the cached resource.k8s.io version turns every
            # relist into a 404 — the hook invalidates the cache and
            # the re-resolved path recovers on the next attempt
            self.api, lambda: f"{self._resource_api()}/resourceslices",
            on_event=self._on_slice_watch_event,
            on_sync=self._on_slice_watch_sync,
            on_list_404=self._note_api_404,
            name=f"slice-{self.node_name}",
            resync_interval_s=resync_interval_s,
            poll_interval_s=poll_interval_s,
            watch_timeout_s=watch_timeout_s,
            backoff=backoff,
            # narrow both list and watch to OUR slice: without this a
            # fleet of N drivers each receives (and parses, and
            # discards) all N slices' events — O(N^2) apiserver egress
            # for a reconciler that only ever acts on one name. The
            # handlers still name-check: a server that ignores the
            # selector is correct, just louder.
            query=f"fieldSelector=metadata.name={self.slice_name()}")
        self._slice_watch.start()
        log.info("DRA: slice watch reconciler started (resync %.0fs, "
                 "degraded-poll %.0fs)", resync_interval_s,
                 poll_interval_s)
        return True

    def stop_watch_reconciler(self) -> None:
        """Tear down the slice watch; publish reverts to its liveness
        GET. Idempotent. The autopilot self-heal drill quiesces the
        watch plane through this so a count-limited injected fault
        lands on the victim's publishes instead of stream churn."""
        watch, self._slice_watch = self._slice_watch, None
        if watch is not None:
            watch.stop()

    def _watch_live(self) -> bool:
        """The watch plane currently covers wipe detection (lock-free)."""
        ref = self._slice_watch
        return ref is not None and ref.stream_live()

    def _on_slice_watch_event(self, evt: dict) -> None:
        """Watch handler — IDEMPOTENT by construction (the reflector's
        at-least-once contract): an event matching the desired
        projection (our own publish echo, a duplicate delivery) changes
        nothing; only a real divergence triggers the guarded repair.

        STALENESS guard: watch delivery lags writes, so an event can
        describe a state OLDER than our own latest write (a flip
        storm's intermediate publishes arriving after the final one).
        Comparing that history against current desired would read as
        divergence and spam repair publishes — an event older than our
        last write's resourceVersion is history, not evidence."""
        obj = evt.get("object") or {}
        if ((obj.get("metadata") or {}).get("name")) != self.slice_name():
            return
        last = self._last_publish          # GIL-atomic ref read
        try:
            last_rv = int(last["rv"]) if last else 0
        except (TypeError, ValueError):
            last_rv = 0
        try:
            evt_rv = int((obj.get("metadata") or {})
                         .get("resourceVersion") or 0)
        except (TypeError, ValueError):
            evt_rv = 0
        if evt_rv and last_rv and evt_rv <= last_rv:
            # older: stale history. EQUAL: the echo of our own last
            # write (resourceVersions are per-resource monotonic, so
            # the same rv IS the state we just wrote) — returning here
            # spares a full build_slice + projection compare per
            # publish on the reflector thread
            return
        # the evidence context (r17): when the fabric stamped the
        # causal write's traceparent onto this event, the repair joins
        # that trace and the convergence-lag histogram carries it as
        # the exemplar
        evidence = (time.monotonic(), evt.get("traceparent"))
        if evt.get("type") == "DELETED":
            if self._should_repair():
                self._watch_repair("deleted", evidence=evidence)
            elif self._repair_wanted():
                self._watch_deferred_seq += 1
            return
        if self._should_repair():
            if self._slice_diverged(obj):
                self._watch_repair("diverged", evidence=evidence)
        elif self._repair_wanted() and self._slice_diverged(obj):
            # divergence read against an in-flight publish's window may
            # be a false positive — deferring costs one liveness GET,
            # never a spurious repair publish
            self._watch_deferred_seq += 1

    def _on_slice_watch_sync(self, items: list) -> None:
        """Relist/resync handler: the full collection state — the
        missed-event backstop. Same idempotency contract as the event
        handler."""
        mine = [obj for obj in items
                if ((obj.get("metadata") or {}).get("name"))
                == self.slice_name()]
        if self._should_repair():
            if not mine:
                self._watch_repair("missing")
            elif self._slice_diverged(mine[0]):
                self._watch_repair("diverged")
        elif self._repair_wanted():
            if not mine or self._slice_diverged(mine[0]):
                self._watch_deferred_seq += 1

    def _repair_wanted(self) -> bool:
        # repair only what we ever published, never after stop(), and
        # never an inventory-empty state (that withdraws the slice —
        # absence IS the desired state there)
        if not self._has_published or self._stopped:
            return False
        return bool(self._inv_store.current.by_name)

    def _should_repair(self) -> bool:
        # a publish in flight already carries current state: an event
        # observed against its half-updated window is not divergence
        # evidence — but it is not FORGOTTEN either: the handlers defer
        # it (_watch_deferred) so the next unchanged-projection publish
        # keeps its liveness GET, and the resync backstop still covers
        # the rest
        return self._repair_wanted() and not self._publish_lock.locked()

    def _slice_diverged(self, live_obj: dict) -> bool:
        live_spec = live_obj.get("spec") or {}
        live_gen = ((live_spec.get("pool") or {}).get("generation")) or 1
        if live_gen < self._last_generation:
            # a foreign delete+recreate reset pool.generation: even with
            # a matching device projection the live pool now claims to be
            # OLDER than allocations we already handed out, breaking
            # stale-allocation detection — that is divergence too
            return True
        desired = self.build_slice()
        return (self._spec_projection(live_spec)
                != self._spec_projection(desired["spec"]))

    def _watch_repair(self, reason: str, evidence=None) -> None:
        # evidence = (monotonic arrival of the divergence observation,
        # the causal write's traceparent when the fabric stamped one):
        # the repair event links the causing trace, and a successful
        # repair observes the watch-convergence-lag histogram with that
        # trace as the bucket exemplar (the SLO plane's fourth objective)
        t0, raw_tp = evidence or (time.monotonic(), None)
        ctx = trace.parse_traceparent(raw_tp) if raw_tp else None
        self.watch_repairs.add()
        log.warning("DRA: watch observed slice %s %s; repairing via the "
                    "guarded publish path", self.slice_name(), reason)
        # the repair is a node-stamped SPAN (not a bare event): the
        # repair publish's kubeapi spans inherit node= — the fleet
        # trace collector attributes the repair to the host that ran
        # it, never to the unattributed "scheduler" bucket — and its
        # duration is the repair wall itself
        with trace.span("dra.watch.repair", reason=reason, link=ctx,
                        node=self.node_name):
            # the observed divergence invalidates the delta baseline: a
            # wiped slice's cached rv is dead, a foreign write bumped
            # it — and the unchanged-projection fast paths (watch-read
            # skip, delta PUT) must not conclude "nothing to do" from a
            # cache the fabric just contradicted. The repair publish
            # then takes the classic GET-or-POST read-modify-write,
            # which heals both shapes.
            with self._publish_lock:
                self._last_publish = None
            # the repair publish below acks any deferred observation it
            # covers (the _paced_publish seq/ack handshake) — on success
            # only, so a failed repair keeps the deferral for the retry
            if self.publish_resource_slices():
                trace.observe(
                    "tdp_watch_convergence_ms",
                    (time.monotonic() - t0) * 1e3,
                    exemplar=ctx["trace_id"] if ctx else None)
            else:
                self._arm_republish_retry()

    def watch_stats(self) -> dict:
        """The /status + /metrics watch-plane surface: the reflector's
        counters (zeros when no reconciler is attached — polling mode)
        plus the repair counter. Lock-free."""
        ref = self._slice_watch
        if ref is None:
            out = {key: 0 for key in Reflector.STAT_KEYS}
            out["enabled"] = False
        else:
            out = ref.snapshot()
            out["enabled"] = True
        out["watch_repairs_total"] = self.watch_repairs.value
        return out

    def apply_gone(self, raws) -> bool:
        """Hot-unplug: REMOVE departed devices from the published
        inventory entirely.

        Distinct from `apply_health(healthy=False)`: an unhealthy device
        stays in `by_name` (a prepare against it still plans — the chip
        may answer again next probe) and is merely pruned from the slice
        body; a DEPARTED device's sysfs/devfs nodes no longer exist, so
        it must vanish from `by_name` too — a prepare against it fails
        with a "departed" error instead of handing the pod dead device
        nodes, and the ResourceSlice stops advertising it under a bumped
        pool generation. The epoch publish also retires every planner's
        precompiled fragments by construction. Returns True when the
        inventory changed (and a republish was attempted)."""
        raws = set(raws)
        with self._lock:
            ep = self._inv_store.current
            gone = {name: self._raw_id(kind, obj)
                    for name, (kind, _, obj) in ep.by_name.items()
                    if self._raw_id(kind, obj) in raws}
            if not gone:
                return False
            by_name = {name: entry for name, entry in ep.by_name.items()
                       if name not in gone}
            # departed, not unhealthy: the device cannot "recover" in
            # place — only a replug (rediscovery readmission) returns it
            self._unhealthy -= raws
            for name, raw in gone.items():
                self._departed[raw] = name
                kind, group, obj = ep.by_name[name]
                if kind == "chip" and obj.ici_coords is not None:
                    self._departed_meta[raw] = (group,
                                                tuple(obj.ici_coords))
            self._inv_store.publish(epoch_mod.build_inventory_epoch(
                ep.epoch_id + 1, by_name, ep.planners, ep.parent_planner,
                frozenset(self._unhealthy),
                frozenset(self._departed.values())))
            self._recompute_fragmentation_locked()
        log.warning("DRA: device(s) %s departed (hot-unplug); removed "
                    "from the published ResourceSlice", sorted(gone.values()))
        if not self.publish_resource_slices():
            self._arm_republish_retry()
        return True

    def attach_lifecycle(self, fsm) -> None:
        """Wire the host lifecycle FSM (lifecycle_fsm.DeviceLifecycle):
        prepares/unprepares mark their devices allocated/detaching/
        released, and the FSM's hot-unplug hook routes back into
        `on_devices_gone`. Call before start()."""
        self._lifecycle = fsm
        fsm.on_devices_gone = self.on_devices_gone
        fsm.on_device_readmitted = self.on_device_readmitted
        # replay the checkpoint's claim marks into the (possibly fresh)
        # FSM: a daemon restart must not forget which devices carry
        # prepared claims, or a post-restart hot-unplug would orphan
        # nothing. Already-orphaned entries stay orphaned — their
        # devices are not re-marked allocated.
        claims_by_raw: Dict[str, List[str]] = {}
        with self._lock:
            for uid, entry in self._checkpoint.items():
                if "orphaned" in entry:
                    continue
                for raw in entry.get("device_raws", ()):
                    claims_by_raw.setdefault(raw, []).append(uid)
        if claims_by_raw:
            fsm.restore_claims(claims_by_raw)

    def on_devices_gone(self, events) -> None:
        """Lifecycle hook: `events` is [(raw, claim_uids), ...] — every
        device hot-unplugged in one observation, allocated or not.
        Claims prepared against them are marked ORPHANED in the
        checkpoint (the guest-visible surprise removal is recorded on
        the entry), the devices are dropped from the published
        ResourceSlice in ONE epoch publish + ONE republish (a PCIe
        switch dropping four chips costs one API round-trip, not four),
        and the checkpoint converges in the background — no flush
        barrier, because nothing ACKs on this path and the marks are
        reconstructed from the checkpoint by attach_lifecycle's replay
        after a crash."""
        now = time.time()
        marked = []
        with self._lock:
            for raw, claim_uids in events:
                for uid in claim_uids:
                    entry = self._checkpoint.get(uid)
                    if entry is not None and "orphaned" not in entry:
                        # replace wholesale: the group-commit writer may
                        # be serializing a shallow snapshot of the old
                        # entry right now, and an in-place mutation
                        # could race it
                        self._checkpoint[uid] = dict(
                            entry, orphaned={"device": raw, "at": now})
                        marked.append(uid)
                        # flight-recorder marker: the claim's trace ends
                        # with its orphaning (event() is lock-free, so
                        # emitting under _lock costs no reader anything)
                        trace.event("dra.claim.orphaned", claim_uid=uid,
                                    device=raw)
        if marked:
            log.error("DRA: claim(s) %s orphaned by surprise removal",
                      ", ".join(marked))
            self._checkpoint_mark_dirty()
        self.apply_gone([raw for raw, _ in events])

    def on_device_readmitted(self, raw: str) -> None:
        """Lifecycle hook: a departed device passed replug identity
        reconciliation. When the unplug and replug both land within one
        rediscovery tick the registry signature never changes — no
        inventory event would re-run set_inventory, and the device would
        stay out of the slice forever. Rebuild from the LAST discovery
        snapshot (which still carries the device); a replug that
        rediscovery did observe readmits via the normal set_inventory
        path instead (the raw id is absent from self.registry here and
        the departed mark survives until that snapshot arrives)."""
        if raw not in self._departed:      # GIL-atomic peek; cheap filter
            return
        self.set_inventory(self.registry, self.generations)
        if raw in self._departed:
            return   # not in the last snapshot: rediscovery will readmit
        log.info("DRA: device %s readmitted after replug; republishing "
                 "the ResourceSlice", raw)
        if not self.publish_resource_slices():
            self._arm_republish_retry()

    def orphaned_claims(self) -> List[str]:
        """Claim UIDs whose device was surprise-removed (lock-free read:
        C-atomic list copy + GIL-atomic key reads)."""
        return sorted(uid for uid, entry in list(self._checkpoint.items())
                      if "orphaned" in entry)

    def departed_devices(self) -> List[str]:
        """Raw ids currently marked departed (hot-unplugged, not yet
        readmitted); lock-free C-atomic copy."""
        return sorted(list(self._departed))

    # ---------------------------------------- slice placement (placement.py)

    def host_views(self) -> Dict[str, placement.HostView]:
        """Per-generation placement snapshots of THIS node — the input to
        plan_slice/propose_defrag and the fleetsim coordinator. Lock-free:
        one epoch reference read plus C-atomic dict copies of the claim
        checkpoint and departed map."""
        return self._build_host_views(self._inv_store.current,
                                      dict(self._checkpoint),
                                      dict(self._departed))

    def _build_host_views(self, ep: epoch_mod.InventoryEpoch,
                          checkpoint: Dict[str, dict],
                          departed: Dict[str, str]
                          ) -> Dict[str, placement.HostView]:
        """Pure assembly over immutable/copied inputs (no self state reads
        beyond the static generations table and the last discovery
        snapshot, which still carries departed devices' coords — the
        epoch dropped them from by_name but their HOLE must keep counting
        toward fragmentation)."""
        infos = {info.name: info for info in self.generations.values()}
        claim_raws: Dict[str, List[str]] = {}
        claimed: Dict[str, str] = {}
        for uid, entry in checkpoint.items():
            if "orphaned" in entry:
                continue
            for raw in entry.get("device_raws", ()):
                claimed[raw] = uid
                claim_raws.setdefault(uid, []).append(raw)
        per_gen: Dict[str, dict] = {}
        for name, (kind, group, obj) in ep.by_name.items():
            if kind != "chip" or obj.ici_coords is None:
                continue
            info = infos.get(group)
            if info is None:
                continue
            g = per_gen.setdefault(group, {
                "dims": tuple(info.host_topology), "coords": {},
                "names": {}, "free": set(), "departed": set()})
            g["coords"][obj.bdf] = tuple(obj.ici_coords)
            g["names"][obj.bdf] = name
            if obj.bdf not in ep.unhealthy and obj.bdf not in claimed:
                g["free"].add(obj.bdf)
        departed_meta = dict(self._departed_meta)   # C-atomic copy
        for raw, name in departed.items():
            meta = departed_meta.get(raw)
            if meta is None:
                continue
            gen, coords = meta
            g = per_gen.get(gen)
            if g is None:
                info = infos.get(gen)
                if info is None:
                    continue
                # every chip of the generation departed at once (a whole
                # switch dropped): the view survives as all-holes so the
                # fragmentation gauges show 0 free, not a vanished series
                g = per_gen.setdefault(gen, {
                    "dims": tuple(info.host_topology), "coords": {},
                    "names": {}, "free": set(), "departed": set()})
            if coords in set(g["coords"].values()):
                # The heuristic (hint-less) layout re-packed the surviving
                # chips over the hole's slot on the next rediscovery.
                # Relocate the hole to an unoccupied grid slot so the
                # CAPACITY accounting stays exact (a departed chip still
                # subtracts one placeable slot); with explicit topology
                # hints coords are stable and this branch never runs.
                taken = set(g["coords"].values())
                coords = next(
                    (c for c in itertools.product(
                        *[range(d) for d in g["dims"]]) if c not in taken),
                    None)
                if coords is None:
                    continue
            g["coords"][raw] = coords
            g["names"][raw] = name
            g["departed"].add(raw)
        views: Dict[str, placement.HostView] = {}
        for gen, g in per_gen.items():
            claims = {uid: tuple(r for r in raws if r in g["coords"])
                      for uid, raws in claim_raws.items()}
            views[gen] = placement.HostView(
                node=self.node_name, dims=g["dims"], coords=g["coords"],
                names=g["names"], free=frozenset(g["free"]),
                departed=frozenset(g["departed"]),
                claims={uid: raws for uid, raws in claims.items() if raws},
                host_coords=self.cfg.host_coords)
        return views

    def _recompute_fragmentation_locked(self) -> None:
        """Writer-side (caller holds _lock): rebuild the per-generation
        fragmentation records from the just-published epoch + current
        claim map and swap the attribute wholesale. Pure compute — the
        hot-lock blocking-call lint vocabulary stays clean."""
        views = self._build_host_views(self._inv_store.current,
                                       self._checkpoint, self._departed)
        self._fragmentation = {gen: placement.fragmentation(view)
                               for gen, view in views.items()}
        self.placement_stats["frag_recomputes_total"] += 1

    def _recompute_fragmentation(self) -> None:
        with self._lock:
            self._recompute_fragmentation_locked()

    def fragmentation_stats(self) -> Dict[str, dict]:
        """Per-generation fragmentation records for /status + /metrics.
        Lock-free: the attribute is swapped wholesale by the writer and
        its records are never mutated in place."""
        return self._fragmentation

    def propose_defrag(self, shape, generation: Optional[str] = None) -> dict:
        """The /debug/defrag advisory for THIS node (placement.py
        documents the format). With several generations present the
        caller must name one — a shape is meaningless across different
        tori. Single-node views mean migrations may carry
        target_node=None ("move it off this host"); the fleetsim
        coordinator re-plans with every node's view to fill targets in.
        """
        shape = placement.parse_shape(shape)
        views = self.host_views()
        if generation is None and len(views) == 1:
            generation = next(iter(views))
        view = views.get(generation)
        if view is None:
            # a named generation with NO host view (never discovered, or
            # every chip departed without a surviving grid) is a caller
            # error the /debug/defrag handler answers 400, not an empty
            # advisory that reads as "nothing to do"
            raise ValueError(
                f"unknown generation {generation!r}; have {sorted(views)}")
        proposal = placement.propose_defrag(shape, [view])
        proposal["generation"] = generation
        # the advisory carries the SAME per-generation fragmentation
        # records /status + /metrics publish (lock-free swap-read), so
        # an operator reading a proposal sees the scores that motivated
        # it without a second scrape
        proposal["fragmentation"] = dict(self.fragmentation_stats())
        with self._lock:
            self.placement_stats["defrag_proposals_total"] += 1
            if not proposal["satisfiable"]:
                self.placement_stats["defrag_unsatisfiable_total"] += 1
        return proposal

    @property
    def _by_name(self) -> Dict[str, Tuple[str, str, object]]:
        """The current epoch's published-name map (read-only view);
        kept as an attribute-shaped surface for tests/debugging."""
        return self._inv_store.current.by_name

    def unhealthy_devices(self) -> List[str]:
        # epoch frozenset: no lock, no copy-while-mutating hazard
        return sorted(self._inv_store.current.unhealthy)

    def _node_owner_ref(self) -> Optional[dict]:
        """Owner reference to the Node so slices are garbage-collected when
        the node goes away. Best-effort: published without one if the node
        GET fails (RBAC may only grant resourceslices)."""
        if self.api is None:
            return None
        if self._node_uid is None:
            try:
                node = self.api.get_json(f"/api/v1/nodes/{self.node_name}")
                self._node_uid = (node.get("metadata") or {}).get("uid")
            except (ApiError, ValueError) as exc:
                log.debug("node GET for ownerReference failed: %s", exc)
                return None
        if not self._node_uid:
            return None
        return {"apiVersion": "v1", "kind": "Node", "name": self.node_name,
                "uid": self._node_uid, "controller": True}

    def publish_resource_slices(self) -> bool:
        """Create-or-update this node's ResourceSlice; True on success.

        Pool generation semantics: an unchanged inventory republishes the
        live object untouched; a changed one bumps spec.pool.generation so
        the scheduler knows older allocations reference a stale pool.
        """
        if self.api is None:
            log.warning("DRA: no API client; ResourceSlice not published")
            return False
        # paced + coalesced (kubeapi.PublishPacer): the pacer invokes
        # _paced_publish AFTER its admission wait, so a caller that
        # coalesced onto an in-flight wave gets its state published by
        # that wave's build
        ok = self.pacer.run(self._paced_publish)
        if ok:
            self.republish_backoff.reset()
        return ok

    def _watch_evidence_pending(self) -> bool:
        return self._watch_deferred_ack != self._watch_deferred_seq

    def _paced_publish(self) -> bool:
        # node= rides the publish root span like the prepare RPC root:
        # the kubeapi.request children share its trace, so a slow
        # publish's SLO exemplar attributes to THIS node on the fleet
        # waterfall (remediation.py biases repeat offenders by exactly
        # that label)
        with trace.span("dra.publish", node=self.node_name), \
                self._publish_lock:
            seq0 = self._watch_deferred_seq
            ok = self._publish_locked()
            if ok:
                # every successful outcome resolves the evidence that
                # existed when we started: the guarded PUT proved our
                # cached rv still live, the classic path re-read the
                # fabric, create/withdraw re-established the desired
                # state. Evidence deferred DURING this publish has
                # seq > seq0 and stays pending.
                self._watch_deferred_ack = seq0
            return ok

    def _publish_locked(self) -> bool:
        with self._lock:
            if self._stopped:
                return False
            inventory_empty = not self._by_name
        # fault point "dra.publish" (value kind): simulate an apiserver
        # refusing the publish, exercising the self-armed republish retry
        if faults.fire("dra.publish"):
            return False
        name = self.slice_name()
        # resolve the REST version ONCE per publish: independent lookups
        # (path here, schema inside build_slice) could disagree mid-blip
        # and POST a v1 body to a v1beta1 path
        version = self.resource_api_version()
        api_base = f"/apis/resource.k8s.io/{version}"
        path = f"{api_base}/resourceslices/{name}"
        if inventory_empty:
            # empty INVENTORY: withdraw the slice entirely. All-devices-
            # unhealthy is NOT this case — that publishes an empty device
            # list under a bumped generation, because a delete/recreate
            # cycle would reset pool.generation to 1 and make allocations
            # from the old generation look newer than the live pool
            # (breaking stale-allocation detection).
            try:
                self.api.delete(path)
                log.info("DRA: deleted ResourceSlice %s (no devices)", name)
            except ApiError as exc:
                # an absent slice is the steady state here, NOT a version
                # signal — do not invalidate the discovered version
                if exc.code != 404:
                    log.error("DRA: slice delete failed: %s", exc)
                    return False
            self._last_publish = None
            self._has_published = False   # absence is the desired state
            return True
        # Delta fast path: this driver is the slice's only legitimate
        # writer, so the rv/generation/projection of OUR last write is
        # normally still live — publish the new state as one PUT keyed by
        # the local pool generation, skipping the GET. The resourceVersion
        # guard keeps it exactly-once: an interleaved writer (or a slice
        # wiped behind our back) turns into a 409/404 and the classic
        # read-modify-write below reconciles.
        cached = self._last_publish
        if cached is not None and cached["version"] == version:
            desired = self.build_slice(
                pool_generation=cached["generation"] + 1, version=version)
            proj = self._spec_projection(desired["spec"])
            # On an unchanged projection fall through to the classic path
            # below instead: its GET doubles as the liveness check that
            # recreates a slice wiped behind our back (a change-free
            # republish healed that before the delta path existed, and
            # must keep doing so) — UNLESS a live watch stream covers
            # wipe detection (ISSUE 12): a DELETED/diverged event repairs
            # through _watch_repair, so the probe read is pure churn and
            # is skipped, counted. A degraded or absent watch keeps the
            # GET: the ladder never trades a read away for a blind spot.
            if proj != cached["projection"]:
                desired["metadata"]["resourceVersion"] = cached["rv"]
                try:
                    live = self.api.put_json(path, desired)
                except ApiError as exc:
                    self._last_publish = None
                    if exc.code == 409:
                        self.publish_stats["delta_conflicts"] += 1
                        log.info("DRA: delta publish of %s conflicted; "
                                 "falling back to read-modify-write", name)
                    elif exc.code == 404:
                        # slice wiped behind our back (operator/GC) — NOT
                        # an API-version signal (same 404 semantics as the
                        # delete and classic-GET paths); the
                        # read-modify-write below recreates it
                        log.info("DRA: slice %s vanished under delta "
                                 "publish; recreating", name)
                    else:
                        log.error("DRA: delta slice PUT failed: %s", exc)
                        return False
                else:
                    self.publish_stats["delta"] += 1
                    self._remember_publish(live, desired, proj, version)
                    log.info("DRA: updated ResourceSlice %s to pool "
                             "generation %d (%d devices, delta)", name,
                             desired["spec"]["pool"]["generation"],
                             len(desired["spec"]["devices"]))
                    return True
            elif self._watch_live():
                if not self._watch_evidence_pending():
                    self.publish_stats["watch_read_skips"] += 1
                    return True
                # a wipe/divergence observation arrived while an
                # earlier publish held the lock and was never acted on:
                # fall through to the classic liveness GET this round
                # instead of skipping it, so the deferred evidence
                # heals within one republish period rather than
                # waiting for resync (acked in _paced_publish on
                # success only)
        # a CREATE continues the generation sequence (1 on first boot;
        # last+1 when recreating a slice wiped behind our back)
        desired = self.build_slice(
            pool_generation=self._last_generation + 1, version=version)
        try:
            live = self.api.get_json(path)
        except ApiError as exc:
            if exc.code != 404:
                log.error("DRA: slice GET failed: %s", exc)
                return False
            try:
                created = self.api.post_json(f"{api_base}/resourceslices",
                                             desired)
            except ApiError as exc2:
                log.error("DRA: slice POST failed: %s", exc2)
                if exc2.code == 404:
                    self._note_api_404()
                return False
            self.publish_stats["full"] += 1
            self._remember_publish(
                created, desired, self._spec_projection(desired["spec"]),
                version)
            log.info("DRA: published ResourceSlice %s (%d devices)",
                     name, len(desired["spec"]["devices"]))
            return True
        live_spec = live.get("spec") or {}
        live_gen = ((live_spec.get("pool") or {}).get("generation")) or 1
        # a foreign recreate can carry a LOWER generation than we already
        # published (delete + recreate resets it to 1); the floor keeps
        # the sequence monotonic so old allocations never look newer than
        # the live pool and the exactly-once audit never sees a replay
        floor_gen = max(live_gen, self._last_generation)
        if live_gen >= self._last_generation and \
                self._spec_projection(live_spec) == \
                self._spec_projection(desired["spec"]):
            # adopt the live object as the delta baseline: the next health
            # flip can go straight to the guarded-PUT path. A live object
            # with a REGRESSED generation is never adopted, even with a
            # matching projection — the guarded PUT below restores the
            # advertised generation the fleet's staleness checks rely on.
            self._remember_publish(live, live, self._spec_projection(
                live_spec), version, generation=live_gen)
            return True
        desired = self.build_slice(pool_generation=floor_gen + 1,
                                   version=version)
        desired["metadata"]["resourceVersion"] = (
            (live.get("metadata") or {}).get("resourceVersion"))
        try:
            updated = self.api.put_json(path, desired)
        except ApiError as exc:
            log.error("DRA: slice PUT failed: %s", exc)
            if exc.code == 404:
                self._note_api_404()
            return False
        self.publish_stats["full"] += 1
        self._remember_publish(
            updated, desired, self._spec_projection(desired["spec"]), version)
        log.info("DRA: updated ResourceSlice %s to pool generation %d "
                 "(%d devices)", name, floor_gen + 1,
                 len(desired["spec"]["devices"]))
        return True

    def _remember_publish(self, live_obj: dict, desired: dict,
                          projection: tuple, version: str,
                          generation: Optional[int] = None) -> None:
        """Record the apiserver's view of our last write for the delta path;
        an apiserver that returns no resourceVersion just disables it."""
        self._has_published = True   # the watch reconciler may repair now
        rv = ((live_obj or {}).get("metadata") or {}).get("resourceVersion")
        if generation is None:
            generation = ((desired.get("spec") or {}).get("pool")
                          or {}).get("generation") or 1
        self._last_generation = max(self._last_generation, generation)
        if not rv:
            self._last_publish = None
            return
        self._last_publish = {"rv": rv, "generation": generation,
                              "projection": projection, "version": version}

    @staticmethod
    def _spec_projection(spec: dict) -> tuple:
        """The fields THIS driver owns, for change detection. Comparing the
        raw spec dict against the live object would see any apiserver-side
        defaulting/normalization as a permanent diff — bumping
        pool.generation (and PUTting) on every republish and churning
        scheduler state. pool.generation itself is excluded (it is the
        version, not the content). Wrapper-agnostic across resource.k8s.io
        versions: v1beta1 nests attributes under "basic", v1 flattens."""
        def attrs(d):
            return ((d.get("basic") or {}).get("attributes")
                    or d.get("attributes") or {})

        devices = tuple(
            (d.get("name"), json.dumps(attrs(d), sort_keys=True))
            for d in (spec.get("devices") or []))
        return (spec.get("driver"), spec.get("nodeName"), devices)

    # ------------------------------------------------------- checkpointing

    def _load_checkpoint(self) -> Dict[str, Dict[str, dict]]:
        """Load + forward-migrate the claim checkpoint.

        Returns {"claims": {...}, "handoffs": {...}} at
        CHECKPOINT_VERSION. A missing/unreadable/corrupt-JSON file keeps
        the legacy lenient semantics (fresh state — a missing file IS
        the normal first boot), but a parseable checkpoint whose version
        is NEWER than this binary's raises CheckpointVersionError so the
        daemon refuses to start: silently truncating a future schema and
        then group-committing over it would corrupt the node's claim
        state during a rollback.
        """
        try:
            with open(self.checkpoint_path, "r", encoding="utf-8") as f:
                data = json.load(f)
        except (OSError, ValueError):
            return {"claims": {}, "handoffs": {}}
        if not isinstance(data, dict):
            return {"claims": {}, "handoffs": {}}
        version = data.get("version", 0)
        if not isinstance(version, int) or isinstance(version, bool) \
                or version < 0:
            raise CheckpointVersionError(
                f"checkpoint {self.checkpoint_path} carries a malformed "
                f"schema version {version!r}; refusing to start rather "
                f"than guess (move the file aside to discard its claims)")
        if version > CHECKPOINT_VERSION:
            raise CheckpointVersionError(
                f"checkpoint {self.checkpoint_path} is schema v{version}, "
                f"newer than this daemon's v{CHECKPOINT_VERSION}; refusing "
                f"to start — roll the daemon forward (or move the file "
                f"aside to discard its claims)")
        while version < CHECKPOINT_VERSION:
            data = _CKPT_MIGRATIONS[version](data)
            new_version = data["version"]
            log.info("DRA: migrated checkpoint schema v%d -> v%d",
                     version, new_version)
            version = new_version
        claims = {uid: entry
                  for uid, entry in (data.get("claims") or {}).items()
                  if isinstance(entry, dict)}
        handoffs = {uid: rec
                    for uid, rec in (data.get("handoffs") or {}).items()
                    if isinstance(rec, dict)}
        return {"claims": claims, "handoffs": handoffs}

    def _sweep_orphan_specs(self) -> int:
        """Delete claim-spec CDI files whose UID the loaded checkpoint
        does not know. A crash between the spec write and the checkpoint
        commit (prepare's rollback only runs on a FAILED commit, not on
        a process death) used to leak the stale spec forever; counted on
        /status as `orphan_specs_removed`."""
        prefix = f"{self._driver_fs}-claim-"
        try:
            entries = os.listdir(self.cdi_dir)
        except OSError:
            return 0
        removed = 0
        for name in entries:
            if not (name.startswith(prefix) and name.endswith(".json")):
                continue
            uid = name[len(prefix):-len(".json")]
            if uid in self._checkpoint:
                continue
            try:
                os.unlink(os.path.join(self.cdi_dir, name))
            except OSError:
                continue
            removed += 1
            log.warning("DRA: removed orphaned claim spec %s (uid %s not "
                        "in the checkpoint)", name, uid)
        return removed

    # Group-commit protocol: a claim task (1) mutates self._checkpoint under
    # self._lock, (2) calls _checkpoint_flush(), which bumps the dirty
    # generation, wakes the writer, and blocks until a write attempt covers
    # that generation. The writer snapshots the WHOLE dict per commit, so
    # one atomic write makes every mutation up to its generation durable —
    # a 32-claim burst costs ~1-2 writes instead of 32 full-file rewrites
    # behind the global lock. A failed write fails every waiter of that
    # window (none of their entries are on disk); each rolls its own
    # mutation back and reports a per-claim error, so a kubelet retry
    # re-runs the claim from scratch — crash-safety and exactly-once
    # semantics are exactly the old per-claim _save_checkpoint()'s.

    @contextmanager
    def _claim_lock(self, uid: str):
        """Serialize prepare/unprepare of ONE claim UID (idempotent kubelet
        retries); distinct UIDs proceed in parallel."""
        with self._claim_locks_lock:
            entry = self._claim_locks.get(uid)
            if entry is None:
                # one shared lockdep name for the whole per-claim family:
                # ordering is claim-lock -> global/checkpoint locks, never
                # claim -> claim, and the shared name makes any nesting of
                # two claim locks show up as a self-inversion
                entry = self._claim_locks[uid] = [
                    lockdep.instrument("dra.DraDriver._claim_lock",
                                       threading.Lock()), 0]
            entry[1] += 1
        entry[0].acquire()
        try:
            yield
        finally:
            entry[0].release()
            with self._claim_locks_lock:
                entry[1] -= 1
                if entry[1] <= 0:
                    self._claim_locks.pop(uid, None)

    def _ensure_checkpoint_writer_locked(self) -> None:
        # NEVER resurrects a stopped writer: a straggler RPC outliving
        # stop()'s grace must fail its flush fast ("writer stopped" — a
        # per-claim error the kubelet retries against the next incarnation)
        # rather than spawn a writer that defeats the drain. start() is the
        # only place that clears _ckpt_stopped.
        if self._ckpt_stopped:
            return
        if self._ckpt_thread is None or not self._ckpt_thread.is_alive():
            self._ckpt_thread = threading.Thread(
                target=self._checkpoint_writer_loop, daemon=True,
                name="dra-ckpt")
            self._ckpt_thread.start()

    def _checkpoint_mark_dirty(self) -> None:
        """Record a mutation WITHOUT waiting for durability (rollback path:
        the claim already failed, the writer just converges disk)."""
        with self._ckpt_cond:
            self._ckpt_dirty_gen += 1
            self._ensure_checkpoint_writer_locked()
            self._ckpt_cond.notify_all()

    def _checkpoint_flush(self, task: dict) -> None:
        """Flush barrier: returns once this task's checkpoint mutation is
        on disk; raises the write error otherwise (the caller rolls back
        and reports it as the claim's error). The span makes the group-
        commit WAIT an explicit child of the claim span (inheriting its
        claim_uid), so "why was this attach slow" decomposes into plan
        time vs durability-wait time on /debug/flight."""
        with trace.span("dra.checkpoint.flush"):
            self._checkpoint_flush_impl(task)

    def _checkpoint_flush_impl(self, task: dict) -> None:
        with self._ckpt_cond:
            self._ckpt_dirty_gen += 1
            self._ckpt_pending_claims += 1
            target = self._ckpt_dirty_gen
            if task.get("active"):
                # reaching the barrier ends this task's pre-durability work;
                # the writer's commit window watches this count
                task["active"] = False
                self._attach_active -= 1
            self._ensure_checkpoint_writer_locked()
            self._ckpt_cond.notify_all()
            while self._ckpt_result_gen < target and not self._ckpt_stopped:
                self._ckpt_cond.wait()
            # FAILED-interval scan BEFORE the durable check: if the
            # attempt covering this target failed, this claim must error
            # and roll back — a later successful retry may already have
            # advanced _ckpt_durable_gen past the target (it covered the
            # other claims' rollbacks and this claim's still-present
            # entry), but that write was never this claim's ACK.
            err: Optional[BaseException] = None
            for gen_lo, gen_hi, fail_err in self._ckpt_failures:
                if gen_lo < target <= gen_hi:
                    err = fail_err
                    break
            if err is None:
                if self._ckpt_durable_gen >= target:
                    return
                err = self._ckpt_error \
                    or OSError("checkpoint writer stopped")
        raise err

    def _checkpoint_writer_loop(self) -> None:
        cond = self._ckpt_cond
        while True:
            with cond:
                idle_deadline = time.monotonic() + CHECKPOINT_WRITER_IDLE_S
                while self._ckpt_dirty_gen == self._ckpt_result_gen \
                        and not self._ckpt_stopped:
                    remaining = idle_deadline - time.monotonic()
                    if remaining <= 0:
                        # idle exit (see CHECKPOINT_WRITER_IDLE_S): clear
                        # the thread slot only if it is still OURS — a
                        # stop()/start() cycle may already have installed
                        # a successor
                        if self._ckpt_thread is threading.current_thread():
                            self._ckpt_thread = None
                        return
                    cond.wait(timeout=remaining)
                if self._ckpt_stopped \
                        and self._ckpt_dirty_gen == self._ckpt_result_gen:
                    return
                # commit window: while other attach tasks are still in
                # flight, hold briefly so their mutations ride this write;
                # a lone prepare sees _attach_active == 0 and commits now
                deadline = time.monotonic() + self.checkpoint_commit_window_s
                while self._attach_active > 0 and not self._ckpt_stopped:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        break
                    cond.wait(timeout=remaining)
                target = self._ckpt_dirty_gen
                n_claims = self._ckpt_pending_claims
                self._ckpt_pending_claims = 0
            with self._lock:
                # versioned envelope (CHECKPOINT_VERSION): claims +
                # migration handoff records ride one atomic write
                snapshot = {"version": CHECKPOINT_VERSION,
                            "claims": dict(self._checkpoint),
                            "handoffs": dict(self._handoffs)}
            err: Optional[BaseException] = None
            payload_bytes = 0
            try:
                # span inside the try: an injected checkpoint.write fault
                # (the event faults.fire emits lands under this span) or a
                # real write failure closes the commit span with
                # outcome=error before the handler swallows it
                with trace.span("dra.checkpoint.commit",
                                histogram="tdp_checkpoint_commit_ms",
                                claims=n_claims):
                    # fault point "checkpoint.write" (raising): a failed
                    # commit must surface as per-claim errors, never
                    # silent ACKs
                    faults.fire("checkpoint.write")
                    # serialized once (compact separators) so the written
                    # size is observable: checkpoint_bytes on /status +
                    # /metrics is how a fleet notices checkpoint growth
                    # before it hurts commit latency
                    payload = _dump_compact(snapshot)
                    payload_bytes = len(payload.encode("utf-8"))
                    _atomic_write_text(self.checkpoint_path, payload)
            except Exception as exc:   # incl. non-OSError serialization
                err = exc
                log.error("DRA: checkpoint commit failed (%d claims "
                          "affected): %s", n_claims, exc)
            if err is None:
                # Claim occupancy changed durably: ONE fragmentation
                # recompute per GROUP COMMIT (not per claim — a
                # 1024-claim burst pays ~the commit count, riding the
                # same coalescing as the write itself). Runs BEFORE the
                # result generations publish below, so a caller whose
                # flush barrier releases already sees the fresh gauges.
                self._recompute_fragmentation()
            with cond:
                if err is not None:
                    # record the failed attempt's generation interval
                    # BEFORE publishing its result: every waiter whose
                    # target lies in (result_gen, target] must see the
                    # failure even if a later retry succeeds first
                    self._ckpt_failures.append(
                        (self._ckpt_result_gen, target, err))
                self._ckpt_result_gen = target
                self._ckpt_error = err
                if err is None:
                    self._ckpt_durable_gen = target
                    self._checkpoint_bytes = payload_bytes
                    stats = self.checkpoint_stats_counters
                    stats["checkpoint_commits_total"] += 1
                    stats["checkpoint_claims_coalesced_total"] += n_claims
                cond.notify_all()

    @contextmanager
    def _claim_task(self, admitted: Optional[list] = None):
        """Bracket one per-claim unit of attach work for the in-flight
        gauges and the writer's commit window. `admitted` is the burst
        pre-admission cell from _run_claim_tasks: when it still holds
        slots, this task TAKES OVER one pre-admitted _attach_active slot
        instead of incrementing again — the gauge counts each claim of
        the burst exactly once, from RPC admission to its durability
        barrier."""
        task = {"active": True}
        with self._ckpt_cond:
            if admitted is not None and admitted[0] > 0:
                admitted[0] -= 1
            else:
                self._attach_active += 1
            self._prepare_inflight += 1
        try:
            yield task
        finally:
            with self._ckpt_cond:
                if task.get("active"):
                    task["active"] = False
                    self._attach_active -= 1
                self._prepare_inflight -= 1
                self._ckpt_cond.notify_all()

    def checkpoint_stats(self) -> dict:
        # lock-free read side: the counter dict has FIXED keys (values
        # mutated under _ckpt_cond by the writer), so dict() is one
        # C-atomic copy and the int reads are GIL-atomic — /status never
        # queues behind a checkpoint commit window
        out = dict(self.checkpoint_stats_counters)
        out["prepare_inflight"] = self._prepare_inflight
        # claim tasks still before their durability barrier (the commit
        # window's input); surfaced so the counter-drift audit can pin
        # every tsalint-registered counter to a public name
        out["attach_active"] = self._attach_active
        out["prepare_workers"] = self.prepare_workers
        # bytes of the last committed checkpoint write (compact
        # serialization): the growth-observability gauge ISSUE 9 adds
        out["checkpoint_bytes"] = self._checkpoint_bytes
        # lifecycle survivability surfaces (same lock-free contract:
        # fixed-key dict copies + GIL-atomic int/len reads)
        out.update(dict(self.handoff_stats))
        out["handoff_records"] = len(self._handoffs)
        out["orphan_specs_removed"] = self.orphan_specs_removed
        out["checkpoint_version"] = CHECKPOINT_VERSION
        return out

    def _load_sticky_names(self):
        """→ (suffixed raw-id set, plain-label → owning raw-id map)."""
        try:
            with open(self.sticky_names_path, "r", encoding="utf-8") as f:
                data = json.load(f)
            if isinstance(data, dict):
                suffixed = {r for r in data.get("suffixed", ())
                            if isinstance(r, str)}
                owners = {lb: rw
                          for lb, rw in (data.get("label_owners") or
                                         {}).items()
                          if isinstance(lb, str) and isinstance(rw, str)}
                return suffixed, owners
        except (OSError, ValueError):
            pass
        return set(), {}

    def _save_sticky_names(self) -> None:
        # called OUTSIDE self._lock (blocking file write; the global lock
        # is hot). _sticky_save_lock serializes writers, and each writer
        # snapshots the CURRENT sets under the global lock, so the last
        # serialized write always carries the newest state — records only
        # ever grow, so converge-to-latest is lossless.
        with self._sticky_save_lock:
            with self._lock:
                payload = {"suffixed": sorted(self._sticky_suffixed),
                           "label_owners": dict(self._label_owners)}
            try:
                _atomic_write_json(self.sticky_names_path, payload)
            except OSError as exc:
                # a failed persist degrades to process-lifetime stickiness;
                # names stay correct until the next restart
                log.warning("DRA: could not persist sticky name set: %s", exc)

    # ------------------------------------------------------------ prepare

    def _claim_cdi_id(self, uid: str) -> str:
        return f"{self.cfg.resource_namespace}/{CDI_CLAIM_CLASS}={uid}"

    def _claim_spec_path(self, uid: str) -> str:
        return os.path.join(self.cdi_dir,
                            f"{self._driver_fs}-claim-{uid}.json")

    def _write_claim_spec(self, uid: str, device_specs, envs) -> str:
        nodes = [{"path": s.container_path, "hostPath": s.host_path,
                  "permissions": s.permissions} for s in device_specs]
        spec = {
            "cdiVersion": CDI_VERSION,
            "kind": f"{self.cfg.resource_namespace}/{CDI_CLAIM_CLASS}",
            "devices": [{
                "name": uid,
                "containerEdits": {
                    "deviceNodes": nodes,
                    "env": [f"{k}={v}" for k, v in sorted(envs.items())],
                },
            }],
        }
        path = self._claim_spec_path(uid)
        _atomic_write_json(path, spec)
        return path

    def _allocation_results(self, claim: drapb.Claim) -> Tuple[List[dict],
                                                               Optional[int]]:
        """(this driver's device results, metadata.generation) from the
        claim's live allocation. The generation is recorded at prepare
        time and validated by the migration-handoff path: a handoff
        emitted for generation N must not prepare a claim whose live
        object has since moved."""
        if self.api is None:
            raise AllocationError("no API server client configured")
        path = (f"{self._resource_api()}/namespaces/{claim.namespace}"
                f"/resourceclaims/{claim.name}")
        try:
            obj = self.api.get_json(path)
        except (ApiError, ValueError) as exc:
            if isinstance(exc, ApiError) and exc.code == 404:
                self._note_api_404()
            raise AllocationError(f"ResourceClaim GET failed: {exc}")
        meta = obj.get("metadata") or {}
        uid = meta.get("uid")
        if uid != claim.uid:
            # the claim was deleted and recreated under the same name; the
            # kubelet's request is for the OLD object — preparing the new
            # one's allocation would hand the pod the wrong devices
            raise AllocationError(
                f"ResourceClaim UID mismatch (live {uid!r} != "
                f"requested {claim.uid!r})")
        generation = meta.get("generation")
        if not isinstance(generation, int):
            generation = None
        alloc = ((obj.get("status") or {}).get("allocation") or {})
        results = ((alloc.get("devices") or {}).get("results")) or []
        return ([r for r in results if r.get("driver") == self.driver_name],
                generation)

    def _inventory_snapshot(self) -> epoch_mod.InventoryEpoch:
        """The current inventory epoch — ONE atomic reference read, no
        lock. Device planning (sysfs reads, fragment assembly) runs
        against this immutable snapshot while set_inventory/apply_health
        stay free to publish successors."""
        return self._inv_store.current

    def _plan_devices(self, results: Sequence[dict], snapshot=None):
        """(device_specs, envs) for a claim's allocated devices.

        Chips group by generation through the same AllocationPlanner the
        device-plugin Allocate uses (TOCTOU revalidation, group expansion,
        iommufd, shared devices); partitions follow vtpu.py's node rules.
        Runs lock-free against an inventory epoch (the lockdep read-path
        gate pins zero registered-lock acquisitions): concurrent claims
        must never queue behind each other's sysfs reads, and the epoch
        id keys each planner's precompiled fragments.
        """
        with lockdep.read_path("dra.plan"):
            return self._plan_devices_impl(
                results,
                snapshot if snapshot is not None
                else self._inventory_snapshot())

    def _plan_devices_impl(self, results: Sequence[dict],
                           ep: epoch_mod.InventoryEpoch):
        by_name, planners, parent_planner = \
            ep.by_name, ep.planners, ep.parent_planner
        specs: List = []
        envs: Dict[str, str] = {}
        seen_paths: set = set()

        def add_specs(new_specs) -> None:
            for s in new_specs:
                if s.host_path not in seen_paths:
                    seen_paths.add(s.host_path)
                    specs.append(s)

        chips_by_gen: Dict[str, List[str]] = {}
        partitions: List[Tuple[str, TpuPartition]] = []
        for r in results:
            name = r.get("device", "")
            entry = by_name.get(name)
            if entry is None:
                if name in ep.departed:
                    # hot-unplugged while the allocation was in flight:
                    # say so — this is a surprise removal, not scheduler
                    # staleness, and the operator remedies differ
                    raise AllocationError(
                        f"allocated device {name!r} departed this node "
                        "(PCIe hot-unplug); the claim must be "
                        "re-allocated")
                raise AllocationError(
                    f"allocated device {name!r} is not in this "
                    "node's inventory (stale ResourceSlice?)")
            kind, group_name, obj = entry
            if kind == "chip":
                chips_by_gen.setdefault(group_name, []).append(obj.bdf)
            else:
                partitions.append((group_name, obj))

        from .kubeletapi import pb
        for gen, bdfs in sorted(chips_by_gen.items()):
            plan = planners[gen].plan(bdfs, epoch=ep.epoch_id)
            add_specs(plan.device_specs)
            envs.update(plan.envs)

        # round 20: prefetch every mdev partition's privileged reads
        # (mdev_type name + iommu_group link) in ONE batched crossing —
        # the loop below used to pay two round trips per partition in
        # spawn mode. A sub-result that refused/failed is simply absent
        # here and the loop's singular read (with its diagnostics) runs.
        prefetch_names: Dict[str, bytes] = {}
        prefetch_groups: Dict[str, Optional[str]] = {}
        mdev_parts = [p for _tn, p in partitions if p.provider == "mdev"]
        client = broker_mod.get_client()
        if mdev_parts and client.mode == "spawn":
            subs: List[dict] = []
            for p in mdev_parts:
                subs.append({"op": "read_attr", "key": p.uuid,
                             "path": os.path.join(
                                 self.cfg.mdev_base_path, p.uuid,
                                 "mdev_type", "name")})
                subs.append({"op": "read_link",
                             "path": os.path.join(
                                 self.cfg.mdev_base_path, p.uuid,
                                 "iommu_group")})
            got = client.run_batch(subs)
            for p, name_res, group_res in zip(mdev_parts, got[0::2],
                                              got[1::2]):
                if ("unavailable" in (name_res.get("kind"),
                                      group_res.get("kind"))):
                    # same typed degradation as the singular path: the
                    # whole claim fails unavailable, retried after the
                    # broker respawns
                    raise broker_mod.BrokerUnavailable(
                        broker_mod._unavailable_detail(
                            str(name_res.get("error")
                                or group_res.get("error")
                                or "batch failed")))
                data = name_res.get("data") if name_res.get("ok") else None
                if data is not None:
                    prefetch_names[p.uuid] = data.encode("latin-1")
                if group_res.get("ok"):
                    prefetch_groups[p.uuid] = group_res.get("target")

        for type_name, p in partitions:
            env_key = (f"{self.cfg.vtpu_env_prefix}_"
                       f"{sanitize_name(type_name)}")
            envs[env_key] = ",".join(
                x for x in (envs.get(env_key), p.uuid) if x)
            if p.provider == "mdev":
                # mirror vtpu.py exactly: the SHARED live mdev-type TOCTOU
                # check (allocate.live_mdev_type), then the per-mdev group
                # — or the reference-compatible wide /dev/vfio mount when
                # the group link is not visible (vtpu.py:169-172);
                # diverging here would let the two APIs prepare the same
                # partition differently
                live = live_mdev_type(self._mdev_name_reader, self.cfg,
                                      p.uuid,
                                      prefetched=prefetch_names.get(
                                          p.uuid))
                if live != type_name:
                    raise AllocationError(
                        f"partition {p.uuid}: live type {live!r} != "
                        f"{type_name!r}")
                mdev_specs = [pb.DeviceSpec(
                    host_path=self.cfg.dev_path("dev/vfio/vfio"),
                    container_path="/dev/vfio/vfio", permissions="mrw")]
                # via the privilege seam (broker.seam_read_link): a
                # read-only daemon prepares mdev partitions without
                # touching the host tree itself (spawn mode brokers it);
                # the batched prefetch above already carries the answer
                # for partitions it covered
                if p.uuid in prefetch_groups:
                    group = prefetch_groups[p.uuid]
                else:
                    group = broker_mod.seam_read_link(os.path.join(
                        self.cfg.mdev_base_path, p.uuid, "iommu_group"))
                if group is not None:
                    mdev_specs.append(pb.DeviceSpec(
                        host_path=self.cfg.dev_path("dev/vfio", group),
                        container_path=f"/dev/vfio/{group}",
                        permissions="mrw"))
                else:
                    mdev_specs.append(pb.DeviceSpec(
                        host_path=self.cfg.dev_path("dev/vfio"),
                        container_path="/dev/vfio", permissions="mrw"))
                add_specs(mdev_specs)
            elif p.accel_index is not None:
                add_specs([pb.DeviceSpec(
                    host_path=self.cfg.dev_path("dev", f"accel{p.accel_index}"),
                    container_path=f"/dev/accel{p.accel_index}",
                    permissions=self.cfg.partition_node_permissions)])
            else:
                plan = parent_planner.plan([p.parent_bdf],
                                           shared_devices=[],
                                           epoch=ep.epoch_id)
                add_specs(plan.device_specs)
                pci_key = (f"{self.cfg.env_prefix}_"
                           f"{sanitize_name(type_name)}")
                joined = ",".join(plan.expanded_bdfs)
                envs[pci_key] = ",".join(
                    x for x in (envs.get(pci_key), joined) if x)
        return specs, envs

    def _prepare_claim(self, claim: drapb.Claim,
                       task: dict) -> List[dict]:
        # crossings-per-claim bracket (round 20): same live gauge the
        # classic Allocate path records — a prepared claim's crossing
        # count lands on /status + /metrics regardless of which API
        # prepared it
        client = broker_mod.get_client()
        cross_before = client.crossings.value
        try:
            return self._prepare_claim_impl(claim, task)
        finally:
            client.note_claim_crossings(
                client.crossings.value - cross_before)

    def _prepare_claim_impl(self, claim: drapb.Claim,
                            task: dict) -> List[dict]:
        # Policy admission throttle (policy.py): BEFORE any state is
        # touched, so a rejected claim leaves nothing to roll back. The
        # rejection is this claim's error string; the kubelet retries and
        # a later policy decision (or an unloaded policy) admits it.
        engine = self._policy
        if engine is not None and engine.has_hook("admit"):
            reason = engine.admit({
                "op": "prepare", "claim_uid": claim.uid,
                "namespace": claim.namespace, "name": claim.name})
            if reason is not None:
                raise AllocationError(
                    f"policy rejected claim {claim.namespace}/{claim.name}:"
                    f" {reason}")
        # Remediation admission throttle (remediation.py): same
        # before-any-state placement and the same typed-error retry
        # contract — a shed prepare is THIS claim's error, counted by
        # the engine, and the kubelet's retry lands once the SLO
        # recovers (or a token frees up).
        remediation = self._remediation
        if remediation is not None:
            shed = remediation.admit({
                "op": "prepare", "claim_uid": claim.uid,
                "namespace": claim.namespace, "name": claim.name})
            if shed is not None:
                raise AllocationError(
                    f"claim {claim.namespace}/{claim.name} shed: {shed}")
        # Caller holds the per-claim-UID lock, so a concurrent retry of the
        # SAME claim waits here while distinct claims run fully parallel.
        # The API-server round-trip and device planning (sysfs reads,
        # fragment assembly) run OUTSIDE the global lock: a slow API server
        # or a hung sysfs read must not stall set_inventory / slice
        # republish or other claims' prepares. Only the checkpoint-map
        # mutation holds it; durability is the group-commit flush barrier.
        with self._lock:
            entry = self._checkpoint.get(claim.uid)
        if entry is not None:
            # idempotent retry: re-materialize the CDI spec if the file
            # was lost (node reboot wipes /var/run) and echo the result.
            # The per-UID lock excludes a concurrent unprepare, so the
            # rewrite can never orphan a spec no checkpoint entry tracks.
            if not os.path.exists(entry["spec_path"]):
                results, _ = self._allocation_results(claim)
                # fresh snapshot after the fetch, same as the main path:
                # a hot-unplug observed mid-fetch fails with the typed
                # "departed" error instead of racing sysfs reads
                specs, envs = self._plan_devices(
                    results, self._inventory_snapshot())
                self._write_claim_spec(claim.uid, specs, envs)
            return entry["devices"]
        results, generation = self._allocation_results(claim)
        # re-snapshot AFTER the API round-trip: a hot-unplug that published
        # a new epoch while the fetch was in flight is observed here, so
        # the plan fails with the typed "departed" error instead of racing
        # sysfs reads against the removal
        snapshot = self._inventory_snapshot()
        # migration handoff (import_handoff staged a record for this UID):
        # validate BEFORE preparing — a stale record means the claim was
        # re-allocated since the source released it, and preparing from
        # it would attach the pod to the wrong devices
        handoff = self._incoming_handoffs.get(claim.uid)
        if handoff is not None:
            try:
                self._validate_handoff(handoff, claim, generation)
            except HandoffValidationError:
                # evict the stale record: generations are monotonic, so
                # it can never validate again — keeping it would fail
                # every kubelet retry forever. The retry prepares from
                # the live allocation (no handoff), which is correct:
                # the claim moved on since the source released it.
                with self._lock:
                    self._incoming_handoffs.pop(claim.uid, None)
                raise
        specs, envs = self._plan_devices(results, snapshot)
        spec_path = self._write_claim_spec(claim.uid, specs, envs)
        raws = self._claim_raw_ids(results, snapshot)
        devices = []
        for r in results:
            devices.append({
                "request_names": (
                    [r["request"]] if r.get("request") else []),
                "pool_name": r.get("pool", self.node_name),
                "device_name": r.get("device", ""),
                # the one composite CDI device (all nodes + env) rides
                # on EVERY entry: the kubelet filters prepared devices
                # by the container's claim request, so an id attached
                # to only one entry would leave containers referencing
                # the claim's other requests with no nodes at all. The
                # kubelet aggregates CDI ids as a set, so the repeats
                # collapse before reaching the runtime.
                "cdi_device_ids": [self._claim_cdi_id(claim.uid)],
            })
        # trace affinity (r17): the entry carries the trace that placed
        # the claim — a migrating claim's handoff record forwards it, so
        # this prepare CONTINUES the original trace when it completes a
        # handoff, and a fresh prepare stamps its own active context
        traceparent = (handoff or {}).get("traceparent") \
            or trace.propagate()
        with self._lock:
            self._checkpoint[claim.uid] = {
                "name": claim.name,
                "namespace": claim.namespace,
                "spec_path": spec_path,
                "devices": devices,
                # lifecycle metadata: the devices' raw ids (orphan
                # mapping on hot-unplug survives a restart) and the
                # allocation generation (handoff validation input)
                "device_raws": raws,
                "generation": generation,
                "traceparent": traceparent,
            }
            # a claim prepared HERE retires any handoff record this node
            # emitted for it (round-trip migration back to the source):
            # both mutations ride the same group commit below
            self._handoffs.pop(claim.uid, None)
        try:
            # ACK only after the entry is durable (group-commit barrier)
            self._checkpoint_flush(task)
        except Exception:
            # the write never landed: roll the mutation back so a kubelet
            # retry re-prepares from scratch instead of ACKing a claim the
            # checkpoint cannot recover after a restart
            with self._lock:
                self._checkpoint.pop(claim.uid, None)
            try:
                os.unlink(spec_path)
            except OSError:
                pass
            self._checkpoint_mark_dirty()   # converge disk to the rollback
            raise
        if handoff is not None:
            with self._lock:
                if self._incoming_handoffs.pop(claim.uid, None) is not None:
                    self.handoff_stats["handoffs_completed_total"] += 1
            # the waterfall's "handoff" act: recorded inside the prepare
            # span (inherits claim_uid/node + the handoff's trace link)
            trace.event("dra.handoff.completed",
                        source=handoff.get("source_node", "?"),
                        generation=handoff.get("generation"))
            log.info("DRA: migration handoff for claim %s/%s completed "
                     "(source %s)", claim.namespace, claim.name,
                     handoff.get("source_node", "?"))
        if self._lifecycle is not None:
            for raw in raws:
                self._lifecycle.note_allocated(raw, claim.uid)
        log.info("DRA: prepared claim %s/%s (%d devices)",
                 claim.namespace, claim.name, len(devices))
        return devices

    def _unprepare_claim(self, claim: drapb.Claim, task: dict) -> None:
        # Caller holds the per-claim-UID lock (see _prepare_claim), which
        # makes this read→unlink→drop sequence atomic PER CLAIM — the
        # global lock only guards the checkpoint-map accesses, so the spec
        # unlink (file I/O on a path only this claim owns) runs outside it
        # and a slow filesystem never stalls other claims or slice builds.
        with self._lock:
            entry = self._checkpoint.get(claim.uid)
        if entry is not None:
            # fault point "migration.handoff" (raising): emitting the
            # handoff record fails BEFORE any state mutates — the
            # unprepare errors per-claim, the entry (and spec) survive,
            # and the kubelet retry re-runs the sequence (exactly-once)
            faults.fire("migration.handoff", claim=claim.uid)
            self._note_detaching(entry, claim.uid)
        spec_path = (entry or {}).get(
            "spec_path", self._claim_spec_path(claim.uid))
        # unlink BEFORE dropping the checkpoint entry: a failed
        # unlink must leave the claim recorded so the kubelet's
        # retry reaches the spec again instead of resurrecting
        # a stale entry on the next driver restart
        try:
            os.unlink(spec_path)
        except FileNotFoundError:
            pass
        if entry is not None:
            # Migration claim handoff: the release is recorded as a
            # durable handoff record riding the SAME group commit as the
            # checkpoint-entry deletion — a migration controller copies
            # it to the destination (export_handoff → import_handoff),
            # whose prepare validates claim UID + allocation generation
            # before attaching. An orphaned claim (device surprise-
            # removed) emits no handoff: there is nothing coherent for a
            # destination to take over.
            with self._lock:
                # re-read at the pop: a racing hot-unplug REPLACES the
                # entry with an orphan-marked copy (on_device_gone swaps
                # wholesale), so the no-handoff-for-orphans decision and
                # the rollback value must use the LIVE entry, not the
                # snapshot read before the spec unlink
                live = self._checkpoint.pop(claim.uid, None)
                if live is not None:
                    entry = live
                record = (None if "orphaned" in entry
                          else self._handoff_record(claim, entry))
                if record is not None:
                    self._handoffs[claim.uid] = record
                    self._prune_handoffs_locked()
            try:
                # ACK only once the deletion is durable — otherwise a
                # driver restart would resurrect the claim the kubelet
                # believes is gone
                self._checkpoint_flush(task)
            except Exception:
                # deletion never landed: restore the entry so the retry
                # re-runs it (the spec file is already gone; the retry's
                # unlink tolerates that); the un-committed handoff record
                # is withdrawn with it — the retry re-emits
                with self._lock:
                    self._checkpoint.setdefault(claim.uid, entry)
                    if record is not None:
                        self._handoffs.pop(claim.uid, None)
                self._checkpoint_mark_dirty()
                raise
            if record is not None:
                with self._lock:
                    self.handoff_stats["handoffs_emitted_total"] += 1
            # the claim's pre-serialized ack retires with its entry (the
            # deletion is durable at this point; a re-prepare re-builds)
            self._ack_cache.pop(claim.uid, None)
            self._note_released(entry, claim.uid)
        log.info("DRA: unprepared claim %s/%s%s",
                 claim.namespace, claim.name,
                 "" if entry else " (not prepared; idempotent ok)")

    # ------------------------------------------------- migration handoff

    def _handoff_record(self, claim: drapb.Claim, entry: dict) -> dict:
        return {
            "uid": claim.uid,
            "name": claim.name,
            "namespace": claim.namespace,
            # the allocation generation recorded at prepare time; the
            # destination refuses the handoff if the live claim moved
            "generation": entry.get("generation"),
            "devices": [d.get("device_name", "")
                        for d in entry.get("devices", ())],
            "source_node": self.node_name,
            "emitted_at": time.time(),
            # trace propagation (r17): the trace that originally placed
            # the claim rides the handoff, so source-unprepare →
            # destination-prepare is ONE trace across hosts
            "traceparent": entry.get("traceparent"),
        }

    def _prune_handoffs_locked(self) -> None:
        # bounded record set (caller holds _lock): drop oldest-emitted
        # first — dict insertion order is emission order within one
        # process, and loaded records predate all new ones
        while len(self._handoffs) > HANDOFF_MAX_RECORDS:
            oldest = min(self._handoffs,
                         key=lambda u: self._handoffs[u].get("emitted_at", 0))
            del self._handoffs[oldest]

    @staticmethod
    def _validate_handoff(record: dict, claim: drapb.Claim,
                          generation: Optional[int]) -> None:
        if record.get("uid") != claim.uid:
            raise HandoffValidationError(
                f"handoff record is for claim uid {record.get('uid')!r}, "
                f"not {claim.uid!r}")
        want = record.get("generation")
        if want is not None and generation is not None and want != generation:
            raise HandoffValidationError(
                f"handoff generation {want!r} != live claim generation "
                f"{generation!r} — the claim was re-allocated after the "
                f"source released it; re-schedule instead of attaching "
                f"stale devices")

    def export_handoff(self, uid: str) -> Optional[dict]:
        """The durable handoff record this node emitted for claim `uid`
        (None when unknown). The migration controller copies it to the
        destination driver's import_handoff; records survive daemon
        restarts (checkpointed) until consumed, re-prepared, or aged out
        of the bounded set."""
        record = self._handoffs.get(uid)     # GIL-atomic read
        return dict(record) if record is not None else None

    def import_handoff(self, record: dict) -> None:
        """Stage a handoff record delivered out-of-band for this node's
        next NodePrepareResources of that claim UID, which validates it
        (claim UID + allocation generation) before preparing."""
        uid = record.get("uid")
        if not isinstance(uid, str) or not uid:
            raise ValueError("handoff record carries no claim uid")
        with self._lock:
            self._incoming_handoffs[uid] = dict(record)
            # bounded like the outgoing set: a record is normally removed
            # by the claim's prepare (consumed) or a failed validation
            # (stale), but migrations retargeted elsewhere would
            # otherwise accrete staged records forever — drop oldest-
            # imported first (dict insertion order)
            while len(self._incoming_handoffs) > HANDOFF_MAX_RECORDS:
                self._incoming_handoffs.pop(
                    next(iter(self._incoming_handoffs)))

    def _claim_raw_ids(self, results: Sequence[dict],
                       ep: epoch_mod.InventoryEpoch) -> List[str]:
        raws = []
        for r in results:
            entry = ep.by_name.get(r.get("device", ""))
            if entry is not None:
                raws.append(self._raw_id(entry[0], entry[2]))
        return raws

    def _note_detaching(self, entry: dict, uid: str) -> None:
        if self._lifecycle is not None:
            for raw in entry.get("device_raws", ()):
                self._lifecycle.note_detaching(raw, uid)

    def _note_released(self, entry: dict, uid: str) -> None:
        if self._lifecycle is not None:
            for raw in entry.get("device_raws", ()):
                self._lifecycle.note_released(raw, uid)

    # ------------------------------------------------------------- RPCs

    def _run_claim_tasks(self, claims, fn, op: str,
                         hist: Optional[str] = None,
                         link_for=None) -> List[Optional[str]]:
        """Run `fn(claim, task)` for every claim — on the bounded prepare
        pool when the request carries several — returning the per-claim
        error string (None = success). ANY exception becomes that claim's
        error, never the RPC's: a non-OSError checkpoint/serialization
        failure used to escape NodeUnprepareResources' `except OSError`
        and kill the whole multi-claim RPC. `op`/`hist` name the
        per-claim trace span and its latency histogram — explicit at the
        two call sites, so a callback rename can never silently detach
        tdp_prepare_wall_ms from the prepare path. `link_for(claim)`
        returns the claim's carried trace context (a staged handoff
        record's traceparent on prepare, the checkpoint entry's on
        unprepare) so the per-claim span JOINS the trace that originally
        placed the claim — the cross-host migration waterfall."""

        # Burst pre-admission cell (see below): slots pre-charged to
        # _attach_active that pool workers take over one by one.
        admitted = [0]

        def run_one(claim) -> Optional[str]:
            # Per-claim child span of the burst fan-out: runs on a pool
            # worker, so the claim context rides the span's own attrs
            # (child spans started inside it — the checkpoint flush, the
            # kubeapi fetch — inherit claim_uid for /debug/flight?claim=)
            try:
                with trace.span(op, histogram=hist, claim_uid=claim.uid,
                                namespace=claim.namespace, name=claim.name,
                                link=(link_for(claim) if link_for
                                      else None)), \
                        self._claim_task(admitted) as tsk, \
                        self._claim_lock(claim.uid):
                    fn(claim, tsk)
                return None
            except Exception as exc:
                log.error("DRA: %s %s/%s failed: %s", fn.__name__.strip("_"),
                          claim.namespace, claim.name, exc)
                return str(exc)

        if len(claims) <= 1 or self.prepare_workers <= 1:
            return [run_one(c) for c in claims]
        # Pre-admit the WHOLE burst into _attach_active before handing it
        # to the pool. _claim_task used to increment the gauge only when a
        # pool worker STARTED its claim, so claims admitted in this RPC but
        # not yet picked up were invisible to the writer's commit window —
        # it saw attach_active drop to 0 after an early lone claim reached
        # its barrier and committed just that one, splitting the burst
        # across checkpoint writes (and letting a count=1 checkpoint.write
        # fault error one claim while its siblings silently ACKed later).
        # Each worker takes over a pre-admitted slot via `admitted`; any
        # slots left if the pool dies mid-burst are released below so the
        # gauge can't drift.
        with self._ckpt_cond:
            self._attach_active += len(claims)
            admitted[0] = len(claims)
        try:
            try:
                return list(self._prepare_pool.map(run_one, claims))
            except RuntimeError:
                # pool shut down under us (stop() racing a straggler RPC):
                # degrade to the inline path — each claim still errors/
                # answers individually instead of the RuntimeError failing
                # the RPC
                return [run_one(c) for c in claims]
        finally:
            with self._ckpt_cond:
                leftover, admitted[0] = admitted[0], 0
                if leftover:
                    self._attach_active -= leftover
                    self._ckpt_cond.notify_all()

    def _ack_segment(self, uid: str, devices: List[dict]) -> bytes:
        """Serialized NodePrepareResourceResponse payload for one prepared
        claim — built once per (uid, devices-list identity), reused by
        every kubelet retry (the byte plane's DRA half). Counted on the
        reused/serializations ledger (/status dra.ack_bytes)."""
        cached = self._ack_cache.get(uid)       # GIL-atomic; no lock
        if cached is not None and cached[0] is devices:
            self._ack_bytes_reused.add()
            return cached[1]
        payload = drapb.NodePrepareResourceResponse(
            devices=[drapb.Device(**d) for d in devices]).SerializeToString()
        self._ack_serializations.add()
        self._ack_cache[uid] = (devices, payload)
        return payload

    def warm_ack_cache(self) -> int:
        """Rebuild the pre-serialized ack payload for every restored,
        non-orphaned checkpoint entry (boot-time byte-plane warm-up).

        The idempotent prepare path returns ``entry["devices"]`` by
        identity, so seeding the cache against that same list object
        gives a kubelet replay an identity-matched byte reuse — the
        replay costs a dict lookup, not a protobuf serialization. An
        orphaned entry is skipped (its replay must build the error path),
        and a malformed legacy entry is skipped rather than failing boot.
        Returns the number of acks warmed."""
        warmed = 0
        for uid, entry in self._checkpoint.items():
            if "orphaned" in entry:
                continue
            devices = entry.get("devices")
            if not isinstance(devices, list):
                continue
            try:
                self._ack_segment(uid, devices)
                warmed += 1
            except Exception as exc:
                log.warning("DRA: could not pre-serialize ack for restored "
                            "claim %s: %s", uid, exc)
        if warmed:
            trace.event("dra.ack_cache.warmed", claims=warmed)
        return warmed

    def ack_byte_stats(self) -> Dict[str, int]:
        return {"reused": self._ack_bytes_reused.value,
                "serializations": self._ack_serializations.value}

    def NodePrepareResources(self, request, context):
        claims = list(request.claims)
        prepared: Dict[str, bytes] = {}

        def prepare_one(claim, task):
            prepared[claim.uid] = self._ack_segment(
                claim.uid, self._prepare_claim(claim, task))

        # node= rides the RPC root span (children inherit): the fleet
        # flight collector labels each waterfall record by it, and a
        # per-node /debug/flight-shaped source filters on it in fleetsim
        with trace.span("dra.NodePrepareResources", claims=len(claims),
                        node=self.node_name):
            errors = self._run_claim_tasks(
                claims, prepare_one, op="dra.prepare.claim",
                hist="tdp_prepare_wall_ms",
                # a staged migration handoff carries the trace that
                # originally placed the claim: the destination prepare
                # links it (GIL-atomic dict read; no staged record = no
                # link — never counted as a drop)
                link_for=lambda c: (self._incoming_handoffs.get(c.uid)
                                    or {}).get("traceparent"))
        # Response assembly is bytes concatenation: one map-entry record
        # per claim (key = uid, value = the pre-serialized ack payload).
        # Error acks are serialized per call — failure is not a hot path.
        segments = []
        for claim, error in zip(claims, errors):
            if error is not None:
                value = drapb.NodePrepareResourceResponse(
                    error=error).SerializeToString()
                self._ack_serializations.add()
            else:
                value = prepared[claim.uid]
            entry = (epoch_mod.encode_delimited(1, claim.uid.encode("utf-8"))
                     + epoch_mod.encode_delimited(2, value))
            segments.append(epoch_mod.encode_delimited(1, entry))
        data = b"".join(segments)
        if wants_raw(context):
            # the passthrough serializer (kubeletapi.draapi) writes these
            # bytes to the wire with no parse and no re-serialize
            return RawResponse(data)
        return drapb.NodePrepareResourcesResponse.FromString(data)

    def NodeUnprepareResources(self, request, context):
        resp = drapb.NodeUnprepareResourcesResponse()
        claims = list(request.claims)
        with trace.span("dra.NodeUnprepareResources", claims=len(claims),
                        node=self.node_name):
            errors = self._run_claim_tasks(
                claims, self._unprepare_claim, op="dra.unprepare.claim",
                # the checkpoint entry carries the trace that placed the
                # claim (stamped at prepare): a migration's source-side
                # unprepare links it, so source release + destination
                # prepare read as ONE trace across hosts
                link_for=lambda c: (self._checkpoint.get(c.uid)
                                    or {}).get("traceparent"))
        for claim, error in zip(claims, errors):
            out = resp.claims[claim.uid]
            if error is not None:
                out.error = error
        return resp

    def GetInfo(self, request, context):
        return regpb.PluginInfo(
            type=draapi.DRA_PLUGIN_TYPE,
            name=self.driver_name,
            endpoint=self.dra_socket_path,
            supported_versions=list(draapi.DRA_API_VERSIONS),
        )

    def NotifyRegistrationStatus(self, request, context):
        if request.plugin_registered:
            log.info("DRA: kubelet registered driver %s", self.driver_name)
            self.registration_error = None
            self.registered.set()
        else:
            log.error("DRA: kubelet REJECTED driver %s: %s",
                      self.driver_name, request.error)
            self.registration_error = request.error or "rejected"
            self.registered.set()
        return regpb.RegistrationStatusResponse()

    def prepared_claim_count(self) -> int:
        return len(self._checkpoint)   # len() is GIL-atomic; no lock

    # ----------------------------------------------------------- serving

    @property
    def serving(self) -> bool:
        return self._dra_server is not None

    def attach_health_hub(self, hub) -> None:
        """Subscribe this driver to the shared health plane.

        The hub watches the driver's REGISTRATION socket with a per-resource
        filter (healthhub.HubSubscription), giving the DRA path the same
        socket-loss recovery the classic plugins get: a kubelet restart that
        wipes plugins_registry/ leaves the gRPC server bound to a dangling
        inode the kubelet can never re-discover — the hub notices the unlink
        and the driver re-serves both sockets. Call before start()."""
        self._health_hub = hub

    def _on_registration_socket_removed(self) -> None:
        with self._lock:
            if self._stopped or self._dra_server is None:
                return
        log.warning("DRA: registration socket %s removed (kubelet "
                    "restart?); re-serving", self.registration_socket_path)
        # off the hub thread: re-serving stops/starts gRPC servers and must
        # not stall every other subscriber's health delivery behind it.
        # Tracked so stop() can join it; event-paced so stop() wakes a
        # mid-backoff sleep instead of abandoning a 30s-deep daemon thread.
        thread = threading.Thread(target=self._restart_serving, daemon=True,
                                  name="dra-reserve")
        self._reserve_thread = thread
        thread.start()

    def _restart_serving(self) -> None:
        # backoff-looped like server.py's restart(): a transient failure
        # while re-binding (kubelet still recreating the registry dir) must
        # retry, not die on a bare thread — once the hub subscription is
        # dropped during teardown, no future socket event would re-trigger
        # recovery for us
        backoff = BackoffPolicy(base_s=1.0, cap_s=30.0)
        while True:
            with self._serve_lock:
                with self._lock:
                    if self._stopped:
                        return
                try:
                    self._stop_servers_locked()
                    self._start_locked()
                    return
                except Exception as exc:
                    delay = backoff.next_delay()
                    log.error("DRA: re-serve after socket wipe failed (%s); "
                              "retrying in %.1fs", exc, delay)
            if self._stopping.wait(timeout=delay):
                return  # stop() won: exit now, not after the backoff

    def start(self) -> None:
        """Serve the DRAPlugin + Registration sockets (kubelet dials both)."""
        with self._serve_lock:
            with self._lock:
                self._stopped = False
            self._stopping.clear()
            # a stop() drained the attach plane; a re-start needs a live
            # pool and a writer allowed to spawn again
            with self._ckpt_cond:
                self._ckpt_stopped = False
                # stale failure intervals from the previous incarnation
                # must not poison fresh targets after a stop()/start()
                self._ckpt_failures.clear()
            if getattr(self._prepare_pool, "_shutdown", False):
                self._prepare_pool = futures.ThreadPoolExecutor(
                    max_workers=self.prepare_workers,
                    thread_name_prefix="dra-prepare")
            self._start_locked()

    def _start_locked(self) -> None:
        os.makedirs(self.driver_dir, exist_ok=True)
        os.makedirs(self.cfg.dra_registry_path, exist_ok=True)
        for path in (self.dra_socket_path, self.registration_socket_path):
            try:
                os.unlink(path)
            except FileNotFoundError:
                pass
        self._dra_server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=4),
            options=[("grpc.optimization_target", "latency")])
        draapi.add_dra_plugin_servicer(self._dra_server, self)
        self._dra_server.add_insecure_port(f"unix://{self.dra_socket_path}")
        self._dra_server.start()
        # the registration socket comes up only after the service socket is
        # live: the kubelet may dial the advertised endpoint immediately
        self._reg_server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=2))
        draapi.add_plugin_registration_servicer(self._reg_server, self)
        self._reg_server.add_insecure_port(
            f"unix://{self.registration_socket_path}")
        self._reg_server.start()
        if self._health_hub is not None:
            from .healthhub import HubSubscription
            self._health_sub = self._health_hub.subscribe(HubSubscription(
                name=f"dra:{self.driver_name}",
                socket_path=self.registration_socket_path,
                on_socket_removed=self._on_registration_socket_removed))
        log.info("DRA: serving %s (registration %s)",
                 self.dra_socket_path, self.registration_socket_path)

    def _stop_servers_locked(self) -> None:
        # unsubscribe FIRST so our own socket unlinks below never read as a
        # kubelet restart
        if self._health_sub is not None and self._health_hub is not None:
            self._health_hub.unsubscribe(self._health_sub)
            self._health_sub = None
        for server in (self._reg_server, self._dra_server):
            if server is not None:
                server.stop(grace=1).wait()
        self._reg_server = self._dra_server = None
        for path in (self.dra_socket_path, self.registration_socket_path):
            try:
                os.unlink(path)
            except FileNotFoundError:
                pass

    def stop(self, withdraw_slice: bool = False) -> None:
        with self._lock:
            self._stopped = True
            timer, self._republish_timer = self._republish_timer, None
        self._stopping.set()
        if timer is not None:
            timer.cancel()
        # stop the watch reconciler first: a late watch event must not
        # "repair" the slice a withdraw below is about to delete
        watch, self._slice_watch = self._slice_watch, None
        if watch is not None:
            watch.stop()
        with self._serve_lock:
            self._stop_servers_locked()
        # reap the hub-triggered re-serve runner: it checks _stopped under
        # the serve lock and its backoff waits are _stopping-keyed, so it
        # exits within one loop turn — unless WE are it (stop from a
        # re-serve failure path), where self-joining would deadlock
        reserve = self._reserve_thread
        if reserve is not None and reserve is not threading.current_thread():
            reserve.join(timeout=2)
        # drain the attach plane: no new claim tasks (pool refuses), then
        # let the checkpoint writer converge any pending mutations and exit
        self._prepare_pool.shutdown(wait=True)
        with self._ckpt_cond:
            self._ckpt_stopped = True
            self._ckpt_cond.notify_all()
            thread = self._ckpt_thread
        if thread is not None:
            thread.join(timeout=5)
        if withdraw_slice and self.api is not None:
            # _publish_lock waits out any in-flight publish (a retry timer
            # callback that already passed its _stopped check), so the
            # delete below cannot be overwritten by a late POST
            with self._publish_lock:
                try:
                    self.api.delete(
                        f"{self._resource_api()}/resourceslices/"
                        f"{self.slice_name()}")
                except ApiError as exc:
                    if exc.code != 404:
                        log.warning("DRA: slice withdraw failed: %s", exc)
