"""trace — the lock-free flight recorder: spans, histograms, slow-span log.

The daemon's counters (/status, /metrics) answer "how many" and "how
fast on average"; they cannot answer the two questions a fleet operator
actually asks during a boot storm: *why was THIS attach slow?* and *what
happened to claim X in the 30 s before it was orphaned?* This module is
the always-on introspection plane for those questions, built under the
same constraint PR 6 put on every other read path: ZERO registered
locks. `tests/test_epoch.py` pins Allocate/GetPreferredAllocation/
ListAndWatch//status at 0 registered-lock acquisitions, and the spans
now bracketed INSIDE those paths are counted by the same gate — the
tracing plane cannot regress the zero-lock contract without failing CI.

Three surfaces, one sharded-cells design (epoch.AtomicCounter's trick):

- **Spans** — ``with trace.span("dra.prepare.claim", claim_uid=uid):``
  records monotonic start/end, outcome (ok/error + the error text), and
  attributes (claim_uid, bdf, resource, epoch_id, ...) into a PER-THREAD
  ring buffer. Child spans inherit the parent's attributes, so a
  checkpoint-flush span started inside a claim span carries the claim
  UID without replumbing. The writer side is the owning thread only:
  the completed record is built as one immutable dict and stored with a
  single C-atomic list-slot assignment, so a concurrent snapshot reader
  can never observe a torn span. ``event()`` records a point-in-time
  record the same way (fault injections, lifecycle transitions).
- **Histograms** — fixed exponential-bucket latency histograms
  (attach wall, claim prepare wall, checkpoint commit, probe cycle,
  kubeapi RTT) with per-thread cells summed at read; exposed in
  Prometheus text format (``_bucket``/``_sum``/``_count``) on /metrics.
- **Flight recorder** — ``snapshot()`` merges every thread's ring into
  one time-ordered list (optionally filtered by claim/bdf/op/trace/
  since_ms); the
  status server serves it as ``/debug/flight``. Spans exceeding a
  per-op threshold (``$TDP_TRACE_SLOW_MS`` overrides the default) are
  additionally kept in a bounded slow-span log and emitted through the
  structured logger with their full attribute context. ``dump()``
  writes the whole ring to a JSON file; ``install_crash_hook()`` wires
  that into sys/threading excepthooks, and cli.py binds an on-demand
  dump to SIGHUP — the post-incident artifact for orphaned claims and
  identity swaps.

Concurrency contract (CPython, same vocabulary as epoch.py): ring slots
are written ONLY by their owning thread; ``list(buf)`` and
``_rings.append`` are C-level atomic; records are immutable once
stored. Readers therefore see each record exactly once per snapshot,
fully formed, at worst missing the very newest writes. Zero registered
locks on every write AND read path — tsalint has a fixture proving a
span inside an epoch read path trips no rule, and the trace counters
are epoch.AtomicCounter (lock-free by design, no owning lock to
configure in tools/tsalint/config.py COUNTERS).

Overhead: a span is two monotonic reads, two dict builds and one list
store (~2-4 us in this sandbox); ``bench.py --trace-overhead`` measures
it on the live attach path and docs/bench_attach_r10.json pins the
bound (guarded by tests/test_perf_honesty.py). ``$TDP_TRACE=0``
disables recording entirely (spans become a cached no-op context).

**Trace propagation (round 17).** Every span carries a W3C-traceparent-
style context: a 128-bit ``trace_id`` minted at the ROOT span of a
thread's stack (per-thread RNG, no locks) and inherited by every child,
plus a 64-bit ``span_id`` per span. The context crosses the process and
privilege boundaries this system owns as an explicit carrier field —
``propagate()`` returns the active span's ``traceparent`` string (one
counted propagation), and a receiving boundary passes it back in as
``span(op, link=...)``. A link NEVER mutates a remote ring (per-thread
rings stay single-writer): a linked ROOT span ADOPTS the remote
trace_id (the trace continues across the boundary), while a linked
child keeps its local trace and records the remote context under
``"link"`` — and ``snapshot(trace=...)`` matches a record by its own
trace_id OR its link's, so a cross-host migration reads as ONE trace.
Children inherit their parent's link like they inherit attrs, so the
whole subtree under a linked span stays query-reachable. Malformed
inbound context is dropped and counted, never raised
(``ctx_dropped_total``). docs/observability.md carries the
boundary-by-boundary carrier taxonomy.
"""

from __future__ import annotations

import json
import logging
import os
import random
import re
import sys
import threading
import time
from bisect import bisect_right
from collections import deque
from typing import Any, Callable, Dict, List, Mapping, Optional, Tuple

from . import schedcheck
from .epoch import AtomicCounter

log = logging.getLogger(__name__)

__all__ = ["span", "event", "snapshot", "drain", "slow_spans", "stats",
           "dump", "install_crash_hook", "uninstall_crash_hook",
           "configure", "reset", "histogram", "observe",
           "render_prometheus", "Histogram", "enabled",
           "current_context", "propagate", "format_traceparent",
           "parse_traceparent", "register_dump_extra",
           "unregister_dump_extra"]


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


# per-thread ring capacity: 256 spans x ~a dozen threads keeps the last
# ~30 s of a busy daemon's story in a few hundred KB
_ring_size = _env_int("TDP_TRACE_RING", 256)
_enabled = os.environ.get("TDP_TRACE", "1").strip().lower() not in (
    "0", "false", "no", "off")
# global slow-span threshold; per-op overrides below win
_slow_default_ms = _env_float("TDP_TRACE_SLOW_MS", 250.0)
# Per-op slow thresholds (ms): the ops where "slow" means something much
# tighter than the global default. Overridable at runtime via configure().
SLOW_THRESHOLDS_MS: Dict[str, float] = {
    # the attach hot path: double-digit ms here is an incident
    "server.Allocate": 50.0,
    "server.GetPreferredAllocation": 50.0,
    "server.ListAndWatch.send": 50.0,
    # a watch stream's span lasts its whole long-poll rotation BY DESIGN
    # (the server-side timeoutSeconds); duration here is lifetime, not
    # latency, so it can never be "slow"
    "kubeapi.watch.stream": float("inf"),
}
# how many slow spans the bounded log retains for /debug/flight
_SLOW_RING = 64


class _Ring:
    """One thread's span ring. `buf` slots are written only by the owner
    thread (single C-atomic store of an immutable record); `idx` is the
    owner's monotonically growing write cursor, so `max(0, idx - size)`
    is the exact overwrite count. `owner` is the owning Thread object —
    `_retire_dead_rings` uses it to bound how many dead threads' rings
    are retained."""

    __slots__ = ("buf", "idx", "thread", "owner")

    def __init__(self, size: int, thread: str) -> None:
        self.buf: List[Optional[dict]] = [None] * size
        self.idx = 0
        self.thread = thread
        self.owner = threading.current_thread()

    def store(self, rec: dict) -> None:
        self.buf[self.idx % len(self.buf)] = rec   # C-atomic slot store
        self.idx += 1                              # owner thread only


class _TLS(threading.local):
    def __init__(self) -> None:
        self.ring: Optional[_Ring] = None
        self.gen = -1
        self.stack: List["_Span"] = []
        self.seq = 0
        # per-thread id RNG (trace_id/span_id minting): seeded once from
        # os.urandom so ids are unique across processes/hosts, then pure
        # compute — no locks, no syscalls on the hot path
        self.rng: Optional[random.Random] = None


_tls = _TLS()
# every live ring, appended C-atomically on a thread's first record; the
# generation counter lets reset() retire all rings without a lock (a
# thread whose cached ring predates the bump re-registers a fresh one)
_rings: List[_Ring] = []
_gen = 0
# DEAD-thread rings retained for post-mortem reading: short-lived threads
# (the idle-exiting checkpoint writer, restart runners, start-pool
# workers) would otherwise accrete one ring per incarnation forever.
# The newest _DEAD_RING_KEEP dead rings stay readable (a crashed thread's
# last spans are exactly what the flight recorder is for); older ones are
# dropped at ring-registration time — a cold path, guarded by a plain
# (UNregistered — invisible to the zero-lock gates, never taken on a
# record/snapshot path) maintenance lock so two registering threads
# cannot double-retire.
_DEAD_RING_KEEP = 16
_maintenance_lock = threading.Lock()
# records made unreadable by ring retirement (mutated only under the
# maintenance lock; read GIL-atomically by stats) — keeps the exposed
# spans_overwritten_total monotonic across retirements
_retired_lost = 0
_slow: deque = deque(maxlen=_SLOW_RING)
_spans_total = AtomicCounter()
_events_total = AtomicCounter()
_slow_total = AtomicCounter()
# trace-propagation accounting (round 17) — all epoch.AtomicCounter
# (lock-free by design; tsalint COUNTERS carries LOCKFREE entries):
# propagated = contexts handed to an outbound boundary (propagate()),
# attached = remote contexts accepted as span/event links,
# dropped = inbound contexts refused as malformed (never raised)
_ctx_propagated = AtomicCounter()
_ctx_attached = AtomicCounter()
_ctx_dropped = AtomicCounter()


def enabled() -> bool:
    return _enabled


def configure(enabled: Optional[bool] = None,
              ring_size: Optional[int] = None,
              slow_ms: Optional[float] = None) -> None:
    """Runtime knobs (tests, bench): toggle recording, resize FUTURE
    rings (existing rings keep their size), or move the global slow
    threshold."""
    global _enabled, _ring_size, _slow_default_ms
    if enabled is not None:
        _enabled = bool(enabled)
    if ring_size is not None:
        if ring_size < 1:
            raise ValueError(f"ring_size must be >= 1, got {ring_size!r}")
        _ring_size = int(ring_size)
    if slow_ms is not None:
        _slow_default_ms = float(slow_ms)


def reset() -> None:
    """Retire every ring, the slow log and the counters (test isolation).
    The generation bump makes every thread's cached ring stale, so the
    next record lands in a fresh ring registered under the new
    generation. Dump extras stay registered (they are wiring, not
    state)."""
    global _rings, _gen, _spans_total, _events_total, _slow_total, \
        _retired_lost, _ctx_propagated, _ctx_attached, _ctx_dropped
    _gen += 1
    _rings = []
    _slow.clear()
    _spans_total = AtomicCounter()
    _events_total = AtomicCounter()
    _slow_total = AtomicCounter()
    _ctx_propagated = AtomicCounter()
    _ctx_attached = AtomicCounter()
    _ctx_dropped = AtomicCounter()
    with _maintenance_lock:
        _retired_lost = 0
    for hist in _histograms.values():
        hist._reset()


def _retire_dead_rings() -> None:
    """Drop all but the newest _DEAD_RING_KEEP dead-owner rings (called
    on the rare ring-registration path; readers snapshot `list(_rings)`
    so concurrent removal is safe for them). The retired rings' records
    are charged to the overwritten counter — they became unreadable
    before any reader drained them."""
    global _retired_lost
    with _maintenance_lock:
        dead = [r for r in list(_rings) if not r.owner.is_alive()]
        for ring in dead[:max(0, len(dead) - _DEAD_RING_KEEP)]:
            try:
                _rings.remove(ring)
            except ValueError:
                continue
            _retired_lost += ring.idx


def _ring() -> _Ring:
    tls = _tls
    if tls.ring is None or tls.gen != _gen:
        tls.ring = _Ring(_ring_size, threading.current_thread().name)
        tls.gen = _gen
        _rings.append(tls.ring)     # C-atomic list append
        _retire_dead_rings()
    return tls.ring


def _next_seq() -> int:
    _tls.seq += 1
    return _tls.seq


def _id_rng() -> random.Random:
    rng = _tls.rng
    if rng is None:
        rng = _tls.rng = random.Random(
            int.from_bytes(os.urandom(16), "big")
            ^ (threading.get_ident() << 64) ^ time.monotonic_ns())
    return rng


# --------------------------------------------------- trace context (r17)

_TRACEPARENT_RE = re.compile(
    r"^00-(?P<trace>[0-9a-f]{32})-(?P<span>[0-9a-f]{16})"
    r"-(?P<flags>[0-9a-f]{2})$")
_HEX32 = re.compile(r"^[0-9a-f]{32}$")
_HEX16 = re.compile(r"^[0-9a-f]{16}$")


def current_context() -> Optional[Dict[str, object]]:
    """The active span's trace context on THIS thread (None outside any
    span, or with tracing disabled): {"trace_id", "span_id", "sampled"}.
    Pure thread-local reads — zero locks."""
    stack = _tls.stack
    if not stack:
        return None
    sp = stack[-1]
    return {"trace_id": sp.trace_id, "span_id": sp.span_id,
            "sampled": True}


def format_traceparent(ctx: Mapping[str, object]) -> str:
    """Context dict → the W3C traceparent wire string
    (``00-<trace_id>-<span_id>-01``)."""
    flags = "01" if ctx.get("sampled", True) else "00"
    return f"00-{ctx['trace_id']}-{ctx['span_id']}-{flags}"


def parse_traceparent(text: object) -> Optional[Dict[str, object]]:
    """Wire string → context dict, or None (counted ctx_dropped_total)
    on anything malformed — an inbound boundary must degrade to 'no
    context', never raise into the request path. All-zero ids are
    invalid per the W3C spec."""
    if not isinstance(text, str):
        _ctx_dropped.add()
        return None
    m = _TRACEPARENT_RE.match(text.strip().lower())
    if m is None or set(m.group("trace")) == {"0"} \
            or set(m.group("span")) == {"0"}:
        _ctx_dropped.add()
        return None
    return {"trace_id": m.group("trace"), "span_id": m.group("span"),
            "sampled": bool(int(m.group("flags"), 16) & 1)}


def _coerce_link(link: object) -> Optional[Dict[str, object]]:
    """Normalize an inbound context (traceparent string, or a dict
    carrying trace_id/span_id — the brokeripc/handoff carrier shapes)
    into a validated link dict. None in → None out (no counting);
    malformed in → None out, counted dropped."""
    if link is None:
        return None
    if isinstance(link, str):
        return parse_traceparent(link)
    if isinstance(link, Mapping):
        tp = link.get("traceparent")
        if tp is not None:
            return parse_traceparent(tp)
        trace_id, span_id = link.get("trace_id"), link.get("span_id")
        if isinstance(trace_id, str) and _HEX32.match(trace_id) \
                and isinstance(span_id, str) and _HEX16.match(span_id):
            return {"trace_id": trace_id, "span_id": span_id,
                    "sampled": bool(link.get("sampled", True))}
    _ctx_dropped.add()
    return None


def propagate() -> Optional[str]:
    """The active span's traceparent string for an OUTBOUND boundary
    (brokeripc frame, apiserver request header, handoff record,
    checkpoint entry); None outside any span. Every non-None return is
    one counted propagation."""
    ctx = current_context()
    if ctx is None:
        return None
    _ctx_propagated.add()
    return format_traceparent(ctx)


def propagate_context() -> Optional[Dict[str, object]]:
    """propagate() in dict shape ({"trace_id", "span_id", "sampled"}) —
    the brokeripc frame carrier. Counted like propagate()."""
    ctx = current_context()
    if ctx is None:
        return None
    _ctx_propagated.add()
    return ctx


class _NullSpan:
    """Cached no-op context for $TDP_TRACE=0: one call + two no-op
    dunders, mirroring lockdep's disabled read_path cost."""

    __slots__ = ()

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, *exc: object) -> None:
        return None

    def set(self, **attrs: Any) -> None:
        return None


_NULL_SPAN = _NullSpan()


class _Span:
    """One active span on its owning thread. The record is built and
    stored at __exit__ — in-flight spans are not visible to snapshots
    (the flight recorder records completed work)."""

    __slots__ = ("op", "attrs", "histogram", "t0", "ts", "seq", "parent",
                 "trace_id", "span_id", "link")

    def __init__(self, op: str, histogram: Optional[str],
                 link: Optional[Dict[str, object]],
                 attrs: Dict[str, Any]) -> None:
        self.op = op
        self.histogram = histogram
        self.attrs = attrs
        self.t0 = 0.0
        self.ts = 0.0
        self.seq = 0
        self.parent: Optional[int] = None
        self.trace_id = ""
        self.span_id = ""
        self.link = link

    def set(self, **attrs: Any) -> None:
        """Attach attributes discovered mid-span (e.g. a probe verdict)."""
        self.attrs.update(attrs)

    def __enter__(self) -> "_Span":
        stack = _tls.stack
        if stack:
            parent = stack[-1]
            self.parent = parent.seq
            # trace context inheritance: one trace id per local span tree
            self.trace_id = parent.trace_id
            if self.link is None:
                # links inherit like attrs: the whole subtree under a
                # linked span stays reachable from the remote trace id
                self.link = parent.link
            # inheritance: a child born inside a claim/bdf-scoped span
            # carries that context without replumbing call signatures
            merged = dict(parent.attrs)
            merged.update(self.attrs)
            self.attrs = merged
        elif self.link is not None:
            # a linked ROOT adopts the remote trace id — the boundary
            # crossing continues the caller's trace instead of minting a
            # parallel one (the remote parent stays recorded as the link)
            self.trace_id = self.link["trace_id"]       # type: ignore
        else:
            self.trace_id = f"{_id_rng().getrandbits(128):032x}"
        self.span_id = f"{_id_rng().getrandbits(64):016x}"
        self.seq = _next_seq()
        stack.append(self)
        self.ts = time.time()
        self.t0 = time.monotonic()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        dur_ms = (time.monotonic() - self.t0) * 1e3
        stack = _tls.stack
        if stack and stack[-1] is self:
            stack.pop()
        elif self in stack:             # defensive: mis-nested exits
            stack.remove(self)
        ring = _ring()
        rec = {
            "kind": "span",
            "op": self.op,
            "thread": ring.thread,      # the ring caches the name
            "seq": self.seq,
            "parent": self.parent,
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "ts": self.ts,
            "dur_ms": round(dur_ms, 3),
            "outcome": "ok" if exc is None else "error",
            "attrs": self.attrs,
        }
        if self.link is not None:
            rec["link"] = self.link
        if exc is not None:
            rec["error"] = f"{type(exc).__name__}: {exc}"
        ring.store(rec)
        _spans_total.add()
        if self.histogram is not None:
            hist = _histograms.get(self.histogram)
            if hist is not None:
                hist.observe(dur_ms, exemplar=self.trace_id)
        threshold = SLOW_THRESHOLDS_MS.get(self.op, _slow_default_ms)
        if dur_ms >= threshold:
            _slow_total.add()
            _slow.append(rec)           # C-atomic bounded append
            log.warning(
                "slow span: op=%s dur_ms=%.1f threshold_ms=%g outcome=%s "
                "attrs=%s", self.op, dur_ms, threshold, rec["outcome"],
                self.attrs)


def span(op: str, histogram: Optional[str] = None, link: Any = None,
         **attrs: Any):
    """Open a span: ``with trace.span("server.Allocate", resource=r): ...``

    Disabled ($TDP_TRACE=0): a cached no-op. Enabled: records into this
    thread's ring at exit; `histogram` names a registered Histogram that
    observes the span's duration (ms). `link` attaches a REMOTE trace
    context (traceparent string or a {trace_id, span_id} dict — a
    handoff record, a brokeripc frame, a gRPC metadata header): a linked
    root adopts the remote trace id, a linked child records it, and
    either way ``snapshot(trace=...)`` finds the span from the remote
    trace. Zero registered locks either way — safe inside every
    lockdep.read_path bracket.
    """
    if not _enabled:
        return _NULL_SPAN
    if link is None:        # the hot-path shape: no boundary crossed
        return _Span(op, histogram, None, attrs)
    coerced = _coerce_link(link)
    if coerced is not None:
        _ctx_attached.add()
    return _Span(op, histogram, coerced, attrs)


def event(op: str, link: Any = None, **attrs: Any) -> None:
    """Record a point-in-time event (fault fired, lifecycle transition).
    Inherits the active span's attributes on this thread, so an injected
    fault inside a probe span carries the probe's bdf. `link` attaches a
    remote trace context like span(link=...) — the event joins that
    trace when it has no local span to inherit one from."""
    if not _enabled:
        return
    if link is None:
        coerced = None
    else:
        coerced = _coerce_link(link)
        if coerced is not None:
            _ctx_attached.add()
    stack = _tls.stack
    trace_id: Optional[str] = None
    if stack:
        top = stack[-1]
        merged = dict(top.attrs)
        merged.update(attrs)
        attrs = merged
        parent: Optional[int] = top.seq
        trace_id = top.trace_id
        if coerced is None:
            coerced = top.link
    else:
        parent = None
        if coerced is not None:
            trace_id = coerced["trace_id"]      # type: ignore[assignment]
    ring = _ring()
    rec: Dict[str, Any] = {
        "kind": "event",
        "op": op,
        "thread": ring.thread,
        "seq": _next_seq(),
        "parent": parent,
        "ts": time.time(),
        "outcome": "ok",
        "attrs": attrs,
    }
    if trace_id is not None:
        rec["trace_id"] = trace_id
    if coerced is not None:
        rec["link"] = coerced
    ring.store(rec)
    _events_total.add()


# ------------------------------------------------------------- read side

def _matches(rec: dict, claim: Optional[str], bdf: Optional[str],
             op: Optional[str], trace: Optional[str],
             since_ms: Optional[float]) -> bool:
    if op is not None and not rec["op"].startswith(op):
        return False
    if trace is not None and rec.get("trace_id") != trace \
            and (rec.get("link") or {}).get("trace_id") != trace:
        return False
    if since_ms is not None and rec["ts"] * 1e3 <= since_ms:
        return False
    attrs = rec.get("attrs") or {}
    if claim is not None and attrs.get("claim_uid") != claim:
        return False
    if bdf is not None and attrs.get("bdf") != bdf \
            and attrs.get("device") != bdf:
        return False
    return True


def snapshot(claim: Optional[str] = None, bdf: Optional[str] = None,
             op: Optional[str] = None,
             limit: Optional[int] = None,
             trace: Optional[str] = None,
             since_ms: Optional[float] = None) -> List[dict]:
    """Merge every thread's ring into one time-ordered record list.

    Lock-free and tear-free: `list(ring.buf)` snapshots each ring's slots
    in one C-atomic copy, each slot is either None or a COMPLETE immutable
    record (writers store fully-built dicts), and (thread, seq) is unique,
    so a snapshot can never contain a torn or duplicated span — at worst
    it misses records stored after its ring copy. Filters: claim matches
    attrs.claim_uid; bdf matches attrs.bdf/attrs.device; op is a prefix;
    trace matches a record's own trace_id OR its link's (the cross-host
    waterfall read); since_ms keeps records strictly newer than that
    epoch-milliseconds cursor. `limit` keeps the newest N after
    filtering. For a limit-bounded oldest-first drain use `drain()` —
    THE one paging implementation the /debug/flight endpoint serves.
    """
    records: List[dict] = []
    for ring in list(_rings):
        for rec in list(ring.buf):
            if rec is not None and _matches(rec, claim, bdf, op, trace,
                                            since_ms):
                records.append(rec)
    records.sort(key=lambda r: (r["ts"], r["seq"]))
    if limit is not None and limit >= 0:
        records = records[len(records) - min(limit, len(records)):]
    return records


def drain(since_ms: float, limit: Optional[int] = None,
          claim: Optional[str] = None, bdf: Optional[str] = None,
          op: Optional[str] = None,
          trace: Optional[str] = None) -> Tuple[List[dict], bool]:
    """One page of a bounded oldest-first drain: (page, more).

    The cursor contract: records strictly newer than `since_ms`, oldest
    first, at most `limit` per page — EXTENDED through any run of
    records sharing the page-final timestamp, because the resume cursor
    is that timestamp and a strictly-greater cursor would otherwise
    skip the equal-ts records a plain slice left behind (concurrent
    threads can share a time.time() float). A caller looping
    `page, more = drain(cursor, N); cursor = page[-1]["ts"] * 1e3`
    therefore never re-reads and never loses a record. A non-positive
    limit reads as unbounded: an empty page with more=True would leave
    the caller's cursor unable to advance — a busy loop, not a drain.
    """
    records = snapshot(claim=claim, bdf=bdf, op=op, trace=trace,
                       since_ms=since_ms)
    if limit is None or limit <= 0 or limit >= len(records):
        return records, False
    end = limit
    last_ts = records[end - 1]["ts"]
    while end < len(records) and records[end]["ts"] == last_ts:
        end += 1
    return records[:end], end < len(records)


def slow_spans() -> List[dict]:
    """The bounded slow-span log, oldest first (C-atomic deque copy)."""
    return list(_slow)


def stats() -> dict:
    """Gauges + counters for /status (lock-free: atomic counter sums,
    C-atomic list copies, GIL-atomic int reads)."""
    # the overwritten total must be MONOTONE (it is exposed as a
    # Prometheus counter): a scrape landing between a retire's
    # _rings.remove and its _retired_lost charge would otherwise dip —
    # so the two are read under the same (unregistered, cold, tiny)
    # maintenance lock the retire path mutates them under. Everything
    # else stays lock-free.
    with _maintenance_lock:
        rings = list(_rings)
        overwritten = _retired_lost + sum(
            max(0, r.idx - len(r.buf)) for r in rings)
    return {
        "enabled": _enabled,
        "ring_size": _ring_size,
        "rings": len(rings),
        "spans_recorded_total": _spans_total.value,
        "events_recorded_total": _events_total.value,
        "spans_overwritten_total": overwritten,
        "slow_spans_total": _slow_total.value,
        "slow_threshold_ms": _slow_default_ms,
        # trace propagation (round 17): outbound contexts handed to a
        # boundary / remote contexts attached as links / malformed
        # inbound contexts refused
        "ctx_propagated_total": _ctx_propagated.value,
        "ctx_attached_total": _ctx_attached.value,
        "ctx_dropped_total": _ctx_dropped.value,
    }


# ------------------------------------------------------------ histograms

# exponential bounds (ms): 100 us .. 10 s covers a sub-ms Allocate and a
# wedged multi-second apiserver round-trip in one bucket family
DEFAULT_BUCKETS_MS: Tuple[float, ...] = (
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0,
    500.0, 1000.0, 2500.0, 5000.0, 10000.0)


class Histogram:
    """Lock-free fixed-bucket histogram, sharded per thread like
    epoch.AtomicCounter: each thread owns one cell (a plain list —
    per-bucket counts plus a value sum), mutated only by its owner;
    `snapshot()` sums C-atomic slice copies of the cells. Consistency by
    construction: `_count` (and the `+Inf` bucket) are DERIVED from the
    copied bucket counts, so a scrape racing an observe can never show a
    finite-`le` bucket exceeding `+Inf` — the strict conformance test
    (tests/test_metrics_format.py) holds on a busy daemon, not just an
    idle one. Cells only accrete, so successive scrapes are monotonic.

    Short-lived threads do not leak cells: a new thread's first observe
    ADOPTS a dead owner's cell (ownership handoff under the cold-path
    maintenance lock; shard totals are sums, so reuse is lossless) —
    the cell count is bounded by the peak number of LIVE threads, not
    by thread churn (the idle-exiting checkpoint writer respawns per
    burst)."""

    __slots__ = ("name", "help", "bounds", "_cells", "_local",
                 "_exemplars")

    def __init__(self, name: str, help_text: str,
                 bounds: Tuple[float, ...] = DEFAULT_BUCKETS_MS) -> None:
        self.name = name
        self.help = help_text
        self.bounds = tuple(sorted(float(b) for b in bounds))
        # entries are [owner_thread, cell]; cell = bucket counts
        # (len(bounds)+1) + [value sum]
        self._cells: List[list] = []
        self._local = threading.local()
        # per-bucket exemplar slots: the last (trace_id, value_ms, ts)
        # observed into each bucket — immutable tuples stored with one
        # C-atomic slot write (last-writer-wins across threads is exactly
        # the semantics wanted: ANY offending trace links the bucket to
        # a real /debug/fleet/trace story)
        self._exemplars: List[Optional[tuple]] = \
            [None] * (len(self.bounds) + 1)

    def _reset(self) -> None:
        # retire the cells wholesale (reset()); threads re-register on
        # their next observe because the thread-local cell is checked
        # against membership via the home-list identity below
        self._cells = []
        self._local = threading.local()
        self._exemplars = [None] * (len(self.bounds) + 1)

    def _claim_cell(self) -> list:
        me = threading.current_thread()
        # the schedule point sits OUTSIDE the maintenance lock: the lock
        # is a real (never-virtualized) module-level primitive, so the
        # interleaving checker must not park a thread while it is held —
        # adopt-vs-adopt is serialized by the lock itself; what races is
        # the claim as a whole against observe/snapshot on other shards
        schedcheck.yield_point("trace.hist.claim", obj=self)
        with _maintenance_lock:
            for entry in self._cells:
                if not entry[0].is_alive():
                    entry[0] = me          # adopt a dead owner's shard
                    return entry[1]
            cell = [0] * (len(self.bounds) + 1) + [0.0]
            self._cells.append([me, cell])
            return cell

    def observe(self, value_ms: float,
                exemplar: Optional[str] = None) -> None:
        cell = getattr(self._local, "cell", None)
        cells = self._cells
        if cell is None or getattr(self._local, "home", None) is not cells:
            cell = self._claim_cell()
            self._local.cell = cell
            self._local.home = cells
        i = bisect_right(self.bounds, value_ms)
        schedcheck.yield_point("trace.hist.bump", obj=self)
        cell[i] += 1                    # owner thread only: exact
        cell[-1] += value_ms            # sum (float; owner-only)
        if exemplar:
            # one C-atomic slot store of an immutable tuple — a scrape
            # racing this sees either the old or the new exemplar, whole
            schedcheck.yield_point("trace.hist.exemplar", obj=self)
            self._exemplars[i] = (exemplar, value_ms, time.time())

    def exemplars(self) -> List[dict]:
        """The per-bucket exemplars, JSON-shaped (lock-free: one C-atomic
        list copy of immutable tuples): [{"le", "trace_id", "value_ms",
        "ts"}, ...] for the buckets that have one."""
        out: List[dict] = []
        for i, ex in enumerate(list(self._exemplars)):
            if ex is None:
                continue
            le = self.bounds[i] if i < len(self.bounds) else float("inf")
            out.append({"le": "+Inf" if le == float("inf")
                        else format(le, "g"),
                        "trace_id": ex[0], "value_ms": round(ex[1], 3),
                        "ts": ex[2]})
        return out

    def snapshot(self) -> dict:
        """{"buckets": [(le, cumulative_count), ...], "count": n,
        "sum": total_ms, "exemplars": [...]} — buckets cumulative,
        Prometheus-style; count derived from the same copied bucket
        values (see class doc)."""
        n_buckets = len(self.bounds) + 1
        per_bucket = [0] * n_buckets
        total = 0.0
        schedcheck.yield_point("trace.hist.snapshot", obj=self, mode="r")
        for entry in list(self._cells):
            copied = entry[1][:]        # one C-atomic slice copy
            for i in range(n_buckets):
                per_bucket[i] += copied[i]
            total += copied[-1]
        buckets: List[Tuple[float, int]] = []
        running = 0
        for bound, n in zip(self.bounds, per_bucket):
            running += n
            buckets.append((bound, running))
        return {"buckets": buckets, "count": sum(per_bucket),
                "sum": round(total, 6), "exemplars": self.exemplars()}


# The registered histogram families (ms). The HELP text doubles as the
# Prometheus exposition's # HELP line.
_histograms: Dict[str, Histogram] = {}


def _register(name: str, help_text: str) -> Histogram:
    hist = Histogram(name, help_text)
    _histograms[name] = hist
    return hist


_register("tdp_attach_wall_ms",
          "Allocate RPC wall time (server.Allocate span).")
_register("tdp_prepare_wall_ms",
          "Per-claim DRA prepare wall time (dra.prepare.claim span).")
_register("tdp_checkpoint_commit_ms",
          "Group-committed checkpoint write wall time "
          "(dra.checkpoint.commit span).")
_register("tdp_probe_cycle_ms",
          "Health hub probe-cycle wall time (health.probe_cycle span).")
_register("tdp_kubeapi_rtt_ms",
          "Kubernetes API request round-trip time (kubeapi.request span).")
_register("tdp_pacing_delay_ms",
          "Publish-pacer admission delay before a ResourceSlice publish "
          "wave (kubeapi.PublishPacer; 0-delay waves are not recorded).")
_register("tdp_broker_crossing_ms",
          "Privilege-boundary crossing wall time (broker.ipc span: one "
          "broker operation, in-process or over the broker IPC).")
_register("tdp_watch_convergence_ms",
          "Watch convergence lag: wall time from a divergence-evidencing "
          "watch observation to the repair publish landing "
          "(dra.start_watch_reconciler).")
_register("tdp_restart_ready_ms",
          "Restart-to-ready wall time: process boot (or explicit "
          "PluginManager.start) to every resource registered and every "
          "DRA slice published (boot.total span; the snapshot fast path "
          "and the counted cold walk both land here).")
_register("tdp_fleet_decision_ms",
          "Fleet scheduler decision latency: submit (or wave entry) to "
          "terminal result — plan, CAS commit, and any conflict replans "
          "included (fleetplace.schedule / schedule_wave).")


def histogram(name: str) -> Histogram:
    return _histograms[name]


def observe(name: str, value_ms: float,
            exemplar: Optional[str] = None) -> None:
    hist = _histograms.get(name)
    if hist is not None and _enabled:
        hist.observe(value_ms, exemplar=exemplar)


def _fmt_bound(bound: float) -> str:
    return format(bound, "g")


def render_prometheus() -> List[str]:
    """Prometheus text-format lines for every registered histogram plus
    the trace-plane counters (appended to status.metrics()). Lock-free —
    the /status zero-lock gate covers the scrape path."""
    lines: List[str] = []
    for name in sorted(_histograms):
        hist = _histograms[name]
        snap = hist.snapshot()
        lines.append(f"# HELP {name} {hist.help}")
        lines.append(f"# TYPE {name} histogram")
        for bound, cumulative in snap["buckets"]:
            lines.append(
                f'{name}_bucket{{le="{_fmt_bound(bound)}"}} {cumulative}')
        lines.append(f'{name}_bucket{{le="+Inf"}} {snap["count"]}')
        lines.append(f'{name}_sum {snap["sum"]}')
        lines.append(f'{name}_count {snap["count"]}')
    s = stats()
    lines += [
        "# HELP tdp_trace_spans_total Spans recorded by the flight "
        "recorder since start.",
        "# TYPE tdp_trace_spans_total counter",
        f"tdp_trace_spans_total {s['spans_recorded_total']}",
        "# HELP tdp_trace_events_total Point events recorded by the "
        "flight recorder since start.",
        "# TYPE tdp_trace_events_total counter",
        f"tdp_trace_events_total {s['events_recorded_total']}",
        "# HELP tdp_trace_slow_spans_total Spans that exceeded their "
        "per-op slow threshold ($TDP_TRACE_SLOW_MS).",
        "# TYPE tdp_trace_slow_spans_total counter",
        f"tdp_trace_slow_spans_total {s['slow_spans_total']}",
        "# HELP tdp_trace_spans_overwritten_total Ring-buffer slots "
        "overwritten before any reader drained them.",
        "# TYPE tdp_trace_spans_overwritten_total counter",
        f"tdp_trace_spans_overwritten_total {s['spans_overwritten_total']}",
        "# HELP tdp_trace_ctx_propagated_total Trace contexts handed to "
        "an outbound boundary (broker frame, apiserver header, handoff "
        "record, fabric multiclaim).",
        "# TYPE tdp_trace_ctx_propagated_total counter",
        f"tdp_trace_ctx_propagated_total {s['ctx_propagated_total']}",
        "# HELP tdp_trace_ctx_attached_total Remote trace contexts "
        "attached as span/event links (lock-free: per-thread rings stay "
        "single-writer).",
        "# TYPE tdp_trace_ctx_attached_total counter",
        f"tdp_trace_ctx_attached_total {s['ctx_attached_total']}",
        "# HELP tdp_trace_ctx_dropped_total Inbound trace contexts "
        "refused as malformed (degraded to no-context, never raised).",
        "# TYPE tdp_trace_ctx_dropped_total counter",
        f"tdp_trace_ctx_dropped_total {s['ctx_dropped_total']}",
    ]
    return lines


# --------------------------------------------------------- crash artifact

# Post-mortem sections contributed by other planes (the SLO engine
# registers "slo"): dump() merges each callable's result into the
# payload, so a crash/SIGHUP artifact carries the latency + burn-rate
# context alongside the span ring. A raising extra degrades to an error
# note — dumping must never add a second crash to the one reported.
_dump_extras: Dict[str, Callable[[], object]] = {}


def register_dump_extra(name: str, fn: Callable[[], object]) -> None:
    _dump_extras[name] = fn


def unregister_dump_extra(name: str) -> None:
    _dump_extras.pop(name, None)


def dump(reason: str, path: Optional[str] = None) -> Optional[str]:
    """Write the merged ring + slow log + stats + histogram snapshots
    (and any registered extras, e.g. SLO/burn-rate state) to a JSON
    file; returns the path (None when the write failed — dumping must
    never add a second crash to the one being reported). Default path:
    $TDP_TRACE_DUMP_PATH, else tdp-flight-<pid>.json under $TMPDIR."""
    path = path or os.environ.get("TDP_TRACE_DUMP_PATH") or os.path.join(
        os.environ.get("TMPDIR", "/tmp"), f"tdp-flight-{os.getpid()}.json")
    payload = {
        "reason": reason,
        "pid": os.getpid(),
        "dumped_at": time.time(),
        "stats": stats(),
        "slow": slow_spans(),
        "spans": snapshot(),
        "histograms": {name: _histograms[name].snapshot()
                       for name in sorted(_histograms)},
    }
    for name, fn in list(_dump_extras.items()):
        try:
            payload[name] = fn()
        except Exception as exc:       # a post-mortem extra must not
            payload[name] = {"error": str(exc)}   # kill the dump
    try:
        with open(path, "w", encoding="utf-8") as f:
            json.dump(payload, f, indent=1, sort_keys=True)
    except OSError as exc:
        log.error("flight-recorder dump to %s failed: %s", path, exc)
        return None
    log.warning("flight recorder dumped to %s (%s; %d spans)", path,
                reason, len(payload["spans"]))
    return path


_prev_excepthook = None
_prev_threading_excepthook = None


def install_crash_hook() -> None:
    """Dump the flight recorder on any unhandled exception (main thread
    via sys.excepthook, daemon threads via threading.excepthook), then
    chain to the previous hook. Idempotent."""
    global _prev_excepthook, _prev_threading_excepthook
    if _prev_excepthook is not None:
        return

    def _sys_hook(exc_type, exc, tb):
        dump(f"unhandled-exception:{exc_type.__name__}")
        (_prev_excepthook or sys.__excepthook__)(exc_type, exc, tb)

    def _thread_hook(args):
        dump(f"unhandled-thread-exception:{args.exc_type.__name__}")
        (_prev_threading_excepthook or threading.__excepthook__)(args)

    _prev_excepthook = sys.excepthook
    _prev_threading_excepthook = threading.excepthook
    sys.excepthook = _sys_hook
    threading.excepthook = _thread_hook


def uninstall_crash_hook() -> None:
    """Restore the pre-install hooks (test teardown)."""
    global _prev_excepthook, _prev_threading_excepthook
    if _prev_excepthook is None:
        return
    sys.excepthook = _prev_excepthook
    threading.excepthook = _prev_threading_excepthook
    _prev_excepthook = None
    _prev_threading_excepthook = None
