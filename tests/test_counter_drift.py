"""Counter-drift audit (ISSUE 8 satellite).

Counter names have drifted across PRs 2-7: counters documented in
docs/perf.md / docs/fault-injection.md, or registered for lock-ownership
in tsalint's COUNTERS config, did not always surface on /status and
/metrics under the documented names. This test pins them:

1. every Prometheus series name mentioned in the docs appears in a
   fully-populated /metrics scrape;
2. every counter registered in tools/tsalint/config.py COUNTERS maps to
   a /status JSON path (asserted to resolve) and a /metrics family
   (asserted to exist) via the explicit table below — adding a counter
   to COUNTERS without extending the table (i.e. without surfacing it)
   fails this test.
"""

import os
import re

from tests.test_metrics_format import full_scrape, parse_scrape  # noqa: F401
from tools.tsalint.config import COUNTERS

_DOCS = ("docs/perf.md", "docs/fault-injection.md", "docs/observability.md")
# backticked tokens that look like Prometheus series names
_METRIC_TOKEN = re.compile(
    r"`((?:tpu_plugin_|tdp_|lifecycle_transitions_total|"
    r"claims_orphaned_total|handoffs_completed_total)[a-z0-9_]*)"
    r"(?:\{[^}]*\})?`")

# COUNTERS ("module.Class" -> {attr: lock}) -> where each counter
# surfaces. status: dotted path into the /status JSON ("plugins[*]." =
# per-plugin snapshot, "dra.", "health.", ...). metrics: the family name
# in the scrape. The two-sided pin the satellite asks for.
SURFACES = {
    ("server.TpuDevicePlugin", "_restart_count"): {
        "status": "plugins[*].restarts",
        "metrics": "tpu_plugin_restarts_total"},
    # response byte plane (round 15): lock-free-owned (tsalint LOCKFREE
    # sentinel) but surfaced like every other counter — the drift test
    # fails if either surface is missed
    ("server.TpuDevicePlugin", "_alloc_bytes_reused"): {
        "status": "plugins[*].response_bytes.reused",
        "metrics": "tpu_plugin_alloc_bytes_reused_total"},
    ("server.TpuDevicePlugin", "_alloc_serializations"): {
        "status": "plugins[*].response_bytes.serializations",
        "metrics": "tpu_plugin_alloc_serializations_total"},
    ("server.TpuDevicePlugin", "_self_dial_reuses"): {
        "status": "plugins[*].self_dial_reuses",
        "metrics": "tpu_plugin_self_dial_reuses_total"},
    ("dra.DraDriver", "_ack_bytes_reused"): {
        "status": "dra.ack_bytes.reused",
        "metrics": "tpu_plugin_dra_ack_bytes_reused_total"},
    ("dra.DraDriver", "_ack_serializations"): {
        "status": "dra.ack_bytes.serializations",
        "metrics": "tpu_plugin_dra_ack_serializations_total"},
    ("healthhub.HealthHub", "_probe_cycles"): {
        "status": "health.probe_cycles_total",
        "metrics": "tpu_plugin_health_probe_cycles_total"},
    ("healthhub.HealthHub", "_probes_last_cycle"): {
        "status": "health.probes_last_cycle",
        "metrics": "tpu_plugin_health_probes_last_cycle"},
    ("healthhub.HealthHub", "_probes_deduped_last_cycle"): {
        "status": "health.probes_deduped_last_cycle",
        "metrics": "tpu_plugin_health_probes_deduped_last_cycle"},
    ("healthhub.HealthHub", "_probe_timeouts"): {
        "status": "health.probe_timeouts_total",
        "metrics": "tpu_plugin_health_probe_timeouts_total"},
    ("healthhub.HealthHub", "_probe_errors"): {
        "status": "health.probe_errors_total",
        "metrics": "tdp_probe_errors_total"},
    ("healthhub.HealthHub", "_existence_scans"): {
        "status": "health.existence_scans_total",
        "metrics": "tpu_plugin_health_existence_scans_total"},
    ("dra.DraDriver", "publish_stats[*]"): {
        "status": "dra.publish_stats.delta",
        "metrics": "tpu_plugin_dra_slice_publishes_total"},
    ("dra.DraDriver", "checkpoint_stats_counters[*]"): {
        "status": "dra.checkpoint_commits_total",
        "metrics": "tpu_plugin_dra_checkpoint_commits_total"},
    ("dra.DraDriver", "_prepare_inflight"): {
        "status": "dra.prepare_inflight",
        "metrics": "tpu_plugin_dra_prepare_inflight"},
    ("dra.DraDriver", "_attach_active"): {
        "status": "dra.attach_active",
        "metrics": "tpu_plugin_dra_attach_active"},
    ("dra.DraDriver", "handoff_stats[*]"): {
        "status": "dra.handoffs_emitted_total",
        "metrics": "tpu_plugin_dra_handoffs_emitted_total"},
    # slice placement (ISSUE 10): the recompute counter anchors the dict
    # group; the defrag twins surface under the same dra.placement.*
    # status object and their own metric families (pinned by the docs
    # half of this audit via perf.md)
    ("dra.DraDriver", "placement_stats[*]"): {
        "status": "dra.placement.frag_recomputes_total",
        "metrics": "tpu_plugin_dra_frag_recomputes_total"},
    ("dra.DraDriver", "_checkpoint_bytes"): {
        "status": "dra.checkpoint_bytes",
        "metrics": "tpu_plugin_dra_checkpoint_bytes"},
    # publish pacing (kubeapi.PublishPacer, ISSUE 9): the wave counter
    # anchors the dict group; coalesce/throttle twins surface under the
    # same dra.pacing.* status object and their own metric families
    # (asserted present by the docs half of this audit via perf.md)
    ("kubeapi.PublishPacer", "stats[*]"): {
        "status": "dra.pacing.publish_waves_total",
        "metrics": "tpu_plugin_dra_publish_waves_total"},
    # watch-stream convergence plane (ISSUE 12): the event counter
    # anchors the reflector's dict group; streams/relists/resyncs/
    # degraded twins surface under the same dra.watch.* status object
    # and their own metric families (asserted present by the docs half
    # of this audit via perf.md/observability.md)
    ("kubeapi.Reflector", "stats[*]"): {
        "status": "dra.watch.watch_events_total",
        "metrics": "tpu_plugin_dra_watch_events_total"},
    ("lifecycle_fsm.DeviceLifecycle", "transition_counts[*]"): {
        "status": "lifecycle.transitions",
        "metrics": "lifecycle_transitions_total"},
    ("lifecycle_fsm.DeviceLifecycle", "claims_orphaned_total"): {
        "status": "lifecycle.claims_orphaned_total",
        "metrics": "claims_orphaned_total"},
    ("lifecycle_fsm.DeviceLifecycle", "identity_swaps_total"): {
        "status": "lifecycle.identity_swaps_total",
        "metrics": "tpu_plugin_lifecycle_identity_swaps_total"},
    ("lifecycle_fsm.DeviceLifecycle", "invalid_transitions_total"): {
        "status": "lifecycle.invalid_transitions_total",
        "metrics": "tpu_plugin_lifecycle_invalid_transitions_total"},
    ("resilience.BackoffPolicy", "attempts"): {
        # current streak, reset on success — transient state surfaced
        # per-owner on /status; the cumulative twin below is the counter
        "status": "plugins[*].restart_backoff.attempts",
        "metrics": None},
    ("resilience.BackoffPolicy", "total_attempts"): {
        "status": "plugins[*].restart_backoff.total_attempts",
        "metrics": "tpu_plugin_restart_retries_total"},
    ("resilience.CircuitBreaker", "trips"): {
        "status": "dra.api_breaker.trips",
        "metrics": "tpu_plugin_kubeapi_breaker_trips_total"},
    ("resilience.CircuitBreaker", "rejected"): {
        "status": "dra.api_breaker.rejected",
        "metrics": "tpu_plugin_kubeapi_breaker_rejected_total"},
    ("resilience.CircuitBreaker", "half_open_rejected"): {
        "status": "dra.api_breaker.half_open_rejected",
        "metrics":
            "tpu_plugin_kubeapi_breaker_half_open_rejected_total"},
    ("resilience.CircuitBreaker", "_consecutive_failures"): {
        # transient breaker state (resets on success): /status only
        "status": "dra.api_breaker.consecutive_failures",
        "metrics": None},
    ("discovery.HostSnapshot", "stats[*]"): {
        "status": "discovery.full_scans",
        "metrics": "tpu_plugin_discovery_scans_total"},
    ("faults", "_fired[*]"): {
        "status": "faults.fired",
        "metrics": "tdp_fault_fires_total"},
    # trace propagation (ISSUE 15): lock-free AtomicCounters (tsalint
    # LOCKFREE sentinel), surfaced like every other counter
    ("trace", "_ctx_propagated"): {
        "status": "trace.ctx_propagated_total",
        "metrics": "tdp_trace_ctx_propagated_total"},
    ("trace", "_ctx_attached"): {
        "status": "trace.ctx_attached_total",
        "metrics": "tdp_trace_ctx_attached_total"},
    ("trace", "_ctx_dropped"): {
        "status": "trace.ctx_dropped_total",
        "metrics": "tdp_trace_ctx_dropped_total"},
    # SLO engine (ISSUE 15): the eval counter anchors the dict group;
    # the breach twin surfaces under the same slo.* status object and
    # its own family (pinned by the docs half via observability.md)
    ("slo.SLOEngine", "counters[*]"): {
        "status": "slo.evals_total",
        "metrics": "tpu_plugin_slo_evals_total"},
    # remediation engine (ISSUE 16): the action counter anchors the
    # dict group; the rollback/veto/shed twins surface under the same
    # remediation.* status object and their own families
    ("remediation.RemediationEngine", "counters[*]"): {
        "status": "remediation.actions_total",
        "metrics": "tpu_plugin_remediation_actions_total"},
    # sharded fleet scheduler (ISSUE 17): the wave counter anchors the
    # scheduler's dict group; conflict/replan twins surface under the
    # same fleet.* status object and their own families. The
    # accountant's counters flatten into the SAME fleet.* snapshot —
    # its delta-apply counter anchors that group, with the
    # recompute/relist-skip twins pinned by the docs half via perf.md
    ("fleetplace.FleetScheduler", "stats[*]"): {
        "status": "fleet.decision_waves_total",
        "metrics": "tpu_plugin_fleet_decision_waves_total"},
    ("fleetplace.FragAccountant", "stats[*]"): {
        "status": "fleet.frag_delta_applies_total",
        "metrics": "tpu_plugin_fleet_frag_delta_applies_total"},
    # broker crossing fast path (ISSUE 18): lock-free AtomicCounters on
    # the client base class (tsalint LOCKFREE sentinel), surfaced via
    # client_stats() -> /status broker.* and their tdp_broker_* families
    ("broker._BaseClient", "batched_ops"): {
        "status": "broker.batched_ops_total",
        "metrics": "tdp_broker_batched_ops_total"},
    ("broker._BaseClient", "ring_hits"): {
        "status": "broker.ring_hits_total",
        "metrics": "tdp_broker_ring_hits_total"},
    ("broker._BaseClient", "ring_fallbacks"): {
        "status": "broker.ring_fallbacks_total",
        "metrics": "tdp_broker_ring_fallbacks_total"},
}


def _resolve(status: dict, path: str):
    node = status
    for part in path.split("."):
        if part == "plugins[*]":
            assert status["plugins"], "rig has no plugins"
            node = node["plugins"][0]
            continue
        assert isinstance(node, dict) and part in node, \
            f"/status path {path!r} broke at {part!r} (have: " \
            f"{sorted(node) if isinstance(node, dict) else type(node)})"
        node = node[part]
    return node


def _doc_metric_names():
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    names = set()
    for rel in _DOCS:
        with open(os.path.join(root, rel)) as f:
            text = f.read()
        for m in _METRIC_TOKEN.finditer(text):
            name = m.group(1)
            if "*" in name or name.endswith("_"):
                continue   # wildcard shorthand like tpu_plugin_dra_checkpoint_*
            names.add(name)
    return names


def test_documented_metric_names_appear_on_metrics(full_scrape):  # noqa: F811
    text, _server = full_scrape
    types, _helps, _samples = parse_scrape(text)
    documented = _doc_metric_names()
    assert len(documented) > 15, documented   # the extraction works
    missing = {n for n in documented if n not in types}
    assert not missing, \
        f"counters documented in {_DOCS} missing from /metrics: " \
        f"{sorted(missing)}"


def test_tsalint_registered_counters_surface_on_status_and_metrics(
        full_scrape):  # noqa: F811
    text, server = full_scrape
    types, _helps, _samples = parse_scrape(text)
    status = server.status()

    registered = {(scope, attr)
                  for scope, table in COUNTERS.items() for attr in table}
    unmapped = registered - set(SURFACES)
    assert not unmapped, \
        f"counters registered in tsalint COUNTERS but not pinned to a " \
        f"/status + /metrics surface here: {sorted(unmapped)} — extend " \
        f"SURFACES (and the endpoints) when adding counters"
    stale = set(SURFACES) - registered
    assert not stale, f"SURFACES entries no longer in COUNTERS: {stale}"

    for key, surface in sorted(SURFACES.items()):
        _resolve(status, surface["status"])
        if surface["metrics"] is not None:
            assert surface["metrics"] in types, \
                f"{key}: family {surface['metrics']} missing from /metrics"
