"""Slice → jax.sharding.Mesh mapping.

Axes follow the scaling-book decomposition: `dp` (pure data parallel,
gradient all-reduce), `tp` (tensor parallel, activation collectives on the
fastest links), `sp` (sequence parallel for long context), plus two optional
axes: `pp` (pipeline stages — layer-stacked weights sharded over it) and
`ep` (expert parallel — MoE expert weights and dispatched tokens sharded
over it). On a passed-through slice all of them ride ICI; the mesh
construction puts `tp` innermost so its collectives land on
nearest-neighbor links, and `pp` outermost (stage boundaries cross the
least-frequent traffic). `pp`/`ep` axes only appear in the mesh when their
size exceeds 1, so the common 3-axis shape is unchanged.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh


def infer_mesh_shape(n_devices: int,
                     tp: Optional[int] = None,
                     sp: Optional[int] = None) -> Tuple[int, int, int]:
    """Factor `n_devices` into (dp, sp, tp).

    Defaults: tp takes the largest power-of-two ≤ min(n, 4) (one host's worth
    of nearest-neighbor links), sp stays 1 unless asked, dp absorbs the rest.
    """
    if tp is None:
        tp = 1
        while tp * 2 <= min(n_devices, 4) and n_devices % (tp * 2) == 0:
            tp *= 2
    if sp is None:
        sp = 1
    if n_devices % (tp * sp) != 0:
        raise ValueError(f"{n_devices} devices not divisible by tp={tp} * sp={sp}")
    dp = n_devices // (tp * sp)
    return dp, sp, tp


def slice_mesh(devices: Optional[Sequence[jax.Device]] = None,
               tp: Optional[int] = None,
               sp: Optional[int] = None,
               pp: Optional[int] = None,
               ep: Optional[int] = None) -> Mesh:
    """Build a mesh over the visible slice.

    Axis order (outermost→innermost): pp, dp, sp, ep, tp — pp/ep included
    only when > 1, so the default is the 3-axis ("dp", "sp", "tp") mesh.
    """
    if devices is None:
        devices = jax.devices()
    pp = pp or 1
    ep = ep or 1
    n = len(devices)
    if n % (pp * ep) != 0:
        raise ValueError(f"{n} devices not divisible by pp={pp} * ep={ep}")
    dp, sp_, tp_ = infer_mesh_shape(n // (pp * ep), tp=tp, sp=sp)
    dims = [("pp", pp), ("dp", dp), ("sp", sp_), ("ep", ep), ("tp", tp_)]
    dims = [(name, size) for name, size in dims
            if size > 1 or name in ("dp", "sp", "tp")]
    grid = np.array(devices).reshape([size for _, size in dims])
    return Mesh(grid, axis_names=tuple(name for name, _ in dims))
