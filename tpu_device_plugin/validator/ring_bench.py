"""Ring-flash vs einsum-ring benchmark (VERDICT r3 item 5).

Round 3 shipped `ring_flash_attention` with compile/parity evidence only —
no measurement showed the Pallas-kernel-per-ring-step actually beats the
einsum ring on hardware, and the ring path's forward blocks were chosen by
inheritance, not sweep. This mode times both ring implementations under
`jax.shard_map` on a real `sp` mesh axis (sp=1 on a single chip: the ring
degenerates to one local step, which is exactly what one chip can measure
— the per-step kernel + merge overhead; multi-chip sp adds ppermute hops
identical between the two, so the single-chip delta is the kernel story).

    python -m tpu_device_plugin.validator --mode ring-bench \
        --seqs 4096,8192 --blocks 128x128,256x256 --repeats 4

Timing methodology is shared with attn_bench (validator/timing.py chained
differencing), so the two sweeps cannot drift.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

from .timing import paired_time as _paired_time


def _chain_fwd(fn_one, repeats: int):
    """Serially-dependent forward chain reduced to a scalar (attn_bench's
    rule: the output feeds the next call's q, so nothing can be DCE'd or
    overlapped)."""
    import jax
    import jax.numpy as jnp

    def run(q, k, v):
        out = jax.lax.fori_loop(
            0, max(repeats, 1), lambda i, qq: fn_one(qq, k, v), q)
        return jnp.sum(out.astype(jnp.float32))
    return jax.jit(run)


def _chain_train(grad_fn, repeats: int):
    """All three grads carried (dq->q, dk/dv perturb k/v) so the dkv work
    cannot be dead-code-eliminated."""
    import jax
    import jax.numpy as jnp

    def run(q, k, v):
        def body(i, qkv):
            qq, kk, vv = qkv
            dq, dk, dv = grad_fn(qq, kk, vv)
            return (dq,
                    kk + (0.001 * dk).astype(kk.dtype),
                    vv + (0.001 * dv).astype(vv.dtype))
        out = jax.lax.fori_loop(0, max(repeats, 1), body, (q, k, v))
        return sum(jnp.sum(x.astype(jnp.float32)) for x in out)
    return jax.jit(run)


def bench_ring(
    seq_lens: Sequence[int] = (4096, 8192),
    blocks: Sequence[Tuple[int, int]] = ((128, 128),),
    sp: Optional[int] = None,
    hb: int = 8,
    head_dim: int = 128,
    iters: int = 5,
    repeats: int = 1,
    devices=None,
    interpret: Optional[bool] = None,
    min_diff_s: float = 0.0,
) -> dict:
    """Time ring_flash_attention vs ring_attention under shard_map.

    seq_lens are GLOBAL sequence lengths; each shard holds seq/sp. Returns
    {"cells": [...], "ring_flash_wins_at": [...]}; speedup > 1 means the
    flash-per-step ring is faster than the einsum ring.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from .ring_attention import ring_attention, ring_flash_attention

    if devices is None:
        devices = jax.local_devices()
    if sp is None:
        sp = len(devices)
    devices = devices[:sp]
    if interpret is None:
        interpret = devices[0].platform != "tpu"
    mesh = Mesh(
        __import__("numpy").array(devices).reshape(sp), axis_names=("sp",))
    sm = head_dim ** -0.5
    spec = P(None, "sp", None)
    sharding = NamedSharding(mesh, spec)

    def shard_fn(inner):
        return jax.shard_map(inner, mesh=mesh, in_specs=(spec,) * 3,
                             out_specs=spec, check_vma=False)

    cells = []
    for seq in seq_lens:
        if seq % sp:
            raise ValueError(f"seq {seq} not divisible by sp={sp}")
        qkv = []
        for i in (1, 2, 3):
            x = jax.random.normal(jax.random.key(i), (hb, seq, head_dim),
                                  jnp.float32).astype(jnp.bfloat16)
            qkv.append(jax.device_put(x, sharding))
        q, k, v = qkv
        reps = (max(2, min(2048, int(repeats * (8192 / seq) ** 2)))
                if repeats > 1 else repeats)

        def measure(fn_one, label):
            grad = jax.grad(
                lambda q, k, v: jnp.sum(
                    fn_one(q, k, v).astype(jnp.float32) ** 2),
                argnums=(0, 1, 2))
            try:
                fwd_s = _paired_time(
                    lambda r: _chain_fwd(fn_one, r), (q, k, v), iters, reps,
                    min_diff_s=min_diff_s)
                train_s = _paired_time(
                    lambda r: _chain_train(grad, r), (q, k, v), iters, reps,
                    min_diff_s=min_diff_s)
                return fwd_s, train_s, ""
            except Exception as exc:   # einsum ring OOMs first at long seq
                return None, None, f"{label}: {type(exc).__name__}: {exc}"

        ein_one = shard_fn(lambda q, k, v: ring_attention(
            q, k, v, sm, "sp").astype(q.dtype))
        ein_fwd, ein_train, ein_err = measure(ein_one, "einsum-ring")
        for bq, bk in blocks:
            fl_one = shard_fn(
                lambda q, k, v, bq=bq, bk=bk: ring_flash_attention(
                    q, k, v, sm, "sp", bq, bk, interpret).astype(q.dtype))
            fl_fwd, fl_train, fl_err = measure(fl_one, "ring-flash")

            def ms(s):
                return None if s is None else s * 1e3

            cells.append({
                "seq": seq, "sp": sp, "block_q": bq, "block_k": bk,
                "reps": reps,
                "ring_flash_fwd_ms": ms(fl_fwd),
                "einsum_ring_fwd_ms": ms(ein_fwd),
                "ring_flash_train_ms": ms(fl_train),
                "einsum_ring_train_ms": ms(ein_train),
                "fwd_speedup": (ein_fwd / fl_fwd
                                if ein_fwd is not None and fl_fwd else None),
                "train_speedup": (ein_train / fl_train
                                  if ein_train is not None and fl_train
                                  else None),
                "error": "; ".join(x for x in (ein_err, fl_err) if x),
            })
    wins = sorted({c["seq"] for c in cells
                   if (c["train_speedup"] or 0) > 1.0})
    return {
        "device_kind": devices[0].device_kind,
        "platform": devices[0].platform,
        "interpret": interpret,
        "sp": sp, "hb": hb, "head_dim": head_dim,
        "cells": cells,
        "ring_flash_wins_at": wins,
        "ring_flash_ok": bool(cells) and all(
            c["ring_flash_fwd_ms"] is not None for c in cells),
    }
