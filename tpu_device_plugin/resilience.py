"""Shared failure-handling policy: jittered backoff + circuit breaker.

Every recovery path in this daemon used to improvise its own retry timing:
`server.py:restart` doubled a fixed backoff (so N plugins restarting after
one kubelet bounce re-dialed in lockstep — a thundering herd against a
kubelet that just came back), `lifecycle.py` re-armed a flat 30 s
inventory-publish retry, and `dra.py` re-armed a flat 30 s republish timer.
This module is the one place those decisions live now:

- `BackoffPolicy` implements decorrelated jitter (each delay is drawn
  uniformly from [base, 3×previous], capped), which both spreads
  simultaneous retriers apart and grows the interval under sustained
  failure. The RNG is injectable so chaos tests (tests/test_chaos.py) are
  seeded and reproducible.

- `CircuitBreaker` trips OPEN after N consecutive failures, fails fast
  while open, and HALF-OPENs a single probe after a cooldown — success
  closes it, failure re-opens. It protects the API server (and our own
  latency) from retry storms the backoff alone cannot prevent when many
  call sites share one dependency.

Both keep counters (attempts, trips, state) that `status.py` surfaces so
operators can see recovery activity per resource instead of inferring it
from log volume.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Any, Callable, Dict, Optional, TypeVar, Union

from . import lockdep

__all__ = ["BackoffPolicy", "CircuitBreaker", "CircuitOpen"]

_T = TypeVar("_T")


class BackoffPolicy:
    """Decorrelated-jitter backoff: delay_n = min(cap, U(base, 3*delay_{n-1})).

    Thread-safe. `reset()` returns to the base interval (call it after a
    success); `attempts` counts delays issued since the last reset,
    `total_attempts` over the object's lifetime (the status counter).
    """

    def __init__(self, base_s: float = 1.0, cap_s: float = 30.0,
                 rng: Optional[random.Random] = None) -> None:
        if base_s <= 0 or cap_s < base_s:
            raise ValueError(f"need 0 < base_s <= cap_s "
                             f"(got base={base_s}, cap={cap_s})")
        self.base_s = base_s
        self.cap_s = cap_s
        self._rng = rng or random.Random()
        self._lock = lockdep.instrument(
            "resilience.BackoffPolicy._lock", threading.Lock())
        self._prev = base_s
        self.attempts = 0
        self.total_attempts = 0

    def next_delay(self) -> float:
        with self._lock:
            delay = min(self.cap_s, self._rng.uniform(self.base_s,
                                                      self._prev * 3.0))
            self._prev = delay
            self.attempts += 1
            self.total_attempts += 1
            return delay

    def reset(self) -> None:
        with self._lock:
            self._prev = self.base_s
            self.attempts = 0

    def snapshot(self) -> Dict[str, float]:
        # lock-free read side (the /status lockdep gate): plain int/float
        # attribute reads are GIL-atomic; mutations stay under _lock
        # (tsalint counter ownership), so a racing next_delay() costs at
        # most a one-mutation-stale value, never a torn one
        return {"attempts": self.attempts,
                "total_attempts": self.total_attempts,
                "current_delay_s": round(self._prev, 3)}


class CircuitOpen(Exception):
    """Raised by CircuitBreaker.call() when the circuit is open."""


class CircuitBreaker:
    """Consecutive-failure circuit breaker with a half-open probe.

    States: "closed" (all calls pass) → after `failure_threshold`
    consecutive `record_failure()`s → "open" (allow() is False) → after
    `reset_timeout_s` → "half-open": exactly ONE caller gets allow()=True
    as the probe; its `record_success()` closes the circuit, its
    `record_failure()` re-opens it (and restarts the cooldown). The clock
    is injectable so the state machine is unit-testable without sleeping.

    The probe is OWNED by the thread allow() handed it to: while the
    circuit is not closed, record_success()/record_failure() from any
    other thread is a STALE result — a call admitted before the trip
    finishing late — and must not resolve the probe window (a stale
    success used to close the circuit under the probe's feet, re-opening
    the floodgates on an unverified dependency). Losers racing the
    half-open window fail fast as open and are counted
    (`half_open_rejected`).
    """

    CLOSED = "closed"
    OPEN = "open"
    HALF_OPEN = "half-open"

    def __init__(self, failure_threshold: int = 5,
                 reset_timeout_s: float = 30.0,
                 clock: Callable[[], float] = time.monotonic,
                 name: str = "") -> None:
        if failure_threshold < 1:
            raise ValueError("failure_threshold must be >= 1")
        self.name = name
        self.failure_threshold = failure_threshold
        self.reset_timeout_s = reset_timeout_s
        self._clock = clock
        self._lock = lockdep.instrument(
            "resilience.CircuitBreaker._lock", threading.Lock())
        self._state = self.CLOSED
        self._consecutive_failures = 0
        self._opened_at = 0.0
        self._probe_owner: Optional[int] = None   # half-open probe's thread
        self.trips = 0            # lifetime CLOSED/HALF_OPEN -> OPEN count
        self.rejected = 0         # calls refused while open
        self.half_open_rejected = 0   # of those, losers racing the probe

    @property
    def state(self) -> str:
        # OPEN past its cooldown still reads as open; only allow() performs
        # the OPEN -> HALF_OPEN transition, when it hands out the probe.
        with self._lock:
            return self._state

    def allow(self) -> bool:
        """True if a call may proceed. Hands out at most one half-open probe
        per cooldown window; record_success/record_failure MUST follow every
        allowed call or the breaker's failure count goes stale."""
        with self._lock:
            if self._state == self.CLOSED:
                return True
            if self._state == self.OPEN:
                if self._clock() - self._opened_at >= self.reset_timeout_s:
                    self._state = self.HALF_OPEN
                    self._probe_owner = threading.get_ident()
                    return True
                self.rejected += 1
                return False
            # HALF_OPEN: a probe is already in flight — losers racing the
            # probe window fail fast as open, counted
            self.rejected += 1
            self.half_open_rejected += 1
            return False

    def record_success(self) -> None:
        with self._lock:
            if self._state != self.CLOSED \
                    and self._probe_owner != threading.get_ident():
                # stale success (admitted pre-trip, finished late): it
                # proves nothing about the dependency NOW — only the
                # probe's own result may resolve the window
                return
            self._consecutive_failures = 0
            self._state = self.CLOSED
            self._probe_owner = None

    def record_failure(self) -> None:
        with self._lock:
            self._consecutive_failures += 1
            if self._state == self.HALF_OPEN:
                if self._probe_owner != threading.get_ident():
                    return   # stale failure: the probe alone re-opens
                # failed probe: straight back to open, cooldown restarts
                self._state = self.OPEN
                self._opened_at = self._clock()
                self._probe_owner = None
                self.trips += 1
            elif (self._state == self.CLOSED
                    and self._consecutive_failures >= self.failure_threshold):
                self._state = self.OPEN
                self._opened_at = self._clock()
                self.trips += 1

    def call(self, fn: Callable[..., _T], *args: Any, **kwargs: Any) -> _T:
        """Run fn through the breaker; raises CircuitOpen when rejected."""
        if not self.allow():
            raise CircuitOpen(
                f"circuit {self.name or 'breaker'} open "
                f"({self._consecutive_failures} consecutive failures)")
        try:
            result = fn(*args, **kwargs)
        except Exception:
            self.record_failure()
            raise
        self.record_success()
        return result

    def snapshot(self) -> Dict[str, Union[str, int]]:
        # lock-free read side, same contract as BackoffPolicy.snapshot:
        # each field is one GIL-atomic read; the dict is a diagnostic
        # snapshot, not a transactional view
        return {"state": self._state,
                "consecutive_failures": self._consecutive_failures,
                "trips": self.trips,
                "rejected": self.rejected,
                "half_open_rejected": self.half_open_rejected}
