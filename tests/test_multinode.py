"""Multi-node slice (BASELINE config 5): two nodes, one v5p-16 slice.

No new mechanism is needed (SURVEY.md §5 "long-context" note): each node's
DaemonSet pod independently advertises its local chips; the Topology Manager
and KubeVirt compose the multi-VMI slice. This test runs two full plugin
stacks against two fake kubelets — one per "node" — and checks that each
advertises its own chips with per-node ICI coordinates, and that allocations
on both nodes succeed independently.
"""

import os
import threading

import grpc
import pytest

from tests.fakehost import FakeChip, FakeHost, FakeKubelet
from tpu_device_plugin import kubeletapi as api
from tpu_device_plugin.config import Config
from tpu_device_plugin.kubeletapi import pb
from tpu_device_plugin.lifecycle import PluginManager


class Node:
    """One simulated TPU host: fake sysfs + fake kubelet + plugin manager."""

    def __init__(self, root: str, n_chips: int = 4, device_id: str = "0064"):
        self.host = FakeHost(root)
        for i in range(n_chips):
            self.host.add_chip(FakeChip(
                f"0000:00:{4 + i:02x}.0", device_id=device_id,
                iommu_group=str(11 + i), numa_node=i // 2))
        self.cfg = Config().with_root(root)
        os.makedirs(self.cfg.device_plugin_path, exist_ok=True)
        self.kubelet = FakeKubelet(self.cfg.kubelet_socket)
        self.manager = PluginManager(self.cfg)

    @property
    def registrations(self):
        return self.kubelet.registrations

    def start(self):
        self.manager.start()

    def wait_registered(self, timeout=10):
        return self.kubelet.wait_for(1, timeout)

    def plugin_stub(self, suffix="v5p"):
        sock = os.path.join(self.cfg.device_plugin_path,
                            f"tpukubevirt-{suffix}.sock")
        channel = grpc.insecure_channel(f"unix://{sock}")
        return channel, api.DevicePluginStub(channel)

    def stop(self):
        self.manager.stop()
        self.kubelet.stop()


@pytest.fixture
def two_nodes(short_root):
    nodes = [Node(os.path.join(short_root, f"n{i}")) for i in range(2)]
    for n in nodes:
        n.start()
    yield nodes
    for n in nodes:
        n.stop()


def test_each_node_advertises_local_chips(two_nodes):
    for node in two_nodes:
        assert node.wait_registered()
        assert node.registrations[0].resource_name == "cloud-tpus.google.com/v5p"
        ch, stub = node.plugin_stub()
        with ch:
            resp = next(iter(stub.ListAndWatch(pb.Empty())))
            assert len(resp.devices) == 4
            assert all(d.health == "Healthy" for d in resp.devices)


def test_parallel_allocation_across_nodes(two_nodes):
    """A 2-VMI slice: each VMI lands on one node; both Allocates succeed and
    each returns only its own node's devfs paths."""
    envs = []
    for node in two_nodes:
        assert node.wait_registered()
        ch, stub = node.plugin_stub()
        with ch:
            pref = stub.GetPreferredAllocation(
                pb.PreferredAllocationRequest(container_requests=[
                    pb.ContainerPreferredAllocationRequest(
                        available_deviceIDs=[f"0000:00:{4 + i:02x}.0"
                                             for i in range(4)],
                        allocation_size=4)]),
                timeout=5)
            picked = list(pref.container_responses[0].deviceIDs)
            assert len(picked) == 4
            resp = stub.Allocate(
                pb.AllocateRequest(container_requests=[
                    pb.ContainerAllocateRequest(devices_ids=picked)]),
                timeout=5)
            cresp = resp.container_responses[0]
            for spec in cresp.devices:
                assert spec.host_path.startswith(node.cfg.root_path)
            envs.append(dict(cresp.envs))
    assert envs[0] == envs[1]  # same shape per node; paths differ per root


def test_node_failure_isolated(two_nodes):
    """Killing chips on node 0 must not disturb node 1's advertisement."""
    n0, n1 = two_nodes
    assert n0.wait_registered() and n1.wait_registered()
    updates0, updates1 = [], []

    def consume(node, sink):
        ch, stub = node.plugin_stub()
        with ch:
            try:
                for resp in stub.ListAndWatch(pb.Empty()):
                    sink.append({d.ID: d.health for d in resp.devices})
            except grpc.RpcError:
                pass

    threading.Thread(target=consume, args=(n0, updates0), daemon=True).start()
    threading.Thread(target=consume, args=(n1, updates1), daemon=True).start()
    import time
    deadline = time.monotonic() + 5
    while (not updates0 or not updates1) and time.monotonic() < deadline:
        time.sleep(0.05)
    n0.host.remove_vfio_group("11")
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline:
        if updates0 and updates0[-1].get("0000:00:04.0") == "Unhealthy":
            break
        time.sleep(0.05)
    assert updates0[-1]["0000:00:04.0"] == "Unhealthy"
    # node 1 was actually observed, and saw no unhealthy transition at all
    assert updates1, "node 1 stream produced no updates"
    assert all(set(u.values()) == {"Healthy"} for u in updates1)


def test_each_node_publishes_distinct_facts(short_root):
    """Config-5 flow: each node's labeler facts reflect ITS local inventory,
    so label-driven VMI placement can distinguish hosts."""
    from tpu_device_plugin.discovery import discover
    from tpu_device_plugin.labeler import node_facts
    facts = []
    for name, n_chips in (("na", 4), ("nb", 2)):
        host = FakeHost(os.path.join(short_root, name))
        for i in range(n_chips):
            host.add_chip(FakeChip(f"0000:00:{4 + i:02x}.0",
                                   device_id="0064", iommu_group=str(11 + i)))
        cfg = Config().with_root(host.root)
        registry, generations = discover(cfg)
        facts.append(node_facts(cfg, registry, generations))
    fa, fb = facts
    assert fa["cloud-tpus.google.com/v5p.chips"] == "4"
    assert fb["cloud-tpus.google.com/v5p.chips"] == "2"
    assert fa["cloud-tpus.google.com/v5p.torus"] == "2x2x1"


def test_distributed_two_process_slice():
    """The multi-VMI composition path for real: two OS processes rendezvous
    via `validator --coordinator` (jax.distributed), each holding 2 local
    CPU devices; the 4-device global slice must train with IDENTICAL losses
    on both ranks (proof the gradient collectives actually crossed
    processes)."""
    import json
    import socket
    import subprocess
    import sys

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    code = ("import jax; jax.config.update('jax_platforms','cpu'); "
            "import sys; from tpu_device_plugin.validator.probe import main; "
            "sys.exit(main(['--coordinator','127.0.0.1:%d',"
            "'--num-processes','2','--process-id','%%d',"
            "'--steps','2','--seq-len','32']))" % port)
    env = dict(os.environ,
               JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=2")
    procs = [subprocess.Popen(
        [sys.executable, "-c", code % rank],
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
        for rank in range(2)]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=300)
            assert p.returncode == 0, f"rank failed: {err[-800:]}"
            outs.append(json.loads(out.strip().splitlines()[-1]))
    finally:
        # never orphan a rank at the rendezvous barrier (a failed rank 0
        # assert would otherwise leave rank 1 blocked with open pipes)
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.communicate()
    for report in outs:
        assert report["ok"], report["error"]
        assert report["n_devices"] == 4          # global slice, not local
    assert outs[0]["loss_end"] == outs[1]["loss_end"]  # collectives synced
