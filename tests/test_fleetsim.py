"""Fleet-scale simulation harness tests (ISSUE 9).

Small-N deterministic versions of the storms bench.py --fleet runs at
{16,64,256}: coordinated boot, mass attach, health-flip coalescing,
rolling drain/upgrade — each asserting the counted fleet contracts
(exactly-once slice generations, zero lost claims, convergence) rather
than wall-clock. The 64-node chaos soak is @pytest.mark.slow and gated
on TDP_CHAOS_SOAK=1 (`make fleet-soak`, lockdep-enabled).
"""

import os
import time
import threading

import pytest

from tpu_device_plugin import faults
from tpu_device_plugin.fleetsim import (FleetApiServer, FleetSim,
                                        assert_fleet_invariants)
from tpu_device_plugin.kubeapi import ApiClient, ApiError, PublishPacer


@pytest.fixture()
def fleet():
    sims = []

    def build(**kw):
        kw.setdefault("n_nodes", 4)
        kw.setdefault("devices_per_node", 4)
        kw.setdefault("latency_s", 0.002)
        kw.setdefault("seed", 3)
        sim = FleetSim(**kw)
        sims.append(sim)
        return sim

    yield build
    for sim in sims:
        sim.stop()


# ------------------------------------------------------------ fabric


def test_fabric_serves_the_dra_surface_and_audits_writes():
    srv = FleetApiServer()
    try:
        client = ApiClient(srv.url, token_path="/nonexistent")
        group = client.get_json("/apis/resource.k8s.io")
        assert group["versions"][0]["version"] == "v1beta1"
        node = client.get_json("/api/v1/nodes/n1")
        assert node["metadata"]["uid"] == "uid-n1"
        obj = {"metadata": {"name": "s1"},
               "spec": {"pool": {"generation": 1}, "devices": []}}
        created = client.post_json(
            "/apis/resource.k8s.io/v1beta1/resourceslices", obj)
        # duplicate create = 409, like a real apiserver (exactly-once)
        with pytest.raises(ApiError) as exc:
            client.post_json(
                "/apis/resource.k8s.io/v1beta1/resourceslices", obj)
        assert exc.value.code == 409
        # guarded PUT honors resourceVersion
        created["spec"]["pool"]["generation"] = 2
        client.put_json(
            "/apis/resource.k8s.io/v1beta1/resourceslices/s1", created)
        stale = dict(created, metadata={"name": "s1",
                                        "resourceVersion": "0"})
        with pytest.raises(ApiError) as exc:
            client.put_json(
                "/apis/resource.k8s.io/v1beta1/resourceslices/s1", stale)
        assert exc.value.code == 409
        audit = srv.exactly_once_audit()
        assert audit["exactly_once"], audit
        assert audit["slices_audited"] == 1
    finally:
        srv.stop()


def test_fabric_throttles_beyond_capacity_and_client_retries_gets():
    srv = FleetApiServer(latency_s=0.4, max_inflight=1)
    try:
        client = ApiClient(srv.url, token_path="/nonexistent")
        blocker = threading.Thread(
            target=lambda: client.get_json("/api/v1/nodes/slow"),
            daemon=True)
        blocker.start()
        # wait until the blocker actually OCCUPIES the single admission
        # slot, so the probe below deterministically draws a 429 first
        deadline = time.monotonic() + 5
        while srv._admitted < 1 and time.monotonic() < deadline:
            time.sleep(0.005)
        assert srv._admitted >= 1
        # the blocked slot forces 429s; the client's bounded in-call GET
        # retry (jittered, client-wide backoff) absorbs most of the
        # window, and the outer loop models the caller retrying a GET
        # whose in-call budget expired while the slot was still held —
        # the budget is deliberately bounded, so exhausting it under a
        # 400 ms hold is legitimate behavior, not a failure
        out = ApiClient(srv.url, token_path="/nonexistent")
        node = None
        for _ in range(5):
            try:
                node = out.get_json("/api/v1/nodes/n2")
                break
            except ApiError as exc:
                assert exc.code == 429, exc
        assert node is not None and node["metadata"]["name"] == "n2"
        assert out.throttled_total.value >= 1
        assert out.thread_throttled_count() >= 1
        blocker.join(timeout=5)
        assert srv.snapshot()["throttled_total"] >= 1
    finally:
        srv.stop()


def test_fabric_load_dependent_latency_degrades_with_inflight():
    """congestion_k: service time scales 1 + inflight/k — concurrent
    requests are measurably slower than a lone one (the herd makes
    itself slow; what the pacing bench's peak-in-flight cells model)."""
    srv = FleetApiServer(latency_s=0.05, congestion_k=1)
    try:
        lone = ApiClient(srv.url, token_path="/nonexistent")
        t0 = time.monotonic()
        lone.get_json("/api/v1/nodes/a")
        lone_wall = time.monotonic() - t0

        clients = [ApiClient(srv.url, token_path="/nonexistent")
                   for _ in range(4)]
        walls = []

        def hit(c):
            t0 = time.monotonic()
            c.get_json("/api/v1/nodes/b")
            walls.append(time.monotonic() - t0)

        threads = [threading.Thread(target=hit, args=(c,), daemon=True)
                   for c in clients]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=10)
        # the slowest concurrent request saw >= 2 in flight: its service
        # time is at least ~2x the lone request's base
        assert max(walls) > lone_wall * 1.5, (lone_wall, walls)
    finally:
        srv.stop()


# --------------------------------------------------------- pacing unit


def test_pacer_coalesces_concurrent_publishers():
    """Publishers arriving during a wave's admission wait ride that wave:
    5 concurrent requests -> 1 publish_fn call, every caller sees the
    wave's result."""
    calls = []
    release = threading.Event()

    def publish():
        calls.append(threading.get_ident())
        return True

    pacer = PublishPacer(base_window_s=0.3)
    results = []

    def caller():
        release.wait(5)
        results.append(pacer.run(publish))

    threads = [threading.Thread(target=caller, daemon=True)
               for _ in range(5)]
    for t in threads:
        t.start()
    release.set()
    for t in threads:
        t.join(timeout=10)
    assert len(calls) == 1, calls
    assert results == [True] * 5
    snap = pacer.snapshot()
    assert snap["publish_waves_total"] == 1
    assert snap["publishes_coalesced_total"] == 4


def test_pacer_zero_window_adds_no_delay_and_adapts_on_throttle():
    class FakeApi:
        def __init__(self):
            self.last_code = None
            self.last_rtt_s = 0.001

        def reset_thread_error(self):
            self.last_code = None

        def thread_last_error_code(self):
            return self.last_code

    api = FakeApi()
    pacer = PublishPacer(api=api, base_window_s=0.0, max_window_s=2.0)
    assert pacer.run(lambda: True) is True
    assert pacer.snapshot()["window_ms"] == 0      # uncongested: no pacing
    assert pacer.snapshot()["pacing_delays_total"] == 0

    # a throttled failure (the wave's final request answered 429) grows
    # the window and re-admits; success through the grown window decays
    outcomes = [False, True]

    def publish():
        ok = outcomes.pop(0)
        api.last_code = None if ok else 429
        return ok

    assert pacer.run(publish) is True
    snap = pacer.snapshot()
    assert snap["publish_throttled_total"] == 1
    assert snap["pacing_delays_total"] >= 1        # the re-admission wait
    assert outcomes == []


def test_pacer_non_throttle_failure_with_earlier_throttled_get():
    """A wave whose internal GET drew a (retried-away) 429 but whose
    final request failed 5xx is NOT throttled: it returns to the
    caller's republish machinery immediately instead of re-admitting."""
    class FakeApi:
        def __init__(self):
            self.last_code = None
            self.last_rtt_s = 0.001

        def reset_thread_error(self):
            self.last_code = None

        def thread_last_error_code(self):
            return self.last_code

    api = FakeApi()
    pacer = PublishPacer(api=api, base_window_s=0.0, max_window_s=2.0)
    calls = []

    def publish():
        calls.append(1)
        api.last_code = 500     # the request that made the wave give up
        return False

    assert pacer.run(publish) is False
    assert len(calls) == 1
    assert pacer.snapshot()["publish_throttled_total"] == 0


def test_pacer_non_throttle_failure_returns_immediately():
    pacer = PublishPacer(base_window_s=0.0)
    calls = []

    def publish():
        calls.append(1)
        return False

    assert pacer.run(publish) is False
    assert len(calls) == 1     # no blind retry: the caller's machinery owns it


# ------------------------------------------------------------- storms


def test_boot_storm_publishes_every_node_exactly_once(fleet):
    sim = fleet(n_nodes=4)
    boot = sim.boot_storm()
    assert boot["published_ok"] == 4
    assert boot["exactly_once"], boot["audit"]
    assert boot["apiserver"]["slices"] == 4
    # one accepted write per node at boot: no duplicated POSTs
    assert boot["apiserver"]["accepted_writes"] == 4
    assert sim.assert_converged()


def test_boot_storm_converges_through_a_throttling_fabric(fleet):
    """A capped fabric 429s the herd; the adaptive windows + in-pacer
    re-admission land every node's slice exactly once. A node may
    legitimately exhaust its in-call retry budget under extreme
    throttling (production hands off to the republish timer); settle()
    compresses that timer, after which convergence and the exactly-once
    write audit must hold unconditionally."""
    sim = fleet(n_nodes=6, latency_s=0.05, max_inflight=2, pace=True)
    boot = sim.boot_storm()
    assert boot["published_ok"] >= 4     # the storm mostly lands in-call
    sim.settle()
    assert sim.assert_converged()
    audit = sim.apiserver.exactly_once_audit()
    assert audit["exactly_once"], audit
    assert audit["slices_audited"] == 6


def test_attach_storm_prepares_every_claim(fleet):
    sim = fleet(n_nodes=4)
    sim.boot_storm()
    attach = sim.attach_storm(4)
    assert attach["errors"] == []
    assert attach["prepared_total"] == 16
    # group commit held fleet-wide: commits well under one per claim
    assert attach["checkpoint_commits"] < 16


def test_flip_wave_coalesces_and_lands_final_state(fleet):
    sim = fleet(n_nodes=4, latency_s=0.02, max_inflight=2)
    sim.boot_storm()
    flip = sim.flip_wave(6)
    assert flip["converged"]
    assert flip["exactly_once"]
    # the fabric never saw one write per flip: pacing + effective-flip
    # publishing bound the wave count below the raw flip count
    assert flip["accepted_writes"] < 4 * 7


def test_drain_upgrade_wave_preserves_claims(fleet):
    sim = fleet(n_nodes=4)
    sim.boot_storm()
    sim.attach_storm(2)
    wave = sim.drain_upgrade_wave(2)
    assert wave["waves"] == 2
    assert wave["converged"]
    assert wave["exactly_once"]
    assert wave["prepared_total"] == 8     # every claim survived upgrade


@pytest.mark.slow
@pytest.mark.skipif(os.environ.get("TDP_CHAOS_SOAK") != "1",
                    reason="soak: set TDP_CHAOS_SOAK=1 (make fleet-soak)")
def test_fleet_soak_64_node_boot_storm_with_chaos():
    """`make fleet-soak`: a 64-node boot storm + flip wave + attach storm
    + rolling upgrade with the chaos registry armed (publish refusals and
    apiserver transport faults firing mid-storm), under TDP_LOCKDEP=1
    (the make target bakes it in). Every fleet contract must hold
    through the faults — and the soak invariant pass
    (fleetsim.assert_fleet_invariants, shared with the autopilot's
    continuous checker) is asserted BETWEEN storms, not only at the
    end."""
    faults.reset()
    faults.arm("dra.publish", kind="drop", count=8)
    faults.arm("kubeapi.request", kind="error", count=8)
    try:
        sim = FleetSim(n_nodes=64, devices_per_node=4, latency_s=0.02,
                       max_inflight=8, pace=True, seed=1337)
        try:
            boot = sim.boot_storm()
            # armed dra.publish faults fail some first publishes; the
            # nodes' own retry (pacer returns False -> storm result
            # False) is out of scope here — republish and convergence
            # are: re-drive the failed nodes once, then audit
            for node in sim.nodes:
                name = node.driver.slice_name()
                with sim.apiserver._lock:
                    missing = name not in sim.apiserver.slices
                if missing:
                    assert node.driver.publish_resource_slices()
            assert sim.assert_converged()
            assert_fleet_invariants(sim)
            flip = sim.flip_wave(4)
            assert flip["converged"] and flip["exactly_once"]
            assert_fleet_invariants(sim)
            attach = sim.attach_storm(4)
            assert attach["errors"] == []
            assert attach["prepared_total"] == 256
            assert_fleet_invariants(sim)
            wave = sim.drain_upgrade_wave(16)
            assert wave["converged"] and wave["exactly_once"]
            assert wave["prepared_total"] == 256
            assert boot["exactly_once"]
            assert_fleet_invariants(sim)
        finally:
            sim.stop()
    finally:
        faults.reset()


# ------------------------------------- multi-host slice placement (ISSUE 10)


@pytest.fixture()
def placement_fleet():
    """Lean placement fleet: full 2x4 v5e hosts, zero fabric latency —
    placement facts are counted, never timed."""
    sims = []

    def build(n_nodes=3, pod_dims=None):
        sim = FleetSim(n_nodes=n_nodes, devices_per_node=8,
                       latency_s=0.0, max_inflight=0, seed=7,
                       pod_dims=pod_dims)
        sims.append(sim)
        return sim

    yield build
    for sim in sims:
        sim.stop()


def _raw_at(node):
    return {c: r for r, c in node.host_view().coords.items()}


def test_four_chip_request_lands_on_one_ring_on_fragmented_host(
        placement_fleet):
    """THE single-host acceptance: a fragmented host still holding one
    free 2x2 ICI ring gets the 4-chip slice ON that ring — scored 1.0
    and asserted by coordinates, not luck. The fuller-but-ringless node
    never wins."""
    sim = placement_fleet(n_nodes=2)
    a, b = sim.nodes
    # node-000: claims leave EXACTLY one 2x2 ring free at columns 2-3
    ra = _raw_at(a)
    a.claim_devices("a-1", [ra[(0, 0)]])
    a.claim_devices("a-2", [ra[(1, 1)]])
    a.claim_devices("a-3", [ra[(0, 1)]])
    a.claim_devices("a-4", [ra[(1, 0)]])
    # node-001: MORE free chips (5) but checkerboarded — no box of 4
    rb = _raw_at(b)
    b.claim_devices("b-1", [rb[(0, 1)]])
    b.claim_devices("b-2", [rb[(1, 2)]])
    b.claim_devices("b-3", [rb[(0, 3)]])
    res = sim.prepare_slice("2x2", "ring-claim")
    assert res["placed"] and res["score"] == 1.0 and res["hosts"] == 1
    (node_name, raws), = res["shards"]
    assert node_name == a.name
    coords = sorted(a.host_view().coords[r] for r in raws)
    assert coords == [(0, 2), (0, 3), (1, 2), (1, 3)]
    audit = sim.apiserver.multiclaim_audit()
    assert audit["exactly_once"] and audit["claims_audited"] == 1
    # the prepared shard is real claim state, not advisory: it occupies
    frag = a.driver.fragmentation_stats()["v5e"]
    assert frag["free"] == 0


def test_multi_host_slice_tiles_full_tori(placement_fleet):
    """4x4 over 2x4 hosts = two whole tori joined by a pod-level ICI
    link (ISSUE 14: the hosts must be ADJACENT on the pod grid, not
    just free); a host with any claim is ineligible, and the committed
    claim is audited exactly-once."""
    sim = placement_fleet(n_nodes=3, pod_dims=(3, 1))
    dirty = sim.nodes[2]
    dirty.claim_devices("pin", [sorted(dirty.host_view().free)[0]])
    res = sim.prepare_slice("4x4", "mesh-16")
    assert res["placed"] and res["hosts"] == 2 and res["score"] == 1.0
    assert {s[0] for s in res["shards"]} == {sim.nodes[0].name,
                                             sim.nodes[1].name}
    assert all(len(raws) == 8 for _n, raws in res["shards"])
    assert sim.apiserver.multiclaim_audit()["exactly_once"]
    # both member drivers now report zero free capacity
    for node in sim.nodes[:2]:
        assert node.driver.fragmentation_stats()["v5e"]["free"] == 0


def test_multi_host_failure_rolls_back_whole_claim(placement_fleet):
    """ISSUE 10 satellite: one node's prepare fails mid-slice (after the
    first shard already landed) -> the WHOLE claim rolls back, no
    orphaned per-node specs or checkpoint entries anywhere, and both
    fabric audits stay exactly-once under an armed dra.publish fault."""
    faults.reset()
    # (2,1) pod column: the two hosts share a pod-level ICI link, so
    # the 4x4 plans (and then deterministically fails mid-prepare)
    sim = placement_fleet(n_nodes=2, pod_dims=(2, 1))
    try:
        free_before = [len(n.host_view().free) for n in sim.nodes]
        plan_nodes = [n.name for n in sim.nodes]
        # publishes during the storm get dropped by the armed fault; the
        # claim path must stay exactly-once regardless
        faults.arm("dra.publish", kind="drop", count=2)
        res = sim.prepare_slice("4x4", "doomed", fail_node=plan_nodes[1])
        assert not res["placed"] and res["rolled_back"]
        assert plan_nodes[1] in res["error"]
        assert res["residue"] == []          # no orphaned per-node specs
        assert sim.slice_residue("doomed") == []
        # every chip is free again on every node
        assert [len(n.host_view().free) for n in sim.nodes] == free_before
        for node in sim.nodes:
            assert node.driver.prepared_claim_count() == 0
        audit = sim.apiserver.multiclaim_audit()
        assert audit["exactly_once"]
        assert audit["pending"] == []        # the abort is recorded
        sim.settle()
        assert sim.apiserver.exactly_once_audit()["exactly_once"]
    finally:
        faults.reset()


def test_defrag_proposal_application_makes_shape_placeable(
        placement_fleet):
    """THE defrag acceptance: an unplaceable-but-satisfiable 2x2 yields
    an advisory whose application — riding the PR 7 migration-handoff
    machinery claim by claim — makes the shape placeable, with the
    handoff completions counted and every fabric audit green."""
    from tpu_device_plugin import placement as pl
    sim = placement_fleet(n_nodes=2)
    a, b = sim.nodes
    ra, rb = _raw_at(a), _raw_at(b)
    # checkerboard node-000 (free 4, no box); nearly fill node-001
    for i, c in enumerate([(0, 1), (1, 0), (0, 3), (1, 2)]):
        a.claim_devices(f"a-{i}", [ra[c]])
    for i, c in enumerate([(0, 0), (0, 1), (0, 2), (0, 3), (1, 0),
                           (1, 1)]):
        b.claim_devices(f"b-{i}", [rb[c]])
    assert pl.plan_slice((2, 2), sim.host_views()) is None
    prop = sim.propose_defrag("2x2")
    assert not prop["placeable"] and prop["satisfiable"]
    assert 1 <= prop["moves"] <= 2
    completed_before = sum(
        n.driver.handoff_stats["handoffs_completed_total"]
        for n in sim.nodes)
    moves = sim.apply_defrag(prop)
    assert moves == prop["moves"]
    plan = pl.plan_slice((2, 2), sim.host_views())
    assert plan is not None and plan.score == 1.0
    # and the slice actually prepares end to end now
    res = sim.prepare_slice("2x2", "post-defrag")
    assert res["placed"] and res["score"] == 1.0
    assert sum(n.driver.handoff_stats["handoffs_completed_total"]
               for n in sim.nodes) == completed_before + moves
    assert sim.apiserver.multiclaim_audit()["exactly_once"]
    sim.settle()
    assert sim.apiserver.exactly_once_audit()["exactly_once"]


# --------------------------- managed node: PR 7 lifecycle through fleetsim


def test_hot_unplug_of_allocated_chip_through_managed_fleet_node(
        short_root):
    """ISSUE 10 satellite (ROADMAP item 1 follow-on): the PR 7
    hot-unplug-of-an-allocated-chip scenario driven through a fleetsim
    node with the FULL PluginManager + HealthHub wiring cli.main builds
    — and the orphan + slice republish observed in the shared fabric's
    accepted-write generation log (exactly-once)."""
    from tpu_device_plugin.fleetsim import ManagedFleetNode

    api = FleetApiServer(latency_s=0.0, max_inflight=0)
    node = None
    try:
        node = ManagedFleetNode(short_root, api, n_devices=4)
        # full wiring is live: plugins registered with the kubelet sim,
        # FSM tracking every chip as bound
        assert list(node.kubelet.endpoints)
        assert node.manager.lifecycle_stats()["states"] == {"bound": 4}
        assert len(node.published_devices()) == 4
        views = node.driver.host_views()["v5e"]
        raw_at = {c: r for r, c in views.coords.items()}
        victim = raw_at[(0, 1)]
        node.claim_devices("vm1", [victim])
        assert node.manager.device_lifecycle.state_of(victim) == "allocated"
        gens_before = [g for _t, _m, g in node.slice_log()]

        node.hot_unplug(victim)
        node.tick()                          # one run-loop rediscovery

        # orphan observed end to end
        assert node.driver.orphaned_claims() == ["vm1"]
        assert node.driver.departed_devices() == [victim]
        ls = node.manager.lifecycle_stats()
        assert ls["claims_orphaned_total"] == 1
        assert ls["transitions"].get("allocated->gone") == 1
        # ... and the republish landed in the fabric's generation log:
        # strictly increasing generations, exactly one new accepted
        # write, with the departed chip gone from the published slice
        log = node.slice_log()
        gens = [g for _t, _m, g in log]
        assert len(gens) > len(gens_before)
        assert gens == sorted(set(gens)), gens
        assert len(node.published_devices()) == 3
        audit = api.exactly_once_audit()
        assert audit["exactly_once"], audit
        # the departed slot keeps counting toward fragmentation
        frag = node.driver.fragmentation_stats()["v5e"]
        assert frag["departed"] == 1 and frag["free"] == 3
    finally:
        if node is not None:
            node.stop()
        api.stop()


def test_broker_backed_managed_node_boot_and_claim_storm(short_root):
    """ISSUE 11: a ManagedFleetNode with the REAL privilege-separated
    wiring — a spawned broker process owns every privileged read while
    the full PluginManager + DRA stack drives a boot + claim storm
    through the versioned IPC, exactly-once audited in the fabric; a
    broker kill -9 degrades attaches to typed unavailable errors and a
    respawn + handshake recovers without restarting the serving side."""
    from tpu_device_plugin.fleetsim import ManagedFleetNode

    api = FleetApiServer(latency_s=0.0, max_inflight=0)
    node = None
    try:
        node = ManagedFleetNode(short_root, api, n_devices=4,
                                spawn_broker=True)
        assert node.broker_proc.poll() is None
        # boot storm landed through the broker: plugins registered,
        # slice published, crossings counted
        assert list(node.kubelet.endpoints)
        assert len(node.published_devices()) == 4
        from tpu_device_plugin import broker as broker_mod
        client = broker_mod.get_client()
        assert client.mode == "spawn"
        # the health plane is brokered: probe closures cross the IPC
        assert isinstance(node.manager._shim, broker_mod.BrokeredHealth)
        node.manager._shim.chip_alive(
            node.cfg.pci_base_path, node.bdfs[0])
        boot_crossings = client.crossings.value
        assert boot_crossings > 0

        # claim storm: every prepare's TOCTOU revalidation crosses
        names = {}
        for v in node.driver.host_views().values():
            names.update(v.names)
        uids = [f"vm-{i}" for i in range(4)]
        for i, uid in enumerate(uids):
            node.apiserver.add_claim(
                "fleet", uid, uid, node.driver.driver_name,
                [{"device": names[node.bdfs[i]]}])
        resp = node.attach(uids)
        for uid in uids:
            assert resp.claims[uid].error == "", resp.claims[uid].error
        assert client.crossings.value > boot_crossings
        assert client.stats()["broker"]["ops"].get("revalidate", 0) >= 4

        # broker kill -9 mid-fleet: typed unavailable, claims intact
        node.kill_broker()
        node.apiserver.add_claim(
            "fleet", "vm-degraded", "vm-degraded",
            node.driver.driver_name, [{"device": names[node.bdfs[0]]}])
        resp = node.attach(["vm-degraded"])
        assert "broker unavailable" in resp.claims["vm-degraded"].error
        assert node.driver.prepared_claim_count() == 4

        # respawn + handshake: the retry lands, fabric audit still clean
        node.respawn_broker()
        resp = node.attach(["vm-degraded"])
        assert resp.claims["vm-degraded"].error == "", \
            resp.claims["vm-degraded"].error
        assert node.driver.prepared_claim_count() == 5
        audit = api.exactly_once_audit()
        assert audit["exactly_once"], audit
    finally:
        if node is not None:
            node.stop()
        api.stop()
