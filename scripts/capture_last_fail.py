#!/usr/bin/env python3
"""Preserve the latest COMPLETE validator attempt record.

The attempt loop truncates docs/validator_tpu_train_r05.json at attempt
start, so a complete record only exists in the ~30 s window between
attempts. This watcher polls and copies any parseable record to
docs/validator_tpu_train_r05_last.json so the round always ends with a
full artifact (success or the structured failure signature), not a
zero-byte truncation snapshot. Exits when .stop_tpu_attempts appears and
the loop has wound down, or after --max-hours.
"""
import json
import os
import shutil
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SRC = os.path.join(REPO, "docs", "validator_tpu_train_r05.json")
DST = os.path.join(REPO, "docs", "validator_tpu_train_r05_last.json")
SENTINEL = os.path.join(REPO, ".stop_tpu_attempts")


def main() -> int:
    max_s = float(sys.argv[sys.argv.index("--max-hours") + 1]) * 3600 \
        if "--max-hours" in sys.argv else 12 * 3600
    deadline = time.time() + max_s
    last = None
    while time.time() < deadline:
        try:
            with open(SRC, encoding="utf-8") as f:
                obj = json.load(f)
            blob = json.dumps(obj, sort_keys=True)
            if blob != last:
                shutil.copyfile(SRC, DST)
                last = blob
        except (OSError, ValueError):
            pass   # absent, truncated, or mid-write — try again
        if os.path.exists(SENTINEL):
            break
        time.sleep(5)
    return 0


if __name__ == "__main__":
    sys.exit(main())
