"""Ring attention — sequence-parallel causal attention over the ICI ring.

The KV-all-gather form of sequence parallelism (workload.py's einsum path)
materializes the full K/V on every chip: O(S) memory per chip. Ring attention
keeps K/V sharded — each of the `sp` shards holds S/sp keys/values — and
rotates the KV block around the mesh axis with `jax.lax.ppermute` while
accumulating attention with the same online-softmax recurrence the Pallas
flash kernel uses. Forward-pass K/V residency is O(S/sp) per chip and every
hop is a nearest-neighbor ICI transfer, which is exactly what the torus is
for. (Under plain autodiff the backward pass still saves the rotated blocks
and per-step score tiles — a rematerializing custom_vjp like the flash
kernel's would extend the bound to training; the burn-in's sequences are
short enough that exact autodiff is the simpler, safer choice here.)

Causality at block granularity: shard i's queries attend fully to KV blocks
j < i, causally to block j == i, and not at all to j > i. The rotation
schedule visits the local block first, so the running max is finite from
step 0.

Runs inside `jax.shard_map`; the loop over ring steps is a static Python
unroll (mesh size is static), XLA-friendly.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                   sm_scale: float, axis_name: str = "sp") -> jax.Array:
    """Causal attention with KV rotating around `axis_name`.

    Local shapes: q, k, v are (heads_batch, seq_local, head_dim); the global
    sequence is the concatenation of shards along `axis_name` in axis order.
    """
    n = jax.lax.axis_size(axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    bh, s_local, d = q.shape
    qf = q.astype(jnp.float32)

    m = jnp.full((bh, s_local, 1), NEG_INF, jnp.float32)
    l = jnp.zeros((bh, s_local, 1), jnp.float32)
    acc = jnp.zeros((bh, s_local, d), jnp.float32)
    tril = jnp.tril(jnp.ones((s_local, s_local), jnp.bool_))[None]

    k_cur, v_cur = k, v
    for step in range(n):
        # the KV block now held locally originated at shard (my_idx - step)
        src = (my_idx - step) % n
        s = jnp.einsum("bqd,bkd->bqk", qf, k_cur.astype(jnp.float32)) * sm_scale
        allow = (src < my_idx) | ((src == my_idx) & tril)
        s = jnp.where(allow, s, NEG_INF)
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m, m_cur)
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l = alpha * l + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * alpha + jnp.einsum(
            "bqk,bkd->bqd", p, v_cur.astype(jnp.float32))
        m = m_new
        if step != n - 1:
            perm = [(i, (i + 1) % n) for i in range(n)]
            k_cur = jax.lax.ppermute(k_cur, axis_name, perm)
            v_cur = jax.lax.ppermute(v_cur, axis_name, perm)
    return (acc / l).astype(q.dtype)
